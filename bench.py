"""ResNet-50 synthetic benchmark — the TPU equivalent of the reference's
`examples/tensorflow2_synthetic_benchmark.py:110-131` (batch 64/device,
synthetic ImageNet-shaped data, warmup then timed rounds, images/sec).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "step_time_ms": N, "tflops_per_chip": N, "mfu": N, "baseline": "..."}

Baseline: the reference's only published absolute throughput is ResNet-101
at 1656.82 images/sec over 16 Pascal P100s (`docs/benchmarks.rst:43`) =
103.55 images/sec/GPU; `vs_baseline` is images/sec/chip over that number
(cross-model when --model != resnet101 — the `baseline` field says so).
Rows with no reference measurement at all (LM configs, word2vec, the
zoo aggregate) emit `"vs_baseline": null` — never a literal 0.0 that an
aggregator would read as a measured 0% delta.

MFU honesty: FLOPs per step come from XLA's own cost analysis of the
compiled train step (not a hand-count), divided by measured step time and
the chip's peak bf16 FLOP/s.
"""

import argparse
import json
import os
import re
import socket
import statistics
import subprocess
from functools import partial
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))

# Peak bf16 dense FLOP/s per chip, by jax device_kind substring (public
# TPU spec sheet numbers). Used only for the MFU denominator.
_PEAK_BF16 = [
    ("v6", 918e12), ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5", 459e12), ("v4 lite", 138e12), ("v4", 275e12), ("v3", 123e12),
    ("v2", 45e12),
]


def peak_flops(device):
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_BF16:
        if key in kind:
            return val
    return None


def compiled_flops(step, *args):
    """Per-device FLOPs of the compiled step, from XLA's own cost
    analysis (no hand-counting)."""
    try:
        cost = step.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost["flops"])
    except Exception as e:  # cost analysis is best-effort diagnostics
        print("bench: cost_analysis unavailable (%s)" % e, file=sys.stderr)
        return None


def _time_steps(step, state, batch, iters, warmup=3):
    """Median-of-3 step time (seconds) with a host-read barrier."""
    params_p, opt_state = state
    for _ in range(warmup):
        params_p, opt_state, loss = step(params_p, opt_state, batch)
    float(loss)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            params_p, opt_state, loss = step(params_p, opt_state, batch)
        float(loss)
        times.append((time.perf_counter() - t0) / iters)
    return sorted(times)[1]


def scaling_worker(args):
    """Weak-scaling measurement subprocess (virtual CPU mesh): runs the
    full jitted DP train step over an `n`-device mesh (or the same total
    work on one device with --scaling-single — the contention-fair
    baseline on a shared-core host) and prints a JSON step-time line."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.parallel import data_parallel_mesh, make_train_step

    n = args.scaling_worker
    b = args.scaling_batch
    width, layers = 1024, 4
    rng = jax.random.PRNGKey(0)

    def init_params():
        ks = jax.random.split(rng, layers)
        return [jax.random.normal(k, (width, width), jnp.float32) * 0.02
                for k in ks]

    def loss_fn(params, batch):
        h = batch["x"]
        for w in params:
            h = jnp.tanh(h @ w)
        return jnp.mean((h - batch["y"]) ** 2)

    params = init_params()
    opt = optax.sgd(0.01)
    total_batch = b * n
    x = jax.random.normal(rng, (total_batch, width), jnp.float32)
    y = jax.random.normal(rng, (total_batch, width), jnp.float32)

    # Explicitly the cpu backend: a TPU plugin may register even under
    # JAX_PLATFORMS=cpu, making bare jax.devices() return the real chip.
    cpus = jax.devices("cpu")
    if len(cpus) < n:
        raise RuntimeError(
            "scaling worker expected >=%d cpu devices, got %d (XLA_FLAGS "
            "device-count override lost?)" % (n, len(cpus)))
    devices = cpus[:1] if args.scaling_single else cpus[:n]
    mesh = data_parallel_mesh(devices=devices)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    params_p, opt_state, batch = step.place(params, opt.init(params),
                                            {"x": x, "y": y})
    dt = _time_steps(step, (params_p, opt_state), batch, args.num_iters)
    print(json.dumps({"n": n, "single": bool(args.scaling_single),
                      "step_ms": round(dt * 1000.0, 3)}))


def _run_weak_scaling(batch, iters):
    """Spawns scaling_worker subprocesses on a virtual CPU mesh; returns
    rows of {n, mesh_ms, single_ms, efficiency}."""
    rows = []
    for n in (1, 2, 4, 8):
        res = {}
        for single in (False, True):
            from horovod_tpu.run.util import cpu_worker_env
            env = cpu_worker_env()
            # Hard platform pin (not just NAME-priority): the mesh MUST
            # be the virtual CPU devices.
            env["JAX_PLATFORMS"] = "cpu"
            # Appended last: XLA's flag parsing takes the last
            # occurrence, so an inherited device-count flag can't
            # silently shrink the mesh under us.
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "") +
                " --xla_force_host_platform_device_count=%d" % n)
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--scaling-worker", str(n),
                   "--scaling-batch", str(batch),
                   "--num-iters", str(iters)]
            if single:
                cmd.append("--scaling-single")
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 env=env, timeout=1200)
            if out.returncode != 0:
                raise RuntimeError("scaling worker n=%d failed:\n%s" %
                                   (n, out.stderr))
            res[single] = json.loads(out.stdout.strip().splitlines()[-1])
        mesh_ms = res[False]["step_ms"]
        single_ms = res[True]["step_ms"]
        rows.append({"n": n, "mesh_step_ms": mesh_ms,
                     "single_device_same_work_ms": single_ms,
                     "efficiency": round(single_ms / mesh_ms, 3)})
        print("weak-scaling n=%d: mesh %.1f ms, single-device-same-work "
              "%.1f ms, efficiency %.3f" %
              (n, mesh_ms, single_ms, rows[-1]["efficiency"]),
              file=sys.stderr)
    return rows


def _reserve_ports(n):
    """Reserves n ephemeral ports, HOLDING the sockets (SO_REUSEPORT)
    so no other process can be handed one before the slowest worker
    binds; workers bind alongside via HVD_TPU_LISTEN_REUSEPORT=1 (the
    same mechanism rendezvous.reserve_port(hold=True) uses)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if hasattr(socket, "SO_REUSEPORT"):
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    return socks, ports


def _spawn_local_workers(n, script, extra_env=None, rank_env=None):
    """Reserves ports and spawns n local control-plane worker
    subprocesses (numpy+ctypes only) of tests/`script` with the shared
    rank/rendezvous env; returns (procs, socks) — the caller owns
    communicate/kill and closing the sockets. `rank_env[r]` adds
    per-rank overrides (e.g. a forced (local, cross) topology)."""
    socks, ports = _reserve_ports(n)
    addrs = ",".join("127.0.0.1:%d" % p for p in ports)
    procs = []
    for r in range(n):
        env = dict(os.environ)
        # The workers are numpy+ctypes only; drop PYTHONPATH entries
        # that exist to register accelerator plugins (their
        # sitecustomize costs seconds of interpreter boot per worker —
        # at 256 serialized starts that dwarfs the measurement).
        inherited = [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and not os.path.exists(os.path.join(p,
                                                     "sitecustomize.py"))]
        env["PYTHONPATH"] = os.pathsep.join([REPO] + inherited)
        env.update({
            "HVD_TPU_RANK": str(r), "HVD_TPU_SIZE": str(n),
            "HVD_TPU_LOCAL_RANK": str(r), "HVD_TPU_LOCAL_SIZE": str(n),
            "HVD_TPU_CROSS_RANK": "0", "HVD_TPU_CROSS_SIZE": "1",
            "HVD_TPU_ADDRS": addrs, "HVD_TPU_CYCLE_TIME": "0",
            "HVD_TPU_LISTEN_REUSEPORT": "1",
            # Interpreter startup for n ranks is serialized on small
            # hosts; the default 60s accept timeout starves out at
            # high rank counts.
            "HVD_TPU_START_TIMEOUT": str(max(120, 4 * n)),
        })
        if extra_env:
            # A None value REMOVES the key — e.g. the autotune A/B must
            # drop the harness's HVD_TPU_CYCLE_TIME=0 pin (an env-pinned
            # knob is excluded from tuning; the A/B measures defaults).
            for k, v in extra_env.items():
                if v is None:
                    env.pop(k, None)
                else:
                    env[k] = v
        if rank_env and r in rank_env:
            env.update(rank_env[r])
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    return procs, socks


def _run_negotiation_bench(n, iters, extra_env=None, timeout=1800):
    """Launches n local control-plane workers (numpy+ctypes only);
    returns (rank-0 negotiation latency us/op, protocol counters by
    rank for ranks 0 and 1 — bytes/messages/cycle kinds)."""
    env = {"HVD_TPU_BENCH_ITERS": str(iters)}
    env.update(extra_env or {})
    procs, socks = _spawn_local_workers(n, "negotiation_bench_worker.py",
                                        env)
    outputs = []
    us = None
    counters = {}
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
            if p.returncode != 0:
                raise RuntimeError("rank %d failed:\n%s" % (r, out))
            m = re.search(r"NEGOTIATION_US_PER_OP ([\d.]+)", out)
            if m:
                us = float(m.group(1))
            m = re.search(r"PROTOCOL_COUNTERS (\{.*\})", out)
            if m:
                d = json.loads(m.group(1))
                counters[d["rank"]] = d
            m = re.search(r"METRICS_SNAPSHOT (\{.*\})", out)
            if m:
                counters.setdefault(r, {})["metrics"] = json.loads(m.group(1))
            m = re.search(r"TRACE_COUNTERS (\{.*\})", out)
            if m:
                counters.setdefault(r, {})["trace"] = json.loads(m.group(1))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in socks:
            s.close()
    if us is None:
        raise RuntimeError(
            "no NEGOTIATION_US_PER_OP line in any worker output; rank 0 "
            "said:\n%s" % (outputs[0] if outputs else "<no output>"))
    return us, counters


# Model-zoo sweep configs: the models in the reference's published
# scaling table (docs/benchmarks.rst:13-14) plus the long-context
# transformer and the GroupNorm roofline experiment. Batch/size choices
# are each model's measured-fastest from PERF.md.
_ZOO = [
    ("resnet50", ["--batch-size", "256"]),
    # Fused Pallas BN statistics vs the XLA lowering — the round-4
    # kernel's primary and secondary measurement targets.
    ("resnet50pbn", ["--batch-size", "256"]),
    ("resnet50gn", ["--batch-size", "256"]),
    ("resnet50nf", ["--batch-size", "256"]),
    # Round 10: the traffic-lean graph-level BN (custom-VJP x_hat/mask
    # recompute, ops/batch_norm.py — the island-tax lesson turned into
    # shipped code) and the AGC-trainable norm-free depth row.
    ("resnet50lean", ["--batch-size", "256"]),
    ("resnet101nf", ["--batch-size", "128"]),
    ("resnet101", ["--batch-size", "128"]),
    ("vgg16", ["--batch-size", "64"]),
    ("inception3", ["--batch-size", "128", "--image-size", "299"]),
    ("inception3pbn", ["--batch-size", "128", "--image-size", "299"]),
    ("transformer", []),
    ("transformer", ["--moe-experts", "8", "--fused-xent"]),
    # Long-context row (VERDICT r3 item 8): L=8192 MUST use the fused
    # streaming xent (dense f32 logits at this length exceed v5e HBM
    # and have killed the tunnel before) and a reduced batch.
    ("transformer", ["--seq-len", "8192", "--fused-xent",
                     "--tokens-batch", "2"]),
    # TPU-native head shape at long context: 6 x D=128 heads, identical
    # FLOPs to GPT-2's 12 x D=64, but every attention matmul runs the
    # MXU at full width (D=64 caps contraction/output at 64 of 128
    # lanes). Measured v5e: 36.4% vs 27.6% kernel-counted MFU.
    ("transformer", ["--seq-len", "8192", "--fused-xent",
                     "--tokens-batch", "2", "--num-heads", "6"]),
    # Fused rotary alone (isolates the saved q/k HBM round trip), then
    # GQA G=2 on top (kv projections a third the size, grouped-rows
    # kernel layout) — the modern-LM kernel surface at the same
    # long-context shape as the h6 row above.
    ("transformer", ["--seq-len", "8192", "--fused-xent",
                     "--tokens-batch", "2", "--num-heads", "6",
                     "--fused-rope"]),
    ("transformer", ["--seq-len", "8192", "--fused-xent",
                     "--tokens-batch", "2", "--num-heads", "6",
                     "--num-kv-heads", "2", "--fused-rope"]),
    # Sparse (indices,values) embedding-gradient plane vs the dense
    # full-table path — BASELINE.json config #4's IndexedSlices
    # rationale with an on-chip number (both variants in one row;
    # vocab matches the reference example's 50000 — the sparse win
    # grows linearly with vocab, see PERF.md's V-sweep).
    ("word2vec", ["--vocab-size", "50000", "--num-iters", "100"]),
]


def _tpu_probe_or_report(timeout=240):
    """True when `import jax` + device enumeration completes (probed
    in a killable subprocess — with the tunnel plugin's relay dead it
    hangs forever in-process); on failure prints the diagnostic JSON
    line and returns False. Skipped when HVD_TPU_SKIP_TPU_PROBE=1 or
    no pool pointer is present."""
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return True
    if os.environ.get("HVD_TPU_SKIP_TPU_PROBE") == "1":
        return True
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout)
        ok = probe.returncode == 0 and "ok" in probe.stdout
        err = (probe.stderr or probe.stdout)[-300:]
    except subprocess.TimeoutExpired:
        ok, err = False, "import jax timed out (tunnel relay down)"
    if not ok:
        print(json.dumps({
            "metric": "bench_unavailable", "value": 0.0,
            "unit": "error", "vs_baseline": None,
            "baseline": "TPU backend unreachable; see PERF.md / "
                        "BENCH_ZOO_r03.json for the last good "
                        "captures", "error": err.strip()}))
    return ok


def all_models_main(args):
    """bench.py --all-models: runs every zoo config in a subprocess
    (clean device state per model) and prints one JSON line with all
    results, so the PERF.md model-zoo numbers are reproducible."""
    if not _tpu_probe_or_report():
        return 1
    # Children inherit a verified backend; don't re-pay the probe 7x.
    os.environ["HVD_TPU_SKIP_TPU_PROBE"] = "1"
    results = []
    for model, extra in _ZOO:
        cmd = [sys.executable, os.path.abspath(__file__),
               "--model", model,
               "--num-warmup", str(args.num_warmup),
               "--num-rounds", str(args.num_rounds),
               "--num-iters", str(args.num_iters)] + extra
        print("=== %s ===" % model, file=sys.stderr)
        # One retry: the remote-compile tunnel occasionally drops a
        # response mid-read; losing a 30-minute sweep to that transient
        # is worse than a duplicate attempt.
        proc = None
        for attempt in (1, 2):
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=3600)
            except subprocess.TimeoutExpired as e:
                # A hung child (tunnel dropped mid-read) counts as a
                # failed attempt too, not a sweep-ending exception.
                print("=== %s attempt %d timed out: %s ===" %
                      (model, attempt, e), file=sys.stderr)
                proc = None
                continue
            sys.stderr.write(proc.stderr[-2000:])
            if proc.returncode == 0:
                break
            print("=== %s attempt %d failed ===" % (model, attempt),
                  file=sys.stderr)
        if proc is None or proc.returncode != 0:
            raise RuntimeError(
                "bench for %s failed twice:\n%s" %
                (model, proc.stderr[-4000:] if proc else "timed out"))
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    best_mfu = max(r.get("mfu", 0.0) or 0.0 for r in results)
    emit({
        "metric": "model_zoo_sweep",
        "value": round(best_mfu, 3),
        "unit": "best_mfu",
        "vs_baseline": None,
        "baseline": "per-model details in `models`",
        "models": results,
    })


def zoo_headroom_main(args):
    """bench.py --zoo-headroom (PERF.md "Sharded-update memory
    headroom"): per zoo model, the TRAINING-STATE residency — params,
    gradients, Adam moments — against the v5e 16 GiB HBM budget, with
    the ZeRO-style sharded update (HVD_TPU_SHARDED_UPDATE=1) applied to
    the optimizer state at N ranks.

    Byte accounting is exact: parameter trees come from
    jax.eval_shape over the real model init (no compute, no chip), the
    Adam state from optax.adam's init over the same tree, and the
    sharded per-rank optimizer bytes divide by N per the 1/N law
    BENCH_r07 measured EXACTLY on the wire (opt_state_bytes gauge:
    8388608 -> 4194304/2097152 B at N=2/4). Activations are deliberately
    excluded (they depend on the measured step context; see the
    per-model sections of PERF.md).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import models

    n_shard = int(os.environ.get("HVD_TPU_HEADROOM_RANKS", "8"))
    hbm = 16 * (1 << 30)  # v5e
    rng = jax.random.PRNGKey(0)

    def tree_bytes(tree):
        return int(sum(int(np.prod(l.shape, dtype=np.int64)) *
                       np.dtype(l.dtype).itemsize
                       for l in jax.tree_util.tree_leaves(tree)))

    # One row per DISTINCT parameter tree — the zoo's seq-len/kernel
    # variants share params with these base configs, so this list IS
    # the deduplicated zoo.
    rows = []
    zoo_cases = [
        ("resnet50", lambda: models.ResNet50()),
        ("resnet101", lambda: models.ResNet101()),
        ("vgg16", lambda: models.VGG16()),
        ("inception3", lambda: models.InceptionV3()),
        ("transformer_gpt2s", lambda: models.Transformer(
            models.TransformerConfig(
                vocab_size=32000, num_layers=12, num_heads=12,
                embed_dim=768, mlp_dim=3072, attention="dense",
                dtype=jnp.float32, max_seq_len=2048))),
        ("transformer_moe8", lambda: models.Transformer(
            models.TransformerConfig(
                vocab_size=32000, num_layers=12, num_heads=12,
                embed_dim=768, mlp_dim=3072, attention="dense",
                dtype=jnp.float32, max_seq_len=2048, moe_experts=8,
                moe_every=2, moe_capacity_factor=1.25))),
    ]
    for name, build in zoo_cases:
        model = build()
        if name.startswith("transformer"):
            tokens = jnp.zeros((1, 128), jnp.int32)
            pos = jnp.zeros((1, 128), jnp.int32)
            shapes = jax.eval_shape(model.init, rng, tokens, pos)
        else:
            img = jnp.zeros((1, 224, 224, 3), jnp.float32)
            shapes = jax.eval_shape(model.init, rng, img)
        params = shapes["params"] if "params" in shapes else shapes
        p_bytes = tree_bytes(params)
        opt_shapes = jax.eval_shape(
            lambda p: optax.adam(1e-3).init(p), params)
        o_bytes = tree_bytes(opt_shapes)
        repl_state = p_bytes * 2 + o_bytes  # params + grads + moments
        shard_state = p_bytes * 2 + o_bytes // n_shard
        rows.append({
            "model": name,
            "param_bytes": p_bytes,
            "grad_bytes": p_bytes,
            "adam_state_bytes": o_bytes,
            "sharded_adam_state_bytes_per_rank": o_bytes // n_shard,
            "train_state_replicated": repl_state,
            "train_state_sharded": shard_state,
            "headroom_replicated": hbm - repl_state,
            "headroom_sharded": hbm - shard_state,
            "headroom_delta_bytes": (hbm - shard_state) -
                                    (hbm - repl_state),
            "headroom_delta_pct_of_hbm": round(
                100.0 * (o_bytes - o_bytes // n_shard) / hbm, 3),
        })
        print("%-20s params %8.1f MB  adam %8.1f MB -> %7.1f MB/rank "
              "(N=%d)  headroom +%5.1f MB"
              % (name, p_bytes / 2**20, o_bytes / 2**20,
                 o_bytes / n_shard / 2**20, n_shard,
                 (o_bytes - o_bytes // n_shard) / 2**20),
              file=sys.stderr)

    emit({
        "metric": "zoo_sharded_headroom_delta",
        "unit": "bytes_headroom_gained_max_model_n%d" % n_shard,
        "value": max(r["headroom_delta_bytes"] for r in rows),
        "ranks": n_shard,
        "hbm_budget_bytes": hbm,
        # Provenance, honestly: this is MODELED accounting (eval_shape
        # bytes + the r07-measured 1/N law), not a job that ran with
        # the env knob — record the env as it actually was.
        "sharded_update_env": os.environ.get("HVD_TPU_SHARDED_UPDATE",
                                             "<unset>"),
        "accounting": "modeled (eval_shape bytes x BENCH_r07 1/N law)",
        "models": rows,
        "vs_baseline": None,
        "baseline": "same-run replicated Adam state; sharded per-rank "
                    "bytes apply BENCH_r07's exactly-measured 1/N "
                    "opt_state_bytes law; activations excluded (see "
                    "the measured per-model step contexts in PERF.md)",
    })
    return 0


def durable_commit_main(args):
    """bench.py --durable-commit: measures ElasticState.commit() latency
    with the durable writer OFF vs ON (async sharded CRC'd writes to a
    tmp dir, elastic/durable.py) — the "training never blocks on
    storage" claim measured, not asserted. Acceptance (ISSUE 5):
    durable-on commit latency within 10% of durable-off."""
    import shutil
    import statistics
    import tempfile

    from horovod_tpu.elastic.state import ElasticState

    mb = 8
    n_arrays = 8
    params = {"p%d" % i: np.arange(mb * 1024 * 1024 // n_arrays // 4,
                                   dtype=np.float32) + i
              for i in range(n_arrays)}
    state = ElasticState(params=params, step=0)
    iters = 30

    def time_commits(count):
        times = []
        for _ in range(count):
            state.step += 1
            t0 = time.perf_counter()
            state.commit()
            times.append(time.perf_counter() - t0)
        return times

    time_commits(3)  # warmup (page in the deep-copy path)
    off = time_commits(iters)
    tmpdir = tempfile.mkdtemp(prefix="hvd_durable_bench_")
    try:
        state.enable_durable(tmpdir)
        on = time_commits(iters)
        drained = state._durable.flush(timeout=120)
        wrote = state._durable.last_durable_step
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)
    off_ms = statistics.median(off) * 1e3
    on_ms = statistics.median(on) * 1e3
    emit({
        "metric": "durable_commit_overhead",
        "value": round(on_ms / off_ms, 3),
        "unit": "x_commit_latency_durable_on_vs_off",
        "commit_ms_off": round(off_ms, 3),
        "commit_ms_on": round(on_ms, 3),
        "state_mb": mb,
        "writer_drained": bool(drained),
        "last_durable_step": wrote,
        "vs_baseline": None,
        "baseline": "durable-off in-memory commit (same %dMB state); "
                    "acceptance: <= 1.10 (writes overlap training)" % mb,
    })
    return 0


def _serve_port_block(n):
    """A base port with n consecutive free ports (probe-and-release;
    the serve plane needs CONTIGUOUS ports: endpoint = base + wid)."""
    import random
    for _ in range(64):
        base = random.randint(21000, 55000)
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
        return base
    raise RuntimeError("no free port block found")


def serve_main(args):
    """bench.py --serve (docs/SERVE.md, PERF.md round 12): the serving
    plane under seeded open-loop load on this container's CPUs.

    Phase 1, the RPS/latency curve: a fixed 2-replica pool (numpy
    forward, HVD_TPU_SERVE_JIT=0 — the bench measures the SERVING
    machinery: admission, micro-batching, HTTP, split-back; not XLA)
    takes open-loop load at stepped offered rates; each row records
    achieved RPS and p50/p99 latency, with every response verified
    against the weight set its fingerprint names (ok must equal
    offered — the curve is invalid if the pool dropped or mislabeled
    anything).

    Phase 2, the autoscale row: a pool deliberately born TOO SMALL
    (1 replica, ceiling 2) takes a traffic step; the supervisor's
    queue-pressure autoscaler must absorb the freed capacity (grow to
    2) DURING the step, and the step must still finish loss-free —
    elasticity as a serving property, not just a training one.
    """
    import tempfile
    import threading

    from horovod_tpu.elastic.state import EXIT_DRAINED
    from horovod_tpu.serve import model as smodel
    from horovod_tpu.serve.loadgen import run_load
    from horovod_tpu.serve.supervisor import ServeSupervisor
    from horovod_tpu.serve.swap import publish_leaves

    tmpdir = tempfile.mkdtemp(prefix="hvd-serve-bench-")

    def pool(np_initial, max_np, port_base, model_name, dim, ckpt,
             **sup_kwargs):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "HVD_TPU_SERVE_JIT": "0",
            "HVD_TPU_SERVE_MODEL": model_name,
            "HVD_TPU_SERVE_DIM": str(dim),
            "HVD_TPU_SERVE_PORT": str(port_base),
            "HVD_TPU_CKPT_DIR": ckpt,
        })
        sup = ServeSupervisor(
            [sys.executable, "-m", "horovod_tpu.serve.replica"],
            {"localhost": max_np}, min_replicas=1, max_replicas=max_np,
            np_initial=np_initial, port_base=port_base, env=env,
            **sup_kwargs)
        box = {}
        t = threading.Thread(
            target=lambda: box.update(
                rc=sup.driver.run(install_signal_handlers=False)),
            daemon=True)
        t.start()
        deadline = time.time() + 60
        while True:
            up = sum(1 for v in sup.replica_views(timeout=1.0)
                     if v.get("state") == "serving")
            if up >= np_initial:
                break
            if time.time() > deadline:
                raise RuntimeError("serve pool never became healthy")
            time.sleep(0.1)
        return sup, t, box

    def shutdown(sup, t, box):
        sup.driver.request_drain("all")
        t.join(timeout=90)
        return box.get("rc")

    # --- Phase 1: the curve on a fixed 2-replica pool (cheap affine
    # forward — this phase measures the serving MACHINERY's latency).
    dim = 16
    leaves = smodel.init_leaves("affine", dim, seed=1)
    crc = smodel.fingerprint(leaves)
    by_crc = {crc: leaves}
    ckpt1 = os.path.join(tmpdir, "curve")
    publish_leaves(ckpt1, 10, leaves)
    rates = [20, 40, 80]
    curve = []
    sup, t, box = pool(2, 2, _serve_port_block(2), "affine", dim, ckpt1)
    try:
        for i, rate in enumerate(rates):
            res, wall = run_load(sup.endpoints, rate=rate,
                                 duration=3.0, dim=dim, seed=12,
                                 leaves_by_crc=by_crc, workers=8,
                                 total_deadline=10.0,
                                 rid_base=i * 100000)
            row = res.summary(wall)
            assert not res.mismatches, res.mismatches[:3]
            curve.append({
                "offered_rps": rate,
                "achieved_rps": row["rps_achieved"],
                "ok": row["ok"], "errors": row["errors"],
                "p50_ms": row["p50_ms"], "p99_ms": row["p99_ms"],
            })
            print("bench: serve curve %d rps -> %.1f achieved, "
                  "p50 %.1fms p99 %.1fms (%d ok, %d err)"
                  % (rate, row["rps_achieved"], row["p50_ms"],
                     row["p99_ms"], row["ok"], row["errors"]),
                  file=sys.stderr)
    finally:
        rc = shutdown(sup, t, box)
    curve_ok = (rc == EXIT_DRAINED and
                all(r["errors"] == 0 for r in curve))

    # --- Phase 2: the traffic step against a 1-replica pool that may
    # grow to 2; the autoscaler runs on its own cadence thread. The
    # forward is a dim-2048 mlp (~4ms/row in numpy — one replica tops
    # out around 200-250 rps), so the 280 rps step is a GENUINE
    # overload only the scale-up can absorb.
    step_dim, step_rate = 2048, 280
    step_leaves = smodel.init_leaves("mlp", step_dim, seed=2)
    step_by_crc = {smodel.fingerprint(step_leaves): step_leaves}
    ckpt2 = os.path.join(tmpdir, "step")
    publish_leaves(ckpt2, 10, step_leaves)
    sup, t, box = pool(1, 2, _serve_port_block(2), "mlp", step_dim,
                       ckpt2, scale_up_queue=2.0,
                       autoscale_interval=0.2)
    stop = threading.Event()

    def autoscale_loop():
        while not stop.wait(0.2):
            try:
                sup.autoscale_once()
            except Exception:
                pass

    scaler = threading.Thread(target=autoscale_loop, daemon=True)
    scaler.start()
    try:
        replicas_before = len(sup.driver.live_workers())
        res, wall = run_load(sup.endpoints, rate=step_rate,
                             duration=4.0, dim=step_dim, seed=13,
                             model_name="mlp",
                             leaves_by_crc=step_by_crc, workers=8,
                             total_deadline=30.0, rid_base=900000)
        row = res.summary(wall)
        replicas_after = len(sup.driver.live_workers())
        events = list(sup.scale_events)
    finally:
        stop.set()
        rc2 = shutdown(sup, t, box)
    autoscale_row = {
        "offered_rps": step_rate,
        "model": "mlp", "dim": step_dim,
        "replicas_before": replicas_before,
        "replicas_after": replicas_after,
        "scale_events": len(events),
        "achieved_rps": row["rps_achieved"],
        "ok": row["ok"], "errors": row["errors"],
        "p99_ms": row["p99_ms"],
    }
    print("bench: serve autoscale step %d rps: %d -> %d replicas "
          "(%d event(s)), %d ok, %d err"
          % (step_rate, replicas_before, replicas_after, len(events),
             row["ok"], row["errors"]), file=sys.stderr)
    import shutil
    shutil.rmtree(tmpdir, ignore_errors=True)

    scaled = replicas_after > replicas_before and len(events) >= 1
    emit({
        "metric": "serve_open_loop_p99_ms",
        "value": curve[-1]["p99_ms"],
        "unit": "ms_p99_at_%drps_2_replicas" % rates[-1],
        "dim": dim,
        "curve": curve,
        "autoscale": autoscale_row,
        "autoscaled_on_traffic_step": bool(scaled),
        "drained_clean": bool(curve_ok and rc2 == EXIT_DRAINED),
        "vs_baseline": None,
        "baseline": "no prior serving round (BENCH_r12 introduces the "
                    "plane); acceptance: zero errors/mismatches on the "
                    "curve, autoscale 1->2 during the traffic step",
    })
    return 0 if (curve_ok and scaled and rc2 == EXIT_DRAINED) else 1


def _run_compression_bench(n, iters, mb, mode, timeout=900):
    """Launches n local workers allreducing an `mb`-MB f32 payload under
    compression `mode` (control-plane + numpy only, no jax); returns
    per-rank dicts of wall time and socket-layer wire counters."""
    procs, socks = _spawn_local_workers(
        n, "compression_bench_worker.py",
        {"HVD_TPU_BENCH_ITERS": str(iters),
         "HVD_TPU_BENCH_MB": str(mb),
         "HVD_TPU_COMPRESSION": mode})
    outputs = []
    rows = {}
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
            if p.returncode != 0:
                raise RuntimeError("compression bench rank %d (mode %s) "
                                   "failed:\n%s" % (r, mode, out))
            m = re.search(r"COMPRESSION_BENCH (\{.*\})", out)
            if m:
                d = json.loads(m.group(1))
                rows[d["rank"]] = d
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in socks:
            s.close()
    if 0 not in rows:
        raise RuntimeError("no COMPRESSION_BENCH line from rank 0:\n%s"
                           % (outputs[0] if outputs else "<no output>"))
    return rows


def _compression_convergence(steps=40, tolerance=0.05):
    """Trains the same tiny MLP regression twice on an 8-device virtual
    CPU mesh — exact fp32 psum gradients vs the int8 block-quantized
    ring — and compares the loss curves. Returns the curve stats; the
    caller asserts `loss_match`."""
    # The int8 ring only engages over a >= 2-device mesh: force the
    # virtual CPU device count BEFORE jax initializes, and fail loudly
    # if a pre-initialized 1-device jax sneaks through — a 1-device
    # "A/B" would be two identical fp32 runs and a vacuous loss_match.
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_"
                                   "count=8").strip()
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.parallel.ring import ring_allreduce

    cpus = jax.devices("cpu")
    n = min(8, len(cpus))
    if n < 2:
        raise RuntimeError(
            "compression convergence A/B needs >= 2 cpu devices; got %d "
            "(jax initialized before the device-count flag applied?)" % n)
    mesh = Mesh(np.array(cpus[:n]), ("dp",))
    rng = np.random.RandomState(0)
    d_in, d_h, batch = 64, 128, 32 * n
    x = rng.randn(batch, d_in).astype(np.float32)
    w_true = rng.randn(d_in, 1).astype(np.float32)
    y = np.tanh(x @ w_true) + 0.01 * rng.randn(batch, 1).astype(np.float32)

    def init_params():
        r = np.random.RandomState(1)
        return {"w1": jnp.asarray(r.randn(d_in, d_h).astype(np.float32)
                                  * 0.1),
                "w2": jnp.asarray(r.randn(d_h, 1).astype(np.float32) * 0.1)}

    def make_step(mode, lr=0.05):
        def step(params, bx, by):
            def loss_fn(p):
                h = jnp.tanh(bx @ p["w1"])
                return jnp.mean((h @ p["w2"] - by) ** 2)

            loss, g = jax.value_and_grad(loss_fn)(params)
            if mode == "none":
                g = {k: lax.psum(v, "dp") / n for k, v in g.items()}
            else:
                g = {k: ring_allreduce(v, "dp", compression=mode) / n
                     for k, v in g.items()}
            params = {k: params[k] - lr * g[k] for k in params}
            return params, lax.pmean(loss, "dp")

        return jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P("dp"), P("dp")),
            out_specs=(P(), P()), check_vma=False))

    curves = {}
    for mode in ("none", "int8"):
        step = make_step(mode)
        params = init_params()
        losses = []
        for _ in range(steps):
            params, loss = step(params, x, y)
            losses.append(float(loss))
        curves[mode] = losses

    ref = np.asarray(curves["none"])
    got = np.asarray(curves["int8"])
    # Relative divergence after the first few steps (early steps have
    # near-zero denominators as both curves drop fast).
    rel = np.abs(got[3:] - ref[3:]) / (np.abs(ref[3:]) + 1e-8)
    return {
        "steps": steps, "devices": n,
        "fp32_final_loss": round(float(ref[-1]), 6),
        "int8_final_loss": round(float(got[-1]), 6),
        "max_rel_divergence_after_step3": round(float(rel.max()), 4),
        "tolerance": tolerance,
        "loss_match": bool(rel.max() < tolerance),
    }


def compression_main(args):
    """bench.py --compression {none,bf16,int8}: A/B the host data
    plane's wire compression stage (docs/COMPRESSION.md). Measures the
    actual data-ring socket bytes (net_ring_bytes counters, headers
    included) and wall time per 4MB allreduce with compression off vs
    the requested mode, plus the int8-vs-fp32 convergence run.
    Acceptance (ISSUE 6): bf16 moves >= 1.9x fewer allreduce wire bytes
    than none, and the int8 loss curve matches fp32 within tolerance."""
    mode = args.compression
    iters, mb = max(10, args.num_iters), 4
    rows = {"none": _run_compression_bench(2, iters, mb, "none")}
    if mode != "none":
        rows[mode] = _run_compression_bench(2, iters, mb, mode)

    def rank0(m, field):
        return rows[m][0][field]

    none_bytes = rank0("none", "ring_bytes_sent")
    out = {
        "metric": "compression_allreduce_wire_reduction",
        "unit": "x_ring_bytes_none_over_%s" % mode,
        "mode": mode,
        "payload_mb": mb, "iters": iters, "ranks": 2,
        "none_ring_bytes_sent": none_bytes,
        "none_us_per_op": rank0("none", "us_per_op"),
    }
    if mode != "none":
        mode_bytes = rank0(mode, "ring_bytes_sent")
        out["value"] = round(none_bytes / mode_bytes, 3)
        out["%s_ring_bytes_sent" % mode] = mode_bytes
        out["%s_us_per_op" % mode] = rank0(mode, "us_per_op")
        out["codec_ratio"] = round(
            rank0(mode, "codec_bytes_in") /
            max(1, rank0(mode, "codec_bytes_out")), 3)
        print("compression %s: wire %.2fx smaller (%d -> %d B), "
              "%.0f -> %.0f us/op"
              % (mode, out["value"], none_bytes, mode_bytes,
                 out["none_us_per_op"], out["%s_us_per_op" % mode]),
              file=sys.stderr)
    else:
        out["value"] = 1.0

    out["convergence_int8_vs_fp32"] = _compression_convergence()
    if not out["convergence_int8_vs_fp32"]["loss_match"]:
        raise RuntimeError("int8 convergence diverged from fp32: %s"
                           % out["convergence_int8_vs_fp32"])
    # BENCH_r05 predates the compression stage, so the baseline is the
    # same-run compression=none wire bytes; vs_baseline is the measured
    # reduction over that baseline.
    out["vs_baseline"] = out["value"]
    out["baseline"] = ("same-run compression=none data-ring bytes "
                      "(BENCH_r05 predates the compression stage); "
                      "acceptance: bf16 >= 1.9x, int8 convergence "
                      "loss_match true")
    emit(out)
    return 0


def _run_shm_bench(n, iters, mode, shm, extra_env=None, rank_env=None,
                   timeout=900):
    """Launches n local workers allreducing several payload sizes under
    compression `mode` with the shared-memory plane forced on or off;
    returns per-rank dicts of per-size wall time and transport
    counters."""
    env = {"HVD_TPU_BENCH_ITERS": str(iters),
           "HVD_TPU_COMPRESSION": mode,
           "HVD_TPU_SHM": "1" if shm else "0",
           # Deterministic transport + knobs: the A/B measures the
           # transport, not the tuner's exploration.
           "HVD_TPU_AUTOTUNE": "0"}
    if extra_env:
        env.update(extra_env)
    procs, socks = _spawn_local_workers(n, "shm_bench_worker.py", env,
                                        rank_env)
    outputs = []
    rows = {}
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
            if p.returncode != 0:
                raise RuntimeError("shm bench rank %d (mode %s, shm %s) "
                                   "failed:\n%s" % (r, mode, shm, out))
            m = re.search(r"SHM_BENCH (\{.*\})", out)
            if m:
                d = json.loads(m.group(1))
                rows[d["rank"]] = d
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in socks:
            s.close()
    if 0 not in rows:
        raise RuntimeError("no SHM_BENCH line from rank 0:\n%s"
                           % (outputs[0] if outputs else "<no output>"))
    return rows


def shm_main(args):
    """bench.py --shm: A/B the shared-memory intra-host data plane
    (docs/TRANSPORT.md) against TCP loopback. Same-host 2- and 4-rank
    allreduce wall time across payload sizes and none/bf16/int8 wire
    codecs (values verified every iteration; tests/test_shm.py pins the
    bitwise shm-vs-TCP parity), plus a hierarchical-composite A/B on the
    emulated cross-host link (forced 2x2 grid + the bandwidth throttle —
    shm legs are intra-host by construction and exempt from the
    emulated NIC). Acceptance (ISSUE 15): shm strictly faster than TCP
    loopback at >= 1MB payloads on this container; small payloads may be
    ~parity and are reported honestly."""
    import ctypes
    iters = max(10, args.num_iters)
    sizes = [4096, 65536, 1048576, 4194304]
    repeats = 3  # alternate A/B runs; medians tame this 2-core box's noise

    # --- per-hop latency (the acceptance headline) ---------------------
    # One ring hop = a full-duplex neighbor exchange (header + CRC, the
    # production pump shape), measured in-process by the native
    # microbench so the control-plane negotiation — which dominates
    # end-to-end op time on this 2-core container — does not drown the
    # transport signal. The TCP baseline is a genuine 127.0.0.1 TCP
    # connection (ConfigureSocket discipline), not an AF_UNIX pair.
    lib = ctypes.CDLL(os.path.join(REPO, "horovod_tpu", "native",
                                   "libhorovod_tpu.so"))
    lib.horovod_tpu_hop_bench.restype = ctypes.c_double
    lib.horovod_tpu_hop_bench.argtypes = [ctypes.c_int, ctypes.c_int64,
                                          ctypes.c_int]
    hop = {}
    for nbytes in sizes:
        ts, ss = [], []
        for _ in range(5):
            t = lib.horovod_tpu_hop_bench(0, nbytes, 50)
            s = lib.horovod_tpu_hop_bench(1, nbytes, 50)
            if t <= 0 or s <= 0:
                raise RuntimeError("hop bench failed at %d bytes" % nbytes)
            ts.append(t)
            ss.append(s)
        t_med, s_med = statistics.median(ts), statistics.median(ss)
        hop[str(nbytes)] = {
            "us_per_hop_tcp": round(t_med, 1),
            "us_per_hop_shm": round(s_med, 1),
            "tcp_over_shm": round(t_med / s_med, 3),
        }
        print("per-hop %d B: tcp %.1f us, shm %.1f us (%.3fx)"
              % (nbytes, t_med, s_med, t_med / s_med), file=sys.stderr)

    def ab_medians(n, mode, extra_env=None, rank_env=None):
        accum = {"tcp": {}, "shm": {}}
        last = {}
        for _ in range(repeats):
            for key, shm_on in (("tcp", False), ("shm", True)):
                rows = _run_shm_bench(n, iters, mode, shm=shm_on,
                                      extra_env=extra_env,
                                      rank_env=rank_env)
                last[key] = rows[0]
                for s, v in rows[0]["us_per_op"].items():
                    accum[key].setdefault(s, []).append(v)
        # Engagement proof, both directions of the A/B. The byte counter
        # is the signal — the segments gauge can already read 0 when a
        # faster-finishing peer's exit tore the job down before this
        # rank's final metrics read.
        if last["shm"]["shm_bytes_sent"] <= 0:
            raise RuntimeError("shm run did not engage the shm plane: %r"
                               % last["shm"])
        if last["tcp"]["shm_bytes_sent"] != 0:
            raise RuntimeError("tcp run moved shm bytes: %r" % last["tcp"])
        med = {key: {s: round(statistics.median(vs), 1)
                     for s, vs in accum[key].items()}
               for key in accum}
        med["tcp_over_shm"] = {s: round(med["tcp"][s] / med["shm"][s], 3)
                               for s in med["tcp"]}
        med["shm_bytes_sent"] = last["shm"]["shm_bytes_sent"]
        return med

    out = {
        "metric": "shm_intra_host_speedup",
        "unit": "x_us_per_hop_tcp_over_shm_4MB",
        "iters": iters,
        "repeats": repeats,
        "sizes_bytes": sizes,
        "per_hop": hop,
        "per_ranks": {},
    }
    for n in (2, 4):
        per_mode = {}
        for mode in ("none", "bf16", "int8"):
            med = ab_medians(n, mode)
            per_mode[mode] = {
                "us_per_op_tcp": med["tcp"],
                "us_per_op_shm": med["shm"],
                "tcp_over_shm": med["tcp_over_shm"],
                # 2 ranks: an allreduce is exactly 2 neighbor exchanges.
                "per_hop_us_shm_smallest": round(
                    med["shm"][str(sizes[0])] / 2.0, 1) if n == 2 else None,
            }
            print("shm A/B n=%d mode=%s: tcp/shm per size %s"
                  % (n, mode, med["tcp_over_shm"]), file=sys.stderr)
        out["per_ranks"][str(n)] = per_mode
    out["value"] = hop["4194304"]["tcp_over_shm"]

    # Hierarchical composite on the emulated cross-host link: forced 2x2
    # grid, 1000 MB/s throttle on socket sends, hierarchical allreduce
    # pinned on — the intra-host legs are the shm consumers.
    rank_env = {r: {"HVD_TPU_LOCAL_RANK": str(r % 2),
                    "HVD_TPU_LOCAL_SIZE": "2",
                    "HVD_TPU_CROSS_RANK": str(r // 2),
                    "HVD_TPU_CROSS_SIZE": "2"} for r in range(4)}
    hier_env = {"HVD_TPU_HIERARCHICAL_ALLREDUCE": "1",
                "HVD_TPU_RING_BANDWIDTH_MBPS": "1000",
                "HVD_TPU_BENCH_SIZES": "4194304"}
    h = ab_medians(4, "none", extra_env=hier_env, rank_env=rank_env)
    out["hierarchical_emulated_link"] = {
        "ranks": 4, "grid": "2x2", "link_mbps": 1000,
        "payload_bytes": 4194304,
        "us_per_op_tcp": h["tcp"]["4194304"],
        "us_per_op_shm": h["shm"]["4194304"],
        "tcp_over_shm": h["tcp_over_shm"]["4194304"],
        "shm_bytes_sent_rank0": h["shm_bytes_sent"],
    }

    # Acceptance: ring hops strictly faster at >= 1MB (the end-to-end
    # allreduce step times above are reported honestly but are
    # negotiation-dominated on this container — the per-hop measurement
    # is the transport A/B).
    for s in ("1048576", "4194304"):
        r = hop[s]["tcp_over_shm"]
        if r <= 1.0:
            raise RuntimeError(
                "shm hop not faster than TCP loopback at %s bytes "
                "(tcp/shm = %.3f <= 1.0)" % (s, r))
    out["vs_baseline"] = out["value"]
    out["baseline"] = ("same-run TCP-loopback per-hop latency "
                       "(BENCH_r10 predates the shm plane); acceptance: "
                       "per-hop tcp/shm > 1.0 at >= 1MB payloads "
                       "(small payloads may be ~parity), bitwise "
                       "shm-vs-TCP parity pinned by tests/test_shm.py")
    emit(out)
    return 0


def _run_sharded_bench(n, iters, mb, sharded, conv=False, timeout=900):
    """Launches n local workers running `iters` Adam steps over an
    `mb`-MB flat parameter buffer, replicated (sharded=False) or
    ZeRO-sharded (sharded=True); returns per-rank dicts of wall time,
    data-ring wire counters and optimizer-state bytes."""
    procs, socks = _spawn_local_workers(
        n, "sharded_bench_worker.py",
        {"HVD_TPU_BENCH_ITERS": str(iters),
         "HVD_TPU_BENCH_MB": str(mb),
         "HVD_TPU_BENCH_SHARDED": "1" if sharded else "0",
         "SHARDED_BENCH_CONV": "1" if conv else "0",
         "JAX_PLATFORMS": "cpu"})
    outputs = []
    rows = {}
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
            if p.returncode != 0:
                raise RuntimeError(
                    "sharded bench rank %d (sharded=%s) failed:\n%s"
                    % (r, sharded, out))
            m = re.search(r"SHARDED_BENCH (\{.*\})", out)
            if m:
                d = json.loads(m.group(1))
                rows[d["rank"]] = d
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in socks:
            s.close()
    if 0 not in rows:
        raise RuntimeError("no SHARDED_BENCH line from rank 0:\n%s"
                           % (outputs[0] if outputs else "<no output>"))
    return rows


def sharded_update_main(args):
    """bench.py --sharded-update: A/B the ZeRO-style sharded weight
    update against the replicated allreduce path at 2 and 4 local
    ranks (docs/ZERO.md). Acceptance (ISSUE 8): per-rank
    optimizer-state bytes <= replicated/world_size + one shard of
    padding, data-ring wire bytes within 5% of the allreduce's, and
    the 2-rank replicated-vs-sharded convergence run diverging by at
    most 1e-4 relative loss."""
    iters, mb = max(10, args.num_iters), 4
    ab = []
    for n in (2, 4):
        repl = _run_sharded_bench(n, iters, mb, sharded=False)
        shd = _run_sharded_bench(n, iters, mb, sharded=True,
                                 conv=(n == 2))
        # Both modes walked the same trajectory (collective regression
        # guard, not a perf stat).
        ps_r, ps_s = repl[0]["params_sum"], shd[0]["params_sum"]
        if abs(ps_s - ps_r) > 1e-3 * max(1.0, abs(ps_r)):
            raise RuntimeError(
                "sharded trajectory diverged from replicated at %d "
                "ranks: params_sum %r vs %r" % (n, ps_s, ps_r))
        opt_repl = repl[0]["opt_state_bytes"]
        opt_shard = max(row["opt_state_bytes"] for row in shd.values())
        # One shard of padding slack: the largest shard (uneven
        # partitions) may carry ceil(total/n) - floor(total/n) extra
        # elements per moment; allow a whole extra element row to stay
        # robust.
        shard_pad = 2 * 4 * (max(row["shard_elems"]
                                 for row in shd.values()) -
                             min(row["shard_elems"]
                                 for row in shd.values()) + 1)
        wire_repl = repl[0]["ring_bytes_sent"]
        wire_shard = shd[0]["ring_bytes_sent"]
        entry = {
            "ranks": n, "payload_mb": mb, "iters": iters,
            "replicated_us_per_step": repl[0]["us_per_step"],
            "sharded_us_per_step": shd[0]["us_per_step"],
            "replicated_opt_state_bytes": opt_repl,
            "sharded_opt_state_bytes_max_rank": opt_shard,
            "opt_state_reduction": round(opt_repl / max(1, opt_shard),
                                         3),
            "replicated_ring_bytes_sent": wire_repl,
            "sharded_ring_bytes_sent": wire_shard,
            "wire_ratio_sharded_over_replicated": round(
                wire_shard / max(1, wire_repl), 4),
            "reduce_scatter_ops": shd[0]["reduce_scatter_ops"],
        }
        if not opt_shard <= opt_repl / n + shard_pad:
            raise RuntimeError(
                "sharded optimizer state is not 1/N: %d > %d/%d + %d"
                % (opt_shard, opt_repl, n, shard_pad))
        if abs(wire_shard - wire_repl) > 0.05 * wire_repl:
            raise RuntimeError(
                "sharded wire bytes not within 5%% of allreduce at %d "
                "ranks: %d vs %d" % (n, wire_shard, wire_repl))
        if n == 2:
            conv = shd[0].get("convergence")
            if not conv or not conv["loss_match"]:
                raise RuntimeError(
                    "sharded convergence diverged from replicated: %s"
                    % conv)
            entry["convergence_sharded_vs_replicated"] = conv
        ab.append(entry)
        print("sharded-update %d ranks: opt state %.2fx smaller "
              "(%d -> %d B/rank), wire %.4fx, %.0f -> %.0f us/step"
              % (n, entry["opt_state_reduction"], opt_repl, opt_shard,
                 entry["wire_ratio_sharded_over_replicated"],
                 entry["replicated_us_per_step"],
                 entry["sharded_us_per_step"]), file=sys.stderr)

    out = dict(ab[0])
    out.update({
        "metric": "sharded_update_opt_state_reduction",
        "unit": "x_opt_state_bytes_replicated_over_sharded_2_ranks",
        "value": ab[0]["opt_state_reduction"],
        "ab": ab,
        # BENCH_r06 predates the sharded update, so the baseline is the
        # same-run replicated path (the r06-era execution mode).
        "vs_baseline": ab[0]["opt_state_reduction"],
        "baseline": "same-run replicated allreduce + full-state Adam "
                    "(BENCH_r06 predates sharded_update); acceptance: "
                    "opt bytes <= replicated/N + shard padding, wire "
                    "within 5% of allreduce, convergence max rel loss "
                    "divergence <= 1e-4",
    })
    emit(out)
    return 0


def model_parallel_main(args):
    """bench.py --model-parallel K (docs/GROUPS.md, BENCH_r09): the
    process-group A/B at 2*K ranks on the (batch, model) mesh.

    1. Wire bytes: a MODEL-group allreduce of the payload tensor must
       move <= (K/world + 5%) of the full-world allreduce of the same
       tensor, per collective (summed over the group's members; a true
       subgroup ring moves 2(K-1)S vs the world's 2(world-1)S, so the
       measured ratio lands well under the bound).
    2. Step time: per-op latency for world vs model-group vs batch-group
       allreduces — subgroup rings cut hops from world-1 to group-1 and
       the disjoint rings run concurrently.
    3. Convergence: examples/jax_tp_lm.py at world ranks with
       model_parallel=K must match the single-process reference loss
       trajectory (max rel divergence <= 1e-3) — the acceptance model
       that cannot run pure-DP at its width.
    """
    k = args.model_parallel
    n = 2 * k
    iters = max(4, args.num_iters)
    env = {
        "HVD_TPU_BENCH_MODEL_PARALLEL": str(k),
        "HVD_TPU_BENCH_PAYLOAD_MB": "1",
        "HVD_TPU_BENCH_ITERS": str(iters),
        # Clean byte accounting: no knob flips mid-measurement, no
        # per-segment pipeline headers.
        "HVD_TPU_AUTOTUNE": "0",
        "HVD_TPU_PIPELINE_CHUNK_BYTES": "0",
    }
    procs, socks = _spawn_local_workers(n, "group_bench_worker.py", env)
    rows = {}
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=900)
            if p.returncode != 0:
                raise RuntimeError("group bench rank %d failed:\n%s"
                                   % (r, out))
            m = re.search(r"GB_RESULT (\{.*\})", out)
            if not m:
                raise RuntimeError("no GB_RESULT from rank %d:\n%s"
                                   % (r, out))
            rows[r] = json.loads(m.group(1))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in socks:
            s.close()

    world_total = sum(rows[r]["world"]["bytes_per_iter"] for r in rows)
    # Every rank reports ITS model group's traffic; with n/k symmetric
    # groups running concurrently, one group's per-collective bytes are
    # the all-rank sum divided by the number of groups.
    model_groups = n // k
    model_per_collective = sum(
        rows[r]["model_group"]["bytes_per_iter"] for r in rows) / \
        model_groups
    batch_groups = k
    batch_per_collective = sum(
        rows[r]["batch_group"]["bytes_per_iter"] for r in rows) / \
        batch_groups
    wire_ratio = model_per_collective / world_total
    bound = k / n + 0.05
    if wire_ratio > bound:
        raise RuntimeError(
            "model-group allreduce wire bytes not <= group/world + 5%%: "
            "ratio %.4f > %.4f" % (wire_ratio, bound))

    step = {
        "world_us_per_op": round(np.mean(
            [rows[r]["world"]["us_per_iter"] for r in rows]), 1),
        "model_group_us_per_op": round(np.mean(
            [rows[r]["model_group"]["us_per_iter"] for r in rows]), 1),
        "batch_group_us_per_op": round(np.mean(
            [rows[r]["batch_group"]["us_per_iter"] for r in rows]), 1),
    }
    print("model-parallel %d of %d: wire ratio %.4f (bound %.4f), "
          "us/op world=%.0f model=%.0f batch=%.0f"
          % (k, n, wire_ratio, bound, step["world_us_per_op"],
             step["model_group_us_per_op"], step["batch_group_us_per_op"]),
          file=sys.stderr)

    # Convergence: the TP example vs its single-process reference.
    import tempfile
    example = os.path.join(REPO, "examples", "jax_tp_lm.py")
    with tempfile.TemporaryDirectory() as td:
        ref_out = os.path.join(td, "ref.json")
        mesh_out = os.path.join(td, "mesh.json")
        conv_env = dict(os.environ)
        conv_env.update({"JAX_PLATFORMS": "cpu",
                         "PYTHONPATH": REPO,
                         "HVD_TPU_TP_REF_ROWS": str(n // k)})
        for key in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_ADDRS"):
            conv_env.pop(key, None)
        steps = "10"
        # Captured output: the bench's stdout is the one-JSON-line
        # contract; the example's per-step loss lines stay out of it.
        ref = subprocess.run(
            [sys.executable, example, "--reference", "--steps", steps,
             "--loss-out", ref_out],
            env=conv_env, timeout=600, capture_output=True, text=True)
        if ref.returncode != 0:
            raise RuntimeError("TP reference run failed:\n%s"
                               % (ref.stdout + ref.stderr))
        mesh = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run.run", "-np", str(n),
             "--", sys.executable, example, "--model-parallel", str(k),
             "--steps", steps, "--loss-out", mesh_out],
            env=conv_env, timeout=1200, capture_output=True, text=True)
        if mesh.returncode != 0:
            raise RuntimeError("TP mesh run failed:\n%s"
                               % (mesh.stdout + mesh.stderr))
        with open(ref_out) as f:
            ref_losses = json.load(f)["losses"]
        with open(mesh_out) as f:
            mesh_losses = json.load(f)["losses"]
    divergence = max(abs(a - b) / max(abs(a), 1e-9)
                     for a, b in zip(ref_losses, mesh_losses))
    if divergence > 1e-3:
        raise RuntimeError("TP loss trajectory diverged from the "
                           "single-process reference: %.3e" % divergence)
    print("model-parallel convergence: max rel loss divergence %.2e "
          "over %s steps" % (divergence, steps), file=sys.stderr)

    emit({
        "metric": "model_parallel_wire_ratio",
        "unit": "model_group_bytes_over_world_bytes_per_collective",
        "value": round(wire_ratio, 4),
        "ranks": n, "model_parallel": k,
        "payload_mb": 1, "iters": iters,
        "world_bytes_per_collective": int(world_total),
        "model_group_bytes_per_collective": int(model_per_collective),
        "batch_group_bytes_per_collective": int(batch_per_collective),
        "acceptance_bound": round(bound, 4),
        "step_time": step,
        "concurrent_mesh_bytes_all_model_groups": int(
            model_per_collective * model_groups),
        "convergence": {
            "steps": int(steps),
            "reference_losses": ref_losses,
            "mesh_losses": mesh_losses,
            "max_rel_divergence": divergence,
            "loss_match": divergence <= 1e-3,
        },
        # First round with process groups: the baseline is the same
        # tensor's full-world allreduce measured in the same run.
        "vs_baseline": round(wire_ratio, 4),
        "baseline": "same-run full-world allreduce of the same tensor "
                    "(BENCH_r08 predates process groups); acceptance: "
                    "wire ratio <= group/world + 5%, convergence max "
                    "rel loss divergence <= 1e-3 vs the single-process "
                    "reference",
    })
    return 0


def _run_autotune_ab(n, extra_env, timeout=900):
    """Launches n local autotune A/B workers (tests/autotune_ab_worker:
    48 x 128KB gradient allreduces per step, rank-0-gated convergence
    wait under HVD_TPU_AUTOTUNE=1); returns the AB_RESULT dict."""
    env = {"HVD_TPU_CYCLE_TIME": None}  # un-pin: the tuner owns pacing
    env.update(extra_env or {})
    procs, socks = _spawn_local_workers(n, "autotune_ab_worker.py", env)
    outputs, result = [], None
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=timeout)
            outputs.append(out)
            if p.returncode != 0:
                raise RuntimeError("autotune A/B rank %d failed:\n%s"
                                   % (r, out))
            m = re.search(r"AB_RESULT (\{.*\})", out)
            if m:
                result = json.loads(m.group(1))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in socks:
            s.close()
    if result is None:
        raise RuntimeError("no AB_RESULT line:\n%s"
                           % (outputs[0] if outputs else "<no output>"))
    return result


def autotune_main(args):
    """bench.py --autotune (docs/AUTOTUNE.md): two measurements.

    1. Closed-loop A/B at 4 ranks on the AUTOTUNE_AB_r05 workload
       (48 x 128KB gradients/step): untuned defaults vs the always-on
       tuner converging on its own, ZERO hand-set knobs. Acceptance
       (ISSUE 9): closed-loop steps/s >= AUTOTUNE_AB_r05's
       tuned_env_replay (the number that previously required manually
       replaying the converged knobs) and >= 1.15x the untuned run.
    2. Pipelined-ring chunk sweep at 2 and 4 ranks on a 16MB fused
       buffer (4 x 4MB gradients/step), autotune off so the chunk knob
       is the only variable, on an emulated 1000 MB/s inter-host link:
       unsliced (0) vs swept HVD_TPU_PIPELINE_CHUNK_BYTES under
       none/bf16/int8 wire modes, interleaved A/B pairs. Acceptance:
       the best (mode, chunk) beats unsliced on step time at EACH rank
       count."""
    with open(os.path.join(REPO, "AUTOTUNE_AB_r05.json")) as f:
        r05 = json.load(f)
    target = r05["tuned_env_replay"]["steps_per_s"]

    ab_iters = str(max(40, args.num_iters * 4))
    untuned = _run_autotune_ab(4, {"HVD_TPU_AUTOTUNE": "0",
                                   "AB_ITERS": ab_iters})
    closed = _run_autotune_ab(4, {"HVD_TPU_AUTOTUNE": "1",
                                  "AB_ITERS": ab_iters,
                                  "AB_TUNE_TIMEOUT": "420"},
                              timeout=1200)
    speedup = round(closed["steps_per_s"] / untuned["steps_per_s"], 3)
    print("autotune closed loop: %.2f -> %.2f steps/s (%.3fx untuned, "
          "target tuned_env_replay %.2f)"
          % (untuned["steps_per_s"], closed["steps_per_s"], speedup,
             target), file=sys.stderr)

    # Pipelined-ring chunk sweep on an EMULATED 8 Gbps inter-host link
    # (HVD_TPU_RING_BANDWIDTH_MBPS=1000): thread overlap cannot
    # manufacture throughput on this container's 2 saturated cores —
    # loopback "transport" is itself CPU work — so the pipelining win is
    # measured where it exists in production: against a link with real
    # serialization delay. A/B pairs run INTERLEAVED (unsliced then
    # sliced, repeated) so host drift cancels; the unsliced loopback
    # numbers ride along for transparency.
    import statistics as _stats

    def _paired(n, mode, chunk, rate, pairs=3):
        a_ms, b_ms = [], []
        for _ in range(pairs):
            for chunk_bytes, acc in ((0, a_ms), (chunk, b_ms)):
                r = _run_autotune_ab(
                    n, {"HVD_TPU_AUTOTUNE": "0",
                        "HVD_TPU_CYCLE_TIME": "0",
                        "HVD_TPU_RING_BANDWIDTH_MBPS": str(rate),
                        "HVD_TPU_PIPELINE_CHUNK_BYTES": str(chunk_bytes),
                        "HVD_TPU_COMPRESSION": mode,
                        "AB_TENSORS": "4", "AB_ELEMS": "1048576",
                        "AB_ITERS": str(max(20, args.num_iters * 2))})
                acc.append(r["ms_per_step"])
        return _stats.median(a_ms), _stats.median(b_ms)

    sweep = {}
    link_mbps = 1000
    for n in (2, 4):
        for mode in ("none", "bf16", "int8"):
            rows = {"workload": "4 x 4MB gradients/step (16MB fused)",
                    "link_mbps": link_mbps}
            best = 0.0
            for chunk in (1048576, 2097152):
                unsliced, sliced = _paired(n, mode, chunk, link_mbps)
                rows["chunk_%d" % chunk] = {
                    "unsliced_ms_per_step": unsliced,
                    "pipelined_ms_per_step": sliced,
                    "speedup": round(unsliced / sliced, 3),
                }
                best = max(best, unsliced / sliced)
                print("pipeline sweep n=%d mode=%s chunk=%d @%dMB/s: "
                      "%.1f -> %.1f ms/step (%.3fx)"
                      % (n, mode, chunk, link_mbps, unsliced, sliced,
                         unsliced / sliced), file=sys.stderr)
            rows["best_speedup_vs_unsliced"] = round(best, 3)
            sweep["%dranks_%s" % (n, mode)] = rows

    pipelined_wins = {k: v["best_speedup_vs_unsliced"]
                      for k, v in sweep.items()}
    # Per-rank-count acceptance: the ISSUE 9 criterion is a measured
    # reduction at 2-4 ranks, so a single lucky cell must not green the
    # whole sweep — each rank count needs a winning (mode, chunk).
    per_rank_best = {
        n: max(v for k, v in pipelined_wins.items()
               if k.startswith("%dranks" % n))
        for n in (2, 4)
    }
    out = {
        "metric": "autotune_closed_loop_steps_per_s",
        "unit": "steps/s_4rank_48x128KB",
        "value": closed["steps_per_s"],
        "workload": r05["workload"],
        "untuned_defaults": untuned,
        "closed_loop": closed,
        "speedup_closed_loop_vs_untuned": speedup,
        "pipelined_ring_sweep": sweep,
        "pipelined_best_speedup_vs_unsliced": pipelined_wins,
        "pipelined_best_speedup_per_rank_count": per_rank_best,
        # The r05 baseline IS this metric's reference measurement: the
        # throughput that used to require a manual tuned-env replay.
        "vs_baseline": round(closed["steps_per_s"] / target, 3),
        "baseline": "AUTOTUNE_AB_r05.json tuned_env_replay %.2f steps/s "
                    "(manually replayed converged knobs); acceptance: "
                    "closed-loop >= that with zero hand-set knobs, "
                    ">= 1.15x untuned, and a measured pipelined-ring "
                    "step-time win on >=1MB fused buffers at 2-4 ranks"
                    % target,
        "acceptance": {
            "closed_loop_vs_tuned_env_replay":
                round(closed["steps_per_s"] / target, 3),
            "closed_loop_vs_untuned": speedup,
            "required": ">= 1.0x replay, >= 1.15x untuned, pipelined "
                        "win > 1.0x",
        },
    }
    if closed["steps_per_s"] < target:
        raise RuntimeError(
            "closed-loop autotune (%.2f steps/s) fell short of the "
            "tuned-env replay target (%.2f)"
            % (closed["steps_per_s"], target))
    if speedup < 1.15:
        raise RuntimeError(
            "closed-loop speedup %.3fx < required 1.15x over untuned"
            % speedup)
    if not all(v > 1.0 for v in per_rank_best.values()):
        raise RuntimeError(
            "pipelined ring did not beat the unsliced path at every "
            "rank count: %r (per-cell: %r)"
            % (per_rank_best, pipelined_wins))
    emit(out)
    return 0


def bn_traffic_step_stats(norm, batch=32, image_size=64, dtype="bfloat16",
                          bn_remat=False, num_classes=1000):
    """Compiles the REAL resnet50 train step (make_train_step over a
    1-device mesh — the same step the throughput bench times) for the
    given norm variant and returns XLA's own accounting of it:
    ``{"bytes_accessed", "flops", "temp_bytes"}``.

    Abstract lowering only (eval_shape params, ShapeDtypeStruct batch):
    no training compute, no chip — reproducible under
    ``JAX_PLATFORMS=cpu``, which is the whole point of the metric
    (PERF.md round 10). Shared with the tier-1 bytes-regression guard
    (tests/test_bn_traffic.py)."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models.resnet import ResNet, BottleneckBlock
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step
    from horovod_tpu.parallel.train import cross_entropy_loss

    model = ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock,
                   norm=norm, num_classes=num_classes,
                   dtype=getattr(jnp, dtype), bn_remat=bn_remat)
    rng = jax.random.PRNGKey(0)
    shapes = jax.eval_shape(
        lambda: model.init(rng, jnp.zeros((1, image_size, image_size, 3)),
                           train=False))
    params = jax.tree_util.tree_map(
        lambda sd: jax.ShapeDtypeStruct(sd.shape, sd.dtype),
        shapes["params"])
    # Running-stat VALUES are irrelevant to the lowering; zeros of the
    # right shape avoid paying a real model init.
    batch_stats = jax.tree_util.tree_map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype),
        shapes.get("batch_stats", {}))
    mutable = ["batch_stats"] if batch_stats else []

    def loss_fn(p, b):
        state = {"params": p}
        if batch_stats:
            state["batch_stats"] = batch_stats
            logits, _ = model.apply(state, b["x"], train=True,
                                    mutable=mutable)
        else:
            logits = model.apply(state, b["x"], train=True)
        return cross_entropy_loss(logits, b["y"])

    mesh = data_parallel_mesh(devices=jax.devices("cpu")[:1])
    opt = optax.sgd(0.01, momentum=0.9)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    opt_state = jax.eval_shape(opt.init, params)
    x = jax.ShapeDtypeStruct((batch, image_size, image_size, 3),
                             jnp.float32)
    y = jax.ShapeDtypeStruct((batch,), jnp.int32)
    compiled = step.lower(params, opt_state, {"x": x, "y": y}).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    mem = compiled.memory_analysis()
    return {
        "bytes_accessed": float(cost["bytes accessed"]),
        "flops": float(cost.get("flops", 0.0)),
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
    }


def _nf_agc_convergence(steps=30, lr=0.5, clipping=0.02):
    """The AGC-makes-norm-free-trainable check on the synthetic task
    (CPU): a small ResNet trained three ways on the same fixed
    synthetic classification batch — BatchNorm baseline, norm-free with
    AGC, norm-free without. The convergence gate: the AGC run must
    reach the BN baseline's end state (final loss within an absolute
    ``tolerance`` of BN's — both runs effectively solve the task) with
    a real decrease; the no-AGC run rides along to show what the clip
    buys (measured: stuck near its initial loss at this lr while AGC
    converges — calibrated on CPU, see BENCH_r10)."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models.resnet import ResNet, BottleneckBlock
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step
    from horovod_tpu.parallel.train import cross_entropy_loss

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 16, 16, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, size=32).astype(np.int32))
    mesh = data_parallel_mesh(devices=None)

    def run(norm, agc):
        model = ResNet(stage_sizes=[2], block_cls=BottleneckBlock,
                       num_classes=10, num_filters=8,
                       dtype=jnp.float32, norm=norm)
        variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        mutable = ["batch_stats"] if batch_stats else []

        def loss_fn(p, b):
            state = {"params": p}
            if batch_stats:
                state["batch_stats"] = batch_stats
                logits, _ = model.apply(state, b["x"], train=True,
                                        mutable=mutable)
            else:
                logits = model.apply(state, b["x"], train=True)
            return cross_entropy_loss(logits, b["y"])

        opt = optax.sgd(lr, momentum=0.9)
        step = make_train_step(loss_fn, opt, mesh, donate=False, agc=agc)
        pp, os_, batch = step.place(params, opt.init(params),
                                    {"x": x, "y": y})
        losses = []
        for _ in range(steps):
            pp, os_, loss = step(pp, os_, batch)
            losses.append(float(loss))
        return losses

    bn = run("batch", None)
    nf_agc = run("none", clipping)
    nf_plain = run("none", None)
    tolerance = 0.15  # absolute final-loss gap; both runs solve the task
    final_ok = np.isfinite(nf_agc[-1]) and \
        nf_agc[-1] <= bn[-1] + tolerance
    decreased = np.isfinite(nf_agc[-1]) and nf_agc[-1] < nf_agc[0] * 0.3
    return {
        "steps": steps, "lr": lr, "agc_clipping": clipping,
        "tolerance_abs_final_loss": tolerance,
        "bn_losses": [round(v, 4) for v in bn],
        "nf_agc_losses": [round(v, 4) for v in nf_agc],
        "nf_no_agc_final_loss": round(nf_plain[-1], 4)
        if np.isfinite(nf_plain[-1]) else None,
        "bn_final_loss": round(bn[-1], 4),
        "nf_agc_final_loss": round(nf_agc[-1], 4)
        if np.isfinite(nf_agc[-1]) else None,
        "loss_match": bool(final_ok and decreased),
    }


def bn_traffic_main(args):
    """bench.py --bn-traffic (PERF.md round 10): the graph-level BN
    A/B, fully reproducible off-chip. Per-step ``cost_analysis()``
    bytes-accessed for the resnet50 train step under stock flax BN vs
    the traffic-lean custom-VJP BN (`norm="lean"`), with the norm-free
    step as the conv-only floor.

    Headline (`value`): the BN-TAX reduction — the share of
    (step - norm-free-floor) bytes the lean path eliminates. The
    whole-step reduction and the zero-BN ceiling ride in the row:
    BN-attributable bytes are ~24% of this step's total on the CPU
    cost model, so the whole-step number is bounded by that ceiling no
    matter how lean the BN is — the tax metric is the honest A/B for
    the BN data path itself. Acceptance: tax reduction >= 20%, AGC
    norm-free convergence gate green."""
    batch, s = args.bn_traffic_batch, args.bn_traffic_image_size
    rows = {}
    for norm in ("batch", "lean", "none"):
        rows[norm] = bn_traffic_step_stats(norm, batch, s)
        print("bn-traffic %-5s: %.4e bytes, temp %.3e" %
              (norm, rows[norm]["bytes_accessed"],
               rows[norm]["temp_bytes"]), file=sys.stderr)
    rows["lean_remat"] = bn_traffic_step_stats("lean", batch, s,
                                               bn_remat=True)

    stock = rows["batch"]["bytes_accessed"]
    lean = rows["lean"]["bytes_accessed"]
    floor = rows["none"]["bytes_accessed"]
    tax_stock = stock - floor
    tax_lean = lean - floor
    tax_reduction = 1.0 - tax_lean / tax_stock
    step_reduction = 1.0 - lean / stock
    ceiling = 1.0 - floor / stock

    conv = _nf_agc_convergence()
    if not conv["loss_match"]:
        raise RuntimeError(
            "norm-free + AGC convergence gate failed: %s" % conv)
    if tax_reduction < 0.20:
        raise RuntimeError(
            "lean BN removed only %.1f%% of the BN-attributable bytes "
            "(acceptance >= 20%%): stock tax %.3e, lean tax %.3e"
            % (100 * tax_reduction, tax_stock, tax_lean))

    emit({
        "metric": "bn_traffic_tax_reduction",
        "value": round(tax_reduction, 4),
        "unit": "frac_bn_attributable_bytes_removed_resnet50_cpu",
        "config": {"model": "resnet50", "batch": batch,
                   "image_size": s, "dtype": "bfloat16",
                   "platform": "cpu_cost_analysis"},
        "stock_bytes_accessed": stock,
        "lean_bytes_accessed": lean,
        "normfree_floor_bytes_accessed": floor,
        "step_bytes_reduction": round(step_reduction, 4),
        "zero_bn_step_ceiling": round(ceiling, 4),
        "bn_tax_bytes": {"stock": tax_stock, "lean": tax_lean},
        "temp_bytes": {k: v["temp_bytes"] for k, v in rows.items()},
        # temp_bytes is 0 on toolchains whose memory_analysis lacks the
        # field — the ratio is diagnostics, never worth crashing the
        # headline metric over.
        "temp_bytes_reduction_lean_vs_stock": round(
            1.0 - rows["lean"]["temp_bytes"] /
            rows["batch"]["temp_bytes"], 4)
        if rows["batch"]["temp_bytes"] else None,
        "lean_remat_bytes_accessed": rows["lean_remat"]["bytes_accessed"],
        "agc_convergence": conv,
        "vs_baseline": None,
        "baseline": "same-run stock flax-BN resnet50 train step "
                    "(cost_analysis bytes; norm='none' is the conv-only "
                    "floor). The whole-step reduction is bounded by the "
                    "zero-BN ceiling (~%.0f%% here): BN-attributable "
                    "bytes are that share of the step on the CPU cost "
                    "model, so the acceptance gate applies to the BN "
                    "tax the lean path actually owns. Acceptance: tax "
                    "reduction >= 20%%, AGC norm-free convergence green"
                    % (100 * ceiling),
    })
    return 0


def _prior_round_value(metric):
    """Newest prior-round row with the same metric name, scanned from
    the BENCH_r*.json / BENCH_ZOO_r*.json artifacts at the repo root
    (single rows under "parsed", per-model rows under "models").
    Returns (filename, value) or None."""
    import glob

    best = None
    for path in (sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))) +
                 sorted(glob.glob(os.path.join(REPO, "BENCH_ZOO_r*.json")))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        row = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else doc
        rows = [row] + [m for m in (row.get("models") or [])
                        if isinstance(m, dict)]
        for r in rows:
            v = r.get("value")
            if r.get("metric") == metric and \
                    isinstance(v, (int, float)) and v:
                best = (os.path.basename(path), float(v))
    return best


def emit(out):
    """Prints the bench's one-JSON-line contract, self-baselining rows
    that have no reference measurement: a null vs_baseline (the
    placeholders PR 1 introduced for LM/word2vec/aggregate rows) is
    filled against the newest prior round's same-metric value now that
    BENCH_r01..r05 / BENCH_ZOO_r03..r05 exist on disk. Rows with no
    prior same-metric round anywhere stay null — never a fabricated
    0.0."""
    if out.get("vs_baseline") is None and out.get("value"):
        prior = _prior_round_value(out.get("metric"))
        if prior:
            fname, value = prior
            out["vs_baseline"] = round(float(out["value"]) / value, 3)
            out["baseline"] = "%s; vs prior-round %s same-metric value %s" \
                % (out.get("baseline", ""), fname, value)
    print(json.dumps(out))


def trace_overhead_main(args):
    """bench.py --trace-overhead (docs/TRACING.md): is the always-on
    span recorder actually free enough to leave on?

    Interleaved A/B pairs (tracing ON first, then OFF, repeated — host
    drift cancels) on two workloads: (1) the autotune A/B step workload
    (48 x 128KB gradients/step at 4 ranks, tuner off) for the steps/s
    number the <3% acceptance bounds, (2) the bucket-mode negotiation
    microbench (16 tensors/step, HVD_TPU_CYCLE_TIME=0) — maximal span
    rate per unit work, the recorder's worst case — for the us/op
    number. The tracing-on negotiation run also proves drops == 0 at
    the DEFAULT ring size: an overhead number measured while silently
    shedding spans would be fiction."""
    import statistics as _stats

    def _steps(trace):
        return _run_autotune_ab(4, {"HVD_TPU_AUTOTUNE": "0",
                                    "HVD_TPU_TRACE": trace,
                                    "AB_ITERS": str(max(150,
                                                        args.num_iters * 4))})

    # One discarded warmup run (the first launcher run of a batch is a
    # consistent cold-start outlier), then pairs with ALTERNATING order
    # so host drift cancels inside the per-pair delta. The overhead the
    # <3% gate bounds is the median per-pair delta in JOB CPU-seconds
    # per step: on a saturated 1-core host steps/s is exactly
    # 1 / job-CPU-per-step, and wall-clock runs swing +/-15% with
    # hypervisor steal while the rusage window doesn't (the same reason
    # the negotiation microbench and SCALING.md measure CPU time). Wall
    # steps/s medians ride along for the record.
    _steps("1")
    on_steps, off_steps, on_cpu, off_cpu, pair_pcts = [], [], [], [], []
    for i in range(12):
        order = ("1", "0") if i % 2 == 0 else ("0", "1")
        pair = {}
        for trace in order:
            pair[trace] = _steps(trace)
        on_steps.append(pair["1"]["steps_per_s"])
        off_steps.append(pair["0"]["steps_per_s"])
        cpu_on = pair["1"]["cpu_ms_per_step_job"]
        cpu_off = pair["0"]["cpu_ms_per_step_job"]
        on_cpu.append(cpu_on)
        off_cpu.append(cpu_off)
        pair_pcts.append((cpu_on - cpu_off) / cpu_off * 100)
        print("trace overhead pair %d (%s first): cpu/step on %.2f / "
              "off %.2f ms (%.2f%%); wall on %.2f / off %.2f steps/s"
              % (i + 1, "on" if order[0] == "1" else "off", cpu_on,
                 cpu_off, pair_pcts[-1], pair["1"]["steps_per_s"],
                 pair["0"]["steps_per_s"]), file=sys.stderr)
    step_on = _stats.median(on_steps)
    step_off = _stats.median(off_steps)
    step_overhead_pct = round(_stats.median(pair_pcts), 2)
    print("trace overhead (step workload): %.2f%% job-CPU-per-step cost "
          "(wall medians %.2f -> %.2f steps/s)"
          % (step_overhead_pct, step_off, step_on), file=sys.stderr)

    neg_iters = max(100, args.num_iters * 10)
    neg_env = {"HVD_TPU_CYCLE_TIME": "0", "HVD_TPU_BENCH_TENSORS": "16"}
    on_us, off_us, neg_pair_pcts = [], [], []
    trace_ctr = None
    for i in range(5):
        order = ("1", "0") if i % 2 == 0 else ("0", "1")
        pair_cpu = {}
        for trace in order:
            us, ctr = _run_negotiation_bench(
                4, neg_iters, dict(neg_env, HVD_TPU_TRACE=trace))
            (on_us if trace == "1" else off_us).append(us)
            c0 = ctr.get(0) or {}
            # Coordinator CPU-us per op — steal-immune, like the step
            # workload's job-CPU metric (wall us/op rides along).
            pair_cpu[trace] = (c0["cpu_us"] /
                               (c0["iters"] * c0["tensors_per_step"]))
            if trace == "1":
                trace_ctr = c0.get("trace") or trace_ctr
        neg_pair_pcts.append(
            (pair_cpu["1"] - pair_cpu["0"]) / pair_cpu["0"] * 100)
    neg_on = _stats.median(on_us)
    neg_off = _stats.median(off_us)
    neg_overhead_pct = round(_stats.median(neg_pair_pcts), 2)
    spans = int((trace_ctr or {}).get("trace_spans_total", 0))
    dropped = int((trace_ctr or {}).get("trace_spans_dropped_total", -1))
    print("trace overhead (negotiation worst case): %.2f%% coordinator-"
          "CPU-per-op cost (wall medians %.1f -> %.1f us/op); rank-0 "
          "spans %d, dropped %d"
          % (neg_overhead_pct, neg_off, neg_on, spans, dropped),
          file=sys.stderr)

    ok = (step_overhead_pct < 3.0 and spans > 0 and dropped == 0)
    emit({
        "round": 13,
        "command": "JAX_PLATFORMS=cpu python bench.py --trace-overhead",
        "note": "always-on trace recorder A/B (docs/TRACING.md): one "
                "discarded warmup run, then 12 on/off pairs in "
                "ALTERNATING order (drift cancels inside each pair); "
                "value = median per-pair delta in JOB CPU-seconds per "
                "step, the determinant of steps/s on a saturated "
                "1-core host (wall runs swing +/-15% with hypervisor "
                "steal; CPU time measures the framework — the "
                "SCALING.md methodology). Step workload = autotune A/B "
                "shape (48 x 128KB gradients/step, 4 ranks, tuner "
                "off); negotiation workload = bucket-mode control-"
                "plane microbench (16 tensors/step, cycle pacing off) "
                "as the recorder's worst case, its overhead likewise "
                "the median per-pair delta in coordinator CPU-us per "
                "op over 5 alternating pairs. "
                "Acceptance: steps/s cost < 3% with ZERO ring drops "
                "at the default HVD_TPU_TRACE_RING.",
        "metric": "trace_overhead_steps_pct",
        "value": step_overhead_pct,
        "unit": "percent_steps_per_s_cost",
        "steps_per_s_tracing_off": step_off,
        "steps_per_s_tracing_on": step_on,
        "cpu_ms_per_step_job_off": _stats.median(off_cpu),
        "cpu_ms_per_step_job_on": _stats.median(on_cpu),
        "negotiation_us_per_op_off": neg_off,
        "negotiation_us_per_op_on": neg_on,
        "negotiation_overhead_pct": neg_overhead_pct,
        "rank0_spans_total": spans,
        "rank0_spans_dropped": dropped,
        "vs_baseline": None,
        "baseline": "no prior tracing round (BENCH_r13 introduces the "
                    "recorder); acceptance: <3% steps/s cost, 0 drops",
    })
    return 0 if ok else 1


def _cpu_per_cycle(ctr):
    """Rank-0 CPU-us per work cycle from a negotiation-bench counter
    dict (None when the worker predates the cpu_us field)."""
    d = ctr.get(0) or {}
    cycles = (d.get("cycles_fast") or 0) + (d.get("cycles_full") or 0)
    if not d.get("cpu_us") or not cycles:
        return None
    return round(d["cpu_us"] / cycles, 1)


def scaling_main(args):
    """bench.py --scaling: regenerates the SCALING.md evidence — (a)
    weak-scaling efficiency of the full jitted DP train step on the
    virtual CPU mesh, (b) control-plane negotiation latency curves at
    32..max-ranks local ranks (cached fast path and full uncached
    negotiation)."""
    weak = _run_weak_scaling(args.scaling_batch, args.num_iters)

    # 512/1024 are extension sizes (real rank processes, several
    # minutes each on a 1-core host) — opt in via --scaling-max-ranks.
    rank_counts = [n for n in (32, 64, 128, 256, 512, 1024)
                   if n <= args.scaling_max_ranks]
    negotiation = []
    metrics_ab = None
    for n in rank_counts:
        iters = max(25, 3200 // n)
        try:
            cached, c_ctr = _run_negotiation_bench(n, iters)
            uncached, u_ctr = _run_negotiation_bench(
                n, max(10, iters // 4), {"HVD_TPU_CACHE_CAPACITY": "0"})
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            # One failing size shouldn't lose the whole evidence run.
            negotiation.append({"ranks": n, "error": str(e)[:500]})
            print("negotiation n=%d FAILED: %s" % (n, str(e)[:200]),
                  file=sys.stderr)
            continue

        def per_step(ctr, rank):
            d = ctr.get(rank)
            if not d or not d.get("iters"):
                return None
            return round((d["ctrl_bytes_sent"] + d["ctrl_bytes_recv"])
                         / d["iters"], 1)

        entry = {
            "ranks": n, "cached_us_per_op": cached,
            "uncached_us_per_op": uncached,
            # Protocol-level fast-path evidence, wall-clock-independent:
            # control bytes (sent+recv, headers included) per op.
            "cached_bytes_per_op_coord": per_step(c_ctr, 0),
            "uncached_bytes_per_op_coord": per_step(u_ctr, 0),
            "cached_bytes_per_op_worker": per_step(c_ctr, 1),
            "uncached_bytes_per_op_worker": per_step(u_ctr, 1),
            "cached_cycle_kinds": {
                "fast": c_ctr.get(0, {}).get("cycles_fast"),
                "full": c_ctr.get(0, {}).get("cycles_full")},
            "uncached_cycle_kinds": {
                "fast": u_ctr.get(0, {}).get("cycles_fast"),
                "full": u_ctr.get(0, {}).get("cycles_full")},
            # Coordinator CPU time per work cycle (user+sys of the
            # rank-0 process / its work-cycle count) — wall clock on a
            # shared core measures the scheduler, CPU time measures
            # the protocol (SCALING.md §2.3).
            "cached_coord_cpu_us_per_cycle": _cpu_per_cycle(c_ctr),
            "uncached_coord_cpu_us_per_cycle": _cpu_per_cycle(u_ctr),
            # Coordinator live-metrics snapshot (docs/METRICS.md):
            # cycle-time histogram, fused bytes, cache hit rate.
            "metrics_snapshot": c_ctr.get(0, {}).get("metrics"),
        }

        # Metrics-plane on/off A/B at the smallest size: the acceptance
        # bar is that metrics-DISABLED runs (the default above) pay
        # nothing, and enabling the plane costs only the ~1/s summary
        # piggyback + forced sync cycle.
        if metrics_ab is None:
            try:
                on_us, _ = _run_negotiation_bench(
                    n, iters, {"HVD_TPU_METRICS": "1"})
                metrics_ab = {
                    "ranks": n,
                    "metrics_off_us_per_op": cached,
                    "metrics_on_us_per_op": on_us,
                    "on_over_off": round(on_us / cached, 3),
                }
                print("metrics A/B n=%d: off %.0f us/op, on %.0f us/op"
                      % (n, cached, on_us), file=sys.stderr)
            except (RuntimeError, subprocess.TimeoutExpired) as e:
                metrics_ab = {"error": str(e)[:300]}

        # Gradient-bucket shape: one training step = 32 long-named
        # async ops negotiated together. Uncached request lists scale
        # with tensors x name length; the cached bit vector doesn't.
        bucket_env = {"HVD_TPU_BENCH_TENSORS": "32"}
        biters = max(10, iters // 4)
        try:
            _, cb_ctr = _run_negotiation_bench(n, biters, bucket_env)
            _, ub_ctr = _run_negotiation_bench(
                n, max(5, biters // 2),
                dict(bucket_env, HVD_TPU_CACHE_CAPACITY="0"))
            entry["bucket32_cached_bytes_per_step_coord"] = \
                per_step(cb_ctr, 0)
            entry["bucket32_uncached_bytes_per_step_coord"] = \
                per_step(ub_ctr, 0)
            entry["bucket32_cached_bytes_per_step_worker"] = \
                per_step(cb_ctr, 1)
            entry["bucket32_uncached_bytes_per_step_worker"] = \
                per_step(ub_ctr, 1)
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            entry["bucket32_error"] = str(e)[:300]

        negotiation.append(entry)
        print("negotiation n=%d: cached %.0f us/op (%s B/op coord), "
              "uncached %.0f us/op (%s B/op coord); bucket32 %s vs %s "
              "B/step coord"
              % (n, cached, entry["cached_bytes_per_op_coord"],
                 uncached, entry["uncached_bytes_per_op_coord"],
                 entry.get("bucket32_cached_bytes_per_step_coord"),
                 entry.get("bucket32_uncached_bytes_per_step_coord")),
              file=sys.stderr)

    out = {
        "metric": "scaling_evidence",
        "value": weak[-1]["efficiency"],
        "unit": "weak_scaling_efficiency_n8_virtual_mesh",
        "vs_baseline": round(weak[-1]["efficiency"] / 0.90, 3),
        "baseline": "reference claims 90% scaling efficiency at 512 GPUs "
                    "(README.rst:75); projection model in SCALING.md",
        "weak_scaling": weak,
        "negotiation_latency": negotiation,
        "metrics_overhead": metrics_ab,
        "host_cores": os.cpu_count(),
    }
    emit(out)


def w2v_make_step(mesh, n, sparse, lr=0.5, num_iters=100, donate=True):
    """Skip-gram NCE multi-step train fn over a dp mesh, sparse or
    dense gradient plane. The IndexedSlices rationale (reference
    horovod/tensorflow/__init__.py:65-76) as a measurable A/B:

    * sparse: grads w.r.t. the GATHERED rows only (O(B*D)), shipped
      through the PRODUCT sparse plane — `horovod_tpu.jax.sparse.
      allreduce_sparse` (allgather (indices, values) over the axis,
      average) + `apply_sparse` (scatter-add; duplicates accumulate,
      exactly IndexedSlices application).
    * dense: differentiate through the gathers (XLA materializes the
      full [V, D] scatter-add gradient), psum it, dense SGD update —
      O(V*D) per step, the `sparse_as_dense` escape hatch.

    Top-level (not nested in word2vec_main) so tests can pin the two
    paths against each other on a CPU mesh."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.jax.sparse import allreduce_sparse, apply_sparse

    def nce(er, pw, pb, nw, nb):
        pos = jnp.sum(er * pw, axis=-1) + pb
        negl = er @ nw.T + nb[None, :]
        return jnp.mean(-jax.nn.log_sigmoid(pos) -
                        jnp.sum(jax.nn.log_sigmoid(-negl), axis=-1))

    def run(emb, nce_w, nce_b, center, context, neg):
        def one(tables, _):
            emb, nce_w, nce_b = tables
            if sparse:
                er = jnp.take(emb, center, axis=0)
                pw = jnp.take(nce_w, context, axis=0)
                pb = jnp.take(nce_b, context, axis=0)
                nw = jnp.take(nce_w, neg, axis=0)
                nb = jnp.take(nce_b, neg, axis=0)
                loss, g = jax.value_and_grad(
                    nce, argnums=(0, 1, 2, 3, 4))(er, pw, pb, nw, nb)

                def sparse_apply(table, ix, vals):
                    ai, av = allreduce_sparse(ix, vals, average=True,
                                              axis_name="dp")
                    return apply_sparse(table, ai, av, scale=-lr)

                emb = sparse_apply(emb, center, g[0])
                nce_w = sparse_apply(nce_w, context, g[1])
                nce_b = sparse_apply(nce_b, context, g[2])
                nce_w = sparse_apply(nce_w, neg, g[3])
                nce_b = sparse_apply(nce_b, neg, g[4])
            else:
                def full_loss(emb, nce_w, nce_b):
                    return nce(jnp.take(emb, center, axis=0),
                               jnp.take(nce_w, context, axis=0),
                               jnp.take(nce_b, context, axis=0),
                               jnp.take(nce_w, neg, axis=0),
                               jnp.take(nce_b, neg, axis=0))
                loss, g = jax.value_and_grad(
                    full_loss, argnums=(0, 1, 2))(emb, nce_w, nce_b)
                emb = emb - lr * (lax.psum(g[0], "dp") / n)
                nce_w = nce_w - lr * (lax.psum(g[1], "dp") / n)
                nce_b = nce_b - lr * (lax.psum(g[2], "dp") / n)
            return (emb, nce_w, nce_b), lax.pmean(loss, "dp")

        tables, losses = lax.scan(one, (emb, nce_w, nce_b), None,
                                  length=num_iters)
        return tables + (losses[-1],)

    sharded = jax.shard_map(
        run, mesh=mesh,
        in_specs=(P(), P(), P(), P("dp"), P("dp"), P()),
        out_specs=(P(), P(), P(), P()), check_vma=False)
    # donate=False exists for the CPU-mesh equivalence test: old jaxlib
    # CPU runtimes intermittently reuse donated buffers before the scan
    # reads them (garbage outputs); the benchmark itself keeps donation
    # for the in-place table-update memory footprint.
    return jax.jit(sharded,
                   donate_argnums=(0, 1, 2) if donate else ())


def word2vec_main(args):
    """bench.py --model word2vec: the sparse (indices, values)
    embedding-gradient plane vs the dense full-table path, on chip.
    Reference counterpart: examples/tensorflow_word2vec.py
    (BASELINE.json config #4, "exercises allgather + broadcast") whose
    embedding grads are IndexedSlices. One JSON row: the sparse path
    is the metric, the dense A/B rides along as fields."""
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    V, D, B, K = args.vocab_size, 256, 4096, 512
    iters = args.num_iters
    rng = np.random.RandomState(0)
    # Zipf-ish ids like natural text; heavy duplication at low ids
    # exercises the scatter-add accumulate path.
    p = 1.0 / np.arange(1, V + 1)
    p /= p.sum()
    center = jnp.asarray(rng.choice(V, size=B, p=p).astype(np.int32))
    context = jnp.asarray(rng.choice(V, size=B, p=p).astype(np.int32))
    neg = jnp.asarray(rng.choice(V, size=K, p=p).astype(np.int32))

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("dp",))
    print("bench: %d device(s), platform=%s" %
          (n, devices[0].platform), file=sys.stderr)

    def tables():
        r = np.random.RandomState(1)
        return (jnp.asarray(r.randn(V, D).astype(np.float32) * 0.1),
                jnp.asarray(r.randn(V, D).astype(np.float32) * 0.1),
                jnp.zeros((V,), jnp.float32))

    results = {}
    for name, sparse in (("sparse", True), ("dense", False)):
        step = w2v_make_step(mesh, n, sparse, num_iters=iters)
        emb, nce_w, nce_b = tables()
        for _ in range(max(1, args.num_warmup)):
            emb, nce_w, nce_b, loss = step(emb, nce_w, nce_b, center,
                                           context, neg)
        float(loss)  # true barrier (block_until_ready is not, here)
        times = []
        for _ in range(max(2, args.num_rounds)):
            t0 = time.perf_counter()
            emb, nce_w, nce_b, loss = step(emb, nce_w, nce_b, center,
                                           context, neg)
            float(loss)
            times.append((time.perf_counter() - t0) / iters)
        results[name] = sorted(times)[len(times) // 2]
        print("word2vec %s: %.3f ms/step" % (name, results[name] * 1e3),
              file=sys.stderr)

    sparse_sps = 1.0 / results["sparse"]
    dense_sps = 1.0 / results["dense"]
    out = {
        "metric": "word2vec_sparse_steps_per_sec_per_chip",
        "value": round(sparse_sps, 1),
        "unit": "steps/sec/chip",
        "vs_baseline": None,
        "baseline": "reference tensorflow_word2vec (BASELINE.json #4) "
                    "publishes no steps/s; the dense-equivalent A/B "
                    "of the same model rides in this row",
        "dense_steps_per_sec": round(dense_sps, 1),
        "sparse_speedup_vs_dense": round(sparse_sps / dense_sps, 2),
        "vocab": V, "embedding_dim": D, "batch_centers": B,
        "num_negatives": K,
        "sparse_rows_per_step": int(2 * B + 2 * K + B),
    }
    emit(out)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256,
                    help="per-chip batch size (the reference script's "
                         "tunable, default 64 on 2016 GPUs; 256 measured "
                         "fastest on v5e — see PERF.md)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-warmup", type=int, default=3)
    ap.add_argument("--num-rounds", type=int, default=5)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "resnet50gn", "resnet50nf",
                             "resnet50lean", "resnet50pbn", "resnet101",
                             "resnet101nf", "resnet152",
                             "vgg16", "inception3", "inception3pbn",
                             "transformer", "word2vec"],
                    help="vgg16/inception3 are the other models in the "
                         "reference's published scaling table "
                         "(docs/benchmarks.rst:13-14); use "
                         "--image-size 299 for inception3's canonical "
                         "input")
    ap.add_argument("--seq-len", type=int, default=2048,
                    help="sequence length (transformer model)")
    ap.add_argument("--tokens-batch", type=int, default=8,
                    help="per-chip sequences per step (transformer model)")
    ap.add_argument("--num-heads", type=int, default=12,
                    help="transformer attention heads; embed_dim stays "
                         "768, so head_dim = 768/H. H=6 gives D=128 "
                         "heads — identical FLOPs to GPT-2's 12xD64 but "
                         "full MXU width (D=64 caps every attention "
                         "matmul at half the systolic array)")
    ap.add_argument("--vocab-size", type=int, default=100000,
                    help="word2vec model: embedding/NCE table rows "
                         "(the dense A/B's per-step cost scales with "
                         "this; the sparse path's does not)")
    ap.add_argument("--num-kv-heads", type=int, default=0,
                    help="transformer GQA/MQA: kv heads < query heads "
                         "(0 = plain MHA). Shrinks the k/v projections "
                         "and runs the flash kernels' grouped-rows "
                         "layout (one kv fetch per query-head group, "
                         "in-kernel dK/dV group reduction)")
    ap.add_argument("--fused-rope", action="store_true",
                    help="fuse rotary embedding into the flash kernels' "
                         "q/k load path (saves the HBM round trip of "
                         "writing rotated q/k outside the kernel)")
    ap.add_argument("--zero1", action="store_true",
                    help="ZeRO-1 optimizer-state sharding in the train "
                         "step (parallel/train.py) - state memory/n, "
                         "same wire bytes")
    ap.add_argument("--moe-experts", type=int, default=0,
                    help="transformer only: >0 swaps every other "
                         "block's MLP for a Switch-MoE layer with this "
                         "many experts (parallel/expert.py)")
    ap.add_argument("--fused-xent", action="store_true",
                    help="use the streaming chunked LM cross entropy "
                         "(ops/losses.py) instead of the dense "
                         "log_softmax loss — required for very long "
                         "sequences (dense f32 logits at L=8192 "
                         "exceed a v5e's HBM)")
    ap.add_argument("--sync-bn", action="store_true",
                    help="cross-replica (sync) BN for the resnet "
                         "variants: batch statistics psum over the "
                         "data-parallel mesh axis inside the train "
                         "step (ops/batch_norm.py; the standard choice "
                         "at small per-chip batch)")
    ap.add_argument("--virtual-batch-size", type=int, default=0,
                    help="ghost BN for the resnet lean/pallas "
                         "variants: per-group virtual batch "
                         "(ops/batch_norm.py; the large-per-chip-batch "
                         "regularizer). 0 = off")
    ap.add_argument("--bn-traffic", action="store_true",
                    help="graph-level BN A/B, CPU-reproducible (PERF.md "
                         "round 10): per-step cost_analysis() bytes "
                         "accessed for the resnet50 train step under "
                         "stock flax BN vs the traffic-lean custom-VJP "
                         "BN, with the norm-free conv-only floor, the "
                         "BN-tax reduction as the headline, and the "
                         "AGC norm-free convergence gate; prints one "
                         "JSON line (works under JAX_PLATFORMS=cpu)")
    ap.add_argument("--bn-traffic-batch", type=int, default=32,
                    help="--bn-traffic batch size (CPU-compilable "
                         "stand-in for the chip's batch-256 shape; the "
                         "A/B ratio, not the absolute bytes, is the "
                         "metric)")
    ap.add_argument("--bn-traffic-image-size", type=int, default=64)
    ap.add_argument("--all-models", action="store_true",
                    help="run the whole model-zoo sweep (one subprocess "
                         "per model) and print a single combined JSON "
                         "line")
    ap.add_argument("--compression", choices=["none", "bf16", "int8"],
                    default=None,
                    help="A/B the wire-compression stage "
                         "(docs/COMPRESSION.md): data-ring bytes + "
                         "step time with compression off vs this mode "
                         "(2 local ranks, CPU-only), plus the int8 vs "
                         "fp32 convergence run; prints one JSON line")
    ap.add_argument("--shm", action="store_true",
                    help="A/B the shared-memory intra-host data plane "
                         "(docs/TRANSPORT.md): same-host allreduce wall "
                         "time shm vs TCP loopback at 2 and 4 ranks "
                         "across none/bf16/int8, plus a hierarchical-"
                         "composite A/B on the emulated cross-host "
                         "link; prints one JSON line (BENCH_r11)")
    ap.add_argument("--sharded-update", action="store_true",
                    help="A/B the ZeRO-style sharded weight update "
                         "(docs/ZERO.md): step time, optimizer-state "
                         "bytes (opt_state_bytes gauge) and data-ring "
                         "wire bytes for reduce-scatter+allgather vs "
                         "plain allreduce at 2 and 4 local ranks, plus "
                         "a 2-rank replicated-vs-sharded convergence "
                         "run; prints one JSON line")
    ap.add_argument("--zoo-headroom", action="store_true",
                    help="per-zoo-model training-state residency vs the "
                         "v5e 16 GiB HBM budget with the sharded update "
                         "applied (exact eval_shape byte accounting + "
                         "BENCH_r07's measured 1/N opt-state law; "
                         "HVD_TPU_HEADROOM_RANKS sets N, default 8); "
                         "prints one JSON line for PERF.md")
    ap.add_argument("--model-parallel", type=int, default=0,
                    metavar="K",
                    help="process-group / 2-D mesh A/B (docs/GROUPS.md, "
                         "BENCH_r09) at 2*K local ranks: model-group vs "
                         "full-world allreduce wire bytes (acceptance "
                         "<= K/world + 5%%), per-op latency for world/"
                         "model/batch rings, and the jax_tp_lm example's "
                         "loss trajectory vs its single-process "
                         "reference; prints one JSON line")
    ap.add_argument("--autotune", action="store_true",
                    help="closed-loop autotune on/off A/B (untuned "
                         "defaults vs the always-on tuner, zero "
                         "hand-set knobs, vs the AUTOTUNE_AB_r05 "
                         "tuned-env replay target) plus a "
                         "pipelined-ring chunk-size sweep on >=1MB "
                         "fused buffers at 2-4 ranks "
                         "(docs/AUTOTUNE.md); prints one JSON line")
    ap.add_argument("--durable-commit", action="store_true",
                    help="measure ElasticState.commit() latency with "
                         "the durable checkpoint writer off vs on "
                         "(docs/ELASTIC.md 'Durability'); CPU-only, "
                         "prints one JSON line")
    ap.add_argument("--serve", action="store_true",
                    help="serving-plane bench (docs/SERVE.md): open-"
                         "loop RPS/latency curve on a 2-replica pool "
                         "plus the autoscale-on-traffic-step row; "
                         "CPU-only, prints one JSON line (BENCH_r12)")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="A/B the always-on trace recorder "
                         "(docs/TRACING.md): tracing on vs off on the "
                         "step and negotiation workloads; CPU-only, "
                         "prints one JSON line (BENCH_r13)")
    ap.add_argument("--scaling", action="store_true",
                    help="regenerate the SCALING.md evidence (weak "
                         "scaling on the virtual CPU mesh + negotiation "
                         "latency curves) instead of the throughput bench")
    ap.add_argument("--scaling-max-ranks", type=int, default=256,
                    help="largest local rank count for the negotiation "
                         "latency curve")
    ap.add_argument("--scaling-batch", type=int, default=128,
                    help="per-shard batch for the weak-scaling step")
    ap.add_argument("--scaling-worker", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--scaling-single", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.model == "transformer":
        if 768 % args.num_heads or (768 // args.num_heads) % 64:
            ap.error("--num-heads must divide embed_dim=768 with a "
                     "64-multiple head_dim (the Pallas kernels need "
                     "lane-tileable D); got H=%d -> D=%d rem %d"
                     % (args.num_heads, 768 // args.num_heads,
                        768 % args.num_heads))
        if args.num_kv_heads and args.num_heads % args.num_kv_heads:
            ap.error("--num-kv-heads must divide --num-heads; got "
                     "G=%d, H=%d" % (args.num_kv_heads, args.num_heads))

    if args.scaling_worker is not None:
        return scaling_worker(args)
    if args.bn_traffic:
        return bn_traffic_main(args)
    if args.compression is not None:
        return compression_main(args)
    if args.shm:
        return shm_main(args)
    if args.sharded_update:
        return sharded_update_main(args)
    if args.model_parallel:
        return model_parallel_main(args)
    if args.zoo_headroom:
        return zoo_headroom_main(args)
    if args.autotune:
        return autotune_main(args)
    if args.durable_commit:
        return durable_commit_main(args)
    if args.serve:
        return serve_main(args)
    if args.trace_overhead:
        return trace_overhead_main(args)
    if args.scaling:
        return scaling_main(args)
    if args.all_models:
        return all_models_main(args)

    # Accelerator-plugin outage guard: with this environment's tunnel
    # plugin dead, `import jax` hangs FOREVER in any process holding
    # the pool pointer. Probe in a killable subprocess so the bench
    # fails loudly (one diagnostic JSON line, exit 1) instead of
    # hanging the caller. --all-models probes once and tells its
    # children to skip.
    if not _tpu_probe_or_report():
        return 1

    if args.model == "word2vec":
        return word2vec_main(args)

    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import models
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step
    from horovod_tpu.parallel.train import cross_entropy_loss

    devices = jax.devices()
    n = len(devices)
    print("bench: %d device(s), platform=%s" % (n, devices[0].platform),
          file=sys.stderr)
    rng = jax.random.PRNGKey(0)
    mesh = data_parallel_mesh(devices=devices)

    if args.model == "transformer":
        # GPT-2-small-shaped causal LM with the Pallas flash-attention
        # kernel — the long-context extension's on-chip evidence (the
        # unit per "image" below is one sequence).
        moe = {}
        if args.moe_experts:
            # Switch-MoE variant (single chip: all experts local, the
            # dispatch/combine einsums + capacity machinery on the MXU;
            # the ep all_to_all engages only on multi-chip meshes).
            moe = dict(moe_experts=args.moe_experts, moe_every=2,
                       moe_capacity_factor=1.25)
        cfg = models.TransformerConfig(
            vocab_size=32000, num_layers=12, num_heads=args.num_heads,
            num_kv_heads=args.num_kv_heads or None,
            rope_fused=args.fused_rope,
            embed_dim=768, mlp_dim=3072, attention="flash",
            dtype=jnp.bfloat16, max_seq_len=max(8192, args.seq_len),
            **moe)
        model = models.Transformer(cfg)
        L = args.seq_len
        global_batch = args.tokens_batch * n
        tokens = jax.random.randint(rng, (global_batch, L), 0,
                                    cfg.vocab_size)
        positions = jnp.broadcast_to(
            jnp.arange(L, dtype=jnp.int32)[None], tokens.shape)
        params = model.init(rng, tokens[:1], positions[:1])["params"]

        if args.fused_xent:
            # Streaming LM loss: chunked vocab projection + logsumexp,
            # never materializing [B, L, V] f32 logits (identical
            # math; ops/losses.py).
            from horovod_tpu.ops.losses import \
                chunked_softmax_cross_entropy

            # Largest power-of-two chunk (<=512) dividing L, so any
            # --seq-len works; L itself as the degenerate fallback.
            # chunk=1024 measured slightly SLOWER at L=8192 h6 on v5e
            # (8.53 vs 8.66 seq/s) — 512 stays the cap.
            chunk = next((c for c in (512, 256, 128, 64)
                          if args.seq_len % c == 0), args.seq_len)

            def loss_fn(params, batch):
                hidden = model.apply({"params": params}, batch["x"],
                                     batch["pos"], return_hidden=True)
                tgt = jnp.roll(batch["x"], -1, axis=1)
                return chunked_softmax_cross_entropy(
                    hidden, params["lm_head"]["kernel"], tgt, chunk=chunk)
        else:
            def loss_fn(params, batch):
                logits = model.apply({"params": params}, batch["x"],
                                     batch["pos"])
                tgt = jnp.roll(batch["x"], -1, axis=1)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32))
                return -jnp.mean(jnp.take_along_axis(
                    logp, tgt[..., None], axis=-1))

        opt = optax.adam(1e-4)
        step = make_train_step(loss_fn, opt, mesh, donate=True,
                               zero1=args.zero1)
        params_p, opt_state, batch = step.place(
            params, opt.init(params),
            {"x": tokens, "pos": positions})
        unit = "sequences/sec/chip"
        per_item_tokens = L
    else:
        model_cls = {"resnet50": models.ResNet50,
                     "resnet50gn": models.ResNet50GN,
                     "resnet50nf": models.ResNet50NF,
                     "resnet50lean": models.ResNet50Lean,
                     "resnet50pbn": models.ResNet50PBN,
                     "resnet101": models.ResNet101,
                     "resnet101nf": models.ResNet101NF,
                     "resnet152": models.ResNet152,
                     "vgg16": models.VGG16,
                     "inception3": models.InceptionV3,
                     "inception3pbn": partial(models.InceptionV3,
                                              norm="pallas")}[args.model]
        extra = {}
        if args.sync_bn or args.virtual_batch_size:
            if not args.model.startswith("resnet") or \
                    args.model.endswith(("nf", "gn")):
                raise SystemExit(
                    "--sync-bn/--virtual-batch-size apply to the "
                    "BN-carrying resnet variants (GroupNorm has no "
                    "cross-sample statistics to sync)")
            if args.sync_bn:
                # The train step's mesh axis (parallel/train.py): the
                # stats psum rides the same shard_map the gradients do.
                extra["bn_axis_name"] = "hvd"
            if args.virtual_batch_size:
                if args.model not in ("resnet50lean", "resnet50pbn"):
                    raise SystemExit("--virtual-batch-size needs the "
                                     "lean or pallas BN variants")
                extra["bn_virtual_batch_size"] = args.virtual_batch_size
        model = model_cls(num_classes=1000, dtype=jnp.bfloat16, **extra)

        s = args.image_size
        variables = model.init(rng, jnp.zeros((1, s, s, 3)), train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        mutable = ["batch_stats"] if batch_stats else []
        drop_rng = jax.random.PRNGKey(1)

        def loss_fn(params, batch):
            state = {"params": params}
            if batch_stats:
                state["batch_stats"] = batch_stats
                logits, _ = model.apply(state, batch["x"], train=True,
                                        mutable=mutable,
                                        rngs={"dropout": drop_rng})
            else:
                logits = model.apply(state, batch["x"], train=True,
                                     rngs={"dropout": drop_rng})
            return cross_entropy_loss(logits, batch["y"])

        # Norm-free variants train with adaptive gradient clipping
        # (ops/agc.py): the knob that makes the measured-fastest route
        # an actual training config, not just a roofline probe. Cost
        # rides in the measured step like any real run. zero1 cannot
        # carry AGC (flat shards destroy the unit structure) — fail
        # loudly rather than silently measure an untrainable config.
        agc = None
        if args.model.endswith("nf"):
            if args.zero1:
                raise SystemExit(
                    "--zero1 with a norm-free model would drop AGC "
                    "(sharded updates see 1/N flat shards, not "
                    "per-filter units) — the measured step would not "
                    "be a trainable config; run nf rows replicated")
            agc = 0.01
        opt = optax.sgd(0.01, momentum=0.9)
        step = make_train_step(loss_fn, opt, mesh, donate=True,
                               zero1=args.zero1, agc=agc)

        global_batch = args.batch_size * n
        x = jax.random.normal(rng, (global_batch, s, s, 3), jnp.float32)
        y = jax.random.randint(rng, (global_batch,), 0, 1000)
        params_p, opt_state, batch = step.place(params, opt.init(params),
                                                {"x": x, "y": y})
        unit = "images/sec/chip"
        per_item_tokens = None

    # Sync via a host read of the loss: the final loss value depends on
    # every prior step's params, so float() is a true end-of-chain
    # barrier (block_until_ready alone is not reliable over remote-device
    # transports).
    for _ in range(args.num_warmup):
        params_p, opt_state, loss = step(params_p, opt_state, batch)
    float(loss)

    # Optional profiler hook (examples/profile_step.py): trace a
    # separate burst of steps BEFORE the timed rounds so trace
    # collection overhead never contaminates the reported numbers.
    profile_dir = os.environ.get("HVD_TPU_PROFILE_DIR")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
        for _ in range(args.num_iters):
            params_p, opt_state, loss = step(params_p, opt_state, batch)
        float(loss)
        jax.profiler.stop_trace()

    rates = []
    for r in range(args.num_rounds):
        t0 = time.perf_counter()
        for _ in range(args.num_iters):
            params_p, opt_state, loss = step(params_p, opt_state, batch)
        float(loss)
        dt = time.perf_counter() - t0
        rates.append(global_batch * args.num_iters / dt)
        print("round %d: %.1f img/sec total" % (r, rates[-1]),
              file=sys.stderr)

    total = float(np.mean(rates))
    per_chip = total / n
    step_time_ms = global_batch / total * 1000.0

    # MFU: XLA-reported per-device FLOPs / measured step time / peak.
    flops = compiled_flops(step, params_p, opt_state, batch)
    peak = peak_flops(devices[0])
    tflops_per_chip = mfu = None
    if flops:
        tflops_per_chip = flops / (step_time_ms / 1000.0) / 1e12
        if peak:
            mfu = tflops_per_chip * 1e12 / peak

    if args.model == "transformer":
        label = "transformer"
        if args.moe_experts:
            label = "transformer_moe%d" % args.moe_experts
        if args.num_heads != 12:
            label += "_h%d" % args.num_heads
        if args.num_kv_heads:
            label += "_gqa%d" % args.num_kv_heads
        if args.fused_rope:
            label += "_frope"
        out = {
            "metric": "%s_flash_L%d_sequences_per_sec_per_chip"
                      % (label, args.seq_len),
            "value": round(per_chip, 2),
            "unit": unit,
            "vs_baseline": None,
            "baseline": "no reference LM baseline (the reference has no "
                        "long-context path); tokens/sec/chip = %.0f"
                        % (per_chip * per_item_tokens),
            "step_time_ms": round(step_time_ms, 2),
        }
        # XLA's cost analysis reports the Pallas attention kernels as
        # ZERO flops, so `mfu` above undercounts the transformer. Add
        # the analytic kernel FLOPs (documented, separately) for the
        # honest total.
        if flops and peak:
            from horovod_tpu.ops.flash_attention import \
                analytic_attention_flops
            attn = cfg.num_layers * analytic_attention_flops(
                args.tokens_batch, cfg.num_heads, L,
                cfg.embed_dim // cfg.num_heads, causal=True, training=True)
            total_tflops = (flops + attn) / (step_time_ms / 1000.0) / 1e12
            out["attn_tflops_uncounted_by_xla"] = round(
                attn / (step_time_ms / 1000.0) / 1e12, 1)
            out["mfu_with_attn_kernels"] = round(
                total_tflops * 1e12 / peak, 3)
    else:
        baseline_per_gpu = 1656.82 / 16.0
        out = {
            "metric": "%s_synthetic_images_per_sec_per_chip" % args.model,
            "value": round(per_chip, 2),
            "unit": unit,
            "vs_baseline": round(per_chip / baseline_per_gpu, 3),
            "baseline": "reference ResNet-101 @ 16xP100, 103.55 img/s/GPU "
                        "(docs/benchmarks.rst:43)%s" % (
                            "" if args.model == "resnet101"
                            else "; cross-model vs %s" % args.model),
            "step_time_ms": round(step_time_ms, 2),
        }
    if tflops_per_chip is not None:
        out["tflops_per_chip"] = round(tflops_per_chip, 1)
    if mfu is not None:
        out["mfu"] = round(mfu, 3)
    emit(out)


if __name__ == "__main__":
    sys.exit(main())
