"""Data-parallel ResNet-50 ImageNet training in PyTorch — the reference
config `examples/pytorch_imagenet_resnet50.py` (BASELINE.json config #3)
rebuilt for horovod_tpu: DistributedOptimizer with gradient predivide,
root-rank parameter/optimizer broadcast, epoch-scaled LR warmup, allreduce
metric averaging, rank-0 checkpointing.

torchvision isn't available in this environment, so the model is a
self-contained ResNet-50 and training runs on ImageNet-shaped synthetic
data (swap `synthetic_loader` for a torchvision ImageFolder DataLoader
with a DistributedSampler to train on real ImageNet).

Run: python -m horovod_tpu.run.run -np 8 -- \
         python examples/pytorch_imagenet_resnet50.py --epochs 90
"""

import argparse
import os
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, in_ch, width, stride=1):
        super().__init__()
        out_ch = width * self.expansion
        self.conv1 = nn.Conv2d(in_ch, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, out_ch, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(out_ch)
        self.down = None
        if stride != 1 or in_ch != out_ch:
            self.down = nn.Sequential(
                nn.Conv2d(in_ch, out_ch, 1, stride, bias=False),
                nn.BatchNorm2d(out_ch))

    def forward(self, x):
        identity = x if self.down is None else self.down(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


class ResNet50(nn.Module):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2d(3, 64, 7, 2, 3, bias=False), nn.BatchNorm2d(64),
            nn.ReLU(inplace=True), nn.MaxPool2d(3, 2, 1))
        chans, layers = [64, 128, 256, 512], [3, 4, 6, 3]
        stages, in_ch = [], 64
        for i, (width, n) in enumerate(zip(chans, layers)):
            for j in range(n):
                stages.append(Bottleneck(in_ch, width,
                                         stride=2 if i > 0 and j == 0 else 1))
                in_ch = width * Bottleneck.expansion
        self.stages = nn.Sequential(*stages)
        self.fc = nn.Linear(in_ch, num_classes)

    def forward(self, x):
        x = self.stages(self.stem(x))
        x = torch.flatten(F.adaptive_avg_pool2d(x, 1), 1)
        return self.fc(x)


def synthetic_loader(batch_size, num_batches, num_classes, image_size, seed):
    rng = np.random.RandomState(seed)
    for _ in range(num_batches):
        x = torch.from_numpy(
            rng.randn(batch_size, 3, image_size, image_size)
            .astype(np.float32))
        y = torch.from_numpy(
            rng.randint(0, num_classes, size=batch_size).astype(np.int64))
        yield x, y


def adjust_lr(optimizer, base_lr, epoch, batch_idx, batches_per_epoch,
              warmup_epochs):
    """Reference LR schedule: linear warmup to base_lr * hvd.size() over
    `warmup_epochs`, then /10 at epochs 30/60/80
    (reference pytorch_imagenet_resnet50.py adjust_learning_rate)."""
    if epoch < warmup_epochs:
        progress = (batch_idx + epoch * batches_per_epoch) / (
            warmup_epochs * batches_per_epoch)
        lr_adj = progress * (hvd.size() - 1) / hvd.size() + 1.0 / hvd.size()
    elif epoch < 30:
        lr_adj = 1.0
    elif epoch < 60:
        lr_adj = 1e-1
    elif epoch < 80:
        lr_adj = 1e-2
    else:
        lr_adj = 1e-3
    for group in optimizer.param_groups:
        group["lr"] = base_lr * hvd.size() * lr_adj


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--batches-per-epoch", type=int, default=4,
                    help="synthetic batches per epoch per rank")
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--image-size", type=int, default=64,
                    help="224 for the full ImageNet shape")
    ap.add_argument("--base-lr", type=float, default=0.0125)
    ap.add_argument("--warmup-epochs", type=int, default=1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--wd", type=float, default=5e-5)
    ap.add_argument("--checkpoint-dir", default=None)
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(42 + hvd.rank())
    torch.set_num_threads(max(1, (os.cpu_count() or 4) // hvd.local_size()))

    model = ResNet50(num_classes=args.num_classes)
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.base_lr * hvd.size(),
                                momentum=args.momentum,
                                weight_decay=args.wd)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    # Consistent start: root's params/opt state everywhere (the
    # reference's broadcast_parameters/broadcast_optimizer_state pattern).
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    for epoch in range(args.epochs):
        model.train()
        t0 = time.time()
        seen = 0
        loader = synthetic_loader(args.batch_size, args.batches_per_epoch,
                                  args.num_classes, args.image_size,
                                  seed=1000 * epoch + hvd.rank())
        for batch_idx, (x, y) in enumerate(loader):
            adjust_lr(optimizer, args.base_lr, epoch, batch_idx,
                      args.batches_per_epoch, args.warmup_epochs)
            optimizer.zero_grad()
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            optimizer.step()
            seen += x.shape[0]
        # Cross-rank metric averaging (reference: Metric/metric_average).
        avg_loss = hvd.allreduce(loss.detach(), average=True,
                                 name="epoch_loss").item()
        rate = seen / (time.time() - t0)
        if hvd.rank() == 0:
            print("epoch %d: loss %.4f, %.1f img/s/rank (x%d ranks)"
                  % (epoch, avg_loss, rate, hvd.size()), flush=True)
            if args.checkpoint_dir:
                os.makedirs(args.checkpoint_dir, exist_ok=True)
                torch.save(
                    {"model": model.state_dict(),
                     "optimizer": optimizer.state_dict(), "epoch": epoch},
                    os.path.join(args.checkpoint_dir,
                                 "checkpoint-%d.pt" % epoch))

    # Final consistency check: trained params must agree across ranks
    # (BN running stats stay rank-local, like the reference).
    for name, p in sorted(dict(model.named_parameters()).items()):
        avg = hvd.allreduce(p.detach(), average=True, name="final.%s" % name)
        assert torch.allclose(avg, p, atol=1e-5), name
    if hvd.rank() == 0:
        print("done", flush=True)


if __name__ == "__main__":
    main()
