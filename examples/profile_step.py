"""Profile one benchmark model's train step and print the aggregated
per-op-category device time (the PERF.md breakdown tables).

Usage: python examples/profile_step.py [--model transformer] [--steps 5]

Writes a jax.profiler trace, then aggregates XLA op durations from the
trace's .xplane.pb via tensorflow's profiler proto (both are in the
image); falls back to printing the trace path for manual inspection.
"""

import argparse
import glob
import gzip
import json
import os
import subprocess
import sys
import tempfile


def aggregate_trace(logdir, top=25):
    """Aggregates device-side op durations from the trace.json.gz the
    profiler writes alongside the xplane."""
    pats = glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                     recursive=True)
    if not pats:
        print("no trace.json.gz under %s" % logdir, file=sys.stderr)
        return None
    with gzip.open(pats[0], "rt") as f:
        trace = json.load(f)
    # Only the device's "XLA Ops" lane: leaf per-op events (the Steps /
    # XLA Modules lanes are enclosing spans and would double-count).
    device_pids = set()
    op_lanes = set()
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "M":
            continue
        args = ev.get("args", {})
        if ev.get("name") == "process_name":
            name = args.get("name", "")
            if "TPU" in name or "/device" in name.lower():
                device_pids.add(ev["pid"])
    for ev in trace.get("traceEvents", []):
        if (ev.get("ph") == "M" and ev.get("name") == "thread_name"
                and ev.get("pid") in device_pids
                and ev.get("args", {}).get("name") == "XLA Ops"):
            op_lanes.add((ev["pid"], ev.get("tid")))
    totals = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X" or \
                (ev.get("pid"), ev.get("tid")) not in op_lanes:
            continue
        name = ev.get("name", "")
        # Collapse fusion instance suffixes: "fusion.123" -> "fusion",
        # "convert_reduce_fusion.5" -> "convert_reduce_fusion".
        base = name.split(".")[0]
        totals[base] = totals.get(base, 0.0) + ev.get("dur", 0.0)
    rows = sorted(totals.items(), key=lambda kv: -kv[1])[:top]
    total = sum(totals.values())
    print("device op time (us, all steps, lanes=%s):" % sorted(op_lanes))
    for name, dur in rows:
        print("  %-44s %10.0f  (%4.1f%%)" % (name, dur, 100 * dur / total))
    print("  %-44s %10.0f" % ("TOTAL", total))
    return totals


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="transformer")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--logdir", default=None)
    args, extra = ap.parse_known_args()

    logdir = args.logdir or tempfile.mkdtemp(prefix="hvdtpu_prof_")
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    env = dict(os.environ)
    env["HVD_TPU_PROFILE_DIR"] = logdir
    env["HVD_TPU_PROFILE_STEPS"] = str(args.steps)
    cmd = [sys.executable, bench, "--model", args.model,
           "--num-warmup", "2", "--num-rounds", "1",
           "--num-iters", str(args.steps),
           "--batch-size", str(args.batch_size),
           "--seq-len", str(args.seq_len)] + extra
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env)
    sys.stderr.write(proc.stderr[-1500:])
    if proc.returncode != 0:
        raise RuntimeError("bench failed")
    print(proc.stdout.strip().splitlines()[-1])
    aggregate_trace(logdir)
    print("trace dir: %s" % logdir)


if __name__ == "__main__":
    main()
