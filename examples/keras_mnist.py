"""Data-parallel Keras MNIST — reference analogue:
`examples/keras_mnist.py` / `examples/tensorflow2_keras_mnist.py`:
wrapped optimizer, broadcast + metric-average + LR-warmup callbacks,
rank-0-only checkpointing.

Run: python -m horovod_tpu.run.run -np 2 -- python examples/keras_mnist.py
"""

import argparse
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np


def synthetic_mnist(n=1024, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    templates = rng.randn(10, 28, 28, 1).astype(np.float32)
    x = templates[y] + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
    return x, y.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    import keras

    import horovod_tpu.keras as hvd

    hvd.init()
    rank, world = hvd.rank(), hvd.size()

    keras.utils.set_random_seed(42)
    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(32, 3, activation="relu"),
        keras.layers.Conv2D(64, 3, activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.01 * world))
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=1),
    ]
    # Only rank 0 writes checkpoints (reference convention).
    if rank == 0:
        callbacks.append(keras.callbacks.ModelCheckpoint(
            "/tmp/hvd_tpu_keras_mnist.keras"))

    x, y = synthetic_mnist()
    x_local, y_local = x[rank::world], y[rank::world]
    model.fit(x_local, y_local, batch_size=args.batch_size,
              epochs=args.epochs, callbacks=callbacks,
              verbose=1 if rank == 0 else 0)
    if rank == 0:
        print("done")


if __name__ == "__main__":
    main()
