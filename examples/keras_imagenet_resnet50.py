"""Keras ImageNet ResNet-50 — reference analogue
`examples/keras_imagenet_resnet50.py`: the real
`keras.applications.ResNet50` graph (not a toy stand-in) trained
data-parallel with the reference's full recipe — fp16 gradient
compression flag, LR warmup then staircase decay schedule, broadcast /
metric-average callbacks, rank-0-only checkpointing, and resume via
`hvd.load_model` (which re-wraps the optimizer on restore).

Synthetic ImageNet-shaped data (no dataset download); sized down by
default so it runs as a smoke test — pass --image-size 224
--batch-size 32 for the real shapes.

Run: python -m horovod_tpu.run.run -np 2 -- python examples/keras_imagenet_resnet50.py
"""

import argparse
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batches-per-epoch", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--base-lr", type=float, default=0.0125)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--warmup-epochs", type=int, default=1)
    ap.add_argument("--fp16-allreduce", action="store_true")
    ap.add_argument("--checkpoint-format",
                    default="/tmp/hvd_tpu_imagenet_ckpt_{epoch}.keras")
    args = ap.parse_args()

    import keras

    import horovod_tpu.keras as hvd

    hvd.init()
    rank, world = hvd.rank(), hvd.size()

    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)

    keras.utils.set_random_seed(1234)
    model = keras.applications.ResNet50(
        weights=None, input_shape=(args.image_size, args.image_size, 3),
        classes=100)
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=args.base_lr,
                             momentum=args.momentum),
        compression=compression)
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    n = args.batch_size * args.batches_per_epoch
    rng = np.random.RandomState(rank)
    x = rng.rand(n, args.image_size, args.image_size, 3) \
        .astype(np.float32)
    y = rng.randint(0, 100, size=n).astype(np.int32)

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=args.warmup_epochs, verbose=(rank == 0)),
        # Staircase decay after warmup — the reference's 30/60/80-of-90
        # boundaries scaled to this run's epoch count (so even the
        # 2-epoch smoke run crosses the first boundary and exercises
        # the decay path).
        hvd.callbacks.LearningRateScheduleCallback(
            multiplier=lambda epoch, _b=sorted(
                {max(args.warmup_epochs, int(args.epochs * f))
                 for f in (1 / 3, 2 / 3, 8 / 9)}):
            hvd.size() * 0.1 ** sum(epoch >= b for b in _b),
            start_epoch=args.warmup_epochs),
    ]
    if rank == 0:
        callbacks.append(keras.callbacks.ModelCheckpoint(
            args.checkpoint_format.format(epoch="last")))

    model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
              callbacks=callbacks, verbose=0)

    if rank == 0:
        # Resume path: hvd.load_model re-wraps the optimizer into a
        # DistributedOptimizer on restore (reference load_model
        # semantics, keras/__init__.py).
        path = args.checkpoint_format.format(epoch="last")
        restored = hvd.load_model(path, compression=compression)
        assert type(restored.optimizer).__name__.startswith(
            "Distributed"), type(restored.optimizer).__name__
        os.remove(path)
        print("done")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
