"""Flash-attention kernel micro-benchmark (the PERF.md table).

Times forward and forward+backward with the lax.scan single-dispatch
recipe (block_until_ready is unreliable over the tunnel), reporting
ms/iter and effective TFLOP/s from the analytic causal FLOP count.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.ops import flash_attention


def timed(fn, args, iters=50):
    def body(carry, _):
        out = fn(*carry[:1]) if len(args) == 1 else fn(*carry)
        q = carry[0] + 1e-30 * out[0] if isinstance(out, tuple) \
            else carry[0] + 1e-30 * out
        return (q,) + carry[1:], ()

    def run(*args):
        carry, _ = lax.scan(body, args, None, length=iters)
        return jnp.sum(carry[0].astype(jnp.float32))

    jitted = jax.jit(run)
    float(jitted(*args))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(jitted(*args))
        times.append((time.perf_counter() - t0) / iters)
    return sorted(times)[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--B", type=int, default=4)
    ap.add_argument("--L", type=int, default=2048)
    ap.add_argument("--H", type=int, default=8)
    ap.add_argument("--D", type=int, default=128)
    ap.add_argument("--iters", type=int, default=50)
    args = ap.parse_args()
    B, L, H, D = args.B, args.L, args.H, args.D

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)

    # Causal-halved analytic FLOPs: fwd = 2 matmuls, bwd = 7 (see
    # flash_attention analytic_attention_flops).
    fwd_flops = 2 * 2 * B * H * L * L * D / 2
    bwd_flops = 7 * 2 * B * H * L * L * D / 2

    t_fwd = timed(lambda q: flash_attention(q, k, v, causal=True),
                  (q,), args.iters)

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    def fb(q, k, v):
        dq, dk, dv = grad(q, k, v)
        return dq + dk + dv, None

    t_fb = timed(lambda q, k, v: fb(q, k, v), (q, k, v), args.iters)

    print("B=%d L=%d H=%d D=%d causal:" % (B, L, H, D))
    print("  fwd:     %6.2f ms  %6.1f TFLOP/s" %
          (t_fwd * 1e3, fwd_flops / t_fwd / 1e12))
    print("  fwd+bwd: %6.2f ms  %6.1f TFLOP/s" %
          (t_fb * 1e3, (fwd_flops + bwd_flops) / t_fb / 1e12))


if __name__ == "__main__":
    main()
