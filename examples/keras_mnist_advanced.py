"""Advanced data-parallel Keras MNIST — reference analogue:
`examples/keras_mnist_advanced.py:69-106`: LR warmup to lr*size over
the first epochs (Goyal et al.), cross-rank metric averaging before
metric-based callbacks (ReduceLROnPlateau here, as in the reference),
rank-0-only verbosity/checkpointing.

Unlike the reference example this one ASSERTS the callback semantics:
the per-epoch logged LR must follow the warmup ramp to lr*size, and
the epoch-end metrics must be identical across ranks (proving
MetricAverageCallback averaged them) while the ranks train on
disjoint, differently-distributed shards.

Run: python -m horovod_tpu.run.run -np 2 -- python examples/keras_mnist_advanced.py
"""

import argparse
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np


def synthetic_mnist(n, seed):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    templates = np.random.RandomState(7).randn(10, 28, 28, 1) \
        .astype(np.float32)
    x = templates[y] + (0.2 + 0.2 * seed) * \
        rng.randn(n, 28, 28, 1).astype(np.float32)
    return x, y.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--warmup-epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--samples", type=int, default=512)
    args = ap.parse_args()
    if args.epochs <= args.warmup_epochs:
        ap.error("--epochs must exceed --warmup-epochs (the assertions "
                 "check the post-warmup LR)")

    import keras

    import horovod_tpu.keras as hvd

    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    base_lr = 0.01

    keras.utils.set_random_seed(42)
    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(16, 3, activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])
    # NOT pre-scaled: the warmup callback ramps lr -> lr*size.
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=base_lr, momentum=0.9))
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    # Rank-disjoint shards with rank-dependent noise levels: local
    # metrics genuinely differ across ranks, so identical logged
    # metrics can only come from the average.
    x, y = synthetic_mnist(args.samples, seed=rank)
    xv, yv = synthetic_mnist(args.samples // 4, seed=100 + rank)

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        # Must precede ReduceLROnPlateau so it sees averaged metrics
        # (the reference example's ordering note).
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            warmup_epochs=args.warmup_epochs, verbose=(rank == 0)),
        keras.callbacks.ReduceLROnPlateau(patience=10, verbose=0),
    ]
    history = model.fit(x, y, batch_size=args.batch_size,
                        epochs=args.epochs, validation_data=(xv, yv),
                        callbacks=callbacks, verbose=0)

    # --- assertion 1: warmup ramp ------------------------------------
    lrs = history.history["lr"]
    final = lrs[args.warmup_epochs]
    assert abs(final - base_lr * world) < 1e-6 * world, \
        "warmup did not reach lr*size: %r" % (lrs,)
    if world > 1:
        ramp = lrs[:args.warmup_epochs]
        assert all(b >= a - 1e-9 for a, b in zip(ramp, ramp[1:])), \
            "warmup not monotone: %r" % (lrs,)
        assert ramp[0] < final, "no ramp happened: %r" % (lrs,)

    # --- assertion 2: metrics identical across ranks ------------------
    import horovod_tpu.tensorflow as hvdtf
    for key in ("val_loss", "loss"):
        mine = np.asarray(history.history[key], np.float64)
        gathered = np.asarray(
            hvdtf.allgather(mine[None, :], name="hist.%s" % key))
        spread = np.abs(gathered - gathered[0]).max()
        assert spread < 1e-5, \
            "%s not averaged across ranks (spread %g)" % (key, spread)

    if rank == 0:
        print("lrs per epoch: %s" % [round(v, 5) for v in lrs])
        print("done")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
