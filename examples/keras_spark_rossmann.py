"""Rossmann-style tabular sales regression on Spark — the reference config
`examples/keras_spark_rossmann.py` (BASELINE.json config #5) rebuilt for
horovod_tpu: an entity-embedding Keras model trained data-parallel across
Spark barrier tasks via ``horovod_tpu.spark.run``.

The reference script ETLs the Kaggle Rossmann CSVs with Spark SQL and
feeds petastorm; this environment has no dataset and no pyspark, so the
feature pipeline is reproduced on a synthetic Rossmann-shaped table
(store / day-of-week / promo / holiday categoricals + continuous
distance/competition features -> log-sales target) and the script falls
back to the horovodrun launcher when pyspark is absent (`--local`):

  pyspark:  spark-submit examples/keras_spark_rossmann.py
  no spark: python -m horovod_tpu.run.run -np 2 -- \
                python examples/keras_spark_rossmann.py --local
"""

import argparse
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np

# Rossmann-shaped categorical schema: (name, cardinality, embedding dim) —
# mirrors the reference's CATEGORICAL_COLS + embedding sizing.
CATEGORICALS = [
    ("store", 200, 10),
    ("day_of_week", 7, 3),
    ("promo", 2, 1),
    ("state_holiday", 4, 2),
    ("month", 12, 4),
]
CONTINUOUS = ["competition_distance", "days_since_promo"]


def make_synthetic_frame(n_rows, seed):
    """Synthetic Rossmann-like table with a learnable structure: sales
    depend multiplicatively on store quality, promo and weekday."""
    rng = np.random.RandomState(seed)
    cols = {name: rng.randint(0, card, n_rows)
            for name, card, _ in CATEGORICALS}
    cols["competition_distance"] = rng.exponential(1.0, n_rows)
    cols["days_since_promo"] = rng.uniform(0, 1, n_rows)
    base = (1.0 + 0.5 * np.sin(cols["store"] * 0.1)
            + 0.3 * (cols["promo"] == 1)
            + 0.1 * np.cos(cols["day_of_week"])
            - 0.2 * cols["competition_distance"])
    cols["log_sales"] = base + rng.normal(0, 0.05, n_rows)
    return cols


def build_model():
    import keras
    from keras import layers

    cat_inputs, embedded = [], []
    for name, card, dim in CATEGORICALS:
        inp = layers.Input(shape=(1,), dtype="int32", name=name)
        emb = layers.Flatten()(layers.Embedding(card, dim)(inp))
        cat_inputs.append(inp)
        embedded.append(emb)
    cont_input = layers.Input(shape=(len(CONTINUOUS),), name="continuous")
    x = layers.Concatenate()(embedded + [cont_input])
    x = layers.Dense(64, activation="relu")(x)
    x = layers.Dense(32, activation="relu")(x)
    out = layers.Dense(1, name="log_sales")(x)
    return keras.Model(cat_inputs + [cont_input], out)


def train_fn(epochs=2, rows_per_rank=2048, batch_size=128, base_lr=1e-3):
    """Runs on every rank (Spark barrier task or launcher worker) with
    horovod_tpu initialized."""
    import keras

    import horovod_tpu.keras as hvd_keras
    import horovod_tpu.tensorflow as hvd

    rank, size = hvd.rank(), hvd.size()
    keras.utils.set_random_seed(1234)  # same init everywhere

    frame = make_synthetic_frame(rows_per_rank, seed=100 + rank)
    x = {name: frame[name].reshape(-1, 1) for name, _, _ in CATEGORICALS}
    x["continuous"] = np.stack([frame[c] for c in CONTINUOUS],
                               axis=1).astype(np.float32)
    y = frame["log_sales"].astype(np.float32)

    model = build_model()
    # Reference recipe: scale LR by world size, wrap the optimizer, make
    # rank 0's weights authoritative, average the logged metrics.
    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.Adam(base_lr * size))
    model.compile(optimizer=opt, loss="mae")
    history = model.fit(
        x, y, batch_size=batch_size, epochs=epochs, verbose=0,
        callbacks=[
            hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd_keras.callbacks.MetricAverageCallback(),
        ])
    final_mae = float(history.history["loss"][-1])
    if rank == 0:
        print("final train MAE (rank-averaged): %.4f" % final_mae,
              flush=True)
    return final_mae


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--rows-per-rank", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-proc", type=int, default=2)
    ap.add_argument("--local", action="store_true",
                    help="run under the horovodrun launcher (already "
                         "inside a worker) instead of Spark")
    args = ap.parse_args()

    if args.local:
        # Launcher path: this process IS one rank.
        import horovod_tpu as hvd
        hvd.init()
        mae = train_fn(args.epochs, args.rows_per_rank, args.batch_size)
        if hvd.rank() == 0:
            print("done", flush=True)
        return

    import horovod_tpu.spark as hvd_spark
    results = hvd_spark.run(
        train_fn, args=(args.epochs, args.rows_per_rank, args.batch_size),
        num_proc=args.num_proc)
    print("per-rank MAE:", results)
    print("done", flush=True)


if __name__ == "__main__":
    main()
