"""On-chip wall-clock comparison of the ring-attention backward paths.

Measures grad(ring_attention) on a 1-device mesh (the largest ring the
single tunnel chip can host — one ring step, which is exactly the
per-step work that repeats n times on an n-chip ring) for:

  * new: the FlashAttention-2-style second ring pass over saved lse
    (current `_ring_flash` VJP);
  * old: the round-2 recompute VJP — differentiate the blockwise jnp
    ring under jax.checkpoint (reconstructed here for comparison).

Timing recipe per PERF.md: iterations chained inside one lax.scan so a
single dispatch covers the loop, then one host read as the barrier
(block_until_ready is not reliable over the tunnel).
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel import ring_attention
from horovod_tpu.parallel.ring import _ring_jnp


def _old_remat_ring(q, k, v, axis_name, causal, scale):
    """Round-2 backward: recompute through the jnp ring under
    jax.checkpoint (per-step remat)."""
    f = jax.checkpoint(
        functools.partial(_ring_jnp, axis_name=axis_name, causal=causal,
                          scale=scale))
    return f(q, k, v)


def bench(fn, mesh, q, k, v, iters=20):
    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    def scan_body(carry, _):
        q, k, v = carry
        dq, dk, dv = grad(q, k, v)
        # Feed gradients back in so scan iterations are data-dependent
        # (nothing can be hoisted or elided).
        return (q + 1e-30 * dq, k + 1e-30 * dk, v + 1e-30 * dv), ()

    def run(q, k, v):
        (q, k, v), _ = lax.scan(scan_body, (q, k, v), None, length=iters)
        return jnp.sum(q.astype(jnp.float32))

    sharded = jax.shard_map(run, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                            out_specs=P(), check_vma=False)
    jitted = jax.jit(sharded)
    float(jitted(q, k, v))  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(jitted(q, k, v))
        times.append((time.perf_counter() - t0) / iters)
    return sorted(times)[1]


def main():
    B, L, H, D = 4, 2048, 8, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.bfloat16)
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))

    t_new = bench(lambda q, k, v: ring_attention(q, k, v, "sp"),
                  mesh, q, k, v)
    t_old = bench(
        lambda q, k, v: _old_remat_ring(q, k, v, "sp", True, D ** -0.5),
        mesh, q, k, v)
    print("B=%d L=%d H=%d D=%d fwd+bwd per iter:" % (B, L, H, D))
    print("  new (lse second ring pass): %.2f ms" % (t_new * 1e3))
    print("  old (jnp remat recompute):  %.2f ms" % (t_old * 1e3))
    print("  speedup: %.2fx" % (t_old / t_new))


if __name__ == "__main__":
    main()
