"""Data-parallel MNIST CNN in PyTorch — reference analogue:
`examples/pytorch_mnist.py` (and the torch leg of BASELINE.json #3).

Run: python -m horovod_tpu.run.run -np 2 -- python examples/torch_mnist.py
Synthetic data (no network egress in this environment).
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 32, 3, 1)
        self.conv2 = nn.Conv2d(32, 64, 3, 1)
        self.fc1 = nn.Linear(9216, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = F.relu(self.conv1(x))
        x = F.max_pool2d(F.relu(self.conv2(x)), 2)
        x = torch.flatten(x, 1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def synthetic_mnist(n=1024, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    templates = rng.randn(10, 1, 28, 28).astype(np.float32)
    x = templates[y] + 0.3 * rng.randn(n, 1, 28, 28).astype(np.float32)
    return torch.from_numpy(x), torch.from_numpy(y.astype(np.int64))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    torch.manual_seed(42)

    model = Net()
    # Horovod recipe: scale LR by world size, wrap optimizer, broadcast
    # initial state (reference: examples/pytorch_mnist.py).
    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr * world,
                                momentum=0.5)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    x, y = synthetic_mnist()
    x_local, y_local = x[rank::world], y[rank::world]
    steps = len(x_local) // args.batch_size

    model.train()
    for epoch in range(args.epochs):
        total = 0.0
        for s in range(steps):
            lo = s * args.batch_size
            optimizer.zero_grad()
            out = model(x_local[lo:lo + args.batch_size])
            loss = F.nll_loss(out, y_local[lo:lo + args.batch_size])
            loss.backward()
            optimizer.step()
            total += float(loss)
        avg = hvd.allreduce(torch.tensor(total / steps), average=True,
                            name="epoch_loss.%d" % epoch)
        if rank == 0:
            print("epoch %d: loss=%.4f" % (epoch, float(avg)))
    if rank == 0:
        print("done")


if __name__ == "__main__":
    main()
