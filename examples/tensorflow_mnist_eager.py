"""Pure-eager TF2 MNIST — reference analogue
`examples/tensorflow_mnist_eager.py`: NO tf.function anywhere; every
step runs op-by-op in eager mode through DistributedGradientTape, with
rank 0's variables broadcast after the first step (the reference's
eager-era idiom) and an allreduced final metric.

Run: python -m horovod_tpu.run.run -np 2 -- python examples/tensorflow_mnist_eager.py
"""

import argparse
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    hvd.init()
    assert tf.executing_eagerly()

    rng = np.random.RandomState(hvd.rank())
    templates = np.random.RandomState(9).randn(10, 28, 28, 1) \
        .astype(np.float32)
    labels_all = rng.randint(0, 10, size=512)
    images_all = templates[labels_all] + \
        0.3 * rng.randn(512, 28, 28, 1).astype(np.float32)

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.losses.SparseCategoricalCrossentropy(from_logits=True)
    opt = tf.optimizers.SGD(0.05 * hvd.size())

    for step in range(args.steps):
        lo = (step * args.batch_size) % 448
        x = tf.constant(images_all[lo:lo + args.batch_size])
        y = tf.constant(labels_all[lo:lo + args.batch_size])
        with hvd.DistributedGradientTape() as tape:
            logits = model(x, training=True)
            loss = loss_fn(y, logits)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if step == 0:
            # Reference idiom: broadcast AFTER the first step so
            # optimizer slots exist too.
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
        if step % 20 == 0 and hvd.rank() == 0:
            print("Step %d Loss %.4f" % (step, float(loss)))

    # Cross-rank averaged final loss; also asserts the ranks stayed in
    # sync (every rank computes the same model on its own shard).
    final = hvd.allreduce(tf.constant(float(loss)), average=True)
    if hvd.rank() == 0:
        print("Final averaged loss %.4f" % float(final))
        print("done")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
