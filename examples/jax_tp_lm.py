"""Tensor-parallel transformer LM on the 2-D (batch x model) mesh
(docs/GROUPS.md) — the acceptance model for process groups.

Megatron-style sharding over the MODEL group of
``hvd.init(model_parallel=k)``: attention heads and the MLP hidden dim
split across the k model ranks (column-parallel QKV / mlp_in,
row-parallel out-proj / mlp_out), with the host-plane f/g operators
(``parallel.tensor_parallel.copy_to_model_parallel`` /
``reduce_from_model_parallel``) completing activations forward and
gradients backward over the model group's ring. Gradients average over
the BATCH group only — the ranks holding the same shard.

The point of the exercise: at the configured width this model CANNOT
run pure data-parallel — the full parameter set exceeds the per-rank
budget (HVD_TPU_TP_BUDGET_BYTES models the chip's HBM headroom), and
the example refuses to start unless model_parallel shards it under
budget. ``--reference`` lifts the budget to produce the single-process
reference loss trajectory the distributed run must match (bench.py
--model-parallel asserts it; the "big host" stand-in for a run that
would not fit the real chip).

Run::

    horovodrun_tpu -np 4 python examples/jax_tp_lm.py --model-parallel 2
    python examples/jax_tp_lm.py --reference          # 1-process reference
"""

import argparse
import json
import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import horovod_tpu.jax as hvd  # noqa: E402
from horovod_tpu.parallel.tensor_parallel import (  # noqa: E402
    copy_to_model_parallel,
    reduce_from_model_parallel,
)


def build_params(rng, vocab, d_model, n_heads, d_head, d_mlp, n_layers):
    """FULL (unsharded) parameter tree, deterministic from `rng`.

    Every rank builds the same full tree and slices its own model shard
    — initial cross-rank agreement by construction, re-asserted by the
    initial broadcast below.
    """
    def normal(key, shape, scale):
        return (scale * jax.random.normal(key, shape)).astype(jnp.float32)

    keys = jax.random.split(rng, 2 + 6 * n_layers)
    params = {
        "embed": normal(keys[0], (vocab, d_model), 0.02),
        "lm_head": normal(keys[1], (d_model, vocab), 0.02),
        "layers": [],
    }
    for i in range(n_layers):
        k = keys[2 + 6 * i:8 + 6 * i]
        params["layers"].append({
            "qkv": normal(k[0], (d_model, 3, n_heads, d_head), 0.02),
            "out": normal(k[1], (n_heads, d_head, d_model), 0.02),
            "mlp_in": normal(k[2], (d_model, d_mlp), 0.02),
            "mlp_out": normal(k[3], (d_mlp, d_model), 0.02),
            "ln1": jnp.ones(d_model),
            "ln2": jnp.ones(d_model),
        })
    return params


def shard_params(params, tp_rank, tp_size):
    """This model rank's shard: heads dim of qkv/out and the MLP hidden
    dim split into tp_size contiguous blocks (block tp_rank kept);
    everything else replicated."""
    def blk(x, dim):
        n = x.shape[dim] // tp_size
        return jax.lax.slice_in_dim(x, tp_rank * n, (tp_rank + 1) * n,
                                    axis=dim)

    out = {"embed": params["embed"], "lm_head": params["lm_head"],
           "layers": []}
    for lyr in params["layers"]:
        out["layers"].append({
            "qkv": blk(lyr["qkv"], 2),      # heads
            "out": blk(lyr["out"], 0),      # heads
            "mlp_in": blk(lyr["mlp_in"], 1),
            "mlp_out": blk(lyr["mlp_out"], 0),
            "ln1": lyr["ln1"],
            "ln2": lyr["ln2"],
        })
    return out


def _ln(x, g):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return g * (x - mu) / jnp.sqrt(var + 1e-5)


def forward(params, tokens, model_group, layer_tag):
    """Loss of the sharded model. `model_group` None = unsharded
    reference (the f/g ops degrade to identity/sum-of-one)."""
    x = params["embed"][tokens]  # [B, T, D]
    T = tokens.shape[1]
    causal = jnp.tril(jnp.ones((T, T), bool))
    for i, lyr in enumerate(params["layers"]):
        h = _ln(x, lyr["ln1"])
        if model_group is not None:
            # f: identity fwd, model-group allreduce bwd — completes the
            # gradient of the replicated input of the column-parallel
            # projections.
            h = copy_to_model_parallel(h, model_group,
                                       name="%s.f.attn.%d" % (layer_tag, i))
        q, k, v = jnp.einsum("btd,dchy->cbthy", h, lyr["qkv"])
        scores = jnp.einsum("bthy,bshy->bhts", q, k) / np.sqrt(q.shape[-1])
        scores = jnp.where(causal[None, None], scores, -1e9)
        att = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bhts,bshy->bthy", att, v)
        partial = jnp.einsum("bthy,hyd->btd", ctx, lyr["out"])
        if model_group is not None:
            # g: model-group allreduce fwd (sums the head shards'
            # partial projections), identity bwd.
            partial = reduce_from_model_parallel(
                partial, model_group, name="%s.g.attn.%d" % (layer_tag, i))
        x = x + partial
        h = _ln(x, lyr["ln2"])
        if model_group is not None:
            h = copy_to_model_parallel(h, model_group,
                                       name="%s.f.mlp.%d" % (layer_tag, i))
        inner = jax.nn.gelu(h @ lyr["mlp_in"])
        partial = inner @ lyr["mlp_out"]
        if model_group is not None:
            partial = reduce_from_model_parallel(
                partial, model_group, name="%s.g.mlp.%d" % (layer_tag, i))
        x = x + partial
    logits = x @ params["lm_head"]
    logp = jax.nn.log_softmax(logits[:, :-1])
    tgt = tokens[:, 1:]
    return -jnp.take_along_axis(logp, tgt[..., None], -1).mean()


def param_bytes(params):
    return sum(np.asarray(p).nbytes
               for p in jax.tree_util.tree_leaves(params))


def assert_fits(params, budget, model_parallel):
    """The acceptance gate: this width does not fit a rank unsharded."""
    have = param_bytes(params)
    if have > budget:
        raise SystemExit(
            "model shard (%d B) exceeds the per-rank parameter budget "
            "(%d B, HVD_TPU_TP_BUDGET_BYTES): model_parallel=%d is too "
            "narrow for this width — raise it (pure data-parallel CANNOT "
            "run this model)" % (have, budget, model_parallel))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-parallel", type=int, default=0,
                    help="mesh model width k (0: HVD_TPU_MODEL_PARALLEL)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--d-head", type=int, default=8)
    ap.add_argument("--d-mlp", type=int, default=128)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch-per-row", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--reference", action="store_true",
                    help="single-process unsharded reference run (lifts "
                         "the parameter budget; the 'big host' stand-in)")
    ap.add_argument("--loss-out", default="",
                    help="write the per-step loss trajectory as JSON")
    args = ap.parse_args()

    if args.reference:
        rank, batch_rows = 0, 1
        model_group, tp_rank, tp_size = None, 0, 1
    else:
        hvd.init(model_parallel=args.model_parallel or None)
        import horovod_tpu as hvd_core
        rank = hvd.rank()
        k = hvd_core.model_parallel_size()
        if k < 2:
            raise SystemExit(
                "this model is the process-group acceptance case and "
                "cannot run pure-DP: start with hvd.init(model_parallel"
                ">=2) (e.g. --model-parallel 2 at 4 ranks)")
        model_group = hvd_core.model_group()
        batch_group = hvd_core.batch_group()
        tp_rank, tp_size = model_group.rank(), k
        batch_rows = hvd.size() // k

    full = build_params(jax.random.PRNGKey(7), args.vocab, args.d_model,
                        args.n_heads, args.d_head, args.d_mlp, args.layers)
    if args.reference:
        params = full
    else:
        # The budget models the chip: the FULL tree must not fit, the
        # 1/k shard must. Default: just under the full parameter bytes.
        budget = int(os.environ.get("HVD_TPU_TP_BUDGET_BYTES",
                                    str(int(param_bytes(full) * 0.75))))
        params = shard_params(full, tp_rank, tp_size)
        assert_fits(params, budget, tp_size)
        # Initial agreement: replicated leaves broadcast from rank 0
        # world-wide; sharded leaves are deterministic slices of the
        # same seeded full tree, re-broadcast within each batch group
        # (same shard) from its first member.
        params = {
            "embed": hvd.broadcast_parameters(params["embed"],
                                              name_prefix="tp.embed"),
            "lm_head": hvd.broadcast_parameters(params["lm_head"],
                                                name_prefix="tp.lm_head"),
            "layers": [
                {k2: hvd.broadcast(v, root_rank=batch_group.ranks[0],
                                   group=batch_group,
                                   name="tp.l%d.%s" % (i, k2))
                 for k2, v in lyr.items()}
                for i, lyr in enumerate(params["layers"])],
        }

    # Synthetic LM stream, deterministic per batch row: model peers in
    # one row MUST consume identical tokens.
    row = 0 if args.reference else rank // tp_size
    loss_grad = jax.value_and_grad(
        lambda p, t: forward(p, t, model_group, "tp"))

    losses = []
    for step in range(args.steps):
        if args.reference:
            toks = np.concatenate([
                np.random.RandomState(1000 + 17 * step + r).randint(
                    0, args.vocab,
                    (args.batch_per_row, args.seq_len))
                for r in range(int(os.environ.get(
                    "HVD_TPU_TP_REF_ROWS", "2")))])
        else:
            toks = np.random.RandomState(1000 + 17 * step + row).randint(
                0, args.vocab, (args.batch_per_row, args.seq_len))
        loss, grads = loss_grad(params, jnp.asarray(toks))
        if not args.reference:
            # Batch-axis sync only: replicated leaves are identical
            # across the model group already (f/g complete them), and
            # sharded leaves are exact per shard.
            grads = hvd.allreduce_gradients(grads, average=True,
                                            name_prefix="tp.grad",
                                            group=batch_group)
            # The loss is row-local; its batch-group mean matches the
            # reference's full-batch loss.
            loss = hvd.allreduce(jnp.asarray(loss), average=True,
                                 group=batch_group, name="tp.loss")
        params = jax.tree_util.tree_map(
            lambda p, g: p - args.lr * g, params, grads)
        losses.append(float(loss))
        if rank == 0:
            print("step %d loss %.6f" % (step, losses[-1]), flush=True)

    if args.loss_out and rank == 0:
        with open(args.loss_out, "w") as f:
            json.dump({"losses": losses,
                       "mode": "reference" if args.reference else
                       "mesh(k=%d)" % tp_size}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
