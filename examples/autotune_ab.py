"""Defaults-vs-autotuned A/B of the runtime knobs (VERDICT r4 item 1).

Three 4-rank localhost runs of the same gradient-bucket workload
(`tests/autotune_ab_worker.py`):

  1. defaults   — fusion 64 MB / cycle 5 ms / cache on, no tuning
  2. autotune   — HVD_TPU_AUTOTUNE=1 (+ CSV log): warmup, Bayesian
                  sampling over (fusion, cycle) x categorical combos,
                  convergence; measurement happens AFTER the tuner
                  fixes the best knobs (reference flow:
                  horovod/common/parameter_manager.cc:27-30,136-160)
  3. tuned-env  — converged knobs re-applied via HVD_TPU_FUSION_
                  THRESHOLD / HVD_TPU_CYCLE_TIME on a fresh run
                  (tuning value clean of any in-process residue)

Writes AUTOTUNE_AB_r05.json at the repo root (runs, converged knobs,
CSV sample log) and prints a summary table. CPU-plane only — safe to
run without TPU access, but it IS load-sensitive: run it alone.

Usage: python examples/autotune_ab.py [--np 4] [--iters 80]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_once(np_, extra_env, timeout=600):
    from horovod_tpu.run.util import cpu_worker_env
    env = cpu_worker_env(extra_env=extra_env, repo_root=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", str(np_),
         "--", sys.executable,
         os.path.join(REPO, "tests", "autotune_ab_worker.py")],
        env=env, timeout=timeout, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError("run failed:\n%s\n%s" %
                           (proc.stdout[-3000:], proc.stderr[-2000:]))
    # The launcher multiplexes rank stdout; the marker can land
    # mid-line after another rank's unflushed tail.
    marker = proc.stdout.find("AB_RESULT ")
    if marker < 0:
        raise RuntimeError("no AB_RESULT in output:\n%s"
                           % proc.stdout[-3000:])
    return json.JSONDecoder().raw_decode(
        proc.stdout[marker + len("AB_RESULT "):])[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=4)
    ap.add_argument("--iters", type=int, default=80)
    ap.add_argument("--tensors", type=int, default=48)
    ap.add_argument("--elems", type=int, default=32768)
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "AUTOTUNE_AB_r05.json"))
    args = ap.parse_args()

    base = {"AB_ITERS": str(args.iters), "AB_TENSORS": str(args.tensors),
            "AB_ELEMS": str(args.elems)}
    log_path = os.path.join(REPO, "autotune_ab_samples.csv")

    print("== defaults ==", file=sys.stderr)
    defaults = run_once(args.np, dict(base))

    print("== autotune ==", file=sys.stderr)
    tuned = run_once(args.np, dict(
        base, HVD_TPU_AUTOTUNE="1", HVD_TPU_AUTOTUNE_LOG=log_path),
        timeout=900)
    p = tuned["params"]

    print("== tuned knobs re-applied via env ==", file=sys.stderr)
    tuned_env = run_once(args.np, dict(
        base,
        HVD_TPU_FUSION_THRESHOLD=str(int(p["fusion_mb"] * 1024 * 1024)),
        HVD_TPU_CYCLE_TIME=str(p["cycle_time_ms"]),
        HVD_TPU_CACHE_CAPACITY=("1024" if p["cache_enabled"] else "0")))

    samples = []
    if os.path.exists(log_path):
        lines = open(log_path).read().strip().splitlines()
        samples = lines[1:]  # header first

    out = {
        "workload": {"np": args.np, "tensors_per_step": args.tensors,
                     "bytes_per_tensor": args.elems * 4,
                     "mb_per_step": args.tensors * args.elems * 4 / 1e6,
                     "measure_iters": args.iters},
        "defaults": defaults,
        "autotuned": tuned,
        "tuned_env_replay": tuned_env,
        "converged": p,
        "speedup_tuned_vs_defaults": round(
            tuned["steps_per_s"] / defaults["steps_per_s"], 3),
        "speedup_tuned_env_vs_defaults": round(
            tuned_env["steps_per_s"] / defaults["steps_per_s"], 3),
        "csv_samples": samples,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: out[k] for k in
                      ("defaults", "autotuned", "tuned_env_replay",
                       "converged", "speedup_tuned_vs_defaults",
                       "speedup_tuned_env_vs_defaults")}, indent=1))
    print("wrote %s (%d CSV samples)" % (args.out, len(samples)),
          file=sys.stderr)


if __name__ == "__main__":
    main()
