"""Estimator-era distributed training — reference analogue:
`examples/tensorflow_mnist_estimator.py`.

`tf.estimator` itself was deleted from TensorFlow (2.16+; this
environment ships 2.21), so this example reproduces the estimator
example's DISTRIBUTED semantics on the v1 graph/session API the
estimator lowered to — same structure, same horovod integration
points (reference lines cited inline):

  * a `model_fn(features, labels, mode)` returning an EstimatorSpec-
    shaped dict (loss/train_op for TRAIN, metrics for EVAL)
  * lr scaled by world size + v1 `DistributedOptimizer` wrapping
    MomentumOptimizer (ref :114-119)
  * `BroadcastGlobalVariablesHook(0)` under MonitoredTrainingSession
    (ref :185-187)
  * checkpoints written by rank 0 ONLY (ref :169-171)
  * `steps // hvd.size()` (ref :198-201), then a single-process-style
    eval pass reporting accuracy

Synthetic MNIST-shaped data (this environment has no egress; the
reference's keras download cache-race dance at :138-151 is obviated).
Self-verifying: loss must drop, ranks must agree post-broadcast, eval
accuracy must beat chance. Run:
  python -m horovod_tpu.run.run -np 2 -- \\
      python examples/tensorflow_mnist_estimator.py
"""

import argparse
import os
import sys
import tempfile

import numpy as np


def synthetic_mnist(n, seed, num_classes=10):
    """Separable MNIST-shaped data: ONE fixed set of per-class spatial
    templates (train and eval must share the task) + seeded noise."""
    templates = np.random.RandomState(42).randn(
        num_classes, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int32)
    x = templates[labels] + 0.7 * rng.randn(n, 784).astype(np.float32)
    return x.astype(np.float32), labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    import tensorflow as tf
    tf.compat.v1.disable_eager_execution()
    v1 = tf.compat.v1

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    r, size = hvd.rank(), hvd.size()

    def conv_relu(x, filters, name):
        in_ch = int(x.shape[-1])
        w = v1.get_variable(name + "_w", [5, 5, in_ch, filters],
                            initializer=v1.glorot_uniform_initializer())
        b = v1.get_variable(name + "_b", [filters],
                            initializer=v1.zeros_initializer())
        return tf.nn.relu(tf.nn.conv2d(x, w, strides=1,
                                       padding="SAME") + b)

    def dense(x, units, name, activation=None):
        w = v1.get_variable(name + "_w", [int(x.shape[-1]), units],
                            initializer=v1.glorot_uniform_initializer())
        b = v1.get_variable(name + "_b", [units],
                            initializer=v1.zeros_initializer())
        y = x @ w + b
        return activation(y) if activation else y

    def model_fn(features, labels, mode):
        """EstimatorSpec-shaped: the reference's cnn_model_fn (ref
        :32-132), shrunk to run fast on CPU and built from raw v1 ops
        (tf.compat.v1.layers is gone under Keras 3)."""
        x = tf.reshape(features, [-1, 28, 28, 1])
        h = conv_relu(x, 8, "conv1")
        h = tf.nn.max_pool2d(h, ksize=4, strides=4, padding="SAME")
        h = tf.reshape(h, [-1, 7 * 7 * 8])
        h = dense(h, 64, "dense", activation=tf.nn.relu)
        logits = dense(h, 10, "logits")
        preds = tf.argmax(logits, axis=1, output_type=tf.int32)
        if mode == "train":
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(
                    labels=labels, logits=logits))
            # lr x size + DistributedOptimizer (ref :114-119).
            opt = hvd.DistributedOptimizer(v1.train.MomentumOptimizer(
                learning_rate=0.01 * size, momentum=0.9))
            train_op = opt.minimize(
                loss, global_step=v1.train.get_or_create_global_step())
            return {"loss": loss, "train_op": train_op}
        accuracy = tf.reduce_mean(
            tf.cast(tf.equal(preds, labels), tf.float32))
        return {"accuracy": accuracy}

    # Rank-disjoint shards (the estimator example downloads per-rank
    # datasets; synthetic seeds differ per rank to the same effect).
    train_x, train_y = synthetic_mnist(2048, seed=100 + r)
    eval_x, eval_y = synthetic_mnist(512, seed=7)

    # Rank-0-only checkpoint dir (ref :169-171).
    model_dir = tempfile.mkdtemp(prefix="mnist_estimator_") \
        if r == 0 else None

    g = tf.Graph()
    with g.as_default():
        x_ph = v1.placeholder(tf.float32, [None, 784])
        y_ph = v1.placeholder(tf.int32, [None])
        with v1.variable_scope("model"):
            train_spec = model_fn(x_ph, y_ph, "train")
        with v1.variable_scope("model", reuse=True):
            eval_spec = model_fn(x_ph, y_ph, "eval")
        bcast_hook = hvd.BroadcastGlobalVariablesHook(0)

        # steps // size (ref :198-201).
        steps = max(10, args.steps // size)
        rng = np.random.RandomState(1234 + r)
        losses = []
        # checkpoint_dir on rank 0 ONLY: MonitoredTrainingSession's
        # own CheckpointSaverHook writes the checkpoint (exactly how
        # an Estimator with model_dir checkpoints; ref :169-176).
        with v1.train.MonitoredTrainingSession(
                hooks=[bcast_hook], checkpoint_dir=model_dir) as sess:
            for _ in range(steps):
                idx = rng.randint(0, len(train_x), size=args.batch_size)
                loss, _ = sess.run(
                    [train_spec["loss"], train_spec["train_op"]],
                    feed_dict={x_ph: train_x[idx], y_ph: train_y[idx]})
                losses.append(float(loss))
            acc = float(sess.run(eval_spec["accuracy"],
                                 feed_dict={x_ph: eval_x, y_ph: eval_y}))

    first, last = np.mean(losses[:10]), np.mean(losses[-10:])
    assert last < first, (first, last)
    assert acc > 0.2, acc  # 10-class chance = 0.1
    # Post-broadcast agreement: every rank evaluated the SAME model, so
    # accuracies must match bit-for-bit. (The numpy host-plane
    # allgather — the TF binding's op is symbolic under the disabled-
    # eager graph mode this example runs in.)
    import horovod_tpu as hvd_np
    gathered = hvd_np.allgather(np.asarray([acc], np.float64),
                                name="estimator_eval_acc")
    assert np.allclose(np.asarray(gathered), acc, atol=1e-12), gathered
    if r == 0:
        assert model_dir and any(
            f.startswith("model.ckpt") for f in os.listdir(model_dir)), \
            os.listdir(model_dir)
        print("eval accuracy %.3f (loss %.3f -> %.3f over %d steps x "
              "%d ranks)" % (acc, first, last, steps, size))
        print("PASS estimator_equivalent")
    print("rank %d done" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
