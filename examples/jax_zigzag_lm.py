"""Zigzag sequence-parallel language model — the causal load-balanced
ring, as a user writes it.

Contiguous causal ring attention leaves the last rank doing all the
lower-triangle work while early ranks idle; `schedule="zigzag"` splits
the sequence into 2n chunks and gives rank r chunks (r, 2n-1-r), so
every rank does equal work at every ring step (SCALING.md "Causal-run
load balance"). The recipe is three moves:

1. zigzag_shard the per-sequence arrays (tokens, positions, shifted
   labels) BEFORE feeding shard_map — the model's rotary embedding
   reads explicit global positions, so the permuted layout stays exact;
2. `TransformerConfig(attention="ring", sp_axis=..,
   sp_schedule="zigzag")`;
3. zigzag_unshard anything you read back in sequence order (here the
   loss is a mean over tokens — order-free — so nothing needs it).

Runs on whatever devices exist; for a CPU demo set
XLA_FLAGS=--xla_force_host_platform_device_count=8
HVD_TPU_PALLAS_INTERPRET=1 (the zigzag path runs the Pallas ring
kernels; interpret mode covers them off-TPU).

Run: python examples/jax_zigzag_lm.py --steps 4
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=2048,
                    help="global sequence length; per-rank shards must "
                         "be 256-multiples (two 128-aligned chunks)")
    ap.add_argument("--sp", type=int, default=4, help="ring size")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.parallel import zigzag_shard

    n = args.sp
    L = args.seq_len
    devices = jax.devices()[:n]
    if len(devices) < n:
        raise SystemExit(f"need {n} devices, have {len(devices)}")
    mesh = Mesh(np.array(devices), ("sp",))

    cfg = TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=4, embed_dim=128,
        mlp_dim=256, max_seq_len=L, dtype=jnp.float32,
        attention="ring", sp_axis="sp", sp_schedule="zigzag")
    model = Transformer(cfg)

    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (args.batch, L), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 tokens.shape)
    # Shift in NATURAL order first, then re-layout: next-token labels
    # are neighbors in sequence order, not zigzag order.
    labels = jnp.roll(tokens, -1, axis=1)
    tz, pz, lz = (zigzag_shard(x, n) for x in (tokens, positions, labels))

    # Init via a dense-attention twin (identical param structure): a
    # ring model can't trace outside shard_map (unbound axis name).
    import dataclasses
    dense_twin = Transformer(dataclasses.replace(
        cfg, attention="dense", sp_axis=None, sp_schedule="contiguous"))
    params = dense_twin.init(jax.random.PRNGKey(1),
                             tokens[:, :16])["params"]
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def local_loss(params, t, p, y):
        logits = model.apply({"params": params}, t, p)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        ll = jnp.take_along_axis(logp, y[..., None], axis=-1)
        # This rank's CONTRIBUTION to the global token mean: local sum
        # over the GLOBAL token count (y is the local shard, B x L/n,
        # so the global count is B * L). No psum inside the
        # differentiated function: under check_vma=False a psum
        # transposes to another psum and scales every cotangent by n.
        # The explicit grads psum in `step` sums contributions instead.
        return -jnp.sum(ll) / (y.shape[0] * L)

    def step(params, opt_state, t, p, y):
        loss, grads = jax.value_and_grad(local_loss)(params, t, p, y)
        # The gradient allreduce (and the loss report), safely OUTSIDE
        # the differentiated closure: summed contributions = the exact
        # global-mean gradient, identical on every rank.
        grads = jax.lax.psum(grads, "sp")
        loss = jax.lax.psum(loss, "sp")
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    f = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P(), P()), check_vma=False))

    for i in range(args.steps):
        params, opt_state, loss = f(params, opt_state, tz, pz, lz)
        print(f"step {i}: loss {float(loss):.4f}")
    print("done: zigzag ring LM trained",
          f"(sp={n}, L={L}, {L // (2 * n)}-token chunks)")


if __name__ == "__main__":
    main()
