"""TF2 eager MNIST — the reference's `examples/tensorflow2_mnist.py`
workflow (custom training loop, DistributedGradientTape, first-batch
variable broadcast, rank-scaled learning rate) on synthetic
MNIST-shaped data so no dataset download is needed."""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd

parser = argparse.ArgumentParser()
parser.add_argument("--steps", type=int, default=200)
parser.add_argument("--batch-size", type=int, default=64)
args = parser.parse_args()

hvd.init()

rng = np.random.RandomState(hvd.rank())
images = rng.rand(args.batch_size * 4, 28, 28, 1).astype(np.float32)
labels = rng.randint(0, 10, size=(args.batch_size * 4,)).astype(np.int64)
dataset = tf.data.Dataset.from_tensor_slices((images, labels)) \
    .repeat().shuffle(1000).batch(args.batch_size)

mnist_model = tf.keras.Sequential([
    tf.keras.layers.Conv2D(16, [3, 3], activation="relu"),
    tf.keras.layers.MaxPooling2D(pool_size=(2, 2)),
    tf.keras.layers.Flatten(),
    tf.keras.layers.Dense(64, activation="relu"),
    tf.keras.layers.Dense(10),
])
loss_fn = tf.losses.SparseCategoricalCrossentropy(from_logits=True)
# Scale the learning rate by the number of ranks (reference convention).
opt = tf.optimizers.Adam(0.001 * hvd.size())


@tf.function
def training_step(images, labels):
    with hvd.DistributedGradientTape() as tape:
        probs = mnist_model(images, training=True)
        loss_value = loss_fn(labels, probs)
    grads = tape.gradient(loss_value, mnist_model.trainable_variables)
    opt.apply_gradients(zip(grads, mnist_model.trainable_variables))
    return loss_value


for batch, (images, labels) in enumerate(dataset.take(args.steps)):
    loss_value = training_step(images, labels)
    if batch == 0:
        # Broadcast initial state after the first step so all ranks
        # start from rank 0's weights (and optimizer slots exist).
        hvd.broadcast_variables(mnist_model.variables, root_rank=0)
        hvd.broadcast_variables(opt.variables, root_rank=0)
    if batch % 50 == 0 and hvd.local_rank() == 0:
        print("Step #%d\tLoss: %.6f" % (batch, loss_value), flush=True)

print("rank %d done" % hvd.rank())
