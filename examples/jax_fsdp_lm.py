"""Fully-sharded data-parallel (ZeRO-3-style) LM training via GSPMD.

The scaling-book recipe as a user writes it: the UNMODIFIED
single-device transformer, `make_fsdp_train_step` sharding params /
gradients / optimizer state over the dp mesh through jit shardings —
XLA inserts the all-gather-before-use and reduce-scatter collectives
and overlaps them with compute. No shard_map, no axis names, no
collective calls in user code.

Run: python examples/jax_fsdp_lm.py --steps 8
(CPU demo: XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.parallel import (data_parallel_mesh,
                                      make_fsdp_train_step)

    mesh = data_parallel_mesh()
    print("fsdp over %d devices" % len(mesh.devices.ravel()))

    cfg = TransformerConfig(vocab_size=512, num_layers=4, num_heads=4,
                            embed_dim=128, mlp_dim=256,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    rng = np.random.RandomState(0)
    tokens_all = rng.randint(
        0, 512, size=(args.steps, args.batch, args.seq_len))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(tokens_all[0][:1]))["params"]

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["tokens"])
        tgt = jnp.roll(batch["tokens"], -1, axis=1)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))

    opt = optax.adam(3e-3)
    step = make_fsdp_train_step(loss_fn, opt, mesh, donate=False)
    p, s, b = step.place(params,
                         batch={"tokens": jnp.asarray(tokens_all[0])})

    first = last = None
    for i in range(args.steps):
        # jit's in_shardings lay out fresh host batches automatically.
        p, s, loss = step(p, s, {"tokens": jnp.asarray(tokens_all[i])})
        last = float(loss)
        first = first if first is not None else last
        print("step %d loss %.4f" % (i, last))
    assert np.isfinite(last) and last < first, (first, last)
    print("done")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
