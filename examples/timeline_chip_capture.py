"""On-chip observability artifact capture (VERDICT r4 item 6).

Launches a 2-rank job where rank 0 trains on the REAL TPU (axon
tunnel) and rank 1 on CPU, gradients allreduced through the host core
with the chrome-trace timeline live (HVD_TPU_TIMELINE +
MARK_CYCLES) and the stall inspector armed at a 2-second threshold —
a mid-run straggler step then makes the coordinator warn during the
live chip-attached loop. Writes:

  * artifacts/timeline_chip_r05.json — the chrome trace (loads in
    Perfetto / chrome://tracing; NEGOTIATE_ALLREDUCE, ALLREDUCE
    state machine, CYCLE_START markers)
  * artifacts/timeline_chip_r05.log — the launcher output with the
    stall-inspector warning and each rank's backend line

Verifies in-process: the trace parses record-wise, carries the
NEGOTIATE/op/cycle markers, rank 0 really ran on the TPU, and the
stall warning names the missing rank. docs/TIMELINE.md walks the
artifact. Usage: python examples/timeline_chip_capture.py
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    from horovod_tpu.run.util import cpu_worker_env

    art_dir = os.path.join(REPO, "artifacts")
    os.makedirs(art_dir, exist_ok=True)
    trace = os.path.join(art_dir, "timeline_chip_r05.json")
    logf = os.path.join(art_dir, "timeline_chip_r05.log")

    pool = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    env = cpu_worker_env(extra_env={
        "HVD_TPU_TIMELINE": trace,
        "HVD_TPU_TIMELINE_MARK_CYCLES": "1",
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "2",
        # The worker re-injects this for rank 0 only.
        "HVD_TPU_AXON_SAVED": pool,
    }, repo_root=REPO)
    if not pool:
        print("warning: no PALLAS_AXON_POOL_IPS — rank 0 will run on "
              "CPU too (artifact will not be chip-attached)",
              file=sys.stderr)

    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "2", "--",
         sys.executable, os.path.join(REPO, "tests",
                                      "timeline_chip_worker.py")],
        env=env, timeout=600, capture_output=True, text=True)
    out = proc.stdout + proc.stderr
    with open(logf, "w") as f:
        f.write(out)
    if proc.returncode != 0:
        print(out[-4000:])
        raise RuntimeError("capture job failed (rc=%d)" % proc.returncode)

    content = open(trace).read()
    for marker in ("NEGOTIATE_ALLREDUCE", "ALLREDUCE", "CYCLE_START"):
        assert marker in content, "trace missing %s" % marker
    records = 0
    for line in content.splitlines():
        line = line.strip().rstrip(",")
        if line in ("[", "") or line.startswith("]"):
            continue
        json.loads(line)
        records += 1
    # The deliberate rank-1 straggle must be the detected stall (a
    # rank-0 compile stall may additionally appear first).
    assert "missing ranks: 1" in out, \
        "no stall-inspector warning naming the straggler in output"
    assert "CHIP_BACKEND tpu" in out or not pool, \
        "rank 0 did not run on the TPU:\n" + out[-2000:]

    print("wrote %s (%d records) and %s" % (trace, records, logf))
    print("stall warning captured; rank-0 backend: %s" %
          ("tpu" if "CHIP_BACKEND tpu" in out else "cpu"))


if __name__ == "__main__":
    main()
