"""TF1 graph-mode MNIST — the reference's `examples/tensorflow_mnist.py`
workflow: graph built once, `MonitoredTrainingSession` with
`BroadcastGlobalVariablesHook` + `StopAtStepHook`, rank-scaled
learning rate, checkpoints only on rank 0. Synthetic MNIST-shaped data
(no download); eager is disabled process-wide, so run standalone."""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch-size", type=int, default=64)
    args = parser.parse_args()

    tf.compat.v1.disable_eager_execution()
    v1 = tf.compat.v1

    hvd.init()
    rng = np.random.RandomState(hvd.rank())

    with tf.Graph().as_default():
        images = v1.placeholder(tf.float32, [None, 784], name="images")
        labels = v1.placeholder(tf.int64, [None], name="labels")

        # v1.layers is gone under Keras 3; plain variables + matmul is
        # the graph-mode-native way.
        w1 = v1.get_variable("w1", [784, 64],
                             initializer=v1.glorot_uniform_initializer())
        b1 = v1.get_variable("b1", [64],
                             initializer=v1.zeros_initializer())
        hidden = tf.nn.relu(tf.matmul(images, w1) + b1)
        w2 = v1.get_variable("w2", [64, 10],
                             initializer=v1.glorot_uniform_initializer())
        b2 = v1.get_variable("b2", [10],
                             initializer=v1.zeros_initializer())
        logits = tf.matmul(hidden, w2) + b2
        loss = v1.losses.sparse_softmax_cross_entropy(labels, logits)

        # Scale the learning rate by the number of ranks (reference
        # convention), wrap in the distributed optimizer.
        opt = v1.train.GradientDescentOptimizer(0.01 * hvd.size())
        global_step = v1.train.get_or_create_global_step()
        grads_and_vars = opt.compute_gradients(loss)
        grads_and_vars = [
            (hvd.allreduce(g, name="gr.%d" % i) if g is not None else g, v)
            for i, (g, v) in enumerate(grads_and_vars)]
        train_op = opt.apply_gradients(grads_and_vars,
                                       global_step=global_step)

        hooks = [
            hvd.BroadcastGlobalVariablesHook(0),
            v1.train.StopAtStepHook(last_step=args.steps),
        ]
        with v1.train.MonitoredTrainingSession(hooks=hooks) as sess:
            step = 0
            while not sess.should_stop():
                x = rng.rand(args.batch_size, 784).astype(np.float32)
                y = rng.randint(0, 10, size=(args.batch_size,))
                _, l = sess.run([train_op, loss],
                                feed_dict={images: x, labels: y})
                if step % 50 == 0 and hvd.rank() == 0:
                    print("Step #%d\tLoss: %.6f" % (step, l), flush=True)
                step += 1

    print("rank %d done" % hvd.rank())
    return 0


if __name__ == "__main__":
    main()
