"""ResNet synthetic benchmark — reference analogue:
`examples/tensorflow2_synthetic_benchmark.py:110-131` (same measurement
protocol: warmup, N rounds x M iters, `Img/sec per device` mean ± 1.96σ).

Run single chip:   python examples/jax_synthetic_benchmark.py
All local devices train over a 1-D data-parallel mesh automatically.
`bench.py` at the repo root is the driver-facing JSON wrapper around the
same loop.
"""

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet18", "resnet34", "resnet50",
                             "resnet101", "resnet152"])
    ap.add_argument("--batch-size", type=int, default=64,
                    help="per-device batch size")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-warmup-batches", type=int, default=10)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--num-batches-per-iter", type=int, default=10)
    ap.add_argument("--fp32", action="store_true",
                    help="disable bf16 compute")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import models
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step
    from horovod_tpu.parallel.train import cross_entropy_loss

    devices = jax.devices()
    n = len(devices)
    model_cls = getattr(models, args.model.replace("resnet", "ResNet"))
    model = model_cls(num_classes=1000,
                      dtype=jnp.float32 if args.fp32 else jnp.bfloat16)

    rng = jax.random.PRNGKey(0)
    s = args.image_size
    variables = model.init(rng, jnp.zeros((1, s, s, 3)), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(params, batch):
        logits, _ = model.apply(
            {"params": params, "batch_stats": batch_stats}, batch["x"],
            train=True, mutable=["batch_stats"])
        return cross_entropy_loss(logits, batch["y"])

    mesh = data_parallel_mesh(devices=devices)
    step = make_train_step(loss_fn, optax.sgd(0.01, momentum=0.9), mesh)

    global_batch = args.batch_size * n
    x = jax.random.normal(rng, (global_batch, s, s, 3), jnp.float32)
    y = jax.random.randint(rng, (global_batch,), 0, 1000)
    params_p, opt_state, batch = step.place(params, optax.sgd(
        0.01, momentum=0.9).init(params), {"x": x, "y": y})

    print("Model: %s, batch size/device: %d, devices: %d (%s)" %
          (args.model, args.batch_size, n, devices[0].platform))

    # float(loss) is a true end-of-chain barrier (each loss depends on
    # every prior step's params); block_until_ready alone is not reliable
    # over remote-device transports.
    for _ in range(args.num_warmup_batches):
        params_p, opt_state, loss = step(params_p, opt_state, batch)
    float(loss)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params_p, opt_state, loss = step(params_p, opt_state, batch)
        float(loss)
        dt = time.perf_counter() - t0
        rate = global_batch * args.num_batches_per_iter / dt / n
        img_secs.append(rate)
        print("Iter #%d: %.1f img/sec per device" % (i, rate))

    mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    print("Img/sec per device: %.1f +-%.1f" % (mean, conf))
    print("Total img/sec on %d device(s): %.1f +-%.1f" %
          (n, n * mean, n * conf))


if __name__ == "__main__":
    main()
