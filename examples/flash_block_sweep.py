"""Flash-kernel block-size sweep at a given attention shape.

The default (bq=256, bk=512) was tuned at D=128; the GPT-2-shaped
bench runs D=64 H=12 where the VMEM budget and the VPU/MXU balance
differ. Sweeps (block_q, block_k) for fwd and fwd+bwd with the
single-dispatch lax.scan recipe and prints a table.

Usage: python examples/flash_block_sweep.py [--B 8 --L 2048 --H 12 --D 64]
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import importlib

# The ops package re-exports the flash_attention FUNCTION under the
# same name; import the module itself for the block-size internals.
fa = importlib.import_module("horovod_tpu.ops.flash_attention")


def timed(fn, args, iters=30):
    def body(carry, _):
        out = fn(*carry)
        if isinstance(out, tuple):
            out = out[0]
        return (carry[0] + 1e-30 * out,) + carry[1:], ()

    def run(*args):
        carry, _ = lax.scan(body, args, None, length=iters)
        return jnp.sum(carry[0].astype(jnp.float32))

    jitted = jax.jit(run)
    float(jitted(*args))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(jitted(*args))
        times.append((time.perf_counter() - t0) / iters)
    return sorted(times)[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--B", type=int, default=8)
    ap.add_argument("--L", type=int, default=2048)
    ap.add_argument("--H", type=int, default=12)
    ap.add_argument("--D", type=int, default=64)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()
    B, L, H, D = args.B, args.L, args.H, args.D

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    g = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    scale = D ** -0.5

    print("shape B=%d L=%d H=%d D=%d (kernel layout)" % (B, L, H, D))
    print("%8s %8s | %9s | %9s" % ("bq", "bk", "fwd ms", "fwd+bwd ms"))
    for bq in (128, 256, 512):
        for bk in (256, 512, 1024):
            if L % bq or L % bk:
                continue
            try:
                fwd = functools.partial(
                    fa._pallas_forward, scale=scale, causal=True,
                    interpret=False, block_q=bq, block_k=bk)
                t_fwd = timed(lambda q: fwd(q, k, v), (q,), args.iters)

                def fb(q, k, v, g, bq=bq, bk=bk):
                    out, lse = fa._pallas_forward_lse(
                        q, k, v, scale, True, False, bq, bk)
                    dq, dk, dv = fa._pallas_backward(
                        q, k, v, out, lse, g, scale, True, False, bq, bk)
                    return dq + dk + dv

                t_fb = timed(lambda q: fb(q, k, v, g), (q,), args.iters)
                print("%8d %8d | %9.3f | %9.3f" %
                      (bq, bk, t_fwd * 1e3, t_fb * 1e3))
            except Exception as e:
                print("%8d %8d | failed: %s" % (bq, bk, str(e)[:60]))


if __name__ == "__main__":
    main()
