"""Flash-kernel block-size sweep at a given attention shape.

The default (bq=256, bk=512) was tuned at D=128; the GPT-2-shaped
bench runs D=64 H=12 where the VMEM budget and the VPU/MXU balance
differ. Sweeps (block_q, block_k) for fwd and fwd+bwd with the
single-dispatch lax.scan recipe and prints a table.

Usage: python examples/flash_block_sweep.py [--B 8 --L 2048 --H 12 --D 64]
GQA/MQA (--G < --H) sweeps the grouped-rows layout: the q-block
candidates become bqp*group rows. The `_grouped_blocks` policy was
tuned from this sweep at two points — B2 H6 G2 L8192 D128 (1536/512)
and B2 H12 G3 L8192 D64 (2048/512; 2048/1024 overflows VMEM) —
grouped layouts want bigger row blocks and bk=512 at long L.
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import importlib

# The ops package re-exports the flash_attention FUNCTION under the
# same name; import the module itself for the block-size internals.
fa = importlib.import_module("horovod_tpu.ops.flash_attention")


def timed(fn, args, iters=30):
    def body(carry, _):
        out = fn(*carry)
        if isinstance(out, tuple):
            out = out[0]
        # Cast: fwd returns a bf16 tensor but the fwd+bwd probe
        # returns an f32 scalar, which would promote the carry.
        return (carry[0] + (1e-30 * out).astype(carry[0].dtype),) \
            + carry[1:], ()

    def run(*args):
        carry, _ = lax.scan(body, args, None, length=iters)
        return jnp.sum(carry[0].astype(jnp.float32))

    jitted = jax.jit(run)
    float(jitted(*args))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(jitted(*args))
        times.append((time.perf_counter() - t0) / iters)
    return sorted(times)[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--B", type=int, default=8)
    ap.add_argument("--L", type=int, default=2048)
    ap.add_argument("--H", type=int, default=12)
    ap.add_argument("--G", type=int, default=0,
                    help="kv heads (GQA/MQA; 0 = H, plain MHA). The "
                         "q-block candidates become bqp*group rows in "
                         "the grouped layout")
    ap.add_argument("--D", type=int, default=64)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()
    B, L, H, D = args.B, args.L, args.H, args.D
    G = args.G or H
    group = H // G

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, G, L, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, G, L, D), jnp.bfloat16)
    g = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    scale = D ** -0.5
    rows = L * group

    print("shape B=%d L=%d H=%d G=%d D=%d (kernel layout, %d rows/slab)"
          % (B, L, H, G, D, rows))
    print("%8s %8s | %9s | %9s" % ("bq", "bk", "fwd ms", "fwd+bwd ms"))
    for bqp in (128, 256, 512):
        bq = bqp * group
        for bk in (256, 512, 1024):
            if rows % bq or L % bk or L % bqp:
                continue
            try:
                fwd = functools.partial(
                    fa._pallas_forward, scale=scale, causal=True,
                    interpret=False, block_q=bq, block_k=bk)
                t_fwd = timed(lambda q: fwd(q, k, v), (q,), args.iters)

                def fb(q, k, v, g, bq=bq, bk=bk):
                    out, lse = fa._pallas_forward_lse(
                        q, k, v, scale, True, False, bq, bk)
                    dq, dk, dv = fa._pallas_backward(
                        q, k, v, out, lse, g, scale, True, False, bq, bk)
                    # All three grads live (dq/dk shapes differ under
                    # GQA; a dead output would let XLA drop a kernel).
                    return (jnp.sum(dq.astype(jnp.float32)) +
                            jnp.sum(dk.astype(jnp.float32)) +
                            jnp.sum(dv.astype(jnp.float32)))

                t_fb = timed(lambda q: fb(q, k, v, g), (q,), args.iters)
                print("%8d %8d | %9.3f | %9.3f" %
                      (bq, bk, t_fwd * 1e3, t_fb * 1e3))
            except Exception as e:
                print("%8d %8d | failed: %s" % (bq, bk, str(e)[:60]))


if __name__ == "__main__":
    main()
