"""TF2 synthetic benchmark over the TensorFlow binding — the reference's
flagship example config (`examples/tensorflow2_synthetic_benchmark.py`,
BASELINE.json config #2) rebuilt for horovod_tpu: Keras ResNet-50 on
synthetic ImageNet-shaped data, DistributedGradientTape with the
compiled custom-op collectives, warmup + timed batches, `Img/sec per
rank` with the mean +/- 1.96 sigma summary the reference prints.

Note: this exercises the TF-on-host-CPU compatibility surface (the TF
binding's role here); for TPU-resident XLA training use `bench.py` /
the jax binding.

Run: python -m horovod_tpu.run.run -np 2 -- \
         python examples/tensorflow2_synthetic_benchmark.py
"""

import argparse
import os
import timeit

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="ResNet50",
                    help="any keras.applications model name")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--num-warmup-batches", type=int, default=10)
    ap.add_argument("--num-batches-per-iter", type=int, default=10)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--fp16-allreduce", action="store_true")
    args = ap.parse_args()

    hvd.init()
    import keras

    keras.utils.set_random_seed(42)
    model = getattr(keras.applications, args.model)(
        weights=None, input_shape=(args.image_size, args.image_size, 3),
        classes=args.num_classes)
    opt = keras.optimizers.SGD(0.01)
    loss_fn = keras.losses.SparseCategoricalCrossentropy(from_logits=False)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)

    rng = np.random.RandomState(hvd.rank())
    data = tf.constant(rng.randn(args.batch_size, args.image_size,
                                 args.image_size, 3).astype(np.float32))
    target = tf.constant(rng.randint(0, args.num_classes,
                                     args.batch_size).astype(np.int64))

    @tf.function
    def benchmark_step():
        with hvd.DistributedGradientTape(
                compression=compression) as tape:
            probs = model(data, training=True)
            loss = loss_fn(target, probs)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

    # Consistent start across ranks (the reference broadcasts after the
    # first step so optimizer slots exist).
    benchmark_step()
    hvd.broadcast_variables(model.variables, root_rank=0)
    hvd.broadcast_variables(opt.variables, root_rank=0)

    if hvd.rank() == 0:
        print("Model: %s, batch size %d, %d ranks"
              % (args.model, args.batch_size, hvd.size()), flush=True)

    timeit.timeit(benchmark_step, number=args.num_warmup_batches)

    img_secs = []
    for i in range(args.num_iters):
        t = timeit.timeit(benchmark_step,
                          number=args.num_batches_per_iter)
        img_sec = args.batch_size * args.num_batches_per_iter / t
        if hvd.rank() == 0:
            print("Iter #%d: %.1f img/sec per rank" % (i, img_sec),
                  flush=True)
        img_secs.append(img_sec)

    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        print("Img/sec per rank: %.1f +- %.1f"
              % (img_sec_mean, img_sec_conf), flush=True)
        print("Total img/sec on %d rank(s): %.1f +- %.1f"
              % (hvd.size(), hvd.size() * img_sec_mean,
                 hvd.size() * img_sec_conf), flush=True)
        print("done", flush=True)


if __name__ == "__main__":
    main()
