"""Protocol-scale extension capture: 256 -> 1024 REAL rank processes
(VERDICT r4 item 5). For each size, the bucket32 gradient-step shape
(32 long-named async allreduces per step) in cached and uncached
modes, recording per-step control bytes (coordinator + representative
worker), cycle kinds, and the coordinator's CPU time per work cycle
(user+sys of the rank-0 process — on a 1-core host wall clock measures
the OS scheduler; CPU time measures the protocol, and its growth with
n pins the O(n) constant of the fast path).

Writes SCALING_EVIDENCE_1024_r05.json. Run alone (heavily
load-sensitive; the 1024-rank size spawns 1024 real processes).

Usage: python examples/protocol_scale_1024.py [--sizes 256,512,1024]
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (the negotiation-bench launcher lives there)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="256,512,1024")
    ap.add_argument("--out", default=os.path.join(
        REPO, "SCALING_EVIDENCE_1024_r05.json"))
    args = ap.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]

    rows = []
    for n in sizes:
        iters = max(4, 2048 // n)
        env = {"HVD_TPU_BENCH_TENSORS": "32"}
        if n >= 1024:
            # One-core 1024-process oversubscription: shrink warmup
            # (each step is a full fleet round-robin) and widen the
            # coordinator's blocking-poll window past scheduler
            # starvation bursts.
            env["HVD_TPU_BENCH_WARMUP"] = "4"
            env["HVD_TPU_CONTROL_POLL_TIMEOUT_SECONDS"] = "600"
        print("== n=%d (iters=%d) ==" % (n, iters), file=sys.stderr)
        try:
            _, c_ctr = bench._run_negotiation_bench(n, iters, env,
                                                    timeout=3600)
            _, u_ctr = bench._run_negotiation_bench(
                n, max(3, iters // 2),
                dict(env, HVD_TPU_CACHE_CAPACITY="0"), timeout=3600)
        except Exception as e:  # keep completed sizes on a failure
            rows.append({"ranks": n, "error": str(e)[:400]})
            print("n=%d FAILED: %s" % (n, str(e)[:200]), file=sys.stderr)
            continue

        def per_step(ctr, rank):
            d = ctr.get(rank)
            if not d or not d.get("iters"):
                return None
            return round((d["ctrl_bytes_sent"] + d["ctrl_bytes_recv"])
                         / d["iters"], 1)

        row = {
            "ranks": n,
            "bucket32_cached_bytes_per_step_coord": per_step(c_ctr, 0),
            "bucket32_uncached_bytes_per_step_coord": per_step(u_ctr, 0),
            "bucket32_cached_bytes_per_step_worker": per_step(c_ctr, 1),
            "bucket32_uncached_bytes_per_step_worker": per_step(u_ctr, 1),
            "cached_cycle_kinds": {
                "fast": c_ctr.get(0, {}).get("cycles_fast"),
                "full": c_ctr.get(0, {}).get("cycles_full")},
            "cached_coord_cpu_us_per_cycle": bench._cpu_per_cycle(c_ctr),
            "uncached_coord_cpu_us_per_cycle": bench._cpu_per_cycle(u_ctr),
        }
        rows.append(row)
        print(json.dumps(row), file=sys.stderr)

    out = {"metric": "protocol_scale_extension", "rows": rows,
           "host_cores": os.cpu_count()}
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
