"""Data-parallel skip-gram word2vec — reference analogue:
`examples/tensorflow_word2vec.py` (BASELINE.json config #4: exercises the
allgather + broadcast paths through sparse embedding gradients).

Run: python -m horovod_tpu.run.run -np 2 -- python examples/jax_word2vec.py
Synthetic Zipf-distributed corpus (no network egress in this environment).
"""

import argparse

import numpy as np


def synthetic_corpus(vocab_size, n_tokens=100000, seed=0):
    rng = np.random.RandomState(seed)
    # Zipf-ish unigram distribution like natural text.
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    return rng.choice(vocab_size, size=n_tokens, p=p).astype(np.int32)


def batches(corpus, batch_size, window, rng):
    centers = rng.randint(window, len(corpus) - window, size=batch_size)
    offsets = rng.randint(1, window + 1, size=batch_size) * \
        rng.choice([-1, 1], size=batch_size)
    return corpus[centers], corpus[centers + offsets]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab-size", type=int, default=5000)
    ap.add_argument("--embedding-dim", type=int, default=64)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-neg", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax
    from horovod_tpu.jax.sparse import allreduce_sparse, apply_sparse
    from horovod_tpu.models import SkipGram

    hvd.init()
    rank, world = hvd.rank(), hvd.size()

    model = SkipGram(vocab_size=args.vocab_size,
                     embedding_dim=args.embedding_dim)
    rng_np = np.random.RandomState(1234 + rank)  # distinct samples per rank
    corpus = synthetic_corpus(args.vocab_size)

    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1,), jnp.int32))["params"]
    # Consistent init across ranks (broadcast path).
    params = hvd_jax.broadcast_parameters(params, root_rank=0)

    @jax.jit
    def loss_and_grads(params, center, context, neg):
        def loss_fn(p):
            return model.apply({"params": p}, center, context, neg,
                               method=SkipGram.nce_loss)
        return jax.value_and_grad(loss_fn)(params)

    for step in range(args.steps):
        center, context = batches(corpus, args.batch_size, 2, rng_np)
        neg = rng_np.randint(0, args.vocab_size,
                             size=args.num_neg).astype(np.int32)
        loss, grads = loss_and_grads(params, jnp.asarray(center),
                                     jnp.asarray(context), jnp.asarray(neg))

        # Embedding-table grads are sparse: only the touched rows are
        # nonzero. Ship (indices, values) via the allgather path instead
        # of densifying — the IndexedSlices analogue.
        emb_grad = grads["embedding"]["embedding"]
        touched = np.unique(center)
        idx, vals = allreduce_sparse(
            jnp.asarray(touched),
            emb_grad[jnp.asarray(touched)],
            name="w2v.emb.%d" % step, average=True)
        new_emb = apply_sparse(params["embedding"]["embedding"],
                               idx, vals, scale=-args.lr)
        params["embedding"]["embedding"] = new_emb

        # NCE weights/biases: dense allreduce like any other gradient.
        for key in ("nce_weight", "nce_bias"):
            g = hvd_jax.allreduce(grads[key], average=True,
                                  name="w2v.%s.%d" % (key, step))
            params[key] = params[key] - args.lr * g

        if step % 50 == 0:
            avg = hvd_jax.metric_average(float(loss), "w2v_loss.%d" % step)
            if rank == 0:
                print("step %d: loss=%.4f" % (step, avg))

    if rank == 0:
        nearest = model.apply({"params": params}, jnp.arange(3), 4,
                              method=SkipGram.nearest)
        print("nearest neighbours of tokens 0..2:", np.asarray(nearest))
        print("done")


if __name__ == "__main__":
    main()
