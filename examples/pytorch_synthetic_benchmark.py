"""PyTorch synthetic benchmark — CLI/output parity with the
reference's `examples/pytorch_synthetic_benchmark.py` (same flags,
same "Img/sec per rank" report), rewritten for the CPU-torch +
horovod_tpu host-core path (TPU-resident training belongs to the jax
binding; this exercises the torch binding end to end)."""

import argparse
import timeit

import numpy as np
import torch
import torch.nn.functional as F
import torch.optim as optim

import horovod_tpu.torch as hvd

try:
    from torchvision import models as _models
except ImportError:  # torchvision absent: use the sibling example's net
    _models = None

parser = argparse.ArgumentParser(
    description="PyTorch Synthetic Benchmark",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--fp16-allreduce", action="store_true", default=False,
                    help="use fp16 compression during allreduce")
parser.add_argument("--model", type=str, default="resnet50",
                    help="model to benchmark")
parser.add_argument("--batch-size", type=int, default=32,
                    help="input batch size")
parser.add_argument("--image-size", type=int, default=224)
parser.add_argument("--num-classes", type=int, default=1000)
parser.add_argument("--num-warmup-batches", type=int, default=10,
                    help="number of warm-up batches")
parser.add_argument("--num-batches-per-iter", type=int, default=10,
                    help="number of batches per benchmark iteration")
parser.add_argument("--num-iters", type=int, default=10,
                    help="number of benchmark iterations")
args = parser.parse_args()

hvd.init()
torch.manual_seed(42 + hvd.rank())

if _models is not None:
    model = getattr(_models, args.model)(num_classes=args.num_classes)
elif args.model == "resnet50":
    from pytorch_imagenet_resnet50 import ResNet50
    model = ResNet50(num_classes=args.num_classes)
else:
    raise SystemExit("torchvision is unavailable; only --model resnet50 "
                     "has a built-in fallback")
optimizer = optim.SGD(model.parameters(), lr=0.01)

compression = (hvd.Compression.fp16 if args.fp16_allreduce
               else hvd.Compression.none)
optimizer = hvd.DistributedOptimizer(
    optimizer, named_parameters=model.named_parameters(),
    compression=compression)

hvd.broadcast_parameters(model.state_dict(), root_rank=0)
hvd.broadcast_optimizer_state(optimizer, root_rank=0)

data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
target = torch.randint(0, args.num_classes, (args.batch_size,))


def benchmark_step():
    optimizer.zero_grad()
    output = model(data)
    loss = F.cross_entropy(output, target)
    loss.backward()
    optimizer.step()


def log(s):
    if hvd.rank() == 0:
        print(s, flush=True)


log("Model: %s" % args.model)
log("Batch size: %d" % args.batch_size)

timeit.timeit(benchmark_step, number=args.num_warmup_batches)

img_secs = []
for x in range(args.num_iters):
    time = timeit.timeit(benchmark_step, number=args.num_batches_per_iter)
    img_sec = args.batch_size * args.num_batches_per_iter / time
    log("Iter #%d: %.1f img/sec per rank" % (x, img_sec))
    img_secs.append(img_sec)

img_sec_mean = np.mean(img_secs)
img_sec_conf = 1.96 * np.std(img_secs)
log("Img/sec per rank: %.1f +-%.1f" % (img_sec_mean, img_sec_conf))
log("Total img/sec on %d rank(s): %.1f +-%.1f" %
    (hvd.size(), hvd.size() * img_sec_mean, hvd.size() * img_sec_conf))
print("rank %d done" % hvd.rank())
