"""Pipeline-parallel language model on a (dp x pp) device mesh.

The pp member of the parallelism family end to end, as a user writes
it: transformer blocks stage-stacked and sharded over `pp`
(`stack_block_params` + `pipeline_apply`'s GPipe schedule), embedding
and norm/head replicated outside the pipelined region, and the
pipeline gradient contract applied exactly as pinned by
tests/test_pipeline.py: local loss scaled by 1/pp, non-staged param
grads psum'd over pp (plus the usual pmean over dp).

Runs on whatever devices exist; for a CPU demo set
XLA_FLAGS=--xla_force_host_platform_device_count=8.

Run: python examples/jax_pp_lm.py --steps 8
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16,
                    help="global batch (sequences)")
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--pp", type=int, default=2, help="pipeline stages")
    ap.add_argument("--microbatches", type=int, default=2)
    args = ap.parse_args()

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.models.transformer import Block
    from horovod_tpu.parallel import (hybrid_mesh, pipeline_apply,
                                      stack_block_params)

    devices = jax.devices()
    n = len(devices)
    pp = args.pp
    dp = n // pp
    if dp * pp != n or args.layers % pp:
        raise SystemExit("need dp*pp == %d devices and pp | layers" % n)
    mesh = hybrid_mesh((dp, pp), ("dp", "pp"), devices=devices)
    print("mesh: dp=%d x pp=%d over %d devices" % (dp, pp, n))

    cfg = TransformerConfig(vocab_size=256, num_layers=args.layers,
                            num_heads=4, embed_dim=64, mlp_dim=128,
                            dtype=jnp.float32)
    block = Block(cfg)
    mb = args.microbatches
    B_local, L = args.batch // dp, args.seq_len

    rng = np.random.RandomState(0)
    tokens_all = rng.randint(0, 256,
                             size=(args.steps, args.batch, L))

    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.asarray(tokens_all[0][:1]))["params"]
    staged = jax.tree_util.tree_map(
        lambda x: x.reshape((pp, args.layers // pp) + x.shape[1:]),
        stack_block_params(params, cfg.num_layers))
    staged_specs = jax.tree_util.tree_map(lambda _: P("pp"), staged)
    rest = {k: params[k] for k in ("embed", "norm_f", "lm_head")}
    rest_specs = jax.tree_util.tree_map(lambda _: P(), rest)

    opt = optax.adam(3e-3)
    opt_state = (opt.init(staged), opt.init(rest))
    opt_specs = (
        (optax.ScaleByAdamState(count=P(), mu=staged_specs,
                                nu=staged_specs), optax.EmptyState()),
        (optax.ScaleByAdamState(count=P(), mu=rest_specs,
                                nu=rest_specs), optax.EmptyState()),
    )
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 (B_local // mb, L))

    def stage_fn(stage_params, x):
        def layer(x, p):
            return block.apply({"params": p}, x, positions), None
        return lax.scan(layer, x, stage_params)[0]

    def forward(staged_local, rest, tokens):
        local = jax.tree_util.tree_map(lambda x: x[0], staged_local)
        emb = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                       param_dtype=jnp.float32, dtype=cfg.dtype)
        x = emb.apply({"params": rest["embed"]}, tokens)
        x_mb = x.reshape((mb, B_local // mb) + x.shape[1:])
        y = pipeline_apply(stage_fn, local, x_mb, "pp")
        y = y.reshape((B_local,) + y.shape[2:])
        y = nn.RMSNorm(dtype=cfg.dtype, param_dtype=jnp.float32).apply(
            {"params": rest["norm_f"]}, y)
        return (y @ rest["lm_head"]["kernel"].astype(y.dtype)) \
            .astype(jnp.float32)

    def step(staged_local, rest, opt_state, tokens):
        def loss_fn(staged_local, rest):
            logits = forward(staged_local, rest, tokens)
            tgt = jnp.roll(tokens, -1, axis=1)
            logp = jax.nn.log_softmax(logits)
            xent = -jnp.mean(
                jnp.take_along_axis(logp, tgt[..., None], axis=-1))
            # Pipeline gradient contract part 1: local loss / pp.
            return xent / lax.psum(1, "pp")

        loss, (g_staged, g_rest) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(staged_local, rest)
        # Contract part 2: non-staged grads psum over pp; then the
        # usual data-parallel mean over dp for everything.
        g_rest = jax.tree_util.tree_map(
            lambda g: lax.psum(g, "pp"), g_rest)
        g_staged, g_rest = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, "dp"), (g_staged, g_rest))
        us, os0 = opt.update(g_staged, opt_state[0], staged_local)
        ur, os1 = opt.update(g_rest, opt_state[1], rest)
        staged_local = optax.apply_updates(staged_local, us)
        rest = optax.apply_updates(rest, ur)
        # Report the UN-scaled loss (psum undoes the 1/pp).
        loss = lax.pmean(lax.psum(loss, "pp"), "dp")
        return staged_local, rest, (os0, os1), loss

    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(staged_specs, rest_specs, opt_specs, P("dp")),
        out_specs=(staged_specs, rest_specs, opt_specs, P()),
        check_vma=False))

    put = lambda tree, specs: jax.tree_util.tree_map(  # noqa: E731
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, specs)
    staged = put(staged, staged_specs)
    rest = put(rest, rest_specs)
    opt_state = put(opt_state, opt_specs)

    first = last = None
    for i in range(args.steps):
        staged, rest, opt_state, loss = mapped(
            staged, rest, opt_state, jnp.asarray(tokens_all[i]))
        last = float(loss)
        first = first if first is not None else last
        print("step %d loss %.4f" % (i, last))
    assert np.isfinite(last) and last < first, (first, last)
    print("done")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
