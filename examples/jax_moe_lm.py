"""Expert-parallel Switch-MoE language model on a (dp x ep) device mesh.

The ep member of the parallelism family end to end, as a user would
write it: expert weights sharded over the `ep` mesh axis
(`ep_param_specs`), tokens sharded over BOTH axes (each device routes
its own shard; the MoE all_to_all exchanges token slots for local
experts), gradients synchronized with `ep_grad_sync` (LOCAL loss +
explicit sync — see parallel/expert.py), and the Switch load-balancing
aux loss wired into the objective.

Runs on whatever devices exist: a TPU slice uses the real chips; for a
CPU demo set XLA_FLAGS=--xla_force_host_platform_device_count=8.

Run: python examples/jax_moe_lm.py --steps 10
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=16,
                    help="global batch (sequences)")
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--ep", type=int, default=0,
                    help="expert-parallel degree (0 = half the devices)")
    ap.add_argument("--aux-weight", type=float, default=0.01)
    args = ap.parse_args()

    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.parallel import (ep_grad_sync, ep_param_specs,
                                      hybrid_mesh)

    devices = jax.devices()
    n = len(devices)
    ep = args.ep or max(1, n // 2)
    dp = n // ep
    if dp * ep != n:
        raise SystemExit("need dp*ep == device count (%d)" % n)
    if args.experts % ep:
        raise SystemExit("--experts must be divisible by ep=%d" % ep)
    mesh = hybrid_mesh((dp, ep), ("dp", "ep"), devices=devices)
    print("mesh: dp=%d x ep=%d over %d devices" % (dp, ep, n))

    base = TransformerConfig(vocab_size=512, num_layers=4, num_heads=4,
                             embed_dim=128, mlp_dim=256,
                             moe_experts=args.experts, moe_every=2,
                             moe_capacity_factor=1.25,
                             dtype=jnp.float32)
    model = Transformer(dataclasses.replace(base, ep_axis="ep",
                                            ep_size=ep))

    rng = np.random.RandomState(0)
    tokens_all = rng.randint(
        0, 512, size=(args.steps, args.batch, args.seq_len))

    variables = Transformer(base).init(
        jax.random.PRNGKey(0), jnp.asarray(tokens_all[0][:1]))
    params = variables["params"]
    specs = ep_param_specs(params, "ep")
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    # Adam moments mirror the param tree: shard them identically.
    opt_specs = (optax.ScaleByAdamState(count=P(), mu=specs, nu=specs),
                 optax.EmptyState())

    def step(params, opt_state, tokens):
        def loss_fn(params):
            logits, state = model.apply({"params": params}, tokens,
                                        mutable=["intermediates"])
            tgt = jnp.roll(tokens, -1, axis=1)
            logp = jax.nn.log_softmax(logits)
            xent = -jnp.mean(
                jnp.take_along_axis(logp, tgt[..., None], axis=-1))
            aux = sum(jax.tree_util.tree_leaves(state["intermediates"]))
            return xent + args.aux_weight * aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = ep_grad_sync(grads, "ep", dp_axis="dp", average=True)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(
            jax.lax.pmean(loss, "ep"), "dp")

    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs, opt_specs, P(("dp", "ep"))),
        out_specs=(specs, opt_specs, P()),
        check_vma=False))

    params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    opt_state = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        opt_state, opt_specs)

    first = last = None
    for i in range(args.steps):
        params, opt_state, loss = mapped(params, opt_state,
                                         jnp.asarray(tokens_all[i]))
        last = float(loss)
        first = first if first is not None else last
        print("step %d loss %.4f" % (i, last))
    assert np.isfinite(last)
    assert last < first, (first, last)
    print("done")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
