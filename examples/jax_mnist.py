"""Data-parallel MNIST CNN in JAX — the flagship framework's analogue of
the reference `examples/tensorflow2_mnist.py` (BASELINE.json config #1).

Run single process:          python examples/jax_mnist.py
Run 2-process CPU cluster:   python -m horovod_tpu.run.run -np 2 -- \
                                 python examples/jax_mnist.py
On a TPU slice the same script trains over all local chips via the mesh.

Uses a deterministic synthetic MNIST-shaped dataset (this environment has
no network egress; swap `synthetic_mnist` for a real loader in practice).
"""

import argparse
import time

import numpy as np


def synthetic_mnist(n=2048, seed=0):
    """Deterministic class-separable 28x28 data (same on every rank)."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    templates = rng.randn(10, 28, 28, 1).astype(np.float32)
    x = templates[y] + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
    return x, y.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64,
                    help="per-process batch size")
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax
    from horovod_tpu.models import MnistCNN

    # Horovod-style: init, then scale LR by world size.
    hvd.init()
    rank, world = hvd.rank(), hvd.size()

    model = MnistCNN(dtype=jnp.float32)
    rng = jax.random.PRNGKey(42)
    params = model.init(rng, jnp.zeros((1, 28, 28, 1)), train=False)["params"]
    opt = hvd_jax.DistributedOptimizer(optax.sgd(args.lr * world))
    opt_state = opt.init(params)

    # Consistent start across ranks (reference: BroadcastGlobalVariables).
    params = hvd_jax.broadcast_parameters(params, root_rank=0)

    @jax.jit
    def forward_loss(params, x, y):
        logits = model.apply({"params": params}, x, train=True)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    grad_fn = jax.jit(jax.value_and_grad(forward_loss))

    x, y = synthetic_mnist()
    # Shard the dataset by rank (each rank sees a distinct slice).
    x_local, y_local = x[rank::world], y[rank::world]
    steps = len(x_local) // args.batch_size

    for epoch in range(args.epochs):
        t0 = time.time()
        total = 0.0
        for s in range(steps):
            lo = s * args.batch_size
            xb = jnp.asarray(x_local[lo:lo + args.batch_size])
            yb = jnp.asarray(y_local[lo:lo + args.batch_size])
            loss, grads = grad_fn(params, xb, yb)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            total += float(loss)
        avg = hvd_jax.metric_average(total / steps, "epoch_loss.%d" % epoch)
        if rank == 0:
            print("epoch %d: loss=%.4f (%.1fs)" %
                  (epoch, avg, time.time() - t0))
    if rank == 0:
        print("done")


if __name__ == "__main__":
    main()
