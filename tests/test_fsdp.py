"""FSDP (ZeRO-3-style) train step via GSPMD shardings: numerically
identical to the shard_map DP path, with params/grads/optimizer state
actually sharded per device."""

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

jax.config.update("jax_default_matmul_precision", "highest")

from horovod_tpu.parallel import (  # noqa: E402
    data_parallel_mesh, make_fsdp_train_step, make_train_step)


def _problem():
    rng = np.random.RandomState(0)
    # 16 rows: dim 0 divisible by 8 (sharded); bias small (replicated).
    params = {
        "w1": jnp.asarray(rng.randn(16, 64).astype(np.float32) * 0.1),
        "w2": jnp.asarray(rng.randn(64, 16).astype(np.float32) * 0.1),
        "b": jnp.asarray(rng.randn(16).astype(np.float32)),
    }
    x = jnp.asarray(rng.randn(32, 16).astype(np.float32))
    y = jnp.asarray(rng.randn(32, 16).astype(np.float32))

    def loss_fn(params, batch):
        h = jnp.tanh(batch["x"] @ params["w1"])
        pred = h @ params["w2"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return params, {"x": x, "y": y}, loss_fn


def test_fsdp_matches_plain_dp():
    params, batch, loss_fn = _problem()
    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    opt = optax.adam(1e-2)

    plain = make_train_step(loss_fn, opt, mesh, donate=False)
    p1, s1, b1 = plain.place(params, opt.init(params), batch)
    fsdp = make_fsdp_train_step(loss_fn, opt, mesh, donate=False,
                                min_size=64)
    p2, s2, b2 = fsdp.place(params, batch=batch)

    for _ in range(3):
        p1, s1, loss1 = plain(p1, s1, b1)
        p2, s2, loss2 = fsdp(p2, s2, b2)
    np.testing.assert_allclose(float(loss2), float(loss1), rtol=1e-6)
    for k in params:
        np.testing.assert_allclose(np.asarray(p2[k]), np.asarray(p1[k]),
                                   rtol=2e-5, atol=2e-5, err_msg=k)


def test_fsdp_state_actually_sharded():
    """Params, grads-side state (Adam moments) sharded on dim 0 for
    eligible leaves; small/indivisible leaves replicated."""
    params, batch, loss_fn = _problem()
    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    n = len(jax.devices("cpu"))
    opt = optax.adam(1e-2)
    fsdp = make_fsdp_train_step(loss_fn, opt, mesh, donate=False,
                                min_size=64)
    p, s, b = fsdp.place(params, batch=batch)

    assert p["w1"].sharding.spec == P("hvd")
    assert p["w2"].sharding.spec == P("hvd")
    assert p["b"].sharding.spec == P()  # too small -> replicated
    assert s[0].mu["w1"].sharding.spec == P("hvd")
    # Per-device shard is 1/n of the leaf.
    assert p["w1"].addressable_shards[0].data.shape[0] == \
        params["w1"].shape[0] // n

    # And the OUTPUT of a step keeps the sharded layout (no silent
    # re-replication by the compiled step).
    p, s, _ = fsdp(p, s, b)
    assert p["w1"].sharding.spec == P("hvd")
    assert s[0].nu["w2"].sharding.spec == P("hvd")


def test_fsdp_cache_keys_on_shapes():
    """Same pytree STRUCTURE but different shapes must get a fresh
    compile (the sharding rule depends on shapes): a 12-row leaf on an
    8-device mesh is replicated and must not reuse the 16-row sharded
    step."""
    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    opt = optax.sgd(0.1)

    def loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"]) ** 2)

    fsdp = make_fsdp_train_step(loss_fn, opt, mesh, donate=False,
                                min_size=8)
    rng = np.random.RandomState(1)
    for rows in (16, 12):  # 16 shards over 8; 12 does not -> replicated
        params = {"w": jnp.asarray(
            rng.randn(rows, 4).astype(np.float32))}
        batch = {"x": jnp.asarray(rng.randn(8, rows).astype(np.float32))}
        p, s, b = fsdp.place(params, batch=batch)
        p, s, loss = fsdp(p, s, b)
        assert np.isfinite(float(loss))
        expect = P("hvd") if rows % 8 == 0 else P()
        assert p["w"].sharding.spec == expect, (rows, p["w"].sharding)


def test_gspmd_fsdp_x_tp_composition():
    """The pure-GSPMD 2-D recipe: the UNMODIFIED single-device
    transformer, params sharded over BOTH mesh axes (tp dims from
    tp_param_specs, dim 0 additionally over 'fsdp' where divisible),
    run under plain jit — XLA inserts every collective; output equals
    the unsharded forward. No shard_map, no axis names in the model."""
    from jax.sharding import Mesh, NamedSharding

    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.parallel.tensor_parallel import tp_param_specs

    fsdp_n, tp_n = 2, 4
    mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(fsdp_n, tp_n),
                ("fsdp", "tp"))
    cfg = TransformerConfig(vocab_size=96, num_layers=2, num_heads=4,
                            embed_dim=32, mlp_dim=64, dtype=jnp.float32)
    model = Transformer(cfg)
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randint(0, 96, (4, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    expected = model.apply({"params": params}, tokens)

    tp_specs = tp_param_specs(params, "tp")

    def combine(p, tp_spec):
        parts = list(tp_spec) + [None] * (p.ndim - len(tp_spec))
        if parts and parts[0] is None and p.shape[0] % fsdp_n == 0:
            parts[0] = "fsdp"
        return P(*parts)

    specs = jax.tree_util.tree_map(combine, params, tp_specs)
    placed = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    # At least the big kernels must actually be 2-D sharded
    # (DenseGeneral qkv kernels are [D, H, Dh]: dim0 fsdp, heads tp).
    assert specs["block_0"]["attn"]["query"]["kernel"] == \
        P("fsdp", "tp", None)

    out = jax.jit(lambda p, t: model.apply({"params": p}, t))(placed,
                                                              tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)
