"""Self-verifying torch-binding test, run under the launcher with N >= 2
ranks (reference analogue: test/test_torch.py — collectives, grads via the
DistributedOptimizer hooks, broadcast of parameters/optimizer state)."""

import sys

import numpy as np
import torch

import horovod_tpu.torch as hvd


def test_allreduce(r, n):
    for dtype in (torch.int32, torch.int64, torch.float32, torch.float64):
        x = torch.arange(12, dtype=dtype).reshape(3, 4) + r
        out = hvd.allreduce(x, average=False, name="t_ar.%s" % dtype)
        exp = sum((torch.arange(12, dtype=dtype).reshape(3, 4) + rr)
                  for rr in range(n))
        assert torch.allclose(out.to(torch.float64), exp.to(torch.float64)), \
            (dtype, out, exp)


def test_allreduce_average(r, n):
    x = torch.ones(5) * (r + 1)
    out = hvd.allreduce(x, average=True, name="t_avg")
    exp = sum(rr + 1 for rr in range(n)) / n
    assert torch.allclose(out, torch.full((5,), exp)), out


def test_allreduce_inplace(r, n):
    x = torch.ones(4) * (r + 1)
    hvd.allreduce_(x, average=False, name="t_ar_")
    exp = sum(rr + 1 for rr in range(n))
    assert torch.allclose(x, torch.full((4,), float(exp))), x


def test_allreduce_bf16(r, n):
    x = torch.ones(8, dtype=torch.bfloat16) * (r + 1)
    out = hvd.allreduce(x, average=False, name="t_bf16")
    assert out.dtype == torch.bfloat16
    exp = float(sum(rr + 1 for rr in range(n)))
    assert torch.allclose(out.float(), torch.full((8,), exp)), out


def test_zero_copy_inplace(r, n):
    """In-place collectives on contiguous CPU tensors must keep the
    SAME storage (the core writes into the tensor's own memory —
    reference in-place semantics, torch/mpi_ops_v2.cc:52-76)."""
    x = torch.arange(1024, dtype=torch.float32) + r
    ptr = x.data_ptr()
    hvd.allreduce_(x, average=False, name="t_zc_ar")
    assert x.data_ptr() == ptr
    exp = n * torch.arange(1024, dtype=torch.float32) + sum(range(n))
    assert torch.allclose(x, exp), (x[:4], exp[:4])

    b = torch.full((64,), float(r))
    ptr = b.data_ptr()
    hvd.broadcast_(b, 0, name="t_zc_bc")
    assert b.data_ptr() == ptr
    assert torch.allclose(b, torch.zeros(64)), b

    # bf16 rides the same zero-copy path via bit-pattern views.
    xb = torch.ones(256, dtype=torch.bfloat16) * (r + 1)
    ptr = xb.data_ptr()
    hvd.allreduce_(xb, average=False, name="t_zc_bf16")
    assert xb.data_ptr() == ptr
    exp = float(sum(rr + 1 for rr in range(n)))
    assert torch.allclose(xb.float(), torch.full((256,), exp)), xb

    # Non-contiguous tensors take the copying fallback but must still
    # produce correct in-place results.
    base = torch.zeros(8, 2)
    col = base[:, 0]
    col.fill_(float(r + 1))
    hvd.allreduce_(col, average=False, name="t_zc_noncontig")
    assert torch.allclose(col, torch.full((8,), exp)), col
    assert torch.allclose(base[:, 1], torch.zeros(8)), base


def test_allgather(r, n):
    x = torch.full((r + 1, 2), float(r))
    out = hvd.allgather(x, name="t_ag")
    assert out.shape == (sum(rr + 1 for rr in range(n)), 2)
    off = 0
    for rr in range(n):
        assert torch.all(out[off:off + rr + 1] == rr)
        off += rr + 1


def test_gradients_through_collectives(r, n):
    """Collectives are differentiable autograd nodes (reference:
    torch/mpi_ops.py autograd Functions); same sum-of-per-rank-losses
    gradient convention as the TF binding."""
    # allreduce: y = mean_r(x_r); L_r = sum(y) * (r+1); dL/dx on every
    # rank is mean_r(r+1) (the grad itself is allreduce-averaged).
    x = torch.ones(3, requires_grad=True)
    y = hvd.allreduce(x, average=True, name="t_gar")
    (y.sum() * (r + 1)).backward()
    exp = sum(rr + 1 for rr in range(n)) / n
    assert np.allclose(x.grad.numpy(), exp), x.grad

    # allgather with unequal first dims: rank r contributes r+1 rows;
    # grads sum across ranks then slice this rank's segment.
    x = torch.full((r + 1, 2), float(r), requires_grad=True)
    y = hvd.allgather(x, name="t_gag")
    w = torch.arange(1.0, y.shape[0] + 1)
    (y[:, 0] * w).sum().backward()
    offset = sum(rr + 1 for rr in range(r))
    exp_rows = (np.arange(offset, offset + r + 1) + 1) * n
    assert np.allclose(x.grad.numpy()[:, 0], exp_rows), x.grad
    assert np.allclose(x.grad.numpy()[:, 1], 0.0)

    # broadcast: every rank's ones-grad sums onto the root; non-roots
    # get zeros.
    x = torch.ones(4, requires_grad=True) * 1.0
    x.retain_grad()
    y = hvd.broadcast(x, 0, name="t_gbc")
    y.sum().backward()
    exp = float(n) if r == 0 else 0.0
    assert np.allclose(x.grad.numpy(), exp), x.grad


def test_broadcast(r, n):
    x = torch.full((2, 2), float(r + 1))
    out = hvd.broadcast(x, 0, name="t_bc")
    assert torch.all(out == 1.0), out


def test_broadcast_object(r, n):
    obj = {"epoch": 7, "note": "hello"} if r == 0 else None
    got = hvd.broadcast_object(obj, root_rank=0, name="t_obj")
    assert got == {"epoch": 7, "note": "hello"}, got


def test_broadcast_parameters(r, n):
    torch.manual_seed(r)  # different init per rank
    model = torch.nn.Linear(4, 2)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    state = {k: v.clone() for k, v in model.state_dict().items()}
    # All ranks must now agree with rank 0's values: allreduce(avg) == own.
    for k, v in sorted(state.items()):
        avg = hvd.allreduce(v, average=True, name="t_bp.%s" % k)
        assert torch.allclose(avg, v, atol=1e-6), k


def test_distributed_optimizer(r, n):
    torch.manual_seed(0)  # same init everywhere
    model = torch.nn.Sequential(torch.nn.Linear(6, 8), torch.nn.ReLU(),
                                torch.nn.Linear(8, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    # Different data per rank; sync DP must keep params identical.
    torch.manual_seed(100 + r)
    for _ in range(3):
        x = torch.randn(8, 6)
        y = torch.randn(8, 1)
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
    for name, p in sorted(model.named_parameters()):
        avg = hvd.allreduce(p.data, average=True, name="t_do.%s" % name)
        assert torch.allclose(avg, p.data, atol=1e-6), name


def test_backward_passes_per_step(r, n):
    torch.manual_seed(0)
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    torch.manual_seed(200 + r)
    for _ in range(2):
        for _ in range(2):  # accumulate two backward passes
            x = torch.randn(4, 3)
            loss = model(x).sum()
            loss.backward()
        opt.step()
        opt.zero_grad()
    for name, p in sorted(model.named_parameters()):
        avg = hvd.allreduce(p.data, average=True, name="t_bpps.%s" % name)
        assert torch.allclose(avg, p.data, atol=1e-6), name


def test_broadcast_optimizer_state(r, n):
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3 * (r + 1))
    # Build some state.
    loss = model(torch.randn(2, 4)).sum()
    loss.backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.state_dict()["param_groups"][0]["lr"] == 1e-3, \
        opt.state_dict()["param_groups"][0]["lr"]


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2
    tests = [v for k, v in sorted(globals().items())
             if k.startswith("test_")]
    for t in tests:
        t(r, n)
        if r == 0:
            print("PASS %s" % t.__name__)
    print("rank %d: all torch tests passed" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
