"""Pipelined ring transport + hierarchical reduce-scatter coverage
(docs/AUTOTUNE.md):

* bitwise parity: slicing ring hops into double-buffered pipeline
  segments (HVD_TPU_PIPELINE_CHUNK_BYTES) must not change a single
  output bit vs the unsliced path — under none/bf16/int8 wire
  compression, for the allreduce legs, the standalone reduce-scatter,
  and allgather, including payloads whose final segment is partial;
* the two-level reduce-scatter produces exactly the flat op's shards on
  a forced 2-host x 2-slot topology, and only runs when enabled.
"""

import json
import re

import pytest

pytestmark = pytest.mark.e2e

from tests.test_hierarchical import run_hierarchical_workers  # noqa: E402


def _digests(stdout):
    """rank -> digest dict, parsed from the PARITY_DIGESTS lines."""
    out = {}
    for m in re.finditer(r"PARITY_DIGESTS (\{.*?\})\n", stdout):
        d = json.loads(m.group(1))
        out[len(out)] = d
    return out


def _metrics_lines(stdout):
    return [json.loads(m) for m in
            re.findall(r"PARITY_METRICS (\{.*?\})\n", stdout)]


def test_pipelined_ring_bitwise_parity(run_launcher):
    """Same job, same seeds, pipe=0 vs pipe=3KB (dozens of segments per
    hop on the large payloads, zero-length tails on the small ones):
    every op's result digest must match bitwise, and the segment counter
    proves the sliced run actually pipelined."""
    base_env = {"HVD_TPU_AUTOTUNE": "0"}
    flat = run_launcher(2, "pipelined_parity_worker.py",
                        extra_env=dict(base_env,
                                       HVD_TPU_PIPELINE_CHUNK_BYTES="0"),
                        timeout=600)
    assert flat.returncode == 0, flat.stdout + flat.stderr
    sliced = run_launcher(2, "pipelined_parity_worker.py",
                          extra_env=dict(
                              base_env,
                              HVD_TPU_PIPELINE_CHUNK_BYTES="3072"),
                          timeout=600)
    assert sliced.returncode == 0, sliced.stdout + sliced.stderr

    d_flat, d_sliced = _digests(flat.stdout), _digests(sliced.stdout)
    assert len(d_flat) == 2 and len(d_sliced) == 2, (flat.stdout,
                                                     sliced.stdout)
    # Outputs are rank-dependent for reduce-scatter/allgather, so compare
    # the MULTISET of per-rank digest dicts (launcher output order can
    # interleave ranks differently between runs).
    flat_set = sorted(json.dumps(d, sort_keys=True)
                      for d in d_flat.values())
    sliced_set = sorted(json.dumps(d, sort_keys=True)
                        for d in d_sliced.values())
    assert flat_set == sliced_set, "pipelined ring changed bits"

    # The sliced run pipelined; the flat run did not.
    assert all(m["pipeline_segments_total"] > 0
               for m in _metrics_lines(sliced.stdout)), sliced.stdout
    assert all(m["pipeline_segments_total"] == 0
               for m in _metrics_lines(flat.stdout)), flat.stdout


def test_hierarchical_reduce_scatter_correct(tmp_path):
    """2x2 topology, HVD_TPU_HIERARCHICAL_REDUCESCATTER=1: shards equal
    the exact expected chunks under all three compression modes, and the
    hierarchical counter proves the two-level path executed on every
    rank."""
    timeline = str(tmp_path / "hrs_timeline.json")
    procs, outs = run_hierarchical_workers(
        "hier_reduce_scatter_worker.py",
        {"HVD_TPU_HIERARCHICAL_REDUCESCATTER": "1",
         "HVD_TPU_AUTOTUNE": "0",
         "HVD_TPU_TIMELINE": timeline})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
        assert "MISMATCH" not in out, out
        m = re.search(r"HRS_METRICS (\{.*?\})", out)
        assert m, out
        stats = json.loads(m.group(1))
        assert stats["hierarchical"] > 0, stats
        assert stats["hierarchical"] == stats["total"], stats
    with open(timeline) as f:
        assert "REDUCE_SCATTER_HIERARCHICAL" in f.read()


def test_hierarchical_reduce_scatter_disabled_uses_flat(tmp_path):
    timeline = str(tmp_path / "hrs_flat_timeline.json")
    procs, outs = run_hierarchical_workers(
        "hier_reduce_scatter_worker.py",
        {"HVD_TPU_HIERARCHICAL_REDUCESCATTER": "0",
         "HVD_TPU_AUTOTUNE": "0",
         "HVD_TPU_TIMELINE": timeline})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
        m = re.search(r"HRS_METRICS (\{.*?\})", out)
        assert m and json.loads(m.group(1))["hierarchical"] == 0, out
    with open(timeline) as f:
        text = f.read()
    assert "REDUCE_SCATTER_HIERARCHICAL" not in text
    assert "REDUCE_SCATTER_RING" in text


def test_hierarchical_reduce_scatter_pipelined_parity(tmp_path):
    """The hierarchical composite's legs ride the same segment pipeline:
    sliced vs unsliced two-level runs must both pass the exact-value
    assertions (the worker's own checks) with segments flowing."""
    procs, outs = run_hierarchical_workers(
        "hier_reduce_scatter_worker.py",
        {"HVD_TPU_HIERARCHICAL_REDUCESCATTER": "1",
         "HVD_TPU_AUTOTUNE": "0",
         "HVD_TPU_PIPELINE_CHUNK_BYTES": "2048"})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
        assert "MISMATCH" not in out, out
