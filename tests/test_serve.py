"""hvd-serve tests (ISSUE 16; docs/SERVE.md).

Unit layer: micro-batcher policy (bucketing, deadline release, bounded
queue, response split-back, per-row CRC integrity gate), the serve
chaos grammar, serve metrics quantiles, model fingerprint/leaf
extraction, the rolling-swap watcher's edge cases (torn/CRC-invalid
newer manifest rejected with fallback; swap landing mid-drain
abandoned), the HTTP front door's cause-named error contract, the
retrying client, the supervisor's autoscaler, and the hvd-top --serve
renderer's mixed-version tolerance.

E2E layer (real replica subprocesses under the elastic driver): a
rolling weight swap drops zero requests and post-swap answers are
PROVABLY from the new weights (fingerprint-checked against recomputed
math); a SIGKILLed replica mid-request costs the client a retry to a
survivor, never a hang or a wrong answer; a whole-pool drain answers
everything admitted and exits EXIT_DRAINED.
"""

import json
import os
import random
import signal
import socket
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.elastic import durable
from horovod_tpu.elastic.state import EXIT_DRAINED
from horovod_tpu.serve import model as smodel
from horovod_tpu.serve.batcher import MicroBatcher, QueueFull, bucket_for
from horovod_tpu.serve.chaos import ServeChaos
from horovod_tpu.serve.client import ServeClient, ServeError
from horovod_tpu.serve.loadgen import check_response, request_input, run_load
from horovod_tpu.serve.metrics import ServeMetrics, histogram_quantile
from horovod_tpu.serve.server import ReplicaContext, start_front_door
from horovod_tpu.serve.supervisor import ServeSupervisor
from horovod_tpu.serve.swap import SwapWatcher, publish_leaves

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DIM = 4


def _leaves(seed):
    return smodel.init_leaves("affine", DIM, seed=seed)


def _run_batches(batcher, forward, stamp=None, stop=None):
    """Drives the batch loop on a thread until `stop` is set."""
    def loop():
        while not stop.is_set():
            tickets = batcher.next_batch(timeout=0.02)
            if tickets:
                batcher.run_batch(forward, tickets, stamp=stamp)
    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# Micro-batcher

def test_bucket_for_powers_of_two_capped():
    assert bucket_for(1, 16) == 1
    assert bucket_for(3, 16) == 4
    assert bucket_for(9, 16) == 16
    assert bucket_for(9, 8) == 8
    assert bucket_for(100, 64) == 64


def test_batcher_batches_and_splits_responses():
    m = ServeMetrics()
    b = MicroBatcher(max_batch=8, max_delay=0.01, metrics=m)
    leaves = _leaves(0)
    fwd = smodel.make_forward("affine", leaves)
    tickets = [b.submit(str(i), np.full(DIM, i, np.float32))
               for i in range(5)]
    batch = b.next_batch(timeout=1.0)
    assert len(batch) == 5
    b.run_batch(fwd, batch, stamp=(3, "abcd1234"))
    for i, t in enumerate(tickets):
        assert t.event.is_set()
        assert t.error is None
        expect = smodel.forward("affine", leaves,
                                np.full(DIM, i, np.float32))
        assert np.allclose(t.response, expect, atol=1e-5)
        assert t.model_step == 3 and t.weights_crc == "abcd1234"
    snap = m.snapshot()
    assert snap["counters"]["serve_batches_total"] == 1
    assert snap["counters"]["serve_responses_total"] == 5


def test_batcher_releases_on_deadline_without_filling():
    b = MicroBatcher(max_batch=64, max_delay=0.02)
    b.submit("1", np.zeros(DIM, np.float32))
    t0 = time.monotonic()
    batch = b.next_batch(timeout=1.0)
    took = time.monotonic() - t0
    assert len(batch) == 1
    assert took < 0.5  # released by max_delay, not the 1s timeout


def test_batcher_bounded_queue_rejects_promptly():
    m = ServeMetrics()
    b = MicroBatcher(max_batch=4, queue_max=3, metrics=m)
    for i in range(3):
        b.submit(str(i), np.zeros(DIM, np.float32))
    with pytest.raises(QueueFull):
        b.submit("overflow", np.zeros(DIM, np.float32))
    assert m.snapshot()["counters"]["serve_rejects_total"] == 1


def test_batcher_close_drains_and_refuses_admission():
    b = MicroBatcher(max_batch=4)
    t = b.submit("1", np.zeros(DIM, np.float32))
    b.close()
    with pytest.raises(QueueFull):
        b.submit("2", np.zeros(DIM, np.float32))
    # The queued ticket is still served by the remaining iterations.
    batch = b.next_batch(timeout=0.5)
    assert batch == [t]
    assert b.next_batch(timeout=0.05) == []


def test_batcher_shape_mismatch_fails_only_that_request():
    b = MicroBatcher(max_batch=4)
    good = b.submit("g", np.zeros(DIM, np.float32))
    bad = b.submit("b", np.zeros(DIM + 1, np.float32))
    batch = b.next_batch(timeout=0.5)
    b.run_batch(smodel.make_forward("affine", _leaves(0)), batch)
    assert good.error is None and good.response is not None
    assert bad.cause == "shape" and bad.event.is_set()


def test_batcher_rejects_non_flat_input_at_admission():
    """A 2-D body whose inner length matches the model dim must be
    refused at submit (ValueError -> the front door's 400), never
    reach frame assembly where it would crash the batch loop."""
    b = MicroBatcher(max_batch=4)
    with pytest.raises(ValueError):
        b.submit("2d", [[0.0] * DIM, [1.0] * DIM])
    with pytest.raises(ValueError):
        b.submit("3d", np.zeros((1, 2, DIM), np.float32))
    assert b.depth() == 0  # nothing was admitted
    # The replica serves on: a well-formed request still works.
    t = b.submit("ok", np.zeros(DIM, np.float32))
    b.run_batch(smodel.make_forward("affine", _leaves(0)),
                b.next_batch(timeout=0.5))
    assert t.error is None and t.response is not None


def test_run_batch_never_raises_on_malformed_ticket():
    """run_batch's 'never raises' contract must hold even for a ticket
    whose x stopped being a flat row (hand-made ticket / future
    admission bug): that request fails cause-named, the rest answer."""
    b = MicroBatcher(max_batch=4)
    good = b.submit("g", np.zeros(DIM, np.float32))
    bad = b.submit("b", np.zeros(DIM, np.float32))
    bad.x = np.zeros((2, DIM), np.float32)  # simulate the bypass
    batch = b.next_batch(timeout=0.5)
    b.run_batch(smodel.make_forward("affine", _leaves(0)), batch)
    assert good.error is None and good.response is not None
    assert bad.cause == "shape" and bad.event.is_set()


def test_cancelled_ticket_dropped_without_forward_row():
    """A deadline-expired (cancelled) ticket is purged before frame
    assembly: no forward row, no response counter — only
    serve_cancelled_total moves."""
    m = ServeMetrics()
    b = MicroBatcher(max_batch=4, metrics=m)
    kept = b.submit("kept", np.zeros(DIM, np.float32))
    gone = b.submit("gone", np.ones(DIM, np.float32))
    gone.cancel()  # what server._infer does when 504ing
    batch = b.next_batch(timeout=0.5)
    assert gone not in batch  # purged in next_batch
    b.run_batch(smodel.make_forward("affine", _leaves(0)), batch)
    assert kept.response is not None
    assert gone.response is None and not gone.event.is_set()
    snap = m.snapshot()
    assert snap["counters"]["serve_cancelled_total"] == 1
    assert snap["counters"]["serve_responses_total"] == 1
    # Cancellation after the batch was taken is caught by run_batch.
    late = b.submit("late", np.zeros(DIM, np.float32))
    batch = b.next_batch(timeout=0.5)
    late.cancel()
    b.run_batch(smodel.make_forward("affine", _leaves(0)), batch)
    assert late.response is None
    assert m.snapshot()["counters"]["serve_cancelled_total"] == 2


def test_corrupt_frame_fails_request_with_named_cause():
    m = ServeMetrics()
    chaos = ServeChaos(seed=7, corrupt_batches=(1,))
    b = MicroBatcher(max_batch=8, metrics=m, chaos=chaos)
    leaves = _leaves(0)
    fwd = smodel.make_forward("affine", leaves)
    tickets = [b.submit(str(i), np.full(DIM, i, np.float32))
               for i in range(4)]
    b.run_batch(fwd, b.next_batch(timeout=0.5))
    corrupted = [t for t in tickets if t.cause == "frame-corrupt"]
    answered = [t for t in tickets if t.error is None]
    assert len(corrupted) == 1  # chaos flips ONE byte in ONE row
    assert len(answered) == 3
    assert "not computed" in corrupted[0].error
    snap = m.snapshot()
    assert snap["counters"]["serve_frame_corrupt_total"] == 1
    # Batch 2 is untouched (spec said corrupt_batch=1 only).
    t2 = [b.submit("x%d" % i, np.full(DIM, i, np.float32))
          for i in range(2)]
    b.run_batch(fwd, b.next_batch(timeout=0.5))
    assert all(t.error is None for t in t2)


# ---------------------------------------------------------------------------
# Chaos grammar

def test_serve_chaos_parse_grammar():
    c = ServeChaos.parse("seed=9;corrupt_batch=2,5;kill_after=1.5")
    assert c.seed == 9
    assert set(c.corrupt_batches) == {2, 5}
    assert c.kill_after == 1.5
    assert ServeChaos.from_env({"HVD_TPU_SERVE_CHAOS_SPEC": ""}) is None
    got = ServeChaos.from_env(
        {"HVD_TPU_SERVE_CHAOS_SPEC": "seed=3;corrupt_batch=1"})
    assert got.seed == 3
    with pytest.raises(ValueError):
        ServeChaos.parse("seed=1;explode=now")


# ---------------------------------------------------------------------------
# Metrics

def test_histogram_quantiles_and_latency():
    m = ServeMetrics()
    for v in [0.002] * 50 + [0.004] * 45 + [0.5] * 5:
        m.observe("serve_request_seconds", v)
    p50, p99 = m.latency_quantiles()
    assert p50 is not None and p50 <= 0.005
    assert p99 >= 0.25
    snap = m.snapshot()["histograms"]["serve_request_seconds"]
    assert snap["count"] == 100
    assert histogram_quantile(snap, 0.0) <= histogram_quantile(snap, 1.0)


def test_metrics_render_prometheus_serve_families():
    from horovod_tpu.serve.metrics import render_prometheus
    m = ServeMetrics()
    m.inc("serve_requests_total", 3)
    m.observe("serve_request_seconds", 0.01)
    text = render_prometheus(m)
    assert "hvdtpu_serve_requests_total 3" in text
    assert "hvdtpu_serve_request_seconds_bucket" in text


# ---------------------------------------------------------------------------
# Model registry / fingerprint / lineage extraction

def test_fingerprint_identifies_weight_sets():
    a, b = _leaves(1), _leaves(2)
    assert smodel.fingerprint(a) == smodel.fingerprint(_leaves(1))
    assert smodel.fingerprint(a) != smodel.fingerprint(b)


def test_extract_leaves_from_training_lineage_paths():
    leaves = _leaves(3)
    raw = {".w": leaves["w"], ".b": leaves["b"],
           ".opt.0.mu.w": np.zeros((DIM, DIM), np.float32),
           ".step": np.int64(7)}
    out = smodel.extract_leaves(raw, _leaves(0))
    assert out is not None
    assert smodel.fingerprint(out) == smodel.fingerprint(leaves)
    # Missing leaf -> None (replica keeps current weights).
    assert smodel.extract_leaves({".w": leaves["w"]}, _leaves(0)) is None
    # Shape mismatch -> None, not a crash.
    assert smodel.extract_leaves(
        {".w": np.zeros((2, 2), np.float32), ".b": leaves["b"]},
        _leaves(0)) is None


def test_forward_jit_numpy_parity():
    leaves = _leaves(4)
    x = np.random.RandomState(0).standard_normal(
        (8, DIM)).astype(np.float32)
    ref = smodel.forward("affine", leaves, x)
    jit_fwd = smodel.make_forward("affine", leaves)
    assert np.allclose(jit_fwd(x), ref, atol=1e-4)
    os.environ["HVD_TPU_SERVE_JIT"] = "0"
    try:
        np_fwd = smodel.make_forward("affine", leaves)
    finally:
        os.environ.pop("HVD_TPU_SERVE_JIT", None)
    assert np.allclose(np_fwd(x), ref, atol=1e-6)


# ---------------------------------------------------------------------------
# Rolling swap watcher (satellite: edge cases)

def _watcher(ckpt_dir, metrics=None, current=(-1,), flips=None,
             draining_fn=None, stagger=0.0):
    flips = flips if flips is not None else []

    def flip(step, leaves, crc):
        current[0] = step
        flips.append((step, crc))

    return SwapWatcher(str(ckpt_dir), _leaves(0),
                       current_step_fn=lambda: current[0],
                       flip_fn=flip, metrics=metrics,
                       draining_fn=draining_fn, stagger=stagger), flips


def test_swap_watcher_flips_to_newer_checkpoint(tmp_path):
    m = ServeMetrics()
    leaves = _leaves(5)
    publish_leaves(str(tmp_path), 10, leaves)
    current = [-1]
    w, flips = _watcher(tmp_path, metrics=m, current=current)
    assert w.poll_once() == 10
    assert flips == [(10, smodel.fingerprint(leaves))]
    # Nothing newer: no re-flip.
    assert w.poll_once() is None
    assert m.snapshot()["counters"]["serve_swaps_total"] == 1


def test_swap_watcher_rejects_torn_manifest_and_falls_back(tmp_path):
    """A torn (truncated) NEWER manifest counts one
    serve_swap_rejects_total and the watcher falls back to the
    next-older valid checkpoint — the replica never serves a
    half-loaded weight set."""
    m = ServeMetrics()
    good = _leaves(6)
    publish_leaves(str(tmp_path), 10, good)
    publish_leaves(str(tmp_path), 20, _leaves(7))
    # Tear step 20's manifest mid-write.
    step20 = [p for s, g, p in durable.list_checkpoints(str(tmp_path))
              if s == 20][0]
    manifest = os.path.join(step20, durable.MANIFEST_NAME)
    raw = open(manifest, "rb").read()
    with open(manifest, "wb") as f:
        f.write(raw[:len(raw) // 2])
    current = [-1]
    w, flips = _watcher(tmp_path, metrics=m, current=current)
    assert w.poll_once() == 10  # fell back to the older valid lineage
    assert flips == [(10, smodel.fingerprint(good))]
    assert m.snapshot()["counters"]["serve_swap_rejects_total"] == 1
    # Re-polling does NOT re-count the same torn directory.
    assert w.poll_once() is None
    assert m.snapshot()["counters"]["serve_swap_rejects_total"] == 1


def test_swap_watcher_rejects_crc_invalid_shard(tmp_path):
    """A flipped bit in a newer checkpoint's shard bytes fails the deep
    validation; the swap is rejected and the current weights keep
    serving."""
    m = ServeMetrics()
    publish_leaves(str(tmp_path), 10, _leaves(8))
    step10 = [p for s, g, p in durable.list_checkpoints(str(tmp_path))
              if s == 10][0]
    shard = [os.path.join(step10, f) for f in os.listdir(step10)
             if f != durable.MANIFEST_NAME][0]
    blob = bytearray(open(shard, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(shard, "wb") as f:
        f.write(bytes(blob))
    current = [5]  # serving something older than the poisoned ckpt
    w, flips = _watcher(tmp_path, metrics=m, current=current)
    assert w.poll_once() is None
    assert flips == []
    assert current[0] == 5  # still on the old weights
    assert m.snapshot()["counters"]["serve_swap_rejects_total"] == 1


def test_swap_abandoned_when_drain_wins_the_race(tmp_path):
    """A drain that lands between shadow-load and flip abandons the
    swap (serve_swap_aborts_total): the remaining queue finishes on the
    weights it was admitted under."""
    m = ServeMetrics()
    publish_leaves(str(tmp_path), 10, _leaves(9))
    calls = [0]

    def draining():
        # False at the scan guard, True at the flip gate: the drain
        # arrives while the shadow is loading.
        calls[0] += 1
        return calls[0] > 1

    current = [-1]
    w, flips = _watcher(tmp_path, metrics=m, current=current,
                        draining_fn=draining)
    assert w.poll_once() is None
    assert flips == []
    snap = m.snapshot()["counters"]
    assert snap["serve_swap_aborts_total"] == 1
    assert snap["serve_swaps_total"] == 0


# ---------------------------------------------------------------------------
# Front door + client

def _replica_fixture(max_batch=8, deadline=5.0):
    m = ServeMetrics()
    b = MicroBatcher(max_batch=max_batch, max_delay=0.003, metrics=m)
    leaves = _leaves(0)
    crc = smodel.fingerprint(leaves)
    ctx = ReplicaContext(b, m, worker_id=0, request_deadline=deadline)
    ctx.set_weights(1, crc)
    httpd, port = start_front_door(0, ctx)
    stop = threading.Event()
    _run_batches(b, smodel.make_forward("affine", leaves),
                 stamp=(1, crc), stop=stop)
    return ctx, b, httpd, port, stop, leaves, crc


def test_front_door_roundtrip_and_error_causes():
    ctx, b, httpd, port, stop, leaves, crc = _replica_fixture()
    try:
        client = ServeClient(["127.0.0.1:%d" % port], total_deadline=5)
        x = np.arange(DIM, dtype=np.float32)
        doc = client.infer(x, rid="r1")
        assert np.allclose(doc["y"], smodel.forward("affine", leaves, x),
                           atol=1e-4)
        assert doc["weights_crc"] == crc and doc["model_step"] == 1

        # Malformed body -> prompt 400 with cause, not a hang.
        req = urllib.request.Request(
            "http://127.0.0.1:%d/infer" % port, data=b"{nope",
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=5)
            raise AssertionError("bad request was accepted")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert json.loads(e.read())["cause"] == "bad-request"

        # A 2-D x whose inner length matches the dim (the remote-DoS
        # vector: it used to pass admission and crash the batch loop
        # at frame assembly) -> prompt 400, replica survives.
        body = json.dumps({"id": "r2",
                           "x": [[0.0] * DIM, [1.0] * DIM]}).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:%d/infer" % port, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=5)
            raise AssertionError("2-D request was accepted")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert json.loads(e.read())["cause"] == "bad-request"
        doc = client.infer(x, rid="r3")  # still serving
        assert np.allclose(doc["y"], smodel.forward("affine", leaves, x),
                           atol=1e-4)

        # /serve document carries the wire fields.
        view = json.loads(urllib.request.urlopen(
            "http://127.0.0.1:%d/serve" % port, timeout=5).read())
        assert view["state"] == "serving"
        assert view["weights_crc"] == crc
        assert view["responses_total"] >= 1

        # Draining -> cause-named 503 the client treats as re-queueable.
        ctx.begin_drain()
        b.close()
        with pytest.raises(ServeError) as err:
            ServeClient(["127.0.0.1:%d" % port],
                        total_deadline=0.4).infer(x)
        assert err.value.cause == "draining"
    finally:
        stop.set()
        httpd.shutdown()


def test_client_retries_to_surviving_replica():
    ctx, b, httpd, port, stop, leaves, crc = _replica_fixture()
    try:
        # First endpoint refuses connections (a SIGKILLed replica);
        # the client's rotation lands on the live one.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        client = ServeClient(
            ["127.0.0.1:%d" % dead_port, "127.0.0.1:%d" % port],
            total_deadline=5)
        for i in range(4):
            doc = client.infer(np.full(DIM, i, np.float32))
            assert doc["replica"] == 0
    finally:
        stop.set()
        httpd.shutdown()


def test_loadgen_detects_wrong_weights():
    x = request_input(seed=0, rid=1, dim=DIM)
    leaves = _leaves(0)
    crc = smodel.fingerprint(leaves)
    y = smodel.forward("affine", leaves, x)
    good = {"y": [float(v) for v in y], "weights_crc": crc}
    assert check_response(good, x, "affine", {crc: leaves}) is None
    # Answer computed with OTHER weights but claiming this crc.
    wrong = {"y": [float(v) for v in smodel.forward(
        "affine", _leaves(1), x)], "weights_crc": crc}
    assert "does not match" in check_response(
        wrong, x, "affine", {crc: leaves})
    unknown = {"y": [0.0] * DIM, "weights_crc": "ffffffff"}
    assert "unknown" in check_response(
        unknown, x, "affine", {crc: leaves})


# ---------------------------------------------------------------------------
# Supervisor autoscaler (unit, against a stub driver)

class _StubDriver:
    def __init__(self, live, hosts=None):
        self._live = list(live)
        self._hosts = hosts or {wid: "localhost" for wid in live}
        self.resized_to = None
        self.drained = None

    def live_workers(self):
        return list(self._live)

    def worker_hosts(self):
        return dict(self._hosts)

    def resize(self, n):
        self.resized_to = n

    def request_drain(self, victims, grace=None):
        self.drained = victims


def _stub_supervisor(live, views, **kwargs):
    sup = ServeSupervisor(
        ["true"], {"localhost": 8}, min_replicas=1, max_replicas=4,
        **kwargs)
    sup.driver = _StubDriver(live)
    sup.replica_views = lambda timeout=0.5: views
    return sup


def test_autoscaler_grows_on_queue_pressure():
    views = [{"queue_depth": 9}, {"queue_depth": 7}]
    sup = _stub_supervisor([0, 1], views, scale_up_queue=4.0)
    assert sup.autoscale_once() == 1
    assert sup.driver.resized_to == 3
    assert sup.scale_events[-1]["to"] == 3


def test_autoscaler_shrinks_after_sustained_idle():
    views = [{"queue_depth": 0}, {"queue_depth": 0}]
    sup = _stub_supervisor([0, 3], views, scale_down_idle=0.0)
    assert sup.autoscale_once() in (0, -1)  # first tick arms the timer
    assert sup.autoscale_once() == -1
    assert sup.driver.resized_to == 1
    assert sup.driver.drained == [3]  # youngest replica drains


def test_autoscaler_respects_ceiling():
    views = [{"queue_depth": 50}] * 4
    sup = _stub_supervisor([0, 1, 2, 3], views)
    assert sup.autoscale_once() == 0
    assert sup.driver.resized_to is None


def test_supervisor_endpoints_follow_worker_hosts():
    """-H accepts multi-host inventories: endpoints must point at the
    host each replica actually landed on (local spellings normalized
    to loopback), not a hardcoded 127.0.0.1."""
    sup = _stub_supervisor([0, 1, 2], [])
    sup.driver = _StubDriver(
        [0, 1, 2], hosts={0: "localhost", 1: "nodeB", 2: "127.0.0.1"})
    base = sup.port_base
    assert sup.endpoints() == ["127.0.0.1:%d" % base,
                               "nodeB:%d" % (base + 1),
                               "127.0.0.1:%d" % (base + 2)]


# ---------------------------------------------------------------------------
# hvd-top --serve rendering + mixed-version tolerance (satellite)

def _serve_doc():
    rep = {"state": "serving", "replica": 0, "model_step": 12,
           "weights_crc": "cafe0123", "queue_depth": 2, "inflight": 1,
           "requests_total": 100, "responses_total": 97,
           "batches_total": 30, "rejects_total": 1, "errors_total": 2,
           "cancelled_total": 0, "frame_corrupt_total": 1,
           "swaps_total": 3,
           "swap_rejects_total": 1, "swap_aborts_total": 0,
           "p50_ms": 4.2, "p99_ms": 19.0}
    return {"kind": "serve-pool", "replicas": 2, "replicas_reporting": 2,
            "draining": 0, "scale_events": 1, "requests_total": 150,
            "responses_total": 140, "rejects_total": 1,
            "errors_total": 2, "swaps_total": 3, "p99_ms": 19.0,
            "frame_corrupt_total": 1, "model_steps": [11, 12],
            "per_replica": [rep,
                            # An OLDER replica mid-rolling-upgrade:
                            # its document predates the swap fields.
                            {"state": "serving", "replica": 1,
                             "model_step": 11, "weights_crc": "beef",
                             "queue_depth": 0, "requests_total": 50}]}


def test_hvd_top_serve_renders_and_tolerates_old_replicas():
    from horovod_tpu.run import top
    frame = top.render_serve(_serve_doc(), "test:0")
    lines = frame.splitlines()
    rows = [ln for ln in lines if ln.strip().startswith(("0 ", "1 "))
            or ln.strip().split()[:1] in (["0"], ["1"])]
    assert len(rows) == 2, frame
    # The new replica renders numbers; the old replica renders '-' in
    # the columns its summary predates, WITHOUT shifting the row.
    new_cells = rows[0].split()
    old_cells = rows[1].split()
    assert len(new_cells) == len(old_cells) == len(top._SERVE_COLUMNS) + 1
    assert "cafe0123" in new_cells
    assert "-" in old_cells  # e.g. the swp/p50 cells
    # Mixed-weights banner: a rolling swap is visibly in flight.
    assert "mixed weights" in frame
    assert "corrupt batch frame" in frame


def test_hvd_top_fleet_kind_column_tolerates_old_controller():
    from horovod_tpu.run import top
    fleet = {"t": 1.0, "free_slots": 0, "counters": {}, "hosts": {},
             "jobs": {"train0": {"state": "running", "priority": 0,
                                 "live": 2, "np": 2, "min_np": 1},
                      "serve0": {"state": "running", "kind": "serve",
                                 "placement": "spread", "priority": 5,
                                 "live": 2, "np": 2, "min_np": 1}}}
    frame = top.render_fleet(fleet, "test:0")
    train_row = [ln for ln in frame.splitlines()
                 if ln.startswith("train0")][0]
    serve_row = [ln for ln in frame.splitlines()
                 if ln.startswith("serve0")][0]
    assert "serve" in serve_row and "spread" in serve_row
    assert "-" in train_row.split()  # old controller doc: kind absent


# ---------------------------------------------------------------------------
# E2E: real replica subprocesses under the elastic driver

def _free_port_base(n):
    """A base port with n consecutive free ports (probe-and-release;
    the tiny race against other suites is retried by the caller's
    health-wait)."""
    for _ in range(64):
        base = random.randint(20000, 55000)
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
        return base
    raise RuntimeError("no free port block found")


class _Pool:
    """Test harness: a real serve pool (supervisor in-process, replica
    subprocesses) bounded by `max_np` slots on localhost."""

    def __init__(self, replicas=2, max_np=None, ckpt_dir=None,
                 extra_env=None, **sup_kwargs):
        from tests.conftest import clean_worker_env
        max_np = max_np or replicas
        self.port_base = _free_port_base(max_np + 2)
        env = clean_worker_env(dict({
            # numpy forward: replica boot must not pay a jax import.
            "HVD_TPU_SERVE_JIT": "0",
            "HVD_TPU_SERVE_MODEL": "affine",
            "HVD_TPU_SERVE_DIM": str(DIM),
            "HVD_TPU_SERVE_PORT": str(self.port_base),
            "HVD_TPU_SERVE_SWAP_INTERVAL": "0.1",
            "HVD_TPU_SERVE_SWAP_STAGGER": "0.2",
        }, **(extra_env or {})))
        if ckpt_dir:
            env["HVD_TPU_CKPT_DIR"] = str(ckpt_dir)
        self.sup = ServeSupervisor(
            [sys.executable, "-m", "horovod_tpu.serve.replica"],
            {"localhost": max_np}, min_replicas=1,
            max_replicas=max_np, np_initial=replicas,
            port_base=self.port_base, env=env, **sup_kwargs)
        self.rc = None
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        try:
            self.rc = self.sup.driver.run(install_signal_handlers=False)
        except Exception as e:  # surfaced by the test's join/assert
            self.rc = ("driver crashed", e)

    def wait_healthy(self, n, timeout=30):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            up = 0
            for ep in self.sup.endpoints():
                try:
                    with urllib.request.urlopen(
                            "http://%s/healthz" % ep, timeout=1) as r:
                        if json.loads(r.read()).get("ok"):
                            up += 1
                except Exception:
                    pass
            if up >= n:
                return
            time.sleep(0.1)
        raise AssertionError("only %d/%d replicas healthy (rc=%r)"
                             % (up, n, self.rc))

    def drain(self, timeout=60):
        self.sup.driver.request_drain("all")
        self.thread.join(timeout=timeout)
        assert not self.thread.is_alive(), "driver did not finish drain"
        return self.rc

    def kill(self):
        if self.thread.is_alive():
            self.sup.driver.terminate()
            self.thread.join(timeout=15)


@pytest.mark.e2e
def test_e2e_rolling_swap_zero_dropped_new_weights_proven(tmp_path):
    """The tentpole acceptance: requests flow through a rolling weight
    swap with zero drops, and post-swap responses are PROVABLY computed
    from the new weights (every answer re-verified against the numpy
    forward of the weight set its fingerprint names)."""
    old, new = _leaves(1), _leaves(2)
    crc_old, crc_new = (smodel.fingerprint(old), smodel.fingerprint(new))
    publish_leaves(str(tmp_path), 10, old)
    pool = _Pool(replicas=2, ckpt_dir=tmp_path)
    try:
        pool.wait_healthy(2)
        by_crc = {crc_old: old, crc_new: new}
        result_box = {}

        def load():
            result_box["r"], result_box["wall"] = run_load(
                pool.sup.endpoints, rate=40, duration=4.0, dim=DIM,
                seed=3, leaves_by_crc=by_crc, workers=4,
                total_deadline=10.0)

        t = threading.Thread(target=load)
        t.start()
        time.sleep(1.0)
        publish_leaves(str(tmp_path), 20, new)  # the rolling swap lands
        t.join(timeout=60)
        assert not t.is_alive()
        res = result_box["r"]
        assert res.errors == [], res.errors[:5]
        assert res.mismatches == [], res.mismatches[:5]
        assert res.ok == 160  # zero dropped: every admitted answered
        # Traffic provably crossed the swap: answers from BOTH weight
        # sets, and the new fingerprint dominates the tail.
        assert res.by_crc.get(crc_old, 0) > 0
        assert res.by_crc.get(crc_new, 0) > 0, res.by_crc
        # Both replicas converged on the new lineage step.
        for ep in pool.sup.endpoints():
            view = json.loads(urllib.request.urlopen(
                "http://%s/serve" % ep, timeout=5).read())
            assert view["model_step"] == 20
            assert view["swaps_total"] >= 1
        rc = pool.drain()
        assert rc == EXIT_DRAINED
    finally:
        pool.kill()


@pytest.mark.e2e
def test_e2e_sigkill_replica_mid_request_no_hang_no_wrong_answer(
        tmp_path, monkeypatch):
    """Chaos acceptance: SIGKILL a replica while requests are in
    flight. Every request gets a correct answer (re-queued to the
    survivor) or a prompt cause-named error — never a hang, never a
    wrong answer. The driver respawns the dead replica (failure
    blacklist cooldown permitting)."""
    monkeypatch.setenv("HVD_TPU_ELASTIC_COOLDOWN", "1")
    leaves = _leaves(4)
    crc = smodel.fingerprint(leaves)
    publish_leaves(str(tmp_path), 10, leaves)
    pool = _Pool(replicas=2, max_np=2, ckpt_dir=tmp_path)
    try:
        pool.wait_healthy(2)
        by_crc = {crc: leaves}
        result_box = {}

        def load():
            result_box["r"], _ = run_load(
                pool.sup.endpoints, rate=30, duration=4.0, dim=DIM,
                seed=5, leaves_by_crc=by_crc, workers=4,
                total_deadline=8.0)

        t = threading.Thread(target=load)
        t.start()
        time.sleep(1.0)
        victim = pool.sup.driver.live_workers()[0]
        pid = pool.sup.driver.worker_pid(victim)
        os.kill(pid, signal.SIGKILL)
        t.join(timeout=90)
        assert not t.is_alive(), "load generator hung after the kill"
        res = result_box["r"]
        # The hard contract: NEVER a wrong answer, NEVER a silent drop.
        assert res.mismatches == [], res.mismatches[:5]
        assert res.ok + len(res.errors) == 120
        # The client absorbed the kill: retries to the survivor answer
        # (allow a small tail of prompt, cause-named errors).
        assert res.ok >= 110, (res.ok, res.errors[:10])
        for rid, cause, msg in res.errors:
            assert cause in ("replica-lost", "draining", "overload",
                             "deadline"), (rid, cause, msg)
        # The pool healed: a respawned replica joins within cooldown.
        pool.wait_healthy(2, timeout=30)
        rc = pool.drain()
        assert rc == EXIT_DRAINED
    finally:
        pool.kill()
