"""Fusion / cycle knob boundary tests (reference semantics:
HOROVOD_FUSION_THRESHOLD and the fused-buffer divisibility rounding,
`/root/reference/horovod/common/controller.cc:300-318`; cycle pacing
`operations.cc` RunLoopOnce). Pins the three regimes — fusion off,
forced split, fused — via the response/tensor counters, the timeline's
fusion-buffer markers, and the effective rounded threshold."""

import re

import pytest

pytestmark = pytest.mark.e2e

BATCHES, PER_BATCH = 8, 4
TENSORS = BATCHES * PER_BATCH  # 32 x 1 KB tensors


def _counters(proc):
    m = re.search(r"FUSION_COUNTERS responses=(\d+) tensors=(\d+) "
                  r"threshold=(-?\d+)", proc.stdout)
    assert m, proc.stdout + proc.stderr
    return int(m.group(1)), int(m.group(2)), int(m.group(3))


def _run(run_launcher, tmp_path, threshold=None, extra=None):
    env = {"HVD_TPU_CYCLE_TIME": "50",
           "HVD_TPU_TIMELINE": str(tmp_path / "tl.json")}
    if threshold is not None:
        env["HVD_TPU_FUSION_THRESHOLD"] = str(threshold)
    if extra:
        env.update(extra)
    proc = run_launcher(2, "fusion_worker.py", extra_env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "MISMATCH" not in proc.stdout, proc.stdout
    return proc, (tmp_path / "tl.json").read_text()


def test_fusion_off_threshold_zero(run_launcher, tmp_path):
    """HVD_TPU_FUSION_THRESHOLD=0: every tensor gets its own response
    and the fusion buffer is never touched."""
    proc, timeline = _run(run_launcher, tmp_path, threshold=0)
    responses, tensors, threshold = _counters(proc)
    assert tensors == TENSORS, (responses, tensors)
    assert responses == tensors, (responses, tensors)
    assert threshold == 0, threshold
    assert "MEMCPY_IN_FUSION_BUFFER" not in timeline


def test_fusion_default_groups_batches(run_launcher, tmp_path):
    """Default threshold (64 MB): each 4-tensor batch fuses into far
    fewer responses, through the fusion buffer."""
    proc, timeline = _run(run_launcher, tmp_path)
    responses, tensors, _ = _counters(proc)
    assert tensors == TENSORS, (responses, tensors)
    # Ideally BATCHES responses; allow stragglers when a cycle fires
    # mid-batch, but require real grouping (strictly fewer than one
    # response per tensor-pair).
    assert responses <= 2 * BATCHES, (responses, tensors)
    assert "MEMCPY_IN_FUSION_BUFFER" in timeline


def test_fusion_tiny_threshold_forces_split(run_launcher, tmp_path):
    """A 2 KB threshold fits exactly two 1 KB tensors: batches must
    split into >= 2 responses each (pair-fused at best), while still
    fusing pairs through the buffer."""
    proc, timeline = _run(run_launcher, tmp_path, threshold=2048)
    responses, tensors, threshold = _counters(proc)
    assert tensors == TENSORS, (responses, tensors)
    assert threshold == 2048, threshold
    # Strictly more responses than the fused case can produce, strictly
    # fewer than fully unfused (pairs still share).
    assert responses >= TENSORS // 2, (responses, tensors)
    assert responses < TENSORS, (responses, tensors)
    assert "MEMCPY_IN_FUSION_BUFFER" in timeline


def test_hierarchical_divisibility_rounding(tmp_path):
    """With hierarchical allreduce on, the working threshold rounds
    down to a multiple of 64 * local_size so the fused buffer splits
    into aligned local chunks (reference controller.cc:300-318). 1000
    bytes at local_size=2 -> 896."""
    from test_hierarchical import run_hierarchical_workers
    procs, outs = run_hierarchical_workers(
        "fusion_worker.py",
        extra_env={"HVD_TPU_FUSION_THRESHOLD": "1000",
                   "HVD_TPU_CYCLE_TIME": "50"})
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "MISMATCH" not in out, out
    joined = "".join(outs)
    m = re.search(r"threshold=(-?\d+)", joined)
    assert m, joined
    assert int(m.group(1)) == 896, joined


def test_cycle_time_zero_vs_paced(run_launcher, tmp_path):
    """Cycle pacing sanity: the same workload completes correctly with
    an unpaced (0 ms) and a long (50 ms) cycle; pacing must not change
    results, only latency."""
    proc, _ = _run(run_launcher, tmp_path,
                   extra={"HVD_TPU_CYCLE_TIME": "0"})
    responses, tensors, _ = _counters(proc)
    assert tensors == TENSORS, (responses, tensors)
