"""Worker for the group-scoped divergence e2e (test_groups.py): a
rank-divergent collective INSIDE one process group must error in
seconds naming the group and both call sites — and must not implicate
(or hang) ranks outside the group, which keep training."""

import os
import signal
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.ops import HorovodInternalError


def alarm(signum, frame):
    sys.stderr.write("watchdog fired: job deadlocked\n")
    sys.exit(3)


signal.signal(signal.SIGALRM, alarm)
signal.alarm(90)

hvd.init()
r, n = hvd.rank(), hvd.size()
assert n == 4
g_front = hvd.new_group([0, 1])
g_back = hvd.new_group([2, 3])

if r in (0, 1):
    # The classic rank-divergent collective, scoped to group 1: each
    # member blocks on a rank-suffixed name the other never submits.
    try:
        ops.allreduce(np.ones(4, np.float32), "div.only_%d" % r,  # hvd-lint: disable=rank-dependent-name,verify-divergent-schedule
                      group=g_front)
        raise AssertionError("group-divergent collective did not fail")
    except HorovodInternalError as e:
        msg = str(e)
        assert "divergence" in msg, msg
        assert "process group 1" in msg, msg
        assert "div.only_0" in msg and "div.only_1" in msg, msg
        print("rank %d divergence reported" % r, flush=True)
    # Outlive the back group's run: exiting now would race a clean
    # shutdown into its in-flight collectives.
    import time
    time.sleep(8)
else:
    # The OTHER group is untouched: it keeps running collectives the
    # whole time the front group is diverged (paced past the front
    # group's grace window so this process outlives the detection — an
    # early exit would race a clean shutdown into the pending tensors).
    import time
    for step in range(12):
        out = ops.allreduce(np.full(8, float(r), np.float32),
                            "back.step", group=g_back)
        assert np.allclose(out, 2 + 3), (r, step, out)
        time.sleep(0.5)
    print("rank %d unaffected group finished" % r, flush=True)
