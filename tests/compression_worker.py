"""Self-verifying host-plane compression worker (docs/COMPRESSION.md),
run under the launcher with N >= 2 ranks.

Checks, on every rank:
  * allreduce correctness under none/bf16/int8 within each codec's
    error bound, with results bitwise-identical across ranks (the
    allgather leg forwards encoded chunks verbatim);
  * compressed modes actually shrink the data-ring wire bytes (socket-
    layer net_ring_bytes counters, headers included);
  * fusion still engages under compression (several small same-mode
    tensors share one ring pass);
  * a mode change on a cached name invalidates the response-cache entry
    and renegotiates (cache-key semantics);
  * with compression off the negotiation/result path is bitwise
    identical to an uncompressed build (none == plain allreduce).

Run: python -m horovod_tpu.run.run -np 2 -- python tests/compression_worker.py
"""

import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


def counters():
    return hvd.metrics()["counters"]


def ring_bytes_for(mode, elems, r, n):
    """Measures data-ring bytes one `elems`-element f32 allreduce moves
    under `mode` (fresh tensor name each call; cycle includes both ring
    legs)."""
    x = (np.arange(elems, dtype=np.float32) / 7.0) + r
    before = counters()["net_ring_bytes_sent_total"]
    out = ops.allreduce(x, "wire.%s.%d" % (mode, elems), compression=mode)
    after = counters()["net_ring_bytes_sent_total"]
    want = (np.arange(elems, dtype=np.float32) / 7.0) * n + sum(range(n))
    tol = {"none": 1e-5, "bf16": 2e-2, "int8": 4e-2}[mode]
    err = np.max(np.abs(out - want)) / max(np.max(np.abs(want)), 1e-9)
    assert err < tol, (mode, err)
    return after - before


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2
    rng = np.random.RandomState(1234)
    base = rng.randn(8192).astype(np.float32) * 3.0

    # Correctness + cross-rank bitwise identity per mode. The reduced
    # value is allgathered (uncompressed) and every rank checks every
    # rank's copy is byte-identical to its own.
    for mode, tol in (("none", 1e-5), ("bf16", 2e-2), ("int8", 4e-2)):
        x = base + r
        out = ops.allreduce(x, "corr.%s" % mode, compression=mode)
        want = base * n + sum(range(n))
        err = np.max(np.abs(out - want)) / np.max(np.abs(want))
        assert err < tol, (mode, err)
        gathered = ops.allgather(out[None, :], "corr.g.%s" % mode)
        for rr in range(n):
            assert np.array_equal(gathered[rr], out), \
                "mode %s: rank %d result differs from rank %d" % (mode, rr, r)

    # Wire-byte A/B at the socket layer: bf16 >= 1.9x, int8 >= 3x off
    # the ring for a payload large enough that headers don't dominate.
    elems = 256 * 1024
    none_b = ring_bytes_for("none", elems, r, n)
    bf16_b = ring_bytes_for("bf16", elems, r, n)
    int8_b = ring_bytes_for("int8", elems, r, n)
    assert none_b / bf16_b >= 1.9, (none_b, bf16_b)
    assert none_b / int8_b >= 3.0, (none_b, int8_b)
    print("rank %d wire bytes none=%d bf16=%d (%.2fx) int8=%d (%.2fx)"
          % (r, none_b, bf16_b, none_b / bf16_b, int8_b, none_b / int8_b),
          flush=True)

    # Fusion under compression: enqueue several small same-mode tensors
    # in one burst; the fused-tensor counter must grow (they shared a
    # response and one compressed ring pass).
    fused_before = counters()["fused_tensors_total"]
    handles = [ops.allreduce_async(np.full(64, float(r + 1), np.float32),
                                   "fuse.%d" % i, compression="int8")
               for i in range(6)]
    for h in handles:
        out = ops.synchronize(h)
        assert np.allclose(out, sum(range(1, n + 1)), atol=0.1), out
    fused_after = counters()["fused_tensors_total"]
    assert fused_after > fused_before, (fused_before, fused_after)

    # Cache-key semantics: warm a name into the cache, then change only
    # the mode — must invalidate (miss) and renegotiate, not reuse.
    x = np.ones(100, np.float32)
    for _ in range(3):
        ops.allreduce(x, "ck", compression="none")  # hvd-lint: disable=verify-mixed-modes
    inval_before = counters()["cache_invalid_total"]
    out = ops.allreduce(x, "ck", compression="bf16")  # hvd-lint: disable=duplicate-collective-name
    assert np.allclose(out, n), out
    assert counters()["cache_invalid_total"] > inval_before

    # Mode accounting: per-mode allreduce counters moved.
    c = counters()
    assert c["allreduce_bf16_total"] >= 2, c["allreduce_bf16_total"]
    assert c["allreduce_int8_total"] >= 2, c["allreduce_int8_total"]
    assert c["compression_bytes_in_total"] > \
        c["compression_bytes_out_total"] > 0

    print("rank %d: compression worker passed" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
