"""Process groups (docs/GROUPS.md): subgroup collectives in the
negotiation core + the 2-D (batch x model) mesh on top.

e2e coverage (the ISSUE 11 acceptance set):
  * every collective kind over disjoint groups with rank remapping and
    the same tensor name live in two groups at once;
  * per-group response-cache hits on repeated steps + INVALID on a
    membership change;
  * a model-group allreduce's wire bytes <= (group/world + 5%) of the
    full-world allreduce of the same tensor;
  * a deliberately group-divergent collective errors in seconds naming
    the group and both call sites, without disturbing the other group;
  * non-member / unknown-group / mixed-membership rejection by name;
  * hvd.init(model_parallel=2) at 4 ranks trains the tensor-parallel
    transformer example to the single-process reference loss curve.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from conftest import REPO_ROOT, clean_worker_env


def test_process_group_handles():
    import horovod_tpu as hvd

    hvd.init()
    assert hvd.WORLD.id == 0
    assert hvd.WORLD.size() == hvd.size()
    g = hvd.new_group([0])
    assert g.id >= 1
    assert g.ranks == (0,)
    assert g.size() == 1
    assert g.rank() == 0  # single process: rank 0 is the member
    assert 0 in g and 1 not in g
    # Degenerate single-member group collectives are identities.
    out = hvd.allreduce(np.arange(4, dtype=np.float32), "g1.t", group=g)
    assert np.allclose(out, np.arange(4))
    with pytest.raises(ValueError):
        hvd.new_group([0, 0])
    with pytest.raises(ValueError):
        hvd.new_group([0, 99])
    with pytest.raises(ValueError):
        hvd.new_group([])


def test_group_resolver_helpers():
    import horovod_tpu as hvd
    from horovod_tpu.groups import resolve_group

    hvd.init()
    assert resolve_group(None) == 0
    assert resolve_group(hvd.WORLD) == 0
    g = hvd.new_group([0])
    assert resolve_group(g) == g.id
    assert resolve_group(3) == 3
    assert hvd.group_size(None) == hvd.size()
    assert hvd.group_rank(None) == hvd.rank()


@pytest.mark.e2e
def test_group_collectives_all_kinds(run_launcher):
    result = run_launcher(4, "group_worker.py",
                          extra_env={"GROUP_MODE": "ops"})
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("group ops ok") == 4


@pytest.mark.e2e
def test_group_cache_hits_and_membership_invalidation(run_launcher):
    """Acceptance: repeated steps in a 2-group job show cache hits in
    both groups; re-scoping a name to a new group id invalidates."""
    result = run_launcher(4, "group_worker.py",
                          extra_env={"GROUP_MODE": "cache"})
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("group cache ok") == 4


@pytest.mark.e2e
def test_group_wire_bytes_ratio(run_launcher):
    """Acceptance: the model-group (k=2 of 4) allreduce of a 1 MiB
    tensor moves <= (2/4 + 5%) of the full-world allreduce's summed
    socket bytes. (A true subgroup ring moves 2(k-1)S total vs the
    world ring's 2(n-1)S, so the measured ratio should be ~1/3.)"""
    result = run_launcher(4, "group_worker.py", extra_env={
        "GROUP_MODE": "wire",
        # Clean byte accounting: no autotune knob flips mid-measurement,
        # no pipeline slicing (extra per-segment headers).
        "HVD_TPU_AUTOTUNE": "0",
        "HVD_TPU_PIPELINE_CHUNK_BYTES": "0",
    })
    assert result.returncode == 0, result.stdout + result.stderr
    rows = re.findall(r"rank (\d+) wire world=(\d+) group=(\d+)",
                      result.stdout)
    assert len(rows) == 4, result.stdout
    world_total = sum(int(w) for _, w, _ in rows)
    group_total = sum(int(g) for _, _, g in rows)
    assert world_total > 0
    ratio = group_total / world_total
    assert ratio <= 2 / 4 + 0.05, (ratio, rows)


@pytest.mark.e2e
def test_group_rejections_by_name(run_launcher):
    result = run_launcher(2, "group_worker.py",
                          extra_env={"GROUP_MODE": "reject"})
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("group reject ok") == 2


@pytest.mark.e2e
def test_unregistered_group_errors_not_hangs(run_launcher):
    """A group the coordinator never registered (a new_group call-order
    divergence) must error past the grace window naming the group —
    the late-registration sweep only covers the benign in-flight race."""
    result = run_launcher(2, "group_worker.py", extra_env={
        "GROUP_MODE": "unknown",
        "HVD_TPU_DIVERGENCE_GRACE_SECONDS": "2",
    })
    assert result.returncode == 0, result.stdout + result.stderr
    assert "unregistered group reported" in result.stdout


@pytest.mark.e2e
def test_group_divergence_names_group_and_call_sites(run_launcher):
    """Acceptance: a deliberately group-divergent collective errors in
    seconds naming the group and both call sites, while the OTHER
    group's collectives keep completing."""
    result = run_launcher(4, "group_divergence_worker.py", extra_env={
        "HVD_TPU_DIVERGENCE_GRACE_SECONDS": "2",
    })
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("divergence reported") == 2
    assert result.stdout.count("unaffected group finished") == 2


@pytest.mark.e2e
def test_mesh_formation(run_launcher):
    result = run_launcher(4, "mesh_worker.py")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("mesh worker ok") == 4


@pytest.mark.e2e
@pytest.mark.slow
def test_tp_example_matches_reference(run_launcher, tmp_path):
    """Acceptance: examples/jax_tp_lm.py under hvd.init(model_parallel=2)
    at 4 ranks matches the single-process reference loss trajectory."""
    example = os.path.join(REPO_ROOT, "examples", "jax_tp_lm.py")
    ref_out = str(tmp_path / "ref.json")
    mesh_out = str(tmp_path / "mesh.json")
    env = clean_worker_env({"HVD_TPU_TP_REF_ROWS": "2"})
    ref = subprocess.run(
        [sys.executable, example, "--reference", "--steps", "6",
         "--loss-out", ref_out],
        env=env, timeout=300, capture_output=True, text=True)
    assert ref.returncode == 0, ref.stdout + ref.stderr

    result = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "4", "--",
         sys.executable, example, "--model-parallel", "2", "--steps", "6",
         "--loss-out", mesh_out],
        env=clean_worker_env(), timeout=600, capture_output=True, text=True)
    assert result.returncode == 0, result.stdout + result.stderr

    ref_losses = json.load(open(ref_out))["losses"]
    mesh_losses = json.load(open(mesh_out))["losses"]
    assert len(ref_losses) == len(mesh_losses) == 6
    div = max(abs(a - b) / max(abs(a), 1e-9)
              for a, b in zip(ref_losses, mesh_losses))
    assert div <= 1e-3, (div, ref_losses, mesh_losses)


@pytest.mark.e2e
def test_tp_example_refuses_pure_dp(tmp_path):
    """The acceptance model must NOT run pure data-parallel at its
    width: without model_parallel >= 2 it exits with the budget/mesh
    message."""
    example = os.path.join(REPO_ROOT, "examples", "jax_tp_lm.py")
    result = subprocess.run(
        [sys.executable, example, "--steps", "1"],
        env=clean_worker_env(), timeout=180, capture_output=True,
        text=True)
    assert result.returncode != 0
    assert "cannot run pure-DP" in (result.stdout + result.stderr)


def test_lint_group_scoped_call_not_flagged():
    """A collective with group= under a rank/membership guard is the
    legitimate mesh pattern; the rank-conditional rule must not fire
    (the runtime's group-scoped divergence detection owns misuse)."""
    import textwrap

    from horovod_tpu.lint import lint_source

    findings = lint_source(textwrap.dedent("""
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 2])
        if g.rank() >= 0:
            hvd.allreduce(x, "scoped", group=g)
    """))
    assert not [f for f in findings
                if f.rule == "rank-conditional-collective"], findings


def test_lint_rank_conditional_still_flags_ungrouped():
    """The classic world-scoped rank-conditional collective still
    errors — including when group=None is written out explicitly."""
    import textwrap

    from horovod_tpu.lint import lint_source

    findings = lint_source(textwrap.dedent("""
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() == 0:
            hvd.allreduce(x, "oops", group=None)
    """))
    assert [f for f in findings
            if f.rule == "rank-conditional-collective"], findings


def test_mesh_2d_jax_mesh():
    from horovod_tpu.parallel.mesh import mesh_2d

    mesh = mesh_2d(2)  # 8 virtual CPU devices -> (4, 2)
    assert mesh.shape["batch"] == 4
    assert mesh.shape["model"] == 2
    with pytest.raises(ValueError):
        mesh_2d(3)


def test_group_qualified_summary_fields():
    """The groups gauge and group_tensors_total ride the metrics
    snapshot (zero before any group exists)."""
    import horovod_tpu as hvd

    hvd.init()
    m = hvd.metrics()
    assert "groups" in m["gauges"]
    assert "group_tensors_total" in m["counters"]
    assert "per_group" in m
