"""Negotiation-latency scaling: the poll-multiplexed control plane must keep
per-cycle latency roughly flat as rank count grows (SURVEY §7.3's
"negotiation latency at 256 chips" wall — the former per-socket serial loop
scaled linearly). Workers are numpy+ctypes only, so launching 16 locally is
cheap."""

import pytest

import os
import re
import socket
import subprocess
import sys

pytestmark = pytest.mark.e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def run_bench(n, extra_env=None, timeout=180):
    ports = _free_ports(n)
    addrs = ",".join("127.0.0.1:%d" % p for p in ports)
    procs = []
    for r in range(n):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.update({
            "HVD_TPU_RANK": str(r),
            "HVD_TPU_SIZE": str(n),
            "HVD_TPU_LOCAL_RANK": str(r),
            "HVD_TPU_LOCAL_SIZE": str(n),
            "HVD_TPU_CROSS_RANK": "0",
            "HVD_TPU_CROSS_SIZE": "1",
            "HVD_TPU_ADDRS": addrs,
            "HVD_TPU_CYCLE_TIME": "0",
        })
        if extra_env:
            env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "tests", "negotiation_bench_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    us = None
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
        m = re.search(r"NEGOTIATION_US_PER_OP ([\d.]+)", out)
        if m:
            us = float(m.group(1))
    assert us is not None
    return us


def test_negotiation_latency_flat_vs_ranks():
    us4 = run_bench(4)
    us16 = run_bench(16)
    # Sanity: negotiation at 16 ranks stays in the tens-of-ms regime
    # even on a loaded single-core CI box (the measured curves live in
    # SCALING.md; this only guards against a protocol-level blow-up).
    assert us16 < 30000, (us4, us16)
    # The flatness claim (poll-multiplexed rank 0 services all workers
    # concurrently instead of serial round-trips) is only measurable when
    # the ranks actually run concurrently; on a 1-core box every cycle is
    # a scheduler round-robin of N processes and latency is ~N * timeslice
    # regardless of the control-plane design.
    if (os.cpu_count() or 1) >= 16:
        assert us16 < 4.0 * us4 + 500, (us4, us16)


def test_negotiation_uncached_path():
    # With the response cache off every cycle does the full gather/bcast
    # negotiation; it must still complete and stay sane.
    us8 = run_bench(8, {"HVD_TPU_CACHE_CAPACITY": "0"})
    assert us8 < 50000, us8
