"""Emits the seeded fleet-churn jobfile the sanitizer smokes run
(`make check-tsan` / `check-asan` in native/Makefile; docs/FLEET.md).

Two jobs on a localhost:4 pool — `hi` (priority 5) and `lo` (priority
0) — whose workers the Makefile's HVD_TPU_FLEET_CHAOS_SPEC then churns
with a seeded SIGKILL and a forced preemption of `lo`, driving the
crash-recovery AND drain/restore paths through the sanitized native
core. The fleet must finish rc 0 with every job completed.

Usage::

    python tests/fleet_churn_jobfile.py BASE_DIR [PRELOAD ENV...]

``BASE_DIR`` holds the per-job checkpoint dirs. When ``PRELOAD`` (a
sanitizer runtime .so) is given, the worker command is prefixed with
``env LD_PRELOAD=PRELOAD ENV...`` — the sanitizer must be preloaded
into the WORKER python only (the controller process forks; see the
Makefile's launch notes), exactly like the other sanitizer runs.
"""

import json
import os
import sys


def main():
    if len(sys.argv) < 2:
        sys.stderr.write(__doc__)
        return 2
    base = os.path.abspath(sys.argv[1])
    preload = sys.argv[2] if len(sys.argv) > 2 else ""
    extra_env = sys.argv[3:]
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "fleet_worker.py")
    command = []
    if preload:
        command += ["env", "LD_PRELOAD=%s" % preload]
        command += list(extra_env)
        command += ["HVD_TPU_METRICS=1"]
    command += [sys.executable, worker]

    def job(name, priority, steps, np_=2, min_np=1):
        return {
            "name": name, "command": command, "np": np_,
            "min_np": min_np, "priority": priority,
            "ckpt_dir": os.path.join(base, "ckpt-%s" % name),
            "env": {"FLEET_TEST_JOB": name,
                    "FLEET_TEST_TOTAL_STEPS": str(steps),
                    "FLEET_TEST_STEP_SLEEP": "0.15"},
        }

    print(json.dumps({
        "hosts": "localhost:4",
        "drain_grace": 60,
        "jobs": [job("hi", priority=5, steps=25),
                 job("lo", priority=0, steps=60)],
    }, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
