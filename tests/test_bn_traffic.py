"""Bytes-accessed regression guard for the traffic-lean BN (tier-1).

Golden JSON of the resnet50 train step's ``cost_analysis()`` bytes
under stock flax BN vs lean BN vs the norm-free floor, at a
CPU-compilable shape. The sensitive invariant is the BN-TAX reduction
(step bytes minus the norm-free floor): a future change that silently
re-materializes an activation pass — a saved x_hat, a stored ReLU mask,
a layout-copying view through the custom-VJP boundary (each measured
during round 10, see PERF.md) — adds a full per-site activation pass,
which moves the tax by ~30% while moving whole-step bytes by only ~1%.

Regenerate the golden after an INTENTIONAL change with the command in
its `regenerate` field.
"""

import json
import os

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO_ROOT, "tests", "golden",
                      "bn_traffic_resnet50.json")

# Absolute-bytes drift allowed for jax/XLA version changes before the
# golden must be regenerated; the tax assertion below is the tight one.
ABS_TOLERANCE = 0.20
# Allowed tax-reduction slack: a single re-materialized activation pass
# at the golden shape moves the tax reduction by ~0.3, far outside.
TAX_TOLERANCE = 0.08


@pytest.fixture(scope="module")
def measured():
    import sys
    sys.path.insert(0, REPO_ROOT)
    import bench

    cfg = json.load(open(GOLDEN))["config"]
    return {norm: bench.bn_traffic_step_stats(
        norm, batch=cfg["batch"], image_size=cfg["image_size"],
        dtype=cfg["dtype"])
        for norm in ("batch", "lean", "none")}


def test_lean_bn_tax_reduction_holds(measured):
    golden = json.load(open(GOLDEN))
    stock = measured["batch"]["bytes_accessed"]
    lean = measured["lean"]["bytes_accessed"]
    floor = measured["none"]["bytes_accessed"]
    assert lean < stock, (lean, stock)
    tax_reduction = 1.0 - (lean - floor) / (stock - floor)
    assert tax_reduction >= golden["bn_tax_reduction"] - TAX_TOLERANCE, (
        "lean BN's bytes-accessed advantage over stock flax BN "
        "regressed: tax reduction %.4f vs golden %.4f (+/-%.2f). A "
        "change re-materialized an activation pass the lean path "
        "exists to eliminate (stored x_hat / stored ReLU mask / "
        "layout-copying view). If intentional, regenerate %s with the "
        "command in its `regenerate` field."
        % (tax_reduction, golden["bn_tax_reduction"], TAX_TOLERANCE,
           GOLDEN))


def test_absolute_bytes_near_golden(measured):
    """Coarse drift alarm: jax/XLA upgrades legitimately move absolute
    bytes; past +/-20% the golden no longer describes this toolchain
    and must be regenerated so the tax assertion stays meaningful."""
    golden = json.load(open(GOLDEN))
    for norm, key in (("batch", "stock_bytes_accessed"),
                      ("lean", "lean_bytes_accessed"),
                      ("none", "normfree_floor_bytes_accessed")):
        got = measured[norm]["bytes_accessed"]
        ref = golden[key]
        assert abs(got - ref) <= ABS_TOLERANCE * ref, (
            "%s train-step bytes drifted beyond %d%% of the golden "
            "(%.4g vs %.4g): regenerate %s (see its `regenerate` "
            "field) so the BN-tax guard keeps a meaningful baseline"
            % (norm, 100 * ABS_TOLERANCE, got, ref, GOLDEN))
