"""TensorFlow/Keras binding tests (reference analogues:
test/test_tensorflow.py, test/test_keras.py). Multi-process correctness
runs via the launcher; sparse helpers in-process."""

import numpy as np
import pytest

pytestmark = pytest.mark.e2e

tf = pytest.importorskip("tensorflow")


def test_tensorflow_distributed(run_launcher):
    proc = run_launcher(2, "tf_ops_worker.py", timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert ("rank %d: all tensorflow tests passed" % r) in proc.stdout, \
            proc.stdout + proc.stderr


def test_tf1_graph_mode_broadcast(run_launcher):
    """TF1 compat surface: BroadcastGlobalVariablesHook +
    broadcast_global_variables under Session/MonitoredTrainingSession
    (reference tensorflow/__init__.py:87-141,160-193)."""
    proc = run_launcher(2, "tf1_worker.py", timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert ("rank %d: tf1 graph-mode broadcast tests passed" % r) in \
            proc.stdout, proc.stdout + proc.stderr


def test_tf_compression_roundtrip():
    from horovod_tpu.tensorflow.compression import Compression
    x = tf.constant(np.random.randn(16).astype(np.float32))
    for codec in (Compression.none, Compression.fp16, Compression.bf16):
        c, ctx = codec.compress(x)
        out = codec.decompress(c, ctx)
        assert out.dtype == x.dtype
        assert np.allclose(out.numpy(), x.numpy(), atol=1e-2)


def test_jax_sparse_helpers():
    import jax.numpy as jnp
    from horovod_tpu.jax.sparse import apply_sparse, densify

    param = jnp.zeros((5, 2))
    idx = jnp.array([1, 1, 3])
    val = jnp.ones((3, 2))
    out = apply_sparse(param, idx, val)
    assert np.allclose(np.asarray(out[1]), 2.0)  # duplicates accumulate
    assert np.allclose(np.asarray(out[3]), 1.0)

    dense = densify(idx, val, 5)
    assert dense.shape == (5, 2)
    assert np.allclose(np.asarray(dense[1]), 2.0)
