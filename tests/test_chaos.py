"""Chaos harness (docs/CHAOS.md): the negotiation fuzz runs under a
matrix of seeded fault specs — drop / corrupt / delay / close / stall,
across the control star and the data ring, on worker and coordinator
sides — with ONE invariant:

    every run either completes with verified-correct results, or fails
    within its deadline with a clean error naming the injected cause.
    No hangs. No wrong answers. No silent success.

The fuzz worker itself asserts numerical correctness of every completed
collective, so "completes" == "completes correctly"; a CRC regression
that let a corrupted frame through would surface as the worker's value
assertion, not a pass.

Two e2e cases cap the acceptance criteria: a mid-stream corrupted frame
raises the recoverable connection-lost error (never wrong gradients),
and a killed-then-restarted control connection reconnects with backoff
without restarting the job.
"""

import os
import re
import time

import pytest

pytestmark = pytest.mark.e2e

# Per-run wall deadline: fault runs must resolve promptly (the timeout
# knobs below put every failure path well under this), and a hang is
# itself a failed invariant.
DEADLINE = 90

# Tight timeouts so provoked failures surface in seconds: net deadline
# 4s, coordinator poll 6s, reconnect window 3s. Stall checks pushed out
# of the way — the transport deadlines, not the stall inspector, must be
# what fires here.
CHAOS_ENV = {
    "HVD_TPU_NET_TIMEOUT_SECONDS": "4",
    "HVD_TPU_CONTROL_POLL_TIMEOUT_SECONDS": "6",
    "HVD_TPU_RECONNECT_SECONDS": "3",
    "HVD_TPU_STALL_CHECK_TIME_SECONDS": "60",
    # Six rounds of nine tensors: negotiation traffic then flows across
    # the WHOLE run (~16 control sends on a worker, ~2-3 per round), so
    # a frame-indexed fault lands mid-run — with work still pending to
    # verify after it — instead of in post-completion heartbeats.
    "HVD_TPU_FUZZ_TENSORS": "9",
    "HVD_TPU_FUZZ_ROUNDS": "6",
}

# (id, spec, outcome, causes)
#   outcome "recover": the job must complete (rc 0) — the fault is
#     absorbed (delays) or healed (control reconnect).
#   outcome "fail": the job must die before DEADLINE with one of
#     `causes` named in its output.
#   outcome "either": both legal — the invariant is only "correct
#     completion OR a prompt cause-named failure".
# Specs filter by rank so worker-side (rank 1) frame counters are
# deterministic; coordinator-side (rank 0) rules use the multiplexed
# control path. Frame indices are low because a 9-tensor fuzz round
# exchanges only a few control frames per worker.
#
# The recoverable close cases pin dir=send: a close before a SEND
# leaves both sides at the same completed-frame cursor, so the resume
# deterministically matches. A close on a RECV races the coordinator's
# send completion — resumable if the response was still in flight,
# cursor-mismatch failover if it had fully left — so that case is
# "either" by design (both outcomes clean, and the refusal proves the
# desync guard).
MATRIX = [
    ("ctl-close-reconnect",
     "seed=1;rank=1,chan=control,dir=send,frame=3,action=close",
     "recover", ["re-established"]),
    ("ctl-close-early-reconnect",
     "seed=2;rank=1,chan=control,dir=send,frame=2,action=close",
     "recover", ["re-established"]),
    ("ctl-close-recv",
     "seed=15;rank=1,chan=control,dir=recv,frame=4,action=close",
     "either", ["re-established", "cursor mismatch", "connection lost"]),
    ("ctl-delay-prob",
     "seed=3;rank=1,chan=control,prob=0.3,action=delay,delay_ms=50",
     "recover", []),
    ("ring-delay-prob",
     "seed=4;rank=1,chan=ring,prob=0.3,action=delay,delay_ms=50",
     "recover", []),
    ("ring-corrupt-send",
     "seed=5;rank=1,chan=ring,dir=send,frame=3,action=corrupt",
     "fail", ["checksum mismatch"]),
    ("ctl-corrupt-send",
     "seed=6;rank=1,chan=control,dir=send,frame=8,action=corrupt",
     "fail", ["checksum mismatch"]),
    ("ctl-corrupt-recv",
     "seed=7;rank=1,chan=control,dir=recv,frame=8,action=corrupt",
     "fail", ["checksum mismatch"]),
    ("coord-corrupt-send",
     "seed=8;rank=0,chan=control,dir=send,frame=8,action=corrupt",
     "fail", ["checksum mismatch"]),
    ("coord-corrupt-recv",
     "seed=16;rank=0,chan=control,dir=recv,frame=8,action=corrupt",
     "fail", ["checksum mismatch"]),
    ("ring-close",
     "seed=9;rank=1,chan=ring,frame=3,action=close",
     "fail", ["connection closed", "connection lost", "timeout",
              "deadline"]),
    ("ctl-drop-send",
     "seed=10;rank=1,chan=control,dir=send,frame=8,action=drop",
     "fail", ["timeout", "deadline", "connection"]),
    ("coord-drop-send",
     "seed=11;rank=0,chan=control,dir=send,frame=8,action=drop",
     "fail", ["timeout", "deadline", "connection"]),
    ("ctl-stall",
     "seed=12;rank=1,chan=control,dir=send,frame=8,action=stall,"
     "delay_ms=30000",
     "fail", ["timeout", "deadline", "connection"]),
    ("ring-stall",
     "seed=13;rank=1,chan=ring,frame=3,action=stall,delay_ms=30000",
     "fail", ["timeout", "deadline", "connection"]),
    ("ring-drop",
     "seed=14;rank=1,chan=ring,dir=send,frame=3,action=drop",
     "fail", ["timeout", "deadline", "connection"]),
    # Shared-memory plane (docs/TRANSPORT.md): chan=shm filters by
    # TRANSPORT — on a same-host 2-rank job every data leg rides shm by
    # default, so these target the new plane directly. The invariant is
    # byte-identical to the socket legs': a corrupted shm frame is a
    # prompt cause-naming CRC failure (never wrong gradients), and a
    # torn-down ring mid-hop is a prompt CONNECTION_LOST (never a hang).
    ("shm-corrupt-send",
     "seed=21;rank=1,chan=shm,dir=send,frame=2,action=corrupt",
     "fail", ["checksum mismatch"]),
    ("shm-close",
     "seed=22;rank=1,chan=shm,dir=send,frame=2,action=close",
     "fail", ["connection closed", "connection lost", "timeout",
              "deadline"]),
    ("shm-stall",
     "seed=23;rank=1,chan=shm,frame=3,action=stall,delay_ms=30000",
     "fail", ["timeout", "deadline", "connection", "stalled"]),
    ("shm-delay-prob",
     "seed=24;rank=1,chan=shm,prob=0.3,action=delay,delay_ms=50",
     "recover", []),
]


@pytest.mark.parametrize("name,spec,outcome,causes",
                         MATRIX, ids=[m[0] for m in MATRIX])
def test_chaos_matrix(run_launcher, name, spec, outcome, causes):
    env = dict(CHAOS_ENV)
    env["HVD_TPU_FAULT_SPEC"] = spec
    t0 = time.monotonic()
    result = run_launcher(2, "negotiation_fuzz_worker.py", extra_env=env,
                          timeout=DEADLINE + 30)
    elapsed = time.monotonic() - t0
    out = result.stdout + result.stderr

    # Invariant 0: no hangs — every run resolves inside the deadline.
    assert elapsed < DEADLINE, \
        "%s: run took %.0fs (hang?)" % (name, elapsed)
    # The spec must actually have armed (a parse error disables
    # injection and would make every case pass vacuously).
    assert "fault injection ACTIVE" in out, out

    if outcome == "recover":
        # Invariant 1: recoverable faults are absorbed — completed run,
        # every collective's value verified by the worker itself.
        assert result.returncode == 0, (name, out[-3000:])
        assert out.count("negotiation fuzz passed") == 2, (name,
                                                           out[-3000:])
    elif outcome == "either":
        # Both outcomes legal; both must be CLEAN: a completed run
        # verified its values, a failed one named its cause promptly.
        assert any(c in out for c in causes), (name, out[-3000:])
        if result.returncode == 0:
            assert out.count("negotiation fuzz passed") == 2, (name,
                                                               out[-3000:])
        assert "SILENT CORRUPTION" not in out
        return
    else:
        # Invariant 2: fatal faults fail CLEANLY — nonzero exit, no
        # silent success, and the output names the injected cause.
        assert result.returncode != 0, \
            "%s: injected fault produced a silent success" % name
        assert "fault injected" in out, (name, out[-3000:])
        assert any(c in out for c in causes), \
            "%s: failure does not name its cause (%s): %s" % (
                name, causes, out[-3000:])
        # Never a wrong answer: a value-assertion failure would mean a
        # corrupted frame made it into a result.
        assert "SILENT CORRUPTION" not in out
    for cause in causes:
        if outcome == "recover" and cause:
            assert cause in out, (name, cause, out[-3000:])


def test_chaos_corrupt_frame_raises_connection_lost(run_launcher):
    """Acceptance: a mid-stream corrupted data-ring frame surfaces as a
    detected checksum mismatch inside a recoverable connection-lost
    error — and every collective that completed before it returned
    correct values (no wrong gradients, ever)."""
    env = dict(CHAOS_ENV)
    env["HVD_TPU_FAULT_SPEC"] = \
        "seed=21;rank=1,chan=ring,dir=send,frame=10,action=corrupt"
    env["HVD_TPU_CHAOS_EXPECT_FAILURE"] = "1"
    t0 = time.monotonic()
    result = run_launcher(2, "chaos_worker.py", extra_env=env,
                          timeout=DEADLINE + 30)
    elapsed = time.monotonic() - t0
    out = result.stdout + result.stderr
    assert elapsed < DEADLINE, "took %.0fs" % elapsed
    # The worker exits 0 IFF the fault surfaced as the expected
    # connection-lost error; wrong values or a missed injection exit
    # nonzero.
    assert result.returncode == 0, out[-3000:]
    assert "chaos: connection lost surfaced cleanly" in out
    assert "checksum mismatch" in out
    assert "SILENT CORRUPTION" not in out


def test_chaos_control_reconnect_without_restart(run_launcher):
    """Acceptance: a killed-then-restarted control connection reconnects
    with capped backoff and the job runs to a verified-correct
    completion — no restart, no elastic rollback."""
    env = dict(CHAOS_ENV)
    env["HVD_TPU_RECONNECT_SECONDS"] = "10"
    env["HVD_TPU_FAULT_SPEC"] = \
        "seed=22;rank=1,chan=control,dir=send,frame=4,action=close"
    result = run_launcher(2, "negotiation_fuzz_worker.py", extra_env=env,
                          timeout=DEADLINE + 30)
    out = result.stdout + result.stderr
    assert result.returncode == 0, out[-3000:]
    assert "fault injected: close" in out
    assert "control connection re-established" in out
    assert "accepted control reconnect from rank 1" in out
    assert out.count("negotiation fuzz passed") == 2


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_chaos_compression_corrupt_frame(run_launcher, mode):
    """Compression-on variant of the corrupt-frame acceptance e2e: the
    ring payloads are now ENCODED (bf16/int8 + in-band scales), and the
    CRC covers the compressed frame — so a mid-stream corruption is
    still a detected checksum mismatch surfacing as the recoverable
    connection-lost error, and every completed collective returned
    correct (codec-bounded) values. Invariant unchanged: verified-
    correct completion or a prompt cause-naming failure."""
    env = dict(CHAOS_ENV)
    env["HVD_TPU_COMPRESSION"] = mode
    env["HVD_TPU_FAULT_SPEC"] = \
        "seed=24;rank=1,chan=ring,dir=send,frame=10,action=corrupt"
    env["HVD_TPU_CHAOS_EXPECT_FAILURE"] = "1"
    t0 = time.monotonic()
    result = run_launcher(2, "chaos_worker.py", extra_env=env,
                          timeout=DEADLINE + 30)
    elapsed = time.monotonic() - t0
    out = result.stdout + result.stderr
    assert elapsed < DEADLINE, "took %.0fs" % elapsed
    assert result.returncode == 0, out[-3000:]
    assert "chaos: connection lost surfaced cleanly" in out
    assert "checksum mismatch" in out
    assert "SILENT CORRUPTION" not in out


def test_chaos_compression_reconnect(run_launcher):
    """Compression-on variant of the reconnect spec: a killed control
    connection heals under backoff while every allreduce rides the int8
    wire — the run completes with all values verified by the worker."""
    env = dict(CHAOS_ENV)
    env["HVD_TPU_COMPRESSION"] = "int8"
    env["HVD_TPU_RECONNECT_SECONDS"] = "10"
    env["HVD_TPU_FAULT_SPEC"] = \
        "seed=25;rank=1,chan=control,dir=send,frame=4,action=close"
    result = run_launcher(2, "negotiation_fuzz_worker.py", extra_env=env,
                          timeout=DEADLINE + 30)
    out = result.stdout + result.stderr
    assert result.returncode == 0, out[-3000:]
    assert "fault injected: close" in out
    assert "control connection re-established" in out
    assert out.count("negotiation fuzz passed") == 2


def test_chaos_reconnect_metrics_counted(run_launcher):
    """The recovery counters (docs/METRICS.md) record the healed fault:
    reconnect attempts/successes and the injected-fault tally are
    visible in the worker's own metrics snapshot."""
    env = dict(CHAOS_ENV)
    env["HVD_TPU_RECONNECT_SECONDS"] = "10"
    env["HVD_TPU_METRICS"] = "1"
    env["HVD_TPU_FAULT_SPEC"] = \
        "seed=23;rank=1,chan=control,dir=send,frame=4,action=close"
    result = run_launcher(2, "metrics_chaos_worker.py", extra_env=env,
                          timeout=DEADLINE + 30)
    out = result.stdout + result.stderr
    assert result.returncode == 0, out[-3000:]
    rows = [tuple(int(v) for v in m)
            for m in re.findall(r"chaos metrics: reconnects=(\d+) "
                                r"attempts=(\d+) faults=(\d+)", out)]
    assert len(rows) == 2, out[-3000:]
    # Rank 1 (the faulted side) shows the healed fault; both rows obey
    # attempts >= successes.
    assert any(rec >= 1 and faults >= 1 for rec, _, faults in rows), rows
    assert all(att >= rec for rec, att, _ in rows), rows
