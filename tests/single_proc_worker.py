"""Single-process short-circuit checks (size == 1 fast paths)."""

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    assert hvd.size() == 1 and hvd.rank() == 0
    assert hvd.local_rank() == 0 and hvd.cross_rank() == 0
    x = np.arange(6, dtype=np.float32)
    assert np.allclose(hvd.allreduce(x, "x"), x)
    assert np.allclose(hvd.allreduce(x, "xa", average=True), x)
    assert np.allclose(hvd.allgather(x.reshape(2, 3), "g"), x.reshape(2, 3))
    assert np.allclose(hvd.broadcast(x, 0, "b"), x)
    print("single-process OK")


if __name__ == "__main__":
    main()
