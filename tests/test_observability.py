"""Timeline + stall-inspector e2e tests (reference analogues:
test/test_timeline.py, test/test_stall.py). The `run_launcher` harness
lives in conftest.py."""

import pytest

import json

pytestmark = pytest.mark.e2e


def test_timeline(run_launcher, tmp_path):
    timeline_file = str(tmp_path / "timeline.json")
    proc = run_launcher(2, "timeline_worker.py", extra_env={
        "HVD_TPU_TIMELINE": timeline_file,
        "HVD_TPU_TIMELINE_MARK_CYCLES": "1",
    })
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(timeline_file) as f:
        content = f.read()
    assert "NEGOTIATE_ALLREDUCE" in content
    assert "ALLREDUCE" in content
    assert "NEGOTIATE_ALLGATHER" in content
    assert "CYCLE_START" in content
    # A cleanly shut down timeline is a strictly valid chrome-tracing
    # JSON array (closed bracket, no trailing comma) — whole-file parse,
    # no record-wise comma stripping.
    records = json.loads(content)
    assert isinstance(records, list) and len(records) > 0
    # Every record is an object with a phase marker.
    assert all(isinstance(r, dict) and "ph" in r for r in records)


def test_stall_detection_and_shutdown(run_launcher):
    proc = run_launcher(2, "stall_worker.py", extra_env={
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS": "5",
    }, timeout=120)
    out = proc.stdout + proc.stderr
    assert "rank 0 exited cleanly" in out, out
    assert "rank 1 exited cleanly" in out, out
    # Coordinator must have warned about the missing rank.
    assert "missing ranks: 1" in out, out


def test_protocol_counters_cache_fast_path(run_launcher):
    """The response cache's PROTOCOL-LEVEL win (SURVEY 7.3 / reference
    response_cache.cc:308-409): with the cache on, steady-state cycles
    are bit-vector-only (cycles_fast dominates, bytes/op small and
    name-independent); with it off, every cycle is a full coordinator
    round trip carrying serialized request lists."""
    import json

    def counters_from(proc):
        out = {}
        for line in proc.stdout.splitlines():
            if line.startswith("COUNTERS "):
                d = json.loads(line[len("COUNTERS "):])
                out[d["rank"]] = d
        return out

    cached = run_launcher(2, "protocol_counters_worker.py")
    assert cached.returncode == 0, cached.stdout + cached.stderr
    uncached = run_launcher(2, "protocol_counters_worker.py",
                            extra_env={"HVD_TPU_CACHE_CAPACITY": "0"})
    assert uncached.returncode == 0, uncached.stdout + uncached.stderr
    c = counters_from(cached)
    u = counters_from(uncached)
    assert set(c) == {0, 1} and set(u) == {0, 1}, (c, u)

    # Cached steady state: every op-carrying cycle rode the fast path
    # (cycles_full counts only WORK cycles — idle heartbeat round
    # trips are excluded by the controller — so any full work cycle
    # here would mean the cache regressed).
    for r in (0, 1):
        assert c[r]["cycles_fast"] > 0, c
        assert c[r]["cycles_full"] == 0, c
        # Uncached: zero fast cycles, every work cycle a round trip.
        assert u[r]["cycles_fast"] == 0, u
        assert u[r]["cycles_full"] >= 1, u

    # The protocol claim: per-op control bytes with the cache are a
    # small fraction of without (bit vector vs serialized RequestList
    # with a long tensor name + frame headers both directions).
    for r in (0, 1):
        per_op_cached = (c[r]["ctrl_bytes_sent"] +
                         c[r]["ctrl_bytes_recv"]) / c[r]["ops"]
        per_op_uncached = (u[r]["ctrl_bytes_sent"] +
                           u[r]["ctrl_bytes_recv"]) / u[r]["ops"]
        assert per_op_cached < per_op_uncached / 2, \
            (r, per_op_cached, per_op_uncached)


def test_stall_warn_then_recover_with_cache(run_launcher):
    """Warn-only stall detection must RECOVER, not livelock: a rank
    straggling past the check threshold on an already-CACHED tensor
    triggers the stall inspector's cache invalidation; the invalidated
    local hit renegotiates and the job completes once the straggler
    returns. Pins the controller's invalid_in_queue fast-path gate —
    without it the renegotiated request is dropped by the all-cached
    fast path and the job deadlocks with a permanent "missing ranks"
    stall (found live during the round-5 timeline capture)."""
    # Straggle must comfortably outlast BOTH stall clocks in sequence
    # (cached-entry invalidation after ~2s, then the renegotiated
    # tensor's own 2s warning window) plus scheduler slop on a loaded
    # single-core host — at 7s the warning intermittently lost the race
    # against the straggler's return and the assert below flaked.
    proc = run_launcher(2, "timeline_chip_worker.py", extra_env={
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVD_TPU_TL_STRAGGLE": "12",
    }, timeout=300)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    # The invalidation path must actually have run: without a stall
    # warning the fast-path-drop scenario this test pins was never
    # reached and a green result would be vacuous.
    assert "missing ranks:" in out, out
    # Both ranks finished with the same model (the straggle step's
    # gradients were not lost or double-applied).
    assert out.count("final loss") == 2, out
    losses = set(l.split("final loss ")[1].split(" ")[0]
                 for l in out.splitlines() if "final loss" in l)
    assert len(losses) == 1, losses
