"""Timeline + stall-inspector e2e tests (reference analogues:
test/test_timeline.py, test/test_stall.py). The `run_launcher` harness
lives in conftest.py."""

import pytest

import json

pytestmark = pytest.mark.e2e


def test_timeline(run_launcher, tmp_path):
    timeline_file = str(tmp_path / "timeline.json")
    proc = run_launcher(2, "timeline_worker.py", extra_env={
        "HVD_TPU_TIMELINE": timeline_file,
        "HVD_TPU_TIMELINE_MARK_CYCLES": "1",
    })
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(timeline_file) as f:
        content = f.read()
    assert "NEGOTIATE_ALLREDUCE" in content
    assert "ALLREDUCE" in content
    assert "NEGOTIATE_ALLGATHER" in content
    assert "CYCLE_START" in content
    # Every emitted record must be valid JSON (file is a trailing-comma
    # chrome-tracing array; validate record-wise).
    for line in content.splitlines():
        line = line.strip().rstrip(",")
        if line in ("[", "") or line.startswith("]"):
            continue
        json.loads(line)


def test_stall_detection_and_shutdown(run_launcher):
    proc = run_launcher(2, "stall_worker.py", extra_env={
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS": "5",
    }, timeout=120)
    out = proc.stdout + proc.stderr
    assert "rank 0 exited cleanly" in out, out
    assert "rank 1 exited cleanly" in out, out
    # Coordinator must have warned about the missing rank.
    assert "missing ranks: 1" in out, out
