"""Shared-memory transport worker (tests/test_shm.py harness): runs
allreduce / reduce-scatter / broadcast across none/bf16/int8 wire codecs
and uneven sizes (small HVD_TPU_PIPELINE_CHUNK_BYTES slices them into
pipelined segments, including ragged tails), asserts exact values, and
prints a transport-independent CRC32 digest of every result plus the shm
counters — so the harness can prove (a) bitwise parity of shm-vs-TCP
runs and (b) whether (and how much) the shm plane engaged.

Values are small integers (exact in f32 under any summation order, and
constant fills for int8 quantize exactly), so assertions are
np.array_equal and the digest is bitwise-stable across transports."""

import json
import sys
import zlib

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


SIZES = [1, 7, 785, 4 * 256 + 5, 65536 + 3]


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    digest = 0
    for mode in ["none", "bf16", "int8"]:
        for size in SIZES:
            if mode == "int8":
                x = np.full(size, float(r + 1), np.float32)
                want = np.full(size, sum(range(1, n + 1)), np.float32)
            else:
                i = np.arange(size, dtype=np.float32)
                x = np.asarray((i % 13) + r + 1, np.float32)
                want = np.asarray(n * (i % 13) + sum(range(1, n + 1)),
                                  np.float32)
            out = ops.allreduce(x, "shm.ar.%s.%d" % (mode, size),
                                compression=mode)
            if not np.array_equal(out, want):
                print("ALLREDUCE MISMATCH mode %s size %d rank %d"
                      % (mode, size, r), flush=True)
                return 1
            digest = zlib.crc32(out.tobytes(), digest)
            shard = ops.reduce_scatter(x, "shm.rs.%s.%d" % (mode, size),
                                       compression=mode)
            counts, offsets = ops.shard_partition(size, n)
            if not np.array_equal(
                    shard, want[offsets[r]:offsets[r] + counts[r]]):
                print("REDUCE_SCATTER MISMATCH mode %s size %d rank %d"
                      % (mode, size, r), flush=True)
                return 1
            digest = zlib.crc32(shard.tobytes(), digest)
    want_b = np.arange(4096, dtype=np.float32) * 3.0
    b = want_b.copy() if r == 0 else np.zeros(4096, np.float32)
    out = ops.broadcast(b, 0, "shm.bcast")
    if not np.array_equal(out, want_b):
        print("BROADCAST MISMATCH rank %d" % r, flush=True)
        return 1
    digest = zlib.crc32(out.tobytes(), digest)
    snap = hvd.metrics()
    print("SHM_DIGEST %08x" % (digest & 0xFFFFFFFF), flush=True)
    print("SHM_METRICS %s" % json.dumps({
        "rank": r,
        "segments": snap["gauges"]["shm_segments_active"],
        "shm_sent": snap["counters"]["net_shm_bytes_sent_total"],
        "shm_recv": snap["counters"]["net_shm_bytes_recv_total"],
        "ring_sent": snap["counters"]["net_ring_bytes_sent_total"],
    }), flush=True)
    print("rank %d shm worker done" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
