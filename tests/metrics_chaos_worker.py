"""Worker for the chaos metrics e2e: run a short verified allreduce
stream under an injected control-close fault, then print the transport
recovery counters from this rank's own metrics snapshot."""

import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    for i in range(20):
        arr = np.full((64,), float(r + 1 + i), np.float32)
        out = ops.synchronize(ops.allreduce_async(arr, "mchaos.%d" % i))
        assert np.allclose(out, sum(rr + 1 + i for rr in range(n))), i
    snap = hvd.metrics()["counters"]
    print("chaos metrics: reconnects=%d attempts=%d faults=%d"
          % (snap["net_reconnects_total"],
             snap["net_reconnect_attempts_total"],
             snap["faults_injected_total"]), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
