"""Metrics-plane tests (docs/METRICS.md): pure-Python units for the
Prometheus renderer / aggregator, plus the 2-process e2e that scrapes
both worker endpoints and the rank-0 job view while a deliberate
straggler runs (tests/metrics_worker.py)."""

import json
import socket

import pytest

from horovod_tpu._metrics import aggregate, render_prometheus


# ---------------------------------------------------------------- units

def _snap(**over):
    snap = {
        "counters": {"tensors_enqueued_total": 7},
        "gauges": {"queue_depth": 3},
        "histograms": {
            "cycle_seconds": {"bounds": [0.1, 1.0, 5.0],
                              "counts": [2, 3, 1, 4],
                              "sum": 2.5, "count": 10},
        },
        "rank_lag_seconds": [0.0, 1.5],
    }
    snap.update(over)
    return snap


def test_render_prometheus_counter_gauge_and_labels():
    text = render_prometheus(_snap(), labels={"rank": 0})
    assert "# TYPE hvdtpu_tensors_enqueued_total counter" in text
    assert 'hvdtpu_tensors_enqueued_total{rank="0"} 7' in text
    assert "# TYPE hvdtpu_queue_depth gauge" in text
    assert 'hvdtpu_queue_depth{rank="0"} 3' in text
    # Coordinator lag table renders per-rank labeled samples.
    assert 'hvdtpu_rank_announce_lag_seconds_total{rank="1"} 1.5' in text


def test_render_prometheus_histogram_buckets_are_cumulative():
    text = render_prometheus(_snap())
    assert "# TYPE hvdtpu_cycle_seconds histogram" in text
    # Raw per-bucket counts [2, 3, 1, 4] must render cumulatively.
    assert 'hvdtpu_cycle_seconds_bucket{le="0.1"} 2' in text
    assert 'hvdtpu_cycle_seconds_bucket{le="1"} 5' in text
    assert 'hvdtpu_cycle_seconds_bucket{le="5"} 6' in text
    assert 'hvdtpu_cycle_seconds_bucket{le="+Inf"} 10' in text
    assert "hvdtpu_cycle_seconds_sum 2.5" in text
    assert "hvdtpu_cycle_seconds_count 10" in text


def test_render_prometheus_no_labels():
    text = render_prometheus(_snap(rank_lag_seconds=[]))
    assert "hvdtpu_tensors_enqueued_total 7" in text
    assert "rank_announce_lag" not in text  # all-zero/absent table elided


def test_aggregate_min_max_mean_argmax():
    agg = aggregate({"0": {"x": 1.0, "y": 5.0},
                     "1": {"x": 3.0, "y": 5.0},
                     "2": {"x": 2.0}})  # missing y -> 0
    assert agg["x"] == {"min": 1.0, "max": 3.0, "mean": 2.0,
                       "argmax_rank": 1}
    assert agg["y"]["min"] == 0.0 and agg["y"]["max"] == 5.0
    assert aggregate({}) == {}


# ------------------------------------------------------------------ e2e

def _free_port_pair():
    """A base port where base and base+1 are both currently free (the
    two workers bind base+rank; ThreadingHTTPServer sets
    allow_reuse_address so the close->bind handoff is safe)."""
    for _ in range(64):
        s1 = socket.socket()
        s1.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s1.bind(("127.0.0.1", 0))
        base = s1.getsockname()[1]
        if base + 1 > 65535:
            s1.close()
            continue
        s2 = socket.socket()
        s2.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s2.bind(("127.0.0.1", base + 1))
        except OSError:
            s1.close()
            continue
        s1.close()
        s2.close()
        return base
    raise RuntimeError("no free adjacent port pair")


@pytest.mark.e2e
def test_metrics_endpoints_parity_and_straggler(run_launcher):
    """The acceptance scenario: 2 workers expose Prometheus endpoints,
    rank 0 exposes the job aggregate, hvd.metrics() matches the scraped
    values, and the deliberately straggling rank 1 is identifiable from
    the job view (announce-lag) and from `hvd-top --once` — all while
    the job is still running. Cache off so every step is a full
    negotiation (the cached path's straggler attribution goes through
    stall-invalidation -> renegotiation, pinned by
    test_stall_warn_then_recover_with_cache)."""
    base = _free_port_pair()
    proc = run_launcher(2, "metrics_worker.py", extra_env={
        "HVD_TPU_METRICS_PORT": str(base),
        "HVD_TPU_METRICS_SYNC_SECONDS": "0.25",
        "HVD_TPU_CACHE_CAPACITY": "0",
        "HVD_TPU_TEST_STRAGGLE": "2.0",
    }, timeout=300)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert "METRICS_E2E_OK" in out, out
    assert out.count("done") >= 2, out
    lag_line = [l for l in proc.stdout.splitlines()
                if l.startswith("METRICS_E2E_OK")][0]
    lag = json.loads(lag_line.split("lag=", 1)[1])
    assert lag[1] > lag[0], lag


@pytest.mark.e2e
def test_launcher_metrics_port_flag(run_launcher, tmp_path):
    """`horovodrun_tpu --metrics-port` injects the base port into the
    worker env (workers offset by rank themselves)."""
    import os
    import subprocess
    import sys

    from conftest import clean_worker_env

    base = _free_port_pair()
    script = tmp_path / "echo_port.py"
    script.write_text(
        "import os\n"
        "import horovod_tpu as hvd\n"
        "hvd.init()\n"
        "print('PORT', os.environ['HVD_TPU_METRICS_PORT'])\n"
        "from horovod_tpu import _metrics\n"
        "print('SERVING', _metrics.server_port())\n")
    env = clean_worker_env()
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "1",
         "--metrics-port", str(base), "--",
         sys.executable, str(script)],
        env=env, timeout=120, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PORT %d" % base in proc.stdout, proc.stdout
    assert "SERVING %d" % base in proc.stdout, proc.stdout
    assert "metrics:" in proc.stderr, proc.stderr
