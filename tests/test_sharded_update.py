"""ZeRO-style sharded weight update (docs/ZERO.md): shard-partition
math units, the jax ring reduce-scatter/allgather pair (parity vs
psum_scatter, wire compression fused per hop, round-trip reassembly),
the single-process degenerate forms of the host-plane sharded
optimizer, zero1 x wire compression in make_train_step, and the
launcher e2es — framework parity at 2 and 4 ranks plus the
mixed-execution-mode rejection."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

jax.config.update("jax_default_matmul_precision", "highest")

from horovod_tpu.common.ops import shard_partition  # noqa: E402


# --- shard partition units --------------------------------------------------


def test_shard_partition_golden():
    assert shard_partition(10, 3) == ([4, 3, 3], [0, 4, 7])
    assert shard_partition(101, 2) == ([51, 50], [0, 51])
    assert shard_partition(7, 8) == ([1] * 7 + [0], list(range(7)) + [7])
    assert shard_partition(0, 4) == ([0] * 4, [0] * 4)


@pytest.mark.parametrize("count,n", [(1, 1), (17, 4), (256, 3), (1000, 7)])
def test_shard_partition_invariants(count, n):
    counts, offsets = shard_partition(count, n)
    assert sum(counts) == count
    assert max(counts) - min(counts) <= 1  # near-equal
    assert offsets[0] == 0
    for i in range(1, n):
        assert offsets[i] == offsets[i - 1] + counts[i - 1]
    # Earlier ranks absorb the remainder (chunk i owned by rank i; the
    # native PartitionChunks mirrors this exactly).
    assert counts == sorted(counts, reverse=True)


# --- jax ring reduce-scatter / allgather ------------------------------------


def _mesh():
    cpus = jax.devices("cpu")
    return Mesh(np.array(cpus), ("hvd",)), len(cpus)


@pytest.mark.parametrize("mode,tol", [("none", 1e-6), ("bf16", 1e-2),
                                      ("int8", 2e-2)])
def test_ring_reduce_scatter_matches_summed_chunks(mode, tol):
    """Every device's shard equals its chunk of the cross-device sum,
    for an odd-sized tensor (pad path) under every wire mode."""
    from horovod_tpu import compression as comp
    from horovod_tpu.parallel.ring import ring_reduce_scatter

    mesh, n = _mesh()
    size = 1003  # odd: exercises the pad-to-block path
    x = np.stack([(np.linspace(-1, 1, size) * (r + 1)).astype(np.float32)
                  for r in range(n)])
    f = jax.jit(jax.shard_map(
        lambda v: ring_reduce_scatter(v.reshape(-1), "hvd",
                                      compression=mode),
        mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
        check_vma=False))
    out = np.asarray(f(jnp.asarray(x)))  # concatenated shards

    c = -(-(-(-size // n)) // comp.BLOCK) * comp.BLOCK
    want = np.zeros(n * c, np.float32)
    want[:size] = x.sum(axis=0)
    assert out.shape == (n * c,)
    # mode none differs from the numpy reference only by f32 sum-order
    # rounding (the ring accumulates sequentially).
    scale = np.abs(want).max()
    assert np.max(np.abs(out - want)) <= tol * scale + 1e-6, mode


def test_ring_allgather_reassembles_in_rank_order():
    """Each device contributes chunk r; every device gets the ordered
    concatenation, bitwise-identical across devices (mode none and the
    encode-once compressed path)."""
    from horovod_tpu.parallel.ring import ring_allgather

    from horovod_tpu import compression as comp

    mesh, n = _mesh()
    for mode in ("none", "int8"):
        # Compressed shards must be int8-block-aligned — exactly what
        # ring_reduce_scatter produces; mode none takes any length.
        c = comp.BLOCK if mode == "int8" else 37
        shards = np.stack([np.full(c, r + 1, np.float32) +
                           np.linspace(0, 1, c).astype(np.float32) * r
                           for r in range(n)])
        f = jax.jit(jax.shard_map(
            lambda v: ring_allgather(v.reshape(-1), "hvd",
                                     compression=mode),
            mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
            check_vma=False))
        out = np.asarray(f(jnp.asarray(shards))).reshape(n, n * c)
        # Bitwise-identical on every device: the compressed payload
        # travels verbatim and the owner decodes its own copy.
        for r in range(1, n):
            np.testing.assert_array_equal(out[r], out[0], err_msg=mode)
        if mode == "none":
            np.testing.assert_array_equal(out[0], shards.reshape(-1))
        else:
            # int8 is lossy but block-bounded.
            assert np.max(np.abs(out[0] - shards.reshape(-1))) < 2e-2 * \
                np.abs(shards).max()


def test_ring_scatter_then_allgather_is_allreduce():
    """ring_allgather(ring_reduce_scatter(x)) == padded cross-device
    sum — the fused sharded-update path reassembles exactly what the
    allreduce would have produced (mode none: bitwise)."""
    from horovod_tpu import compression as comp
    from horovod_tpu.parallel.ring import (ring_allgather,
                                           ring_reduce_scatter)

    mesh, n = _mesh()
    size = 777
    rng = np.random.RandomState(5)
    x = rng.randn(n, size).astype(np.float32)

    def both(v):
        shard = ring_reduce_scatter(v.reshape(-1), "hvd")
        return ring_allgather(shard, "hvd")

    f = jax.jit(jax.shard_map(both, mesh=mesh, in_specs=P("hvd"),
                              out_specs=P("hvd"), check_vma=False))
    c = -(-(-(-size // n)) // comp.BLOCK) * comp.BLOCK
    out = np.asarray(f(jnp.asarray(x))).reshape(n, n * c)
    want = np.zeros(n * c, np.float32)
    want[:size] = x.sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(out[r], want, rtol=1e-6, atol=1e-5)


def test_zero1_with_wire_compression_matches_plain():
    """make_train_step(zero1=True, compression='int8'): the compressed
    scatter leg keeps the loss curve on the exact path's trajectory
    (PR 6 composition, previously rejected)."""
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(13, 7).astype(np.float32) * 0.3),
              "b": jnp.asarray(rng.randn(7).astype(np.float32))}
    x = jnp.asarray(rng.randn(32, 13).astype(np.float32))
    y = jnp.asarray(rng.randn(32, 7).astype(np.float32))

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] + p["b"] - batch["y"]) ** 2)

    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    opt = optax.adam(1e-2)
    plain = make_train_step(loss_fn, opt, mesh, donate=False)
    p1, s1, b1 = plain.place(params, opt.init(params), {"x": x, "y": y})
    z = make_train_step(loss_fn, opt, mesh, donate=False, zero1=True,
                        compression="int8")
    p2, s2, b2 = z.place(params, None, {"x": x, "y": y})
    losses1, losses2 = [], []
    for _ in range(5):
        p1, s1, l1 = plain(p1, s1, b1)
        p2, s2, l2 = z(p2, s2, b2)
        losses1.append(float(l1))
        losses2.append(float(l2))
    rel = np.abs(np.asarray(losses2) - np.asarray(losses1)) / \
        (np.abs(np.asarray(losses1)) + 1e-8)
    assert rel.max() < 0.05, (losses1, losses2)
    # Legacy tensor codecs stay rejected under zero1.
    from horovod_tpu import jax as hvd_jax
    with pytest.raises(ValueError, match="legacy"):
        make_train_step(loss_fn, opt, mesh, zero1=True,
                        compression=hvd_jax.Compression.fp16)
    # ...but the no-op Compression.none codec is exempt (replicated-era
    # call sites pass it explicitly; parity with the wrappers).
    make_train_step(loss_fn, opt, mesh, zero1=True,
                    compression=hvd_jax.Compression.none)


# --- single-process host plane (world size 1 degenerate forms) --------------


@pytest.fixture(scope="module")
def init_hvd():
    import horovod_tpu as hvd
    hvd.init()
    yield hvd


def test_reduce_scatter_world1_identity(init_hvd):
    hvd = init_hvd
    x = np.linspace(-2, 2, 11).astype(np.float32)
    out = hvd.reduce_scatter(x, "rs.w1")
    np.testing.assert_array_equal(np.asarray(out), x)
    avg = hvd.reduce_scatter(x, "rs.w1avg", average=True)
    np.testing.assert_array_equal(np.asarray(avg), x)


def test_sharded_optimizer_world1_matches_plain(init_hvd):
    from horovod_tpu import jax as hvd_jax

    rng = np.random.RandomState(3)
    params = {"w": jnp.asarray(rng.randn(4, 3).astype(np.float32)),
              "b": jnp.asarray(rng.randn(3).astype(np.float32))}
    opt = optax.adam(1e-2)
    sharded = hvd_jax.DistributedOptimizer(opt, sharded_update=True)
    p, s = dict(params), sharded.init(params)
    rp, rs = dict(params), opt.init(params)
    for step in range(3):
        g = {k: jnp.asarray(np.full(v.shape, 0.1 * (step + 1),
                                    np.float32))
             for k, v in params.items()}
        u, s = sharded.update(g, s, p)
        p = optax.apply_updates(p, u)
        ru, rs = opt.update(g, rs, rp)
        rp = optax.apply_updates(rp, ru)
    for k in params:
        np.testing.assert_allclose(np.asarray(p[k]), np.asarray(rp[k]),
                                   rtol=1e-6, atol=1e-6, err_msg=k)
    # Full/shard round-trip at world 1 is the identity.
    full = hvd_jax.sharded_state_full(s)
    assert full["world"] == -1 and full["rank"] == -1
    back = hvd_jax.sharded_state_shard(full)
    for a, b in zip(jax.tree_util.tree_leaves(back["inner"]),
                    jax.tree_util.tree_leaves(s["inner"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_update_requires_params(init_hvd):
    from horovod_tpu import jax as hvd_jax

    sharded = hvd_jax.DistributedOptimizer(optax.sgd(0.1),
                                           sharded_update=True)
    s = sharded.init({"w": jnp.ones(4)})
    with pytest.raises(ValueError, match="params"):
        sharded.update({"w": jnp.ones(4)}, s)


def test_env_default_engages_sharded_mode(init_hvd, monkeypatch):
    """HVD_TPU_SHARDED_UPDATE=1 flips wrappers that got no explicit
    sharded_update= argument (the job-wide knob, docs/ZERO.md)."""
    import horovod_tpu as hvd
    from horovod_tpu import jax as hvd_jax

    monkeypatch.setenv("HVD_TPU_SHARDED_UPDATE", "1")
    assert hvd.get_basics().sharded_update_default() is True
    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1))
    s = opt.init({"w": jnp.ones(4)})
    assert isinstance(s, dict) and s["world"] == 1  # sharded state layout
    monkeypatch.setenv("HVD_TPU_SHARDED_UPDATE", "0")
    assert hvd.get_basics().sharded_update_default() is False
    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1))
    assert not isinstance(opt.init({"w": jnp.ones(4)}), dict)
    # The wrappers share the native strtol parse: any nonzero value
    # engages the mode everywhere (no =2-means-different-things skew).
    monkeypatch.setenv("HVD_TPU_SHARDED_UPDATE", "2")
    assert hvd.get_basics().sharded_update_default() is True
    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1))
    assert isinstance(opt.init({"w": jnp.ones(4)}), dict)


def test_reduce_scatter_out_buffer_validation(init_hvd):
    """A caller-controlled `out` hands its base pointer to the native
    core: wrong size, dtype, or a strided view must be a ValueError,
    never a silent heap overrun."""
    from horovod_tpu.common import ops as _ops

    t = np.arange(8, dtype=np.float32)  # world=1: shard == whole array
    with pytest.raises(ValueError, match="elements"):
        _ops.reduce_scatter_async(t, "rs.out.size",
                                  out=np.empty(5, np.float32))
    with pytest.raises(ValueError, match="C-contiguous"):
        _ops.reduce_scatter_async(t, "rs.out.dtype",
                                  out=np.empty(8, np.float16))
    with pytest.raises(ValueError, match="C-contiguous"):
        _ops.reduce_scatter_async(t, "rs.out.stride",
                                  out=np.empty(16, np.float32)[::2])


def test_sharded_state_full_idempotent_and_shard_guards(init_hvd):
    from horovod_tpu import jax as hvd_jax

    opt = optax.adam(1e-2)
    sharded = hvd_jax.DistributedOptimizer(opt, sharded_update=True)
    s = sharded.init({"w": jnp.ones(8)})
    full = hvd_jax.sharded_state_full(s)
    # Idempotent on an already-full state (no collective, no crash).
    assert hvd_jax.sharded_state_full(full) is full
    back = hvd_jax.sharded_state_shard(full)
    # Pass-through when already sharded for THIS rank/world...
    assert hvd_jax.sharded_state_shard(back) is back
    # ...but a foreign (rank, world) shard cannot be re-sliced locally.
    foreign = dict(back)
    foreign["world"], foreign["rank"] = 7, 3
    with pytest.raises(ValueError, match="rank 3 of 7"):
        hvd_jax.sharded_state_shard(foreign)
    # sharded_state_full refuses a stale membership too: the old
    # world's shards are gone, so allgathering over the CURRENT ranks
    # would reassemble a short buffer and silently label it full.
    with pytest.raises(RuntimeError, match="rank 3 of 7"):
        hvd_jax.sharded_state_full(foreign)


def test_jax_sharded_accepts_legacy_none_codec(init_hvd):
    """Replicated-era `compression=Compression.none` call sites keep
    working under a job-wide HVD_TPU_SHARDED_UPDATE rollout (parity
    with the torch/tf wrappers)."""
    from horovod_tpu import jax as hvd_jax

    opt = hvd_jax.DistributedOptimizer(
        optax.sgd(0.1), sharded_update=True,
        compression=hvd_jax.Compression.none)
    s = opt.init({"w": jnp.ones(4)})
    assert isinstance(s, dict) and s["world"] == 1


def test_torch_sharded_state_dict_roundtrip(init_hvd):
    import torch

    from horovod_tpu import torch as hvd_torch

    def build():
        torch.manual_seed(7)
        model = torch.nn.Linear(5, 3)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9),
            named_parameters=model.named_parameters(),
            sharded_update=True)
        return model, opt

    def step(model, opt, seed):
        g = np.random.RandomState(seed)
        for p in model.parameters():
            p.grad = torch.from_numpy(
                g.randn(*p.shape).astype(np.float32))
        opt.step()

    model1, opt1 = build()
    step(model1, opt1, 0)
    saved = opt1.state_dict()
    assert "hvd_sharded" in saved

    # A fresh wrapper restored from the dict continues the SAME
    # trajectory (moments survive the round trip).
    model2, opt2 = build()
    step(model2, opt2, 0)
    opt2.load_state_dict(saved)
    step(model1, opt1, 1)
    step(model2, opt2, 1)
    for (_, a), (_, b) in zip(model1.named_parameters(),
                              model2.named_parameters()):
        np.testing.assert_array_equal(a.detach().numpy(),
                                      b.detach().numpy())

    # A replicated optimizer's dict (no sharded payload) is rejected
    # loudly instead of silently zeroing the moments.
    with pytest.raises(ValueError, match="sharded"):
        opt2.load_state_dict(
            {k: v for k, v in saved.items() if k != "hvd_sharded"})
    # A foreign (rank, world) shard payload is rejected too.
    foreign = dict(saved)
    foreign["hvd_sharded"] = dict(saved["hvd_sharded"], world=4, rank=2)
    with pytest.raises(RuntimeError, match="rank 2 of 4"):
        opt2.load_state_dict(foreign)


def test_torch_sharded_lr_scheduler_propagates(init_hvd):
    """LR schedulers mutate the WRAPPER's param_groups; the shard-local
    inner optimizer must follow (it once ran at the construction-time
    lr forever), keeping the sharded trajectory on the replicated one."""
    import torch

    from horovod_tpu import torch as hvd_torch

    def run(sharded):
        torch.manual_seed(3)
        model = torch.nn.Linear(4, 2)
        opt = hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9),
            named_parameters=model.named_parameters(),
            sharded_update=sharded)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=2,
                                                gamma=0.1)
        g = np.random.RandomState(11)
        for _ in range(5):
            for p in model.parameters():
                p.grad = torch.from_numpy(
                    g.randn(*p.shape).astype(np.float32))
            opt.step()
            sched.step()
        return model, opt

    m_rep, _ = run(False)
    m_shd, o_shd = run(True)
    # The inner shard optimizer followed the schedule...
    assert o_shd.param_groups[0]["lr"] == pytest.approx(
        o_shd._hvd_inner.param_groups[0]["lr"])
    assert o_shd.param_groups[0]["lr"] < 0.1
    # ...so the trajectories agree (world 1: allreduce == identity).
    for (_, a), (_, b) in zip(m_rep.named_parameters(),
                              m_shd.named_parameters()):
        np.testing.assert_allclose(a.detach().numpy(),
                                   b.detach().numpy(), rtol=1e-6)


def test_sharded_rejects_legacy_codecs(init_hvd):
    import torch

    from horovod_tpu import jax as hvd_jax
    from horovod_tpu import torch as hvd_torch

    with pytest.raises(ValueError, match="wire compression"):
        hvd_jax.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                     compression=hvd_jax.Compression.fp16)
    model = torch.nn.Linear(3, 2)
    with pytest.raises(ValueError, match="wire compression"):
        hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            sharded_update=True, compression=hvd_torch.Compression.fp16)
    with pytest.raises(ValueError, match="backward_passes_per_step"):
        hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            sharded_update=True, backward_passes_per_step=2)


# --- launcher e2es ----------------------------------------------------------


@pytest.mark.e2e
def test_sharded_parity_all_frameworks_2_ranks(run_launcher):
    """jax + torch + tf sharded optimizers match their replicated
    references at 2 ranks, with the opt_state_bytes memory claim and
    int8-on-the-scatter-leg asserted in-worker."""
    result = run_launcher(2, "sharded_update_worker.py",
                          {"SHARDED_TEST_FRAMEWORKS": "jax,torch,tf"},
                          timeout=420)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert result.stdout.count("sharded update worker passed") == 2
    assert result.stdout.count("jax sharded parity passed") == 2
    assert result.stdout.count("torch sharded parity passed") == 2
    assert result.stdout.count("tf sharded parity passed") == 2


@pytest.mark.e2e
def test_sharded_parity_4_ranks_uneven_shards(run_launcher):
    """4 ranks over 101 elements: every shard size differs from the
    padding remainder (26/25/25/25) — the uneven-partition path."""
    result = run_launcher(4, "sharded_update_worker.py", timeout=420)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert result.stdout.count("sharded update worker passed") == 4


@pytest.mark.e2e
def test_elastic_shrink_then_regrow_with_sharded_update():
    """Acceptance (docs/ZERO.md): elastic shrink-then-regrow with the
    sharded update enabled. Worker 1 kills itself at gen-0 step 7; the
    survivors roll back to the step-5 commit, RE-SHARD the committed
    full-form Adam state for world size 2, continue, and a respawned
    worker regrows the job to 3 — training completes with the loss
    decreasing across both membership changes."""
    import os
    import re
    import subprocess
    import sys

    from tests.conftest import REPO_ROOT, clean_worker_env

    env = clean_worker_env({
        "HVD_TPU_ELASTIC_COOLDOWN": "2",
        "HVD_TPU_ELASTIC_DISCOVERY_INTERVAL": "0.3",
        "HVD_TPU_START_TIMEOUT": "30",
        "HVD_TPU_SHARDED_UPDATE": "1",  # the job-wide knob rides too
        "DURABLE_TEST_TOTAL_STEPS": "30",
        "DURABLE_TEST_COMMIT_EVERY": "5",
        "DURABLE_TEST_CRASH_STEP": "7",
        "DURABLE_TEST_CRASH_WIDS": "1",
        "DURABLE_TEST_STEP_SLEEP": "0.25",
    })
    result = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "3",
         "--min-np", "1", "--",
         sys.executable, os.path.join(REPO_ROOT, "tests",
                                      "sharded_durable_worker.py")],
        env=env, timeout=240, capture_output=True, text=True)
    out = result.stdout
    assert result.returncode == 0, (out, result.stderr)
    assert "worker 1 crashing now" in out

    line = re.compile(r"worker (\S+) gen (\d+) step (\d+) size (\d+) "
                      r"loss ([0-9.]+)")
    rows = [(w, int(g), int(s), int(n), float(l))
            for w, g, s, n, l in line.findall(out)]
    gen0 = [r for r in rows if r[1] == 0]
    gen1 = [r for r in rows if r[1] == 1]
    grown = [r for r in rows if r[1] >= 2]
    assert gen0 and gen1 and grown, rows

    # Shrink: generation 1 runs at size 2 and resumes from the step-5
    # commit (the committed full-form optimizer state re-sharded 3->2).
    assert all(r[3] == 2 for r in gen1)
    assert min(r[2] for r in gen1) == 6
    # Grow: a later generation reaches size 3 again with a respawned
    # worker id outside the original cohort (full re-shard 2->3).
    assert any(r[3] == 3 for r in grown)
    assert any(not r[0].isdigit() or int(r[0]) > 2 for r in grown), \
        "replacement worker not absorbed"

    done = re.findall(r"done step (\d+) crc [0-9a-f]{8} loss ([0-9.]+)",
                      out)
    assert len(done) == 3, out
    assert all(int(s) == 30 for s, _ in done)
    final_loss = float(done[0][1])
    assert final_loss < min(r[4] for r in gen0)


@pytest.mark.e2e
def test_mixed_mode_ranks_rejected_naming_both(run_launcher):
    """One sharded rank meeting one replicated rank fails FAST with an
    error naming both ranks and both modes — at the raw-collective level
    and at the optimizer level (acceptance, docs/ZERO.md)."""
    result = run_launcher(2, "sharded_mixed_worker.py", timeout=180)
    assert result.returncode == 0, (result.stdout, result.stderr)
    out = result.stdout
    assert out.count("mixed-mode rejected naming both ranks and modes") == 2
    assert out.count("optimizer-level mixed mode rejected") == 2
    assert out.count("mixed worker passed") == 2
