"""Hierarchical reduce-scatter worker (2-host x 2-slot forced topology,
test_hierarchical.py harness): every rank reduce-scatters deterministic
payloads and asserts its shard equals logical chunk `rank` of the exact
cross-rank sum — identical to what the flat ring op produces — while the
metrics registry proves the TWO-LEVEL path actually executed
(reduce_scatter_hierarchical_total > 0 iff HVD_TPU_HIERARCHICAL_REDUCESCATTER=1).

Values are small integers (exact in f32 under any summation order, and
constant fills for int8 quantize exactly), so the assertion is
np.array_equal even though the hierarchical composite sums in a
different order than the flat ring."""

import json
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


SIZES = [1, 7, 785, 4 * 256 + 5, 65536 + 3]


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert hvd.is_homogeneous()
    for mode in ["none", "bf16", "int8"]:
        for size in SIZES:
            if mode == "int8":
                x = np.full(size, float(r + 1), np.float32)
                expected = np.full(size, sum(range(1, n + 1)), np.float32)
            else:
                i = np.arange(size, dtype=np.float32)
                x = np.asarray((i % 11) + r + 1, np.float32)
                expected = np.asarray(
                    n * (i % 11) + sum(range(1, n + 1)), np.float32)
            shard = ops.reduce_scatter(x, "hrs.%s.%d" % (mode, size),
                                      compression=mode)
            counts, offsets = ops.shard_partition(size, n)
            want = expected[offsets[r]:offsets[r] + counts[r]]
            if not np.array_equal(shard, want):
                print("MISMATCH mode %s size %d rank %d" % (mode, size, r),
                      flush=True)
                return 1
    snap = hvd.metrics()["counters"]
    print("HRS_METRICS %s" % json.dumps({
        "rank": r,
        "hierarchical": snap["reduce_scatter_hierarchical_total"],
        "total": snap["reduce_scatter_total"],
    }), flush=True)
    print("rank %d hier reduce-scatter done" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
