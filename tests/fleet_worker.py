"""Fleet e2e worker: deterministic quadratic training with durable
commits, shared by the fleet chaos / drain-durability tests
(docs/FLEET.md).

Runs under a fleet-controller-owned elastic driver (or plain
``horovodrun_tpu``). Every commit prints a CRC32C fingerprint of the
full state and the first line inside ``train()`` prints the state the
(re)entry STARTED from, so tests can assert a preempted/killed job
resumes bitwise-identically to a state it committed earlier — the
checkpoint-lineage invariant.

Knobs (env):
  FLEET_TEST_JOB          job name echoed in every line   (default "?")
  FLEET_TEST_TOTAL_STEPS  total optimization steps        (default 30)
  FLEET_TEST_COMMIT_EVERY commit cadence in steps         (default 1)
  FLEET_TEST_STEP_SLEEP   per-step sleep seconds          (default 0.1)
"""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.elastic import durable

JOB = os.environ.get("FLEET_TEST_JOB", "?")
TOTAL_STEPS = int(os.environ.get("FLEET_TEST_TOTAL_STEPS", "30"))
COMMIT_EVERY = int(os.environ.get("FLEET_TEST_COMMIT_EVERY", "1"))
STEP_SLEEP = float(os.environ.get("FLEET_TEST_STEP_SLEEP", "0.1"))
LR = 0.05
TARGET = 3.0

WID = os.environ.get("HVD_TPU_WORKER_ID", "?")


def state_crc(state):
    crc = durable.crc32c(np.ascontiguousarray(state.w).tobytes())
    return durable.crc32c(("step=%d" % state.step).encode(), crc)


@elastic.run
def train(state):
    print("job %s worker %s start step %d crc %08x size %d"
          % (JOB, WID, state.step, state_crc(state), hvd.size()),
          flush=True)
    while state.step < TOTAL_STEPS:
        grad_local = 2.0 * (state.w - TARGET)
        grad = np.asarray(hvd.allreduce(grad_local, "grad", average=True))
        state.w = state.w - LR * grad
        state.step += 1
        if state.step % COMMIT_EVERY == 0:
            # Print BEFORE commit(): commit saves the snapshot first and
            # only then checks for drain/membership interrupts, so the
            # printed crc is exactly the state any rollback, durable
            # force-write, or resume must reproduce — even when commit()
            # raises and the line after it would never run.
            print("job %s worker %s commit step %d crc %08x"
                  % (JOB, WID, state.step, state_crc(state)), flush=True)
            state.commit()
        time.sleep(STEP_SLEEP)
    return float(np.sum((state.w - TARGET) ** 2))


def main():
    state = elastic.ElasticState(w=np.zeros(4, np.float64), step=0)
    final_loss = train(state)
    if final_loss is None:  # job finished before this worker could join
        print("job %s worker %s superseded (job already complete)"
              % (JOB, WID), flush=True)
        return 0
    print("job %s worker %s done step %d crc %08x loss %.6f"
          % (JOB, WID, state.step, state_crc(state), final_loss),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
