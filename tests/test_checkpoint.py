"""Checkpoint/consistent-restore e2e (SURVEY §5.4): rank 0 owns the
files; other ranks restore over the broadcast plane with no shared
filesystem."""

import pytest

pytestmark = pytest.mark.e2e


def test_checkpoint_restore_via_broadcast(run_launcher):
    result = run_launcher(2, "checkpoint_worker.py")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("checkpoint tests passed") == 2

