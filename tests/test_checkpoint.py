"""Checkpoint/consistent-restore e2e (SURVEY §5.4): rank 0 owns the
files; other ranks restore over the broadcast plane with no shared
filesystem."""

import pytest

pytestmark = pytest.mark.e2e


def test_checkpoint_restore_via_broadcast(run_launcher):
    result = run_launcher(2, "checkpoint_worker.py")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("checkpoint tests passed") == 2


def test_sharded_params_roundtrip(tmp_path):
    """Multi-chip checkpoint shape: a params tree PLACED on an
    (dp x ep) mesh (expert weights sharded over ep) must save and
    restore losslessly and re-place onto the same shardings — the
    orbax path a pod checkpoint takes."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.jax import checkpoint
    from horovod_tpu.parallel.expert import ep_param_specs

    hvd.init()
    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "ep"))
    rng = np.random.RandomState(11)
    params = {
        "router": jnp.asarray(rng.randn(16, 8).astype(np.float32)),
        "w_in": jnp.asarray(rng.randn(8, 16, 32).astype(np.float32)),
        "w_out": jnp.asarray(rng.randn(8, 32, 16).astype(np.float32)),
    }
    specs = ep_param_specs(params, "ep")
    placed = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)

    path = str(tmp_path / "sharded_ckpt")
    checkpoint.save(path, placed, step=3)
    template = jax.tree_util.tree_map(jnp.zeros_like, params)
    restored = checkpoint.restore(path, template, step=3)
    for k in params:
        np.testing.assert_array_equal(np.asarray(restored[k]),
                                      np.asarray(params[k]))
    # Re-place on the mesh: the pod-resume step.
    replaced = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        restored, specs)
    assert replaced["w_in"].sharding.spec == specs["w_in"]
    np.testing.assert_array_equal(np.asarray(replaced["w_out"]),
                                  np.asarray(params["w_out"]))
