"""Worker for the runtime divergence cross-check e2e (test_divergence.py).

Modes (DIVERGENCE_MODE env):
  cross_stall — every rank sync-blocks on a rank-suffixed collective name
      (the classic rank-divergent collective). Without the detector this
      hangs until the stall-inspector timeout (default: forever); with it,
      every rank gets a prompt HorovodInternalError naming BOTH sides of
      the divergence.
  progress — rank 0 submits an extra async collective under a rank
      conditional, then all ranks keep training in lockstep. The progress
      rule fails the orphan collective once rank 1 has demonstrably moved
      past it, naming the calls rank 1 made instead; training on the
      common path is untouched.
  assert — all collectives complete, but ranks enqueued them in different
      orders; hvd.jax.assert_synchronized() catches the sequence digest
      mismatch.
"""

import os
import signal
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.ops import HorovodInternalError


def alarm(signum, frame):
    sys.stderr.write("watchdog fired: job deadlocked\n")
    sys.exit(3)


signal.signal(signal.SIGALRM, alarm)
signal.alarm(90)

mode = os.environ.get("DIVERGENCE_MODE", "cross_stall")
hvd.init()
r = hvd.rank()
hvd.allreduce(np.ones(4, dtype=np.float32), "warmup")

if mode == "cross_stall":
    t0 = time.time()
    try:
        # hvd-lint: disable=rank-dependent-name
        hvd.allreduce(np.ones(4, dtype=np.float32), "diverged.%d" % r)  # hvd-lint: disable=verify-divergent-schedule
        sys.stderr.write("rank %d: divergent collective completed?!\n" % r)
        sys.exit(4)
    except HorovodInternalError as e:
        msg = str(e)
        assert "divergence" in msg, msg
        assert ("diverged.%d" % r) in msg, msg
        # The report names the OTHER side's call site too.
        assert ("diverged.%d" % (1 - r)) in msg, msg
        print("divergence reported in %.1fs" % (time.time() - t0))
elif mode == "progress":
    handle = None
    if r == 0:
        # hvd-lint: disable=rank-conditional-collective
        handle = ops.allreduce_async(np.ones(2, np.float32), "only_rank0")
    for i in range(100):
        hvd.allreduce(np.ones(4, dtype=np.float32), "step.%d" % i)
    if r == 0:
        try:
            ops.synchronize(handle)
            sys.stderr.write("orphan collective completed?!\n")
            sys.exit(4)
        except HorovodInternalError as e:
            msg = str(e)
            assert "only_rank0" in msg and "rank 1" in msg, msg
            assert "step." in msg, msg  # names what rank 1 did instead
        print("divergence reported")
    else:
        print("finished all steps")
elif mode == "assert":
    import horovod_tpu.jax as hvd_jax

    hvd_jax.assert_synchronized()  # identical so far: must pass
    names = ["a", "b"] if r == 0 else ["b", "a"]
    handles = [ops.allreduce_async(np.ones(2, np.float32), n)
               for n in names]
    for h in handles:
        ops.synchronize(h)
    try:
        hvd_jax.assert_synchronized()
        sys.stderr.write("rank %d: digest mismatch not detected\n" % r)
        sys.exit(4)
    except hvd_jax.DivergenceError as e:
        assert "diverged" in str(e)
        print("divergence reported")
else:
    sys.stderr.write("unknown DIVERGENCE_MODE %r\n" % mode)
    sys.exit(5)
