"""Mixed-compression negotiation rejection (docs/COMPRESSION.md): rank 0
requests bf16 while every other rank requests int8 for the SAME tensor.
The coordinator must reject the op with an error NAMING both ranks and
both modes — on every rank, promptly, never a hang or a silently
mis-decoded frame.

Run: python -m horovod_tpu.run.run -np 2 -- python tests/compression_mixed_worker.py
"""

import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.ops import HorovodInternalError


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2
    x = np.ones(100, np.float32)

    mode = "bf16" if r == 0 else "int8"
    try:
        ops.allreduce(x, "mixed", compression=mode)  # hvd-lint: disable=verify-mixed-modes
    except HorovodInternalError as e:
        msg = str(e)
        assert "Mismatched compression modes" in msg, msg
        assert "bf16" in msg and "int8" in msg, msg
        assert "rank 0" in msg, msg
        print("rank %d: mixed-mode rejected with both modes named" % r,
              flush=True)
    else:
        raise SystemExit("mixed-mode allreduce unexpectedly succeeded")

    # The error is per-tensor, not fatal: a subsequent uniform-mode op
    # on the same communicator completes.
    out = ops.allreduce(x, "uniform", compression="int8")
    assert np.allclose(out, n, atol=0.1), out
    print("rank %d: mixed worker passed" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
