"""Sharded-update x durable-checkpoint e2e worker (docs/ZERO.md):
deterministic training through ``DistributedOptimizer(
sharded_update=True)`` with elastic commits. The optimizer state lives
SHARDED (1/N of the Adam moments per rank); at every commit it is
materialized into its world-size-independent full form
(``sharded_state_full``) so it rides the rank-sharded durable
checkpoint writer and re-shards to ANY world size on restore
(``sharded_state_shard`` at generation entry).

Gradients are identical across ranks and quantized to a 1/1024 grid, so
the ring reduce-scatter's sum and the /N averaging are EXACT in f32 at
world sizes 1, 2 and 4 — the whole training trajectory is bitwise
world-size-independent, which is what lets the test assert a killed
2-rank run resumed at half (1) or double (4) size lands on
bitwise-identical parameters vs an uninterrupted run.

Prints the same start/commit/done CRC32C fingerprint lines as
durable_worker.py.

Knobs (env):
  DURABLE_TEST_TOTAL_STEPS  total optimization steps      (default 24)
  DURABLE_TEST_COMMIT_EVERY commit cadence in steps       (default 2)
  DURABLE_TEST_STEP_SLEEP   per-step sleep seconds        (default 0.1)
  DURABLE_TEST_CRASH_STEP   step at which crashers exit   (-1 = never)
  DURABLE_TEST_CRASH_WIDS   csv of worker ids that crash (generation 0
                            only)
  DURABLE_TEST_PID_DIR      write pid.<wid> files here
"""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu import jax as hvd_jax
from horovod_tpu.elastic import durable

TOTAL_STEPS = int(os.environ.get("DURABLE_TEST_TOTAL_STEPS", "24"))
COMMIT_EVERY = int(os.environ.get("DURABLE_TEST_COMMIT_EVERY", "2"))
STEP_SLEEP = float(os.environ.get("DURABLE_TEST_STEP_SLEEP", "0.1"))
CRASH_STEP = int(os.environ.get("DURABLE_TEST_CRASH_STEP", "-1"))
CRASH_WIDS = set(
    w for w in os.environ.get("DURABLE_TEST_CRASH_WIDS", "").split(",")
    if w)
LR = 0.05
TARGET = 3.0
SHAPES = {"w": (19,), "b": (6,)}  # 25 elements: uneven at 2 and 4 ranks

WID = os.environ.get("HVD_TPU_WORKER_ID", "?")


def state_crc(state):
    """CRC32C over params + full-form optimizer moments + step —
    bitwise identity across restarts AND world sizes."""
    crc = 0
    for k in sorted(state.params):
        crc = durable.crc32c(
            np.ascontiguousarray(state.params[k]).tobytes(), crc)
    if state.opt_full:
        import jax
        for leaf in jax.tree_util.tree_leaves(state.opt_full["inner"]):
            crc = durable.crc32c(
                np.ascontiguousarray(np.asarray(leaf)).tobytes(), crc)
    return durable.crc32c(("step=%d" % state.step).encode(), crc)


def _quantized_grads(params):
    """2*(w - target) rounded to a 1/1024 grid: identical on every rank
    and EXACTLY summable/averagable at world sizes 1/2/4 in f32."""
    out = {}
    for k, v in params.items():
        g = 2.0 * (np.asarray(v, np.float32) - TARGET)
        out[k] = (np.round(g * 1024.0) / 1024.0).astype(np.float32)
    return out


@elastic.run
def train(state):
    import jax.numpy as jnp
    import optax

    opt = optax.adam(LR)
    sharded = hvd_jax.DistributedOptimizer(opt, sharded_update=True)  # hvd-lint: disable=missing-initial-broadcast
    params = {k: jnp.asarray(v) for k, v in state.params.items()}
    # Re-shard the world-independent full form for THIS rank and world
    # size — fresh start (main() seeds the full form of a fresh init,
    # so durable restore always sees a structure-matching state),
    # durable restore, and post-resize rollback all take the same path.
    s = hvd_jax.sharded_state_shard(state.opt_full)
    print("worker %s start step %d crc %08x size %d"
          % (WID, state.step, state_crc(state), hvd.size()), flush=True)
    while state.step < TOTAL_STEPS:
        gen = int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)
        g = {k: jnp.asarray(v)
             for k, v in _quantized_grads(params).items()}
        updates, s = sharded.update(g, s, params)
        params = optax.apply_updates(params, updates)
        state.step += 1
        loss = float(sum(np.sum((np.asarray(v) - TARGET) ** 2)
                         for v in params.values()))
        print("worker %s gen %d step %d size %d loss %.6f"
              % (WID, gen, state.step, hvd.size(), loss), flush=True)
        if WID in CRASH_WIDS and gen == 0 and state.step == CRASH_STEP:
            print("worker %s crashing now" % WID, flush=True)
            os._exit(23)
        if state.step % COMMIT_EVERY == 0:
            state.params = {k: np.asarray(v, np.float32)
                            for k, v in params.items()}
            # Collective: every rank materializes the full optimizer
            # state so the commit snapshot re-shards at any world size.
            state.opt_full = hvd_jax.sharded_state_full(s)
            state.commit()
            print("worker %s commit step %d crc %08x"
                  % (WID, state.step, state_crc(state)), flush=True)
        time.sleep(STEP_SLEEP)
    state.params = {k: np.asarray(v, np.float32)
                    for k, v in params.items()}
    state.opt_full = hvd_jax.sharded_state_full(s)
    return float(sum(np.sum((v - TARGET) ** 2)
                     for v in state.params.values()))


def main():
    pid_dir = os.environ.get("DURABLE_TEST_PID_DIR")
    if pid_dir:
        with open(os.path.join(pid_dir, "pid.%s" % WID), "w") as f:
            f.write(str(os.getpid()))
    import jax.numpy as jnp
    import optax

    rng = np.random.RandomState(0)
    params = {k: (rng.randn(*shape) * 0.25).astype(np.float32)
              for k, shape in sorted(SHAPES.items())}
    # The WORLD-INDEPENDENT full form of a fresh Adam state (zero
    # moments over the full flat parameter vector): gives the elastic
    # state its final structure up front, so a durable restore's
    # structure match succeeds before hvd/jax world info exists.
    total = sum(int(np.prod(s)) for s in SHAPES.values())
    opt_full = {"inner": optax.adam(LR).init(
        jnp.zeros(total, jnp.float32)), "total": total,
        "world": -1, "rank": -1}
    state = elastic.ElasticState(params=params, opt_full=opt_full, step=0)
    final_loss = train(state)
    if final_loss is None:  # job finished before this worker could join
        print("worker %s superseded (job already complete)" % WID,
              flush=True)
        return 0
    print("worker %s done step %d crc %08x loss %.6f"
          % (WID, state.step, state_crc(state), final_loss), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
