"""Examples as smoke tests, mirroring the reference CI
(`.buildkite/gen-pipeline.sh:123-177` runs the MNIST examples under both
launchers). Tiny configs keep the suite fast; the keras example is gated
behind HVD_TPU_RUN_ALL_EXAMPLES because the TF worker already covers that
binding end-to-end."""

import os
import subprocess
import sys

import pytest

# Slow tier: each test launches a 2-process training job (see pytest.ini;
# run with `pytest tests/ -m examples`).
# Both markers: "examples" is the historical opt-in name, "slow" is what
# the tier-1 verify selection (-m "not slow") excludes.
pytestmark = [pytest.mark.examples, pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(np_, script, extra_args=(), timeout=420):
    from conftest import clean_worker_env
    env = clean_worker_env()
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", str(np_), "--",
         sys.executable, os.path.join(REPO, "examples", script)]
        + list(extra_args),
        env=env, timeout=timeout, capture_output=True, text=True)


def run_mesh_example(script, steps, extra_env=None, timeout=420):
    """Single-process example on the 8-device virtual CPU mesh."""
    from conftest import clean_worker_env
    env = clean_worker_env()
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8")
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script),
         "--steps", str(steps)],
        env=env, timeout=timeout, capture_output=True, text=True)


def test_torch_mnist_example():
    proc = run_example(2, "torch_mnist.py", ["--epochs", "1"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_jax_mnist_example():
    proc = run_example(2, "jax_mnist.py", ["--epochs", "1"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_jax_word2vec_example():
    proc = run_example(2, "jax_word2vec.py",
                       ["--steps", "20", "--vocab-size", "500"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_pytorch_imagenet_resnet50_example():
    proc = run_example(2, "pytorch_imagenet_resnet50.py",
                       ["--epochs", "1", "--batches-per-epoch", "2",
                        "--batch-size", "8", "--image-size", "64"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_tensorflow2_synthetic_benchmark_example():
    proc = run_example(2, "tensorflow2_synthetic_benchmark.py",
                       ["--image-size", "64", "--num-classes", "10",
                        "--batch-size", "4", "--num-warmup-batches", "1",
                        "--num-batches-per-iter", "2", "--num-iters", "2"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Img/sec per rank" in proc.stdout
    assert "done" in proc.stdout


def test_pytorch_synthetic_benchmark_example():
    proc = run_example(2, "pytorch_synthetic_benchmark.py",
                       ["--image-size", "64", "--num-classes", "10",
                        "--batch-size", "4", "--num-warmup-batches", "1",
                        "--num-batches-per-iter", "2", "--num-iters", "2"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Img/sec per rank" in proc.stdout
    assert "done" in proc.stdout


def test_tensorflow2_mnist_example():
    proc = run_example(2, "tensorflow2_mnist.py", ["--steps", "60"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Loss" in proc.stdout
    assert "done" in proc.stdout


def test_tensorflow_mnist_tf1_example():
    proc = run_example(2, "tensorflow_mnist.py", ["--steps", "60"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Loss" in proc.stdout
    assert "done" in proc.stdout


def test_keras_spark_rossmann_example():
    proc = run_example(2, "keras_spark_rossmann.py",
                       ["--local", "--epochs", "1",
                        "--rows-per-rank", "256"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


@pytest.mark.skipif(not os.environ.get("HVD_TPU_RUN_ALL_EXAMPLES"),
                    reason="set HVD_TPU_RUN_ALL_EXAMPLES=1 to run")
def test_keras_mnist_example():
    proc = run_example(2, "keras_mnist.py", ["--epochs", "1"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_keras_mnist_advanced_example():
    """The reference's only example exercising LearningRateWarmupCallback
    + MetricAverageCallback in real training
    (examples/keras_mnist_advanced.py:69-106); this equivalent asserts
    the warmup ramp and cross-rank metric averaging internally."""
    proc = run_example(2, "keras_mnist_advanced.py",
                       ["--epochs", "4", "--warmup-epochs", "2",
                        "--samples", "256"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_tensorflow_mnist_eager_example():
    """Pure-eager loop (no tf.function): DistributedGradientTape op-by-op
    + post-first-step variable broadcast (reference
    examples/tensorflow_mnist_eager.py)."""
    proc = run_example(2, "tensorflow_mnist_eager.py", ["--steps", "40"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_keras_imagenet_resnet50_example():
    """The real keras.applications.ResNet50 graph trained data-parallel
    with warmup+schedule callbacks, fp16 compression, rank-0
    checkpointing and an hvd.load_model re-wrap assert (reference
    examples/keras_imagenet_resnet50.py)."""
    proc = run_example(2, "keras_imagenet_resnet50.py",
                       ["--fp16-allreduce"], timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_jax_moe_lm_example():
    """Expert-parallel Switch-MoE LM on a (dp x ep) mesh — the ep
    member of the parallelism family as a user writes it (sharded
    experts, all_to_all dispatch, aux loss in the objective, loss
    decreasing)."""
    proc = run_mesh_example("jax_moe_lm.py", 6)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_jax_zigzag_lm_example():
    """Causal load-balanced sequence parallelism as a user writes it:
    zigzag-shard the data, sp_schedule='zigzag', explicit gradient
    psum — loss decreasing over 4 steps on a 4-way ring (Pallas
    kernels in interpret mode)."""
    proc = run_mesh_example("jax_zigzag_lm.py", 4, timeout=560,
                            extra_env={"HVD_TPU_PALLAS_INTERPRET": "1"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout
    losses = [float(ln.split()[-1]) for ln in proc.stdout.splitlines()
              if ln.startswith("step ")]
    assert losses[-1] < losses[0]


def test_jax_pp_lm_example():
    """Pipeline-parallel LM on a (dp x pp) mesh — the pp member as a
    user writes it, with the pinned pipeline gradient contract."""
    proc = run_mesh_example("jax_pp_lm.py", 6)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_jax_fsdp_lm_example():
    """GSPMD FSDP LM — unmodified model code, sharded params/state,
    XLA-inserted collectives, loss decreasing."""
    proc = run_mesh_example("jax_fsdp_lm.py", 6)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "done" in proc.stdout


def test_tensorflow_mnist_estimator_example():
    """Estimator-era flow (reference tensorflow_mnist_estimator.py)
    on the v1 session API tf.estimator lowered to — tf.estimator
    itself is gone in TF>=2.16. Self-verifying: loss drop, >chance
    eval accuracy, bit-identical post-broadcast eval across ranks,
    rank-0-only checkpoint."""
    proc = run_example(2, "tensorflow_mnist_estimator.py",
                       ["--steps", "120"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS estimator_equivalent" in proc.stdout
