"""Trace e2e worker: the LAST rank straggles HVD_TPU_TL_STRAGGLE seconds
before joining the "straggled" allreduce, so every other rank's
negotiate span for that tensor records the wait the straggler inflicted.
The test merges the per-rank shards (HVD_TPU_TRACE_DIR) and asserts the
critical-path table names the straggler and attributes the wait to
negotiation."""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    straggle = float(os.environ.get("HVD_TPU_TL_STRAGGLE", "2"))

    # Warmup: populates the response cache and gives the control plane a
    # few full cycles to piggyback clock samples on.
    for i in range(5):
        out = hvd.allreduce(np.ones(8, np.float32), "warmup.%d" % i)
        assert np.allclose(out, n), out

    if r == n - 1:
        time.sleep(straggle)
    out = hvd.allreduce(np.full(16, float(r + 1), np.float32), "straggled")
    assert np.allclose(out, sum(range(1, n + 1))), out

    # Post-straggle traffic so the trace has healthy spans on both sides
    # of the event (and more ring hops for the causal check).
    for i in range(5):
        out = hvd.allreduce(np.ones(8, np.float32), "cooldown.%d" % i)
        assert np.allclose(out, n), out

    print("rank %d: straggler trace run done" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
