"""Rank-subset communicator worker: launched with a 4-rank world env, every
process calls ``hvd.init(ranks=[1, 3])``. Members form a 2-rank communicator
and allreduce among themselves; non-members become size-1 self communicators
and sit out (reference capability: ``hvd.init(comm=[0,1])``,
`horovod/common/basics.py:29-60`)."""

import os
import sys

import numpy as np

import horovod_tpu as hvd

SUBSET = [1, 3]


def main():
    world_rank = int(os.environ["HVD_TPU_RANK"])
    hvd.init(ranks=SUBSET)
    if world_rank in SUBSET:
        assert hvd.size() == len(SUBSET), hvd.size()
        assert hvd.rank() == SUBSET.index(world_rank), hvd.rank()
        x = np.full(8, float(world_rank), dtype=np.float32)
        out = hvd.allreduce(x, "subset_sum")
        assert np.allclose(out, float(sum(SUBSET))), out
        b = hvd.broadcast(np.full(4, world_rank, np.int32), 0, "subset_bc")
        assert np.all(b == SUBSET[0]), b
        g = hvd.allgather(np.full((2,), world_rank, np.int64), "subset_ag")
        assert list(g) == [SUBSET[0]] * 2 + [SUBSET[1]] * 2, g
    else:
        assert hvd.size() == 1, hvd.size()
        assert hvd.rank() == 0, hvd.rank()
        x = np.full(8, 7.0, dtype=np.float32)
        out = hvd.allreduce(x, "solo")  # size-1 short-circuit: identity
        assert np.allclose(out, 7.0), out
    print("worldrank %d: subset test passed" % world_rank)
    return 0


if __name__ == "__main__":
    sys.exit(main())
