"""Checkpoint round-trip of mesh-sharded params (in-process, virtual
8-device mesh — no launcher workers, hence no e2e marker)."""

def test_sharded_params_roundtrip(tmp_path):
    """Multi-chip checkpoint shape: a params tree PLACED on a
    (dp x ep) mesh (expert weights sharded over ep) must save through
    orbax and restore losslessly into a host template (re-placement is
    plain device_put and needs no separate assertion)."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.jax import checkpoint
    from horovod_tpu.parallel.expert import ep_param_specs

    hvd.init()
    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "ep"))
    rng = np.random.RandomState(11)
    params = {
        "router": jnp.asarray(rng.randn(16, 8).astype(np.float32)),
        "w_in": jnp.asarray(rng.randn(8, 16, 32).astype(np.float32)),
        "w_out": jnp.asarray(rng.randn(8, 32, 16).astype(np.float32)),
    }
    specs = ep_param_specs(params, "ep")
    placed = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)

    path = str(tmp_path / "sharded_ckpt")
    checkpoint.save(path, placed, step=3)
    template = jax.tree_util.tree_map(jnp.zeros_like, params)
    restored = checkpoint.restore(path, template, step=3)
    for k in params:
        np.testing.assert_array_equal(np.asarray(restored[k]),
                                      np.asarray(params[k]))
