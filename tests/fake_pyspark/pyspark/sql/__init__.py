"""pyspark.sql surface used by horovod_tpu.spark.run: SparkSession."""

from pyspark import _Builder


class SparkSession:
    builder = _Builder()
