"""Minimal pyspark stand-in for driving `horovod_tpu.spark.run` END TO
END without a Spark cluster (reference analogue: test/test_spark.py's
mock-the-shell strategy). Implements exactly the four surfaces run()
touches — SparkSession.builder, sparkContext.parallelize/barrier/
mapPartitions/collect — plus BarrierTaskContext, with REAL semantics:
collect() forks one OS process per partition and allGather() is a real
cross-process barrier, so the barrier tasks perform a genuine
multi-process horovod rendezvous (`hvd.init()`), not a simulation.

Lives under tests/fake_pyspark/ and is only importable when a test puts
that directory on sys.path — the production ImportError path stays
testable.
"""

import multiprocessing


class BarrierTaskContext:
    """Per-process task context; `get()` returns the instance installed
    by the fake runtime in each forked partition process."""

    _current = None

    def __init__(self, rank, world, store, barrier):
        self._rank = rank
        self._world = world
        self._store = store
        self._barrier = barrier

    @classmethod
    def get(cls):
        if cls._current is None:
            raise RuntimeError("BarrierTaskContext.get() outside a "
                               "barrier task")
        return cls._current

    def partitionId(self):
        return self._rank

    def allGather(self, message):
        self._store[self._rank] = str(message)
        self._barrier.wait(timeout=60)
        return [self._store[r] for r in range(self._world)]


def _run_partition(fn, elements, rank, world, store, barrier, queue):
    try:
        BarrierTaskContext._current = BarrierTaskContext(
            rank, world, store, barrier)
        queue.put((rank, list(fn(iter(elements))), None))
    except BaseException as e:  # surface the child's failure to collect()
        queue.put((rank, None, "%s: %s" % (type(e).__name__, e)))


class _BarrierRDD:
    def __init__(self, data, num_partitions):
        data = list(data)
        # parallelize(range(n), n) -> partition i holds [i], like Spark.
        self._parts = [data[i::num_partitions]
                       for i in range(num_partitions)]
        self._fn = None

    def barrier(self):
        return self

    def mapPartitions(self, fn):
        self._fn = fn
        return self

    def collect(self):
        n = len(self._parts)
        ctx = multiprocessing.get_context("fork")
        manager = ctx.Manager()
        store = manager.dict()
        barrier = ctx.Barrier(n)
        queue = ctx.Queue()
        procs = [ctx.Process(target=_run_partition,
                             args=(self._fn, part, r, n, store, barrier,
                                   queue))
                 for r, part in enumerate(self._parts)]
        for p in procs:
            p.start()
        results = []
        errors = []
        try:
            import queue as queue_mod
            import time
            deadline = time.time() + 120
            pending = n
            while pending and time.time() < deadline:
                try:
                    rank, out, err = queue.get(timeout=1)
                except queue_mod.Empty:
                    # A child that died without reporting (segfault in
                    # native code) must not stall the full deadline.
                    dead = [p for p in procs
                            if p.exitcode not in (None, 0)]
                    if dead and queue.empty():
                        errors.append(("?", "child died with exitcode(s) "
                                       "%s" % [p.exitcode for p in dead]))
                        break
                    continue
                pending -= 1
                if err is not None:
                    errors.append((rank, err))
                else:
                    results.extend(out)
            if pending and not errors:
                errors.append(("?", "timed out waiting for %d barrier "
                               "task(s)" % pending))
        finally:
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()
            manager.shutdown()
        if errors:
            raise RuntimeError("barrier task(s) failed: %s" % errors)
        return results


class _FakeSparkContext:
    defaultParallelism = 2

    def parallelize(self, data, num_partitions=None):
        return _BarrierRDD(data, num_partitions or self.defaultParallelism)


class _FakeSession:
    def __init__(self):
        self.sparkContext = _FakeSparkContext()


class _Builder:
    def getOrCreate(self):
        return _FakeSession()
