"""Self-verifying distributed collective matrix, run under the launcher with
N >= 2 ranks. Mirrors the reference's test strategy (test/test_tensorflow.py
/ test_torch.py): real multi-process collectives on localhost, rank-aware
assertions, size-dependent fp tolerance, error-case checks.

Run: python -m horovod_tpu.run.run -np 2 -- python tests/distributed_ops_worker.py
"""

import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common.ops import HorovodInternalError


def tolerance(dtype, n):
    if dtype == np.float16:
        return 1e-2 * n
    if dtype in (np.float32,):
        return 1e-5 * n
    if dtype == np.float64:
        return 1e-10 * n
    return 0


def test_allreduce_matrix(r, n):
    dtypes = [np.uint8, np.int8, np.int32, np.int64, np.float16, np.float32,
              np.float64]
    rng = np.random.RandomState(1234)
    for dtype in dtypes:
        for ndim in range(1, 4):
            shape = (5,) * ndim
            # Identical pseudo-random base on every rank, offset by rank.
            base = rng.uniform(-50, 50, size=shape)
            x = (base + r).astype(dtype)
            result = hvd.allreduce(x, "ar.%s.%d" % (np.dtype(dtype).name,
                                                    ndim))
            # Accumulate in the same dtype so integer wraparound matches.
            expected = np.zeros(shape, dtype=dtype)
            for rr in range(n):
                expected = expected + (base + rr).astype(dtype)
            expected = expected.astype(np.float64)
            got = result.astype(np.float64)
            tol = tolerance(dtype, n) * np.abs(expected).max() + 1e-6
            assert np.allclose(got, expected, atol=max(tol, 1e-6)), (
                dtype, ndim, got, expected)


def test_allreduce_average(r, n):
    x = np.arange(20, dtype=np.float32) + r
    result = hvd.allreduce(x, "avg", average=True)
    expected = np.arange(20, dtype=np.float32) + (n - 1) / 2.0
    assert np.allclose(result, expected, atol=1e-5), (result, expected)


def test_allreduce_bool(r, n):
    x = np.array([r == 0, True, False])
    result = hvd.allreduce(x, "bool")
    assert result.dtype == np.bool_
    assert list(result) == [True, True, False], result


def test_fusion(r, n):
    handles = [hvd.allreduce_async(np.full(4, i + r, dtype=np.float32),
                                   "fuse.%d" % i) for i in range(64)]
    for i, h in enumerate(handles):
        out = hvd.synchronize(h)
        exp = sum(i + rr for rr in range(n))
        assert np.allclose(out, exp), (i, out, exp)


def test_allgather_variable(r, n):
    x = np.full((r + 2, 3), r, dtype=np.int32)
    result = hvd.allgather(x, "ag_var")
    assert result.shape == (sum(rr + 2 for rr in range(n)), 3)
    off = 0
    for rr in range(n):
        block = result[off:off + rr + 2]
        assert np.all(block == rr), (rr, block)
        off += rr + 2


def test_allgather_dtypes(r, n):
    for dtype in (np.uint8, np.int64, np.float16, np.float64):
        x = np.full((2, 2), r, dtype=dtype)
        result = hvd.allgather(x, "ag.%s" % np.dtype(dtype).name)
        assert result.shape == (2 * n, 2)
        for rr in range(n):
            assert np.all(result[2 * rr:2 * rr + 2].astype(np.int64) == rr)


def test_broadcast(r, n):
    for root in range(n):
        for dtype in (np.int32, np.float32, np.float64):
            x = np.full((3, 3), r + 1, dtype=dtype)
            result = hvd.broadcast(x, root, "bc.%d.%s" %
                                   (root, np.dtype(dtype).name))
            assert np.all(result == root + 1), (root, result)


def test_error_mismatched_shape(r, n):
    x = np.zeros(3 + r, dtype=np.float32)  # different shape per rank
    try:
        hvd.allreduce(x, "mismatch_shape")
    except HorovodInternalError as e:
        assert "Mismatched" in str(e), e
    else:
        raise AssertionError("expected shape-mismatch error")


def test_error_mismatched_dtype(r, n):
    x = np.zeros(4, dtype=np.float32 if r == 0 else np.float64)
    try:
        hvd.allreduce(x, "mismatch_dtype")
    except HorovodInternalError as e:
        assert "Mismatched" in str(e), e
    else:
        raise AssertionError("expected dtype-mismatch error")


def test_error_mismatched_root(r, n):
    x = np.zeros(4, dtype=np.float32)
    try:
        hvd.broadcast(x, r % n, "mismatch_root")  # different root per rank
    except HorovodInternalError as e:
        assert "root" in str(e), e
    else:
        raise AssertionError("expected root-mismatch error")


def test_duplicate_name(r, n):
    h1 = hvd.allreduce_async(np.zeros(4, dtype=np.float32), "dup")
    try:
        h2 = hvd.allreduce_async(np.zeros(4, dtype=np.float32), "dup")  # hvd-lint: disable=duplicate-collective-name
        try:
            hvd.synchronize(h2)
        except HorovodInternalError:
            pass
        else:
            raise AssertionError("expected duplicate-name error")
    finally:
        hvd.synchronize(h1)


def test_jit_host_callback_plane(r, n):
    # hvd collectives inside plain `jax.jit` with no mapped axis must ride
    # the host core via ordered io_callback (not emit an unbound psum).
    import os
    if os.environ.get("HVD_TPU_SKIP_JIT_TEST"):
        return
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
    import jax
    import jax.numpy as jnp
    import horovod_tpu.jax as hvd_jax

    @jax.jit
    def step(x):
        s = hvd_jax.allreduce(x, average=False, name="jit_cb")
        b = hvd_jax.broadcast(x, 0, name="jit_bc")
        g = hvd_jax.allgather(x, name="jit_ag")
        return s, b, g

    x = jnp.full((4,), float(r + 1), jnp.float32)
    for _ in range(2):  # 2nd call reuses the compiled program + cache path
        s, b, g = step(x)
        assert np.allclose(np.asarray(s), sum(rr + 1 for rr in range(n)))
        assert np.allclose(np.asarray(b), 1.0)
        assert g.shape == (4 * n,)
        for rr in range(n):
            assert np.allclose(np.asarray(g)[4 * rr:4 * rr + 4], rr + 1)


def test_cache_steady_state(r, n):
    # Same names over many iterations: second-and-later cycles should ride
    # the response-cache fast path; correctness must be identical.
    for it in range(30):
        x = np.full(8, it * (r + 1), dtype=np.float32)
        out = hvd.allreduce(x, "steady")
        exp = it * sum(rr + 1 for rr in range(n))
        assert np.allclose(out, exp), (it, out, exp)


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2, "run under the launcher with -np >= 2"
    tests = [v for k, v in sorted(globals().items())
             if k.startswith("test_")]
    for t in tests:
        t(r, n)
        if r == 0:
            print("PASS %s" % t.__name__)
    print("rank %d: all distributed op tests passed" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
