"""TF1 graph-mode worker: eager disabled process-wide (hence a
dedicated worker), variables initialized differently per rank, then
synchronized via BroadcastGlobalVariablesHook under a
MonitoredTrainingSession and via a direct broadcast_global_variables
run — the reference's TF1 estimator-era API surface
(`/root/reference/horovod/tensorflow/__init__.py:87-141,160-193`)."""

import sys

import numpy as np


def main():
    import tensorflow as tf
    tf.compat.v1.disable_eager_execution()
    v1 = tf.compat.v1

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    r = hvd.rank()

    # --- hook path under MonitoredTrainingSession ---
    g1 = tf.Graph()
    with g1.as_default():
        var = v1.get_variable(
            "w", initializer=tf.constant([10.0 + r, 20.0 + r]))
        hook = hvd.BroadcastGlobalVariablesHook(root_rank=0)
        with v1.train.MonitoredTrainingSession(hooks=[hook]) as sess:
            got = sess.run(var)
    if not np.allclose(got, [10.0, 20.0]):
        print("HOOK MISMATCH rank %d: %r" % (r, got))
        return 1

    # --- direct graph-mode broadcast_global_variables ---
    g2 = tf.Graph()
    with g2.as_default():
        var2 = v1.get_variable(
            "w2", initializer=tf.constant([float(100 + r)]))
        bcast = hvd.broadcast_global_variables(0)
        with v1.Session() as sess:
            sess.run(v1.global_variables_initializer())
            sess.run(bcast)
            got2 = sess.run(var2)
    if not np.allclose(got2, [100.0]):
        print("BCAST MISMATCH rank %d: %r" % (r, got2))
        return 1

    print("rank %d: tf1 graph-mode broadcast tests passed" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
