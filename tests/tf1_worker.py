"""TF1 graph-mode worker: eager disabled process-wide (hence a
dedicated worker), variables initialized differently per rank, then
synchronized via BroadcastGlobalVariablesHook under a
MonitoredTrainingSession and via a direct broadcast_global_variables
run — the reference's TF1 estimator-era API surface
(`/root/reference/horovod/tensorflow/__init__.py:87-141,160-193`)."""

import sys

import numpy as np


def main():
    import tensorflow as tf
    tf.compat.v1.disable_eager_execution()
    v1 = tf.compat.v1

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    r = hvd.rank()

    # --- hook path under MonitoredTrainingSession ---
    g1 = tf.Graph()
    with g1.as_default():
        var = v1.get_variable(
            "w", initializer=tf.constant([10.0 + r, 20.0 + r]))
        hook = hvd.BroadcastGlobalVariablesHook(root_rank=0)
        with v1.train.MonitoredTrainingSession(hooks=[hook]) as sess:
            got = sess.run(var)
    if not np.allclose(got, [10.0, 20.0]):
        print("HOOK MISMATCH rank %d: %r" % (r, got))
        return 1

    # --- estimator-style TRAINING LOOP: BroadcastGlobalVariablesHook +
    # v1 DistributedOptimizer.minimize under MonitoredTrainingSession
    # (reference: examples/tensorflow_mnist_estimator.py:109-115 — the
    # estimator API itself is gone in TF>=2.16, so the hook runs in the
    # session-loop form estimators lower to) ---
    gt = tf.Graph()
    with gt.as_default():
        rng = np.random.RandomState(1234)
        w_true = np.array([[2.0], [-3.0]], np.float32)
        xs = rng.randn(64, 2).astype(np.float32)
        ys = xs @ w_true
        # Rank-disjoint shards: convergence to w_true requires the
        # gradient allreduce to combine them.
        xs_r, ys_r = xs[r::hvd.size()], ys[r::hvd.size()]

        x_ph = v1.placeholder(tf.float32, [None, 2])
        y_ph = v1.placeholder(tf.float32, [None, 1])
        w = v1.get_variable("w_train",
                            initializer=tf.constant([[5.0 * r], [1.0 - r]]))
        loss = tf.reduce_mean((x_ph @ w - y_ph) ** 2)
        opt = hvd.DistributedOptimizer(
            v1.train.GradientDescentOptimizer(0.2))
        # the hook-mismatch bail above returns early on the failing
        # rank only — an accepted hang hazard on a test error path
        train_op = opt.minimize(loss)  # hvd-lint: disable=verify-divergent-schedule
        hook = hvd.BroadcastGlobalVariablesHook(root_rank=0)
        with v1.train.MonitoredTrainingSession(hooks=[hook]) as sess:
            first = None
            for _ in range(60):
                cur, _ = sess.run([loss, train_op],
                                  {x_ph: xs_r, y_ph: ys_r})
                first = cur if first is None else first
            w_final = sess.run(w)
    if not cur < first * 1e-2:
        print("TRAIN LOOP did not converge rank %d: %g -> %g" %
              (r, first, cur))
        return 1
    if not np.allclose(w_final, w_true, atol=0.05):
        print("TRAIN LOOP wrong weights rank %d: %r" % (r, w_final))
        return 1
    # Gradient averaging must have kept every rank's weights identical.
    from horovod_tpu.common import ops as _ops
    gathered = _ops.allgather(w_final.reshape(1, -1), "tf1_w_final")
    if not np.allclose(gathered, gathered[0]):
        print("TRAIN LOOP ranks diverged: %r" % (gathered,))
        return 1

    # --- direct graph-mode broadcast_global_variables ---
    g2 = tf.Graph()
    with g2.as_default():
        var2 = v1.get_variable(
            "w2", initializer=tf.constant([float(100 + r)]))
        bcast = hvd.broadcast_global_variables(0)
        with v1.Session() as sess:
            sess.run(v1.global_variables_initializer())
            sess.run(bcast)
            got2 = sess.run(var2)
    if not np.allclose(got2, [100.0]):
        print("BCAST MISMATCH rank %d: %r" % (r, got2))
        return 1

    print("rank %d: tf1 graph-mode broadcast tests passed" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
