"""Mixed-execution-mode rejection (docs/ZERO.md): rank 0 runs the
sharded update (reduce-scatter) while every other rank runs the
replicated update (allreduce) on the SAME tensor name. The coordinator
must reject the op with an error NAMING both ranks and both modes — on
every rank, promptly, never a hang.

Run: python -m horovod_tpu.run.run -np 2 -- python tests/sharded_mixed_worker.py
"""

import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.ops import HorovodInternalError


def _assert_mixed_error(msg):
    assert "Mixed execution modes" in msg, msg
    assert "sharded_update" in msg and "reduce-scatter" in msg, msg
    assert "allreduce" in msg, msg
    assert "rank 0" in msg and "rank 1" in msg, msg


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2
    x = np.ones(100, np.float32)

    # Raw collective level: the coordinator's type check fires.
    try:
        if r == 0:
            ops.reduce_scatter(x, "mixed")  # hvd-lint: disable=rank-conditional-collective,verify-kind-mismatch
        else:
            ops.allreduce(x, "mixed")  # hvd-lint: disable=rank-conditional-collective,name-attr-mismatch
    except HorovodInternalError as e:
        _assert_mixed_error(str(e))
        print("rank %d: mixed-mode rejected naming both ranks and modes"
              % r, flush=True)
    else:
        raise SystemExit("mixed sharded/replicated op unexpectedly "
                         "succeeded")

    # Optimizer level: a sharded DistributedOptimizer meeting a
    # replicated one collides on the SAME first gradient name
    # ("grad.0") by design, so the mismatch is caught at negotiation
    # instead of hanging. Single-leaf params keep the replicated rank's
    # pending set empty after the error.
    import jax.numpy as jnp
    import optax

    from horovod_tpu import jax as hvd_jax

    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1),  # hvd-lint: disable=missing-initial-broadcast
                                       sharded_update=(r == 0))
    params = {"w": jnp.ones(10, jnp.float32)}
    state = opt.init(params)
    grads = {"w": jnp.full(10, float(r + 1))}
    try:
        opt.update(grads, state, params)  # hvd-lint: disable=verify-mixed-modes
    except HorovodInternalError as e:
        _assert_mixed_error(str(e))
        print("rank %d: optimizer-level mixed mode rejected" % r,
              flush=True)
    else:
        raise SystemExit("mixed optimizer update unexpectedly succeeded")

    # The error is per-tensor, not fatal: a uniform op still completes.
    out = ops.allreduce(x, "uniform")
    assert np.allclose(out, n), out
    print("rank %d: mixed worker passed" % r, flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
