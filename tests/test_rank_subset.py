"""Rank-subset communicator test: a 4-rank world where only ranks [1, 3]
form the training communicator (VERDICT round-1 missing item #2; reference
`horovod/common/basics.py:29-60`)."""

import pytest

import os
import socket
import subprocess
import sys

pytestmark = pytest.mark.e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_rank_subset_allreduce():
    n = 4
    ports = _free_ports(n)
    addrs = ",".join("127.0.0.1:%d" % p for p in ports)
    procs = []
    for r in range(n):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.update({
            "HVD_TPU_RANK": str(r),
            "HVD_TPU_SIZE": str(n),
            "HVD_TPU_LOCAL_RANK": str(r),
            "HVD_TPU_LOCAL_SIZE": str(n),
            "HVD_TPU_CROSS_RANK": "0",
            "HVD_TPU_CROSS_SIZE": "1",
            "HVD_TPU_ADDRS": addrs,
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests",
                                          "rank_subset_worker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    for r, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, "world rank %d:\n%s" % (r, out)
        assert "subset test passed" in out, out
