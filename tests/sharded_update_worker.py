"""Sharded-weight-update e2e worker (docs/ZERO.md): the ZeRO-style
reduce-scatter -> shard-local optimizer -> allgather path must produce
the SAME parameters as the replicated allreduce path, while holding
~1/N of the optimizer state per rank (asserted through the native
opt_state_bytes gauge).

Sections (env ``SHARDED_TEST_FRAMEWORKS``, default "jax"):
  jax    host-plane DistributedOptimizer(sharded_update=True) parity vs
         a locally-computed replicated reference, uneven shard sizes,
         the opt_state_bytes memory claim, int8 wire compression
         layered on the scatter leg, reduce_scatter_total accounting
  torch  _ShardedOptimizer parity vs torch.optim on mean gradients
  tf     Keras-3 sharded optimizer parity (eager apply_gradients)

Run: python -m horovod_tpu.run.run -np 2 -- python tests/sharded_update_worker.py
"""

import os
import sys

import numpy as np

import horovod_tpu as hvd

FRAMEWORKS = [f for f in os.environ.get(
    "SHARDED_TEST_FRAMEWORKS", "jax").split(",") if f]
STEPS = 5


def _rank_grads(shapes, r, step):
    """Deterministic rank- and step-dependent gradients: the collective
    matters (every rank contributes different values), yet every rank
    can also compute every OTHER rank's gradient to build the exact
    replicated reference locally."""
    out = {}
    for k, shape in shapes.items():
        total = int(np.prod(shape))
        base = np.linspace(-1.0, 1.0, total).astype(np.float32)
        out[k] = ((base * (step + 1) + 0.25 * r)
                  .reshape(shape).astype(np.float32))
    return out


def _mean_grads(shapes, n, step):
    return {k: np.mean([_rank_grads(shapes, rr, step)[k]
                        for rr in range(n)], axis=0)
            for k in shapes}


def check_jax(r, n):
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import jax as hvd_jax

    # Odd leaf sizes (13*7 + 7 + 3 = 101 elements) so the shard
    # partition is uneven at every tested world size.
    shapes = {"w": (13, 7), "b": (7,), "s": (3,)}
    rng = np.random.RandomState(0)
    params0 = {k: jnp.asarray(rng.randn(*v).astype(np.float32) * 0.3)
               for k, v in shapes.items()}

    opt = optax.adam(1e-2)
    sharded = hvd_jax.DistributedOptimizer(opt, sharded_update=True)  # hvd-lint: disable=missing-initial-broadcast
    assert isinstance(sharded, optax.GradientTransformation)

    p = dict(params0)
    s = sharded.init(p)
    assert s["world"] == n and s["rank"] == r and s["total"] == 101

    # Replicated reference computed entirely locally from the mean
    # gradients (identical on every rank by construction).
    ref_p = dict(params0)
    ref_s = opt.init(ref_p)

    for step in range(STEPS):
        g = {k: jnp.asarray(v)
             for k, v in _rank_grads(shapes, r, step).items()}
        updates, s = sharded.update(g, s, p)  # hvd-lint: disable=verify-mixed-modes
        p = optax.apply_updates(p, updates)

        ref_g = {k: jnp.asarray(v)
                 for k, v in _mean_grads(shapes, n, step).items()}
        ref_u, ref_s = opt.update(ref_g, ref_s, ref_p)
        ref_p = optax.apply_updates(ref_p, ref_u)

    for k in shapes:
        np.testing.assert_allclose(
            np.asarray(p[k]), np.asarray(ref_p[k]), rtol=2e-5, atol=2e-5,
            err_msg="jax sharded != replicated reference for %r" % k)

    # Cross-rank agreement is exact: the allgather leg ships the updated
    # shards verbatim.
    for k in shapes:
        theirs = np.asarray(hvd.allgather(  # hvd-lint: disable=unordered-name-iteration
            np.asarray(p[k]).ravel()[None, :], "agree.%s" % k))
        for rr in range(n):
            assert np.array_equal(theirs[rr], theirs[0]), \
                "ranks disagree on updated params %r" % k

    # The memory claim (docs/ZERO.md): the inner Adam state holds mu+nu
    # for THIS RANK'S SHARD only. gauge <= replicated/n + one shard of
    # padding slack (+ scalar step counters).
    counts, _ = hvd.shard_partition(101, n)
    gauge = hvd.metrics()["gauges"]["opt_state_bytes"]
    replicated_bytes = 2 * 101 * 4
    assert gauge > 0, gauge
    assert gauge <= replicated_bytes / n + 2 * 4 * (max(counts) + 16), \
        (gauge, replicated_bytes, n)
    expected = 2 * counts[r] * 4
    assert abs(gauge - expected) <= 64, (gauge, expected)

    # Repeated reduce-scatters on one name ride the response cache's
    # fast path (REDUCESCATTER is keyed into the cache like any other
    # op — the Response enum offset must not defeat the hit check).
    # 5 steps = 5 reduce-scatters + 5 param allgathers on stable names;
    # the first of each misses, the rest must HIT (>= 6 proves the
    # reduce-scatters hit too, not just the allgathers).
    hits = hvd.metrics()["counters"]["cache_hit_total"]
    assert hits >= 6, "reduce-scatter never hit the response cache " \
        "(hits=%d)" % hits

    # int8 wire compression layers onto the scatter leg unchanged; the
    # quantization error per hop is bounded by scale/2 per block.
    sc = hvd_jax.DistributedOptimizer(opt, sharded_update=True,
                                      compression="int8")
    pc = dict(params0)
    stc = sc.init(pc)
    before = hvd.metrics()["counters"]["reduce_scatter_total"]
    g = {k: jnp.asarray(v) for k, v in _rank_grads(shapes, r, 0).items()}
    updates, stc = sc.update(g, stc, pc)
    pc = optax.apply_updates(pc, updates)
    after = hvd.metrics()["counters"]["reduce_scatter_total"]
    assert after > before, (before, after)
    ref1_u, _ = opt.update(
        {k: jnp.asarray(v) for k, v in _mean_grads(shapes, n, 0).items()},
        opt.init(params0), params0)
    ref1_p = optax.apply_updates(dict(params0), ref1_u)
    for k in shapes:
        np.testing.assert_allclose(
            np.asarray(pc[k]), np.asarray(ref1_p[k]), atol=5e-3,
            err_msg="int8-compressed sharded update diverged for %r" % k)

    # sharded_state_full materializes the world-independent form;
    # sharded_state_shard slices it back bitwise for this rank.
    full = hvd_jax.sharded_state_full(s)
    assert full["world"] == -1 and full["rank"] == -1
    reshard = hvd_jax.sharded_state_shard(full)
    for a, b in zip(jax.tree_util.tree_leaves(reshard["inner"]),
                    jax.tree_util.tree_leaves(s["inner"])):  # hvd-lint: disable=sharded-update-rank-local-param-read
        assert np.array_equal(np.asarray(a), np.asarray(b))

    print("rank %d: jax sharded parity passed" % r, flush=True)


def check_torch(r, n):
    import torch

    from horovod_tpu import torch as hvd_torch

    torch.manual_seed(0)
    model = torch.nn.Sequential(
        torch.nn.Linear(9, 5), torch.nn.Linear(5, 3))
    # Same init on every rank (seeded), and a replicated twin for the
    # local reference.
    ref_model = torch.nn.Sequential(
        torch.nn.Linear(9, 5), torch.nn.Linear(5, 3))
    ref_model.load_state_dict(model.state_dict())

    shapes = {name: tuple(p.shape)
              for name, p in model.named_parameters()}
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9),
        named_parameters=model.named_parameters(), sharded_update=True)
    ref_opt = torch.optim.SGD(ref_model.parameters(), lr=0.1,
                              momentum=0.9)

    for step in range(STEPS):
        g = _rank_grads(shapes, r, step)
        for name, param in model.named_parameters():
            param.grad = torch.from_numpy(g[name].copy())
        opt.step()

        mg = _mean_grads(shapes, n, step)
        for name, param in ref_model.named_parameters():
            param.grad = torch.from_numpy(mg[name].copy())
        ref_opt.step()

    for (name, p), (_, rp) in zip(model.named_parameters(),
                                  ref_model.named_parameters()):
        np.testing.assert_allclose(
            p.detach().numpy(), rp.detach().numpy(), rtol=2e-5,
            atol=2e-5,
            err_msg="torch sharded != replicated reference for %r" % name)

    # Momentum buffers live ONLY for this rank's flat shard.
    total = sum(int(np.prod(s)) for s in shapes.values())
    counts, _ = hvd.shard_partition(total, n)
    gauge = hvd.metrics()["gauges"]["opt_state_bytes"]
    assert abs(gauge - counts[r] * 4) <= 64, (gauge, counts[r] * 4)

    print("rank %d: torch sharded parity passed" % r, flush=True)


def check_tf(r, n):
    import tensorflow as tf

    from horovod_tpu import tensorflow as hvd_tf

    tf.random.set_seed(0)
    v1 = tf.Variable(np.linspace(-1, 1, 35).reshape(7, 5)
                     .astype(np.float32), name="v1")
    v2 = tf.Variable(np.linspace(1, -1, 5).astype(np.float32), name="v2")
    variables = [v1, v2]
    shapes = {"v1": (7, 5), "v2": (5,)}
    ref_vals = [v.numpy().copy() for v in variables]

    opt = hvd_tf.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=0.1, momentum=0.9),
        sharded_update=True)
    ref_opt = tf.keras.optimizers.SGD(learning_rate=0.1, momentum=0.9)
    ref_vars = [tf.Variable(v, name="r%d" % i)
                for i, v in enumerate(ref_vals)]

    for step in range(STEPS):
        g = _rank_grads(shapes, r, step)
        opt.apply_gradients([(tf.constant(g["v1"]), v1),
                             (tf.constant(g["v2"]), v2)])
        mg = _mean_grads(shapes, n, step)
        ref_opt.apply_gradients(
            [(tf.constant(mg["v1"]), ref_vars[0]),
             (tf.constant(mg["v2"]), ref_vars[1])])

    for v, rv, name in ((v1, ref_vars[0], "v1"), (v2, ref_vars[1], "v2")):
        np.testing.assert_allclose(
            v.numpy(), rv.numpy(), rtol=2e-5, atol=2e-5,
            err_msg="tf sharded != replicated reference for %r" % name)

    # A filtered/reordered variable list no longer matches the shard
    # layout built at the first call — must error, not misalign.
    try:
        opt.apply_gradients([(tf.constant(_rank_grads(shapes, r, 0)["v2"]),
                              v2)])
    except RuntimeError as e:
        assert "variable list" in str(e), e
    else:
        raise AssertionError("reordered variable list was not rejected")

    print("rank %d: tf sharded parity passed" % r, flush=True)


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    if "jax" in FRAMEWORKS:
        check_jax(r, n)
    if "torch" in FRAMEWORKS:
        check_torch(r, n)
    if "tf" in FRAMEWORKS:
        check_tf(r, n)
    print("rank %d: sharded update worker passed" % r, flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
