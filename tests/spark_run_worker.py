"""Orchestrator for the spark.run e2e test: runs in a CLEAN interpreter
(no prior hvd.init in this process — forked barrier children must init
from scratch), puts the fake pyspark on sys.path, and drives the REAL
`horovod_tpu.spark.run` plumbing: SparkSession.builder.getOrCreate ->
parallelize -> barrier -> mapPartitions -> collect, with each barrier
task doing a genuine multi-process rendezvous + collective.
"""

import os
import pathlib
import sys

_HERE = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(_HERE / "fake_pyspark"))
sys.path.insert(0, str(_HERE.parent))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # children never need TPU


def train(scale):
    """Runs inside each barrier task AFTER hvd.init(): a real allreduce
    proves the rendezvous the topology env described actually formed."""
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.common import ops

    out = ops.allreduce(np.ones(4) * (hvd.rank() + 1), "spark_e2e_ar")
    return (float(out[0]) * scale, hvd.rank(), hvd.size())


def main():
    import horovod_tpu.spark as hvd_spark

    results = hvd_spark.run(train, args=(10,), num_proc=2, verbose=1)
    # results are ordered by rank (run() sorts on the task's rank).
    assert len(results) == 2, results
    expected_sum = (1 + 2) * 10.0
    for r, (val, rank_, size_) in enumerate(results):
        assert val == expected_sum, results
        assert rank_ == r and size_ == 2, results
    print("spark run ok: %s" % (results,))
    return 0


if __name__ == "__main__":
    sys.exit(main())
