"""Chip-attached observability worker (round-5 artifact capture).

Rank 0 computes real model gradients ON THE TPU (the axon tunnel chip
— the launcher driver re-injects the pool pointer as
HVD_TPU_AXON_SAVED so only rank 0 engages the plugin; the single chip
cannot be shared); every other rank computes the same model on its CPU
backend. All ranks then allreduce the gradients through the HOST core
(the plane the timeline instruments — on-chip XLA collectives are
compiled into the jit step and invisible to a host-side tracer by
design). One mid-run straggler step on rank 1 crosses the
stall-check threshold, so the coordinator's stall inspector fires its
warning DURING a live chip-attached training loop — not a synthetic
CPU toy. Reference analogue: docs/timeline.rst:1-60 (capture a
timeline from a real training job)."""

import os
import sys
import time

# Rank 0 re-engages the TPU plugin; the launcher scrubbed it for
# everyone (N workers on one tunnel chip deadlock). The plugin
# registers from sitecustomize at INTERPRETER BOOT, so setting the
# pool pointer inside main() is too late — re-exec once with the env
# prepared.
if (os.environ.get("HVD_TPU_RANK", "0") == "0"
        and os.environ.get("HVD_TPU_AXON_SAVED")
        and not os.environ.get("HVD_TPU_TL_REEXECED")):
    os.environ["HVD_TPU_TL_REEXECED"] = "1"
    os.environ["PALLAS_AXON_POOL_IPS"] = os.environ["HVD_TPU_AXON_SAVED"]
    os.environ.pop("JAX_PLATFORM_NAME", None)
    os.environ.pop("JAX_PLATFORMS", None)
    # Its OWN persistent-jit-cache namespace: the tunnel's
    # remote-compile service builds AOT artifacts on a host with
    # different CPU features, and a CPU-backend worker loading them
    # from a SHARED cache dir hangs/SIGILLs (hit live: the first
    # capture run poisoned the common cache for rank 1).
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        os.environ["JAX_COMPILATION_CACHE_DIR"] += "_axon"
    os.execv(sys.executable, [sys.executable] + sys.argv)

import numpy as np


def main():
    r = int(os.environ.get("HVD_TPU_RANK", "0"))

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    backend = jax.default_backend()
    print("rank %d backend=%s" % (r, backend), flush=True)

    # Small-but-real model: 3-layer MLP classifier, grads jitted on
    # this rank's backend (TPU for rank 0).
    rng = np.random.RandomState(0)
    params = [jnp.asarray(rng.randn(256, 256).astype(np.float32) * 0.05)
              for _ in range(3)]
    x = jnp.asarray(rng.randn(64, 256).astype(np.float32))
    y = jnp.asarray(rng.randn(64, 256).astype(np.float32))

    def loss_fn(ps):
        h = x
        for w in ps:
            h = jnp.tanh(h @ w)
        return jnp.mean((h - y) ** 2)

    grads_fn = jax.jit(jax.grad(loss_fn))

    lr = 0.1
    for step in range(6):
        grads = grads_fn(params)
        host_grads = [np.asarray(g, np.float32) for g in grads]
        if r == 1 and step == 3:
            # Straggle WELL past HVD_TPU_STALL_CHECK_TIME_SECONDS. Two
            # things must happen on the coordinator while rank 0
            # waits: the stalled CACHED tensor is invalidated and
            # renegotiated (the path whose fast-path drop once
            # livelocked this exact workload — controller.cc
            # invalid_in_queue gate), and the renegotiated tensor then
            # crosses the threshold again so the stall WARNING names
            # this rank.
            time.sleep(float(os.environ.get("HVD_TPU_TL_STRAGGLE",
                                            "7")))
        reduced = [hvd.allreduce(g, "grad.layer%d" % i)
                   for i, g in enumerate(host_grads)]
        params = [p - lr * jnp.asarray(g)
                  for p, g in zip(params, reduced)]

    final = float(loss_fn(params))
    print("rank %d final loss %.5f (backend=%s)" % (r, final, backend),
          flush=True)
    if r == 0:
        print("CHIP_BACKEND %s" % backend, flush=True)
    print("rank %d done" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
