"""Worker for the 2-D mesh e2e (test_groups.py): hvd.init(model_parallel=2)
at 4 ranks forms the (batch, model) groups, batch-axis collectives span
the model columns, and the host-plane Megatron f/g operators produce
exact values and gradients over the model group."""

import signal
import sys

import numpy as np

import jax
import jax.numpy as jnp

import horovod_tpu as hvd_core
import horovod_tpu.jax as hvd
from horovod_tpu.parallel import tensor_parallel as tp


def alarm(signum, frame):
    sys.stderr.write("watchdog fired: job deadlocked\n")
    sys.exit(3)


signal.signal(signal.SIGALRM, alarm)
signal.alarm(150)

hvd.init(model_parallel=2)
r, n = hvd.rank(), hvd.size()
assert n == 4
bg, mg = hvd_core.mesh_groups()
assert hvd_core.model_parallel_size() == 2
# rank r sits at model row r//2 (consecutive ranks) and batch column r%2.
assert mg.ranks == (2 * (r // 2), 2 * (r // 2) + 1), (r, mg)
assert bg.ranks == tuple(range(r % 2, n, 2)), (r, bg)

# Batch-axis reduction spans the model COLUMN only.
out = hvd.allreduce(np.float32(r), average=False, group=bg, name="col.sum")
assert float(out) == sum(bg.ranks), (r, out)

# DistributedOptimizer defaults to the batch group under the mesh:
# per-rank gradients rank r -> mean over the batch column.
import optax

opt = hvd.DistributedOptimizer(optax.sgd(1.0))  # hvd-lint: disable=missing-initial-broadcast
params = jnp.zeros(3)
state = opt.init(params)
g = jnp.full(3, float(r))
updates, state = opt.update(g, state, params)
expect = -np.mean(bg.ranks)
assert np.allclose(np.asarray(updates), expect), (r, updates, expect)

# Megatron f/g over the model group: exact forward value and exact
# shard gradients under jax.grad.
W = jnp.ones((3, 2)) * (mg.rank() + 1)


def loss(w):
    x = tp.copy_to_model_parallel(jnp.ones((2, 3)), mg, name="mw.f")
    y = tp.reduce_from_model_parallel(x @ w, mg, name="mw.g")
    return jnp.sum(y * y)


val, grad = jax.value_and_grad(loss)(W)
assert abs(float(val) - 4 * 81.0) < 1e-4, (r, val)
assert np.allclose(np.asarray(grad), 36.0), (r, grad)

print("rank %d mesh worker ok" % r, flush=True)
