"""Runtime divergence detection: the dynamic complement of hvd-lint.

e2e: a two-process job with an intentionally rank-divergent collective
must fail promptly with an error naming the offending call site(s) —
via the coordinator's digest/pending cross-check — instead of hanging
until the stall-inspector timeout. Unit: the call tracker's seq/digest
semantics and generation reset.
"""

import numpy as np
import pytest


@pytest.mark.e2e
def test_cross_stall_divergence_reports_call_site(run_launcher):
    """Both ranks block on rank-suffixed names: every rank's error must
    name both sides of the divergence, promptly (grace 2s, while the
    stall inspector is left at its 60s default)."""
    result = run_launcher(2, "divergence_worker.py", extra_env={
        "DIVERGENCE_MODE": "cross_stall",
        "HVD_TPU_DIVERGENCE_GRACE_SECONDS": "2",
    })
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("divergence reported") == 2


@pytest.mark.e2e
def test_progress_divergence_names_missing_ranks_calls(run_launcher):
    """An async rank-conditional orphan fails once the other rank has
    moved 64 calls past it; the error lists what that rank did instead,
    and the common training path is unaffected."""
    result = run_launcher(2, "divergence_worker.py", extra_env={
        "DIVERGENCE_MODE": "progress",
        # keep the cross-stall rule out of the way so the progress rule
        # is what fires
        "HVD_TPU_DIVERGENCE_GRACE_SECONDS": "30",
    })
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("divergence reported") == 1
    assert result.stdout.count("finished all steps") == 1


@pytest.mark.e2e
def test_assert_synchronized_catches_reorder(run_launcher):
    """Sequences that complete but differ in order are invisible to the
    pending-table rules; the explicit digest assertion catches them."""
    result = run_launcher(2, "divergence_worker.py", extra_env={
        "DIVERGENCE_MODE": "assert",
    })
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("divergence reported") == 2


def test_call_digest_tracks_sequence():
    """seq counts enqueued collectives; digest changes with each call and
    is insensitive to nothing (same calls -> same value after re-init)."""
    import horovod_tpu as hvd

    hvd.init()
    basics = hvd.get_basics()

    def run_sequence():
        hvd.allreduce(np.ones(3, dtype=np.float32), "digest.a")
        hvd.allgather(np.ones(2, dtype=np.float32), "digest.b")
        return basics.call_digest()

    hvd.shutdown()
    hvd.init()
    seq0, digest0 = basics.call_digest()
    assert seq0 == 0
    seq1, digest1 = run_sequence()
    assert seq1 == 2
    assert digest1 != digest0

    # Generation reset: the same sequence after re-init reproduces the
    # same (seq, digest) — survivors and fresh workers agree.
    hvd.shutdown()
    hvd.init()
    seq2, digest2 = basics.call_digest()
    assert (seq2, digest2) == (0, digest0)
    seq3, digest3 = run_sequence()
    assert (seq3, digest3) == (seq1, digest1)


def test_assert_synchronized_size1_passes():
    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax

    hvd.init()
    hvd_jax.assert_synchronized()  # size 1: trivially synchronized
