"""Elastic training subsystem tests (ISSUE 1 tentpole).

Unit layer: host-manager blacklist backoff, discovery-script contract,
state commit/restore semantics, and the ``@elastic.run`` rollback loop at
size 1 (exercises the real native shutdown/re-init cycle).

E2E layer (``e2e`` marker, launcher-driven): kill one worker mid-training
-> survivors roll back to the last commit and continue at reduced size
within one generation; after the blacklist backoff expires a replacement
worker is spawned and absorbed back — the job's process tree is never
restarted and the loss keeps decreasing across membership changes.
"""

import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu.elastic.discovery import (FixedHosts, HostDiscoveryScript,
                                           HostManager)
from horovod_tpu.elastic.state import ElasticState, _tree_flatten


# ---------------------------------------------------------------------------
# Host manager / blacklisting

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_blacklist_not_retried_before_backoff_expires():
    clock = _FakeClock()
    mgr = HostManager(FixedHosts({"a": 2, "b": 2}), cooldown=10.0,
                      clock=clock)
    mgr.refresh()
    assert mgr.available_hosts_and_slots() == {"a": 2, "b": 2}
    mgr.record_failure("a")
    assert mgr.is_blacklisted("a")
    assert mgr.available_hosts_and_slots() == {"b": 2}
    clock.t = 9.9  # backoff not yet expired: still excluded
    assert mgr.is_blacklisted("a")
    clock.t = 10.1  # expired: retried again
    assert not mgr.is_blacklisted("a")
    assert mgr.available_hosts_and_slots() == {"a": 2, "b": 2}


def test_blacklist_backoff_doubles_and_success_resets():
    clock = _FakeClock()
    mgr = HostManager(FixedHosts({"a": 1}), cooldown=10.0, clock=clock)
    mgr.refresh()
    mgr.record_failure("a")
    assert mgr.blacklisted_until("a") == pytest.approx(10.0)
    clock.t = 20.0
    mgr.record_failure("a")  # second consecutive failure: 2x backoff
    assert mgr.blacklisted_until("a") == pytest.approx(40.0)
    clock.t = 100.0
    mgr.record_failure("a")  # third: 4x
    assert mgr.blacklisted_until("a") == pytest.approx(140.0)
    mgr.record_success("a")  # healthy worker resets the streak
    mgr.record_failure("a")
    assert mgr.blacklisted_until("a") == pytest.approx(100.0 + 10.0)


def test_blacklist_ignores_success_of_pre_failure_worker():
    """A worker that was already running when the host failed must not
    clear the blacklist — only post-failure evidence counts (otherwise
    one long-lived survivor on a multi-slot host defeats the backoff)."""
    clock = _FakeClock()
    mgr = HostManager(FixedHosts({"a": 2}), cooldown=10.0, clock=clock)
    mgr.refresh()
    clock.t = 50.0
    mgr.record_failure("a")
    mgr.record_success("a", started_at=5.0)  # survivor predates failure
    assert mgr.is_blacklisted("a")
    mgr.record_success("a", started_at=55.0)  # post-failure worker
    assert not mgr.is_blacklisted("a")


def test_blacklist_backoff_capped():
    clock = _FakeClock()
    mgr = HostManager(FixedHosts({"a": 1}), cooldown=10.0,
                      max_backoff=25.0, clock=clock)
    mgr.refresh()
    for _ in range(5):
        mgr.record_failure("a")
    assert mgr.blacklisted_until("a") == pytest.approx(25.0)


def test_host_discovery_script(tmp_path):
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho hosta:4\necho '# comment'\n"
                      "echo hostb\n")
    script.chmod(0o755)
    disc = HostDiscoveryScript(str(script), default_slots=2)
    assert disc.find_available_hosts_and_slots() == {"hosta": 4,
                                                     "hostb": 2}


def test_host_discovery_script_failure_keeps_last(tmp_path):
    flag = tmp_path / "fail"
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\nif [ -e %s ]; then exit 3; fi\n"
                      "echo hosta:2\n" % flag)
    script.chmod(0o755)
    disc = HostDiscoveryScript(str(script))
    assert disc.find_available_hosts_and_slots() == {"hosta": 2}
    flag.write_text("")  # script now fails: previous host set is kept
    assert disc.find_available_hosts_and_slots() == {"hosta": 2}


# ---------------------------------------------------------------------------
# State commit/restore

def test_state_commit_restore_roundtrip():
    state = ElasticState(w=np.arange(4.0), step=3,
                         nested={"a": np.ones(2), "b": [1, 2.5]})
    state.save()
    state.w += 100.0
    state.step = 9
    state.nested["a"][0] = -1.0
    state.nested["b"][1] = 7.0
    state.restore()
    assert np.allclose(state.w, np.arange(4.0))
    assert state.step == 3
    assert np.allclose(state.nested["a"], 1.0)
    assert state.nested["b"] == [1, 2.5]


def test_state_namedtuple_roundtrip():
    """Optax-style optimizer state is a NamedTuple pytree; commit/
    restore must rebuild it with positional fields, not an iterable."""
    import collections

    NT = collections.namedtuple("ScaleState", ["mu", "nu"])
    state = ElasticState(opt=NT(mu=np.zeros(2), nu=np.ones(2)), step=1)
    state.save()
    state.opt = NT(mu=state.opt.mu + 5.0, nu=state.opt.nu * 3.0)
    state.restore()
    assert isinstance(state.opt, NT)
    assert np.allclose(state.opt.mu, 0.0)
    assert np.allclose(state.opt.nu, 1.0)


def test_state_restore_without_commit_is_noop():
    state = ElasticState(step=5)
    state.restore()
    assert state.step == 5


def test_tree_flatten_deterministic_order():
    tree = {"b": [np.zeros(1), 2], "a": {"y": 1, "x": 0}}
    paths = [p for p, _ in _tree_flatten(tree)]
    assert paths == [".a.x", ".a.y", ".b.0", ".b.1"]


def test_state_rejects_underscore_attrs():
    with pytest.raises(ValueError):
        ElasticState(_committed=1)


# ---------------------------------------------------------------------------
# The @elastic.run rollback loop (size-1: real native shutdown/re-init)

def test_run_decorator_rolls_back_to_last_commit():
    import horovod_tpu as hvd
    from horovod_tpu import elastic
    from horovod_tpu.common.ops import HorovodInternalError

    hvd.init()
    state = elastic.ElasticState(w=np.zeros(2), step=0)
    attempts = []

    @elastic.run
    def train(st):
        attempts.append(st.step)
        while st.step < 4:
            st.w = st.w + 1.0
            st.step += 1
            if st.step == 2:
                st.commit()
            if st.step == 3 and len(attempts) == 1:
                # Simulate a peer loss mid-collective: the wrapper must
                # restore the step-2 commit and re-enter func.
                raise HorovodInternalError("simulated peer loss")
        return st.step

    assert train(state) == 4
    # Second attempt resumed from the commit (step 2), not from 0 and
    # not from the failed step-3 state.
    assert attempts == [0, 2]
    assert np.allclose(state.w, 4.0)
    assert hvd.is_initialized()  # re-init happened, job never died


# ---------------------------------------------------------------------------
# E2E: launcher-driven shrink + rollback + grow (acceptance criterion)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LINE = re.compile(r"worker (\d+) gen (\d+) step (\d+) size (\d+) "
                  r"loss ([0-9.]+)")


@pytest.mark.e2e
def test_elastic_shrink_rollback_and_grow():
    from tests.conftest import clean_worker_env

    env = clean_worker_env({
        # Fast cadence so failure detection, blacklist expiry and regrowth
        # all happen within seconds.
        "HVD_TPU_ELASTIC_COOLDOWN": "2",
        "HVD_TPU_ELASTIC_DISCOVERY_INTERVAL": "0.3",
        "HVD_TPU_START_TIMEOUT": "30",
        "ELASTIC_TEST_STEP_SLEEP": "0.25",
    })
    t0 = time.monotonic()
    result = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "3",
         "--min-np", "1", "--",
         sys.executable, os.path.join(REPO_ROOT, "tests",
                                      "elastic_worker.py")],
        env=env, timeout=240, capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    out = result.stdout
    assert result.returncode == 0, (out, result.stderr)
    assert "worker 1 crashing now" in out

    rows = [(int(w), int(g), int(s), int(n), float(l))
            for w, g, s, n, l in LINE.findall(out)]
    gen0 = [r for r in rows if r[1] == 0]
    gen1 = [r for r in rows if r[1] == 1]
    grown = [r for r in rows if r[1] >= 2]
    assert gen0 and gen1 and grown, rows

    # Shrink: generation 1 runs at size 2 and RESUMES FROM THE LAST
    # COMMIT (step 5 committed -> first gen-1 step is 6, re-doing the
    # uncommitted steps 6-7 the crash wiped).
    assert all(r[3] == 2 for r in gen1)
    assert min(r[2] for r in gen1) == 6
    # The crash happened at step 7, so steps 6-7 were rolled back and
    # re-run under the new membership.
    assert max(r[2] for r in gen0) >= 7

    # Grow: a later generation runs at size 3 again, including the
    # respawned worker (a worker id not in the original cohort).
    assert any(r[3] == 3 for r in grown)
    assert any(r[0] > 2 for r in grown), "replacement worker not absorbed"

    # Loss keeps decreasing across the membership changes: the final
    # loss beats everything generation 0 reached, and training ran to
    # completion on every surviving worker.
    done = re.findall(r"train done step (\d+) loss ([0-9.]+)", out)
    assert len(done) == 3, out
    assert all(int(s) == 30 for s, _ in done)
    final_loss = float(done[0][1])
    assert final_loss < min(r[4] for r in gen0)
    assert final_loss < 0.5
    # The whole dance (crash, rollback, regrow, finish) stays well under
    # the classic full-restart cost envelope.
    assert elapsed < 180, "elastic recovery took %.0fs" % elapsed
