"""Subgroup-hierarchical worker (4 ranks, forced 2x2 topology;
tests/test_shm.py harness): a group whose member set forms a uniform
(local, cross) grid must take the HIERARCHICAL reduce-scatter/allreduce
path (counter-proved via reduce_scatter_hierarchical_total), with exact
shard values pinned under all three wire codecs; a ragged group (2
members on one host, 1 on the other) must stay on the flat group ring
(the counter must NOT move for it)."""

import json
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


SIZES = [1, 785, 4 * 256 + 5]


def hier_count():
    return hvd.metrics()["counters"]["reduce_scatter_hierarchical_total"]


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 4 and hvd.is_homogeneous()
    # Every rank registers both groups with identical lists in identical
    # order (the process-group contract, docs/GROUPS.md).
    grid_group = hvd.new_group([0, 1, 2, 3])   # uniform 2x2 grid
    ragged_group = hvd.new_group([0, 1, 3])    # 2 members host 0, 1 host 1

    # Uniform-grid group: hierarchical path, exact shards, all codecs.
    before = hier_count()
    gr, gn = grid_group.rank(), grid_group.size()
    for mode in ["none", "bf16", "int8"]:
        for size in SIZES:
            if mode == "int8":
                x = np.full(size, float(gr + 1), np.float32)
                want = np.full(size, sum(range(1, gn + 1)), np.float32)
            else:
                i = np.arange(size, dtype=np.float32)
                x = np.asarray((i % 11) + gr + 1, np.float32)
                want = np.asarray(gn * (i % 11) + sum(range(1, gn + 1)),
                                  np.float32)
            shard = ops.reduce_scatter(x, "ghier.rs.%s.%d" % (mode, size),
                                       compression=mode, group=grid_group)
            counts, offsets = ops.shard_partition(size, gn)
            if not np.array_equal(
                    shard, want[offsets[gr]:offsets[gr] + counts[gr]]):
                print("GRID RS MISMATCH mode %s size %d rank %d"
                      % (mode, size, r), flush=True)
                return 1
            out = ops.allreduce(x, "ghier.ar.%s.%d" % (mode, size),
                                compression=mode, group=grid_group)
            if not np.array_equal(out, want):
                print("GRID AR MISMATCH mode %s size %d rank %d"
                      % (mode, size, r), flush=True)
                return 1
    grid_hier = hier_count() - before
    # Gauge snapshot while every peer is provably still alive (the last
    # collective just completed): a peer that exits first EOFs the
    # control star and the coordinator's teardown zeroes the gauge.
    segments_live = hvd.metrics()["gauges"]["shm_segments_active"]

    # Ragged group: flat ring path — the hierarchical counter must not
    # move while its reduce-scatters execute (members only).
    before = hier_count()
    if ragged_group.rank() >= 0:
        rr, rn = ragged_group.rank(), ragged_group.size()
        size = 785
        x = np.full(size, float(rr + 1), np.float32)
        want = np.full(size, sum(range(1, rn + 1)), np.float32)
        shard = ops.reduce_scatter(x, "ragged.rs", group=ragged_group)
        counts, offsets = ops.shard_partition(size, rn)
        if not np.array_equal(
                shard, want[offsets[rr]:offsets[rr] + counts[rr]]):
            print("RAGGED RS MISMATCH rank %d" % r, flush=True)
            return 1
    ragged_hier = hier_count() - before

    # World barrier before the final read so the counters cover every
    # phase on every rank.
    ops.allreduce(np.ones(1, np.float32), "ghier.barrier")
    snap = hvd.metrics()
    print("GHIER_METRICS %s" % json.dumps({
        "rank": r,
        "grid_hier": grid_hier,
        "ragged_hier": ragged_hier,
        "segments": segments_live,
        "shm_sent": snap["counters"]["net_shm_bytes_sent_total"],
    }), flush=True)
    print("rank %d group-hier worker done" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
