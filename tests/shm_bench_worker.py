"""Shm-vs-TCP A/B bench worker (bench.py --shm): allreduces payloads of
several sizes HVD_TPU_BENCH_ITERS times under HVD_TPU_COMPRESSION with
the shm plane on or off (HVD_TPU_SHM), verifying values every iteration,
and reports per-size wall time plus the transport counters as one
`SHM_BENCH {...}` JSON line per rank. Per-hop latency comes from the
smallest payload (an allreduce at 2 ranks is exactly 2 neighbor
exchanges, so us_per_op/2 ~ one hop)."""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    iters = int(os.environ.get("HVD_TPU_BENCH_ITERS", "20"))
    mode = os.environ.get("HVD_TPU_COMPRESSION", "none") or "none"
    sizes = [int(s) for s in os.environ.get(
        "HVD_TPU_BENCH_SIZES", "4096,65536,1048576,4194304").split(",")]
    tol = {"none": 1e-5, "bf16": 2e-2, "int8": 4e-2}[mode]

    per_size = {}
    for nbytes in sizes:
        elems = nbytes // 4
        base = (np.arange(elems, dtype=np.float32) % 997) / 31.0
        want = base * n + sum(range(n))
        ops.allreduce(base + r, "shmbench.warm.%d" % nbytes)  # warmup
        t0 = time.perf_counter()
        for i in range(iters):
            out = ops.allreduce(base + r, "shmbench.%d.%d" % (nbytes, i))
            err = np.max(np.abs(out - want)) / np.max(np.abs(want))
            assert err < tol, (mode, nbytes, i, err)
        dt = time.perf_counter() - t0
        per_size[str(nbytes)] = round(dt / iters * 1e6, 1)

    c = hvd.metrics()
    print("SHM_BENCH %s" % json.dumps({
        "rank": r, "size": n, "mode": mode, "iters": iters,
        "us_per_op": per_size,
        "segments": c["gauges"]["shm_segments_active"],
        "shm_bytes_sent": c["counters"]["net_shm_bytes_sent_total"],
        "ring_bytes_sent": c["counters"]["net_ring_bytes_sent_total"],
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
