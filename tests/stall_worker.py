"""Stall-inspector e2e (reference analogue: test/test_stall.py): ranks != 0
delay their second allreduce past the stall-shutdown threshold; the
coordinator must warn (listing missing ranks) and then trigger a coordinated
shutdown rather than deadlock. Run with HVD_TPU_STALL_CHECK_TIME_SECONDS=2
and HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS=5."""

import signal
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common.ops import HorovodInternalError


def alarm(signum, frame):
    sys.stderr.write("watchdog fired: job deadlocked\n")
    sys.exit(3)


signal.signal(signal.SIGALRM, alarm)
signal.alarm(45)

hvd.init()
r = hvd.rank()
hvd.allreduce(np.ones(4, dtype=np.float32), "warmup")
if r != 0:
    time.sleep(10)
try:
    hvd.allreduce(np.ones(4, dtype=np.float32), "stalled")
except HorovodInternalError:
    pass
print("rank %d exited cleanly" % r)
