"""hvd-trace e2e + unit tests (docs/TRACING.md): shard merge with
aligned clocks, critical-path attribution, causal ordering of wire
hops, the flight recorder's post-mortem bundles, timeline repair, and
the hvd-top trc column. The `run_launcher` harness lives in
conftest.py."""

import json
import os
import subprocess
import sys

import pytest

from horovod_tpu.trace import (critical_path_table, merge_shards,
                               repair_timeline)

pytestmark = pytest.mark.e2e

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_straggler_critical_path_and_causal_order(run_launcher, tmp_path):
    """ISSUE 18 acceptance: 4 ranks, rank 3 straggling 2s — the merged
    trace is one valid JSON, the critical-path table names the straggler
    attributing >= 1.5s to negotiation wait, and every paired ring-hop
    edge is causally ordered after clock correction."""
    trace_dir = str(tmp_path / "trace")
    proc = run_launcher(4, "trace_straggler_worker.py", extra_env={
        "HVD_TPU_TRACE_DIR": trace_dir,
        "HVD_TPU_TL_STRAGGLE": "2",
    }, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    shards = sorted(os.listdir(trace_dir))
    assert shards == ["trace_rank%d.jsonl" % r for r in range(4)], shards

    merged = merge_shards([trace_dir])
    assert sorted(merged.ranks) == [0, 1, 2, 3]
    assert merged.world_size == 4

    # Non-reference ranks piggybacked clock samples on the control
    # plane; rank 0 is the reference (offset identically 0).
    assert merged.ranks[0]["offset_ns"] == 0
    for r in (1, 2, 3):
        assert merged.ranks[r]["uncertainty_ns"] < 1 << 60, \
            "rank %d never adopted a clock sample" % r

    # The merged trace round-trips as ONE valid chrome-tracing JSON.
    chrome = json.loads(json.dumps(merged.to_chrome()))
    assert len(chrome["traceEvents"]) > 100
    assert all("ph" in e for e in chrome["traceEvents"])

    rows = critical_path_table(merged)
    straggled = [r for r in rows if r["tensor"] == "straggled"]
    assert straggled, [r["tensor"] for r in rows]
    row = straggled[0]
    assert row["straggler_rank"] == 3, row
    assert row["dominant_phase"] == "negotiate", row
    assert row["negotiation_wait_ns"] >= 1.5e9, row
    # And it dominates the table: nothing else in this run waited
    # anywhere near that long.
    assert rows[0]["tensor"] == "straggled", rows[:3]

    # Causal check: sender's corrected hop start precedes the paired
    # receiver's corrected hop end for every global-ring wire hop.
    violations = merged.check_causal()
    assert violations == [], violations

    # The CLI drives the same pipeline end to end and exits 0.
    cli = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bin", "hvd-trace"),
         trace_dir, "--check-causal"],
        capture_output=True, text=True, timeout=120)
    assert cli.returncode == 0, cli.stdout + cli.stderr
    assert "causal check: all paired ring hops ordered" in cli.stdout
    assert "straggled" in cli.stdout
    with open(os.path.join(trace_dir, "trace_merged.json")) as f:
        assert len(json.load(f)["traceEvents"]) == len(chrome["traceEvents"])


def _load_bundle(path):
    with open(path) as f:
        b = json.load(f)
    assert b.get("hvd_bundle") == 1, path
    pending = b.get("pending")
    if isinstance(pending, str):
        pending = json.loads(pending) if pending else None
    return b, pending


def test_sigkill_survivor_bundles_and_timeline(run_launcher, tmp_path):
    """A SIGKILLed peer (no cleanup, no goodbye frame) must leave a
    post-mortem bundle on EVERY survivor; the coordinator's names the
    missing rank and the in-flight tensor; the launcher failure summary
    lists the bundle paths; and rank 0's timeline file — historically
    left an unterminated JSON array by any crash — parses whole."""
    bundle_dir = str(tmp_path / "bundles")
    timeline_file = str(tmp_path / "timeline.json")
    proc = run_launcher(3, "trace_kill_worker.py", extra_env={
        "HVD_TPU_BUNDLE_DIR": bundle_dir,
        "HVD_TPU_TIMELINE": timeline_file,
        "HVD_TPU_KILL_RANK": "1",
        # No reconnect hold: the coordinator must fail over (and dump
        # its bundle) the moment the peer's socket dies, not after a 5s
        # window the launcher's teardown SIGTERM would win.
        "HVD_TPU_RECONNECT_SECONDS": "0",
    }, timeout=300)
    out = proc.stdout + proc.stderr
    assert proc.returncode != 0, out  # the job failed, by design

    # Satellite 1 regression: the whole timeline file parses — no
    # truncated array, no trailing comma — even though the job died.
    with open(timeline_file) as f:
        records = json.loads(f.read())
    assert isinstance(records, list) and len(records) > 0

    bundles = sorted(os.listdir(bundle_dir))
    by_rank = {}
    for name in bundles:
        assert name.startswith("hvd_bundle_rank"), name
        b, pending = _load_bundle(os.path.join(bundle_dir, name))
        by_rank.setdefault(b["rank"], []).append((name, b, pending))
    # Every SURVIVOR (0 and 2) dumped at least one bundle; the killed
    # rank got no chance to (SIGKILL is uncatchable).
    assert 0 in by_rank and 2 in by_rank, bundles
    assert 1 not in by_rank, bundles

    # The coordinator's connection-lost bundle names the missing rank
    # and the in-flight tensor.
    conn = [(n, b, p) for n, b, p in by_rank[0]
            if "connection_lost" in n]
    assert conn, by_rank[0]
    _, b0, pending0 = conn[0]
    assert b0["world_size"] == 3
    entries = (pending0 or {}).get("pending") or []
    doomed = [e for e in entries if e["name"] == "doomed"]
    assert doomed, pending0
    assert 1 in doomed[0]["missing"], doomed
    assert 1 not in doomed[0]["reported"], doomed

    # The launcher's failure summary points the operator at them.
    assert "post-mortem bundle:" in out, out


def test_stall_warning_rate_limit_escalation_and_bundle(run_launcher,
                                                        tmp_path):
    """The stall inspector's full warning ladder in one run: first
    check emits the full missing-ranks block, the next check collapses
    the unchanged set to the rate-limited 'Stall persists ... repeat'
    line, the shutdown threshold escalates to coordinated shutdown —
    and the escalation arms a flight-recorder dump on every rank."""
    bundle_dir = str(tmp_path / "bundles")
    proc = run_launcher(2, "stall_worker.py", extra_env={
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS": "5",
        "HVD_TPU_BUNDLE_DIR": bundle_dir,
    }, timeout=120)
    out = proc.stdout + proc.stderr
    assert "rank 0 exited cleanly" in out, out
    assert "rank 1 exited cleanly" in out, out
    # Full warning block on the first tripped check...
    assert "missing ranks: 1" in out, out
    # ...the rate-limited repeat line on the next (same missing set)...
    assert "Stall persists" in out, out
    assert "repeat #" in out, out
    # ...then escalation.
    assert "Stall threshold exceeded" in out, out

    # The escalation dumped bundles: rank 0 at the decision point, rank
    # 1 via the flag riding the shutdown broadcast.
    names = os.listdir(bundle_dir) if os.path.isdir(bundle_dir) else []
    esc = [n for n in names if "escalation" in n]
    assert esc, names
    ranks_with_bundle = set()
    for n in esc:
        b, pending = _load_bundle(os.path.join(bundle_dir, n))
        ranks_with_bundle.add(b["rank"])
        if b["rank"] == 0:
            entries = (pending or {}).get("pending") or []
            assert any(e["name"] == "stalled" for e in entries), pending
    assert 0 in ranks_with_bundle, names


def test_repair_truncated_timeline(tmp_path):
    """`hvd-trace --repair` fixes PRE-EXISTING truncated timelines from
    before the emergency-finalize hook: mid-record truncation, dangling
    comma, and an already-valid file (no-op)."""
    good = [{"ph": "B", "ts": 1, "name": "a"},
            {"ph": "E", "ts": 2, "name": "b"},
            {"ph": "X", "ts": 3, "name": 'tricky "}" name'}]
    body = "[\n" + ",\n".join(json.dumps(r) for r in good)

    # Torn mid-record (SIGKILL mid-fprintf).
    torn = tmp_path / "torn.json"
    torn.write_text(body + ',\n{"ph": "B", "ts": 4, "na')
    assert repair_timeline(str(torn)) is True
    assert json.loads(torn.read_text()) == good

    # Dangling comma after a complete record.
    comma = tmp_path / "comma.json"
    comma.write_text(body + ",\n")
    assert repair_timeline(str(comma)) is True
    assert json.loads(comma.read_text()) == good

    # Already valid: untouched, reported as such.
    ok = tmp_path / "ok.json"
    ok.write_text(body + "\n]\n")
    before = ok.read_text()
    assert repair_timeline(str(ok)) is False
    assert ok.read_text() == before

    # The CLI wraps the same repair.
    torn2 = tmp_path / "torn2.json"
    torn2.write_text(body + ',\n{"ph": "B"')
    cli = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bin", "hvd-trace"),
         "--repair", str(torn2)],
        capture_output=True, text=True, timeout=60)
    assert cli.returncode == 0, cli.stdout + cli.stderr
    assert "repaired" in cli.stdout
    assert json.loads(torn2.read_text()) == good


def test_serve_emitter_shares_shard_schema(tmp_path, monkeypatch):
    """The pure-Python serve emitter writes shards the merge tool reads
    with no special casing — and is a no-op without HVD_TPU_TRACE_DIR."""
    from horovod_tpu.trace import emit

    monkeypatch.delenv("HVD_TPU_TRACE_DIR", raising=False)
    emit._shards.clear()
    off = emit.shard_for("serve_r9")
    assert not off.enabled
    off.span("noop", 0, 1)  # must not write anywhere

    trace_dir = tmp_path / "trace"
    monkeypatch.setenv("HVD_TPU_TRACE_DIR", str(trace_dir))
    emit._shards.clear()
    em = emit.shard_for("serve_r9", rank=9)
    assert em.enabled
    t0 = emit.now_ns()
    em.span("serve.batch", t0, emit.now_ns(), nbytes=4, cycle=7)

    shard = trace_dir / "trace_serve_r9.jsonl"
    merged = merge_shards([str(shard)])
    assert 9 in merged.ranks
    spans = merged.ranks[9]["spans"]
    assert len(spans) == 1
    assert spans[0]["n"] == "serve.batch"
    assert spans[0]["p"] == emit.TRACE_REQUEST
    assert spans[0]["b"] == 4 and spans[0]["c"] == 7
    emit._shards.clear()


def test_top_trc_column():
    """hvd-top's trc cell: '-' for a summary predating the trace fields
    (mixed-version elastic job), 'off' when tracing is disabled, span
    rate when flowing, '/dN' suffix once the ring ever dropped."""
    from horovod_tpu.run.top import _trc_state

    assert _trc_state({}, None, 1.0, {}) == "-"
    assert _trc_state({"trace_spans_total": 0}, None, 1.0, {}) == "off"
    cur = {"trace_spans_total": 5000.0, "trace_spans_dropped_total": 0}
    prev = {"trace_spans_total": 2000.0, "trace_spans_dropped_total": 0}
    assert _trc_state(cur, prev, 2.0, {}) == "1.5k"
    cur = {"trace_spans_total": 5000.0, "trace_spans_dropped_total": 37}
    assert _trc_state(cur, prev, 2.0, {}) == "1.5k/d37"
