"""JAX binding tests: host-path collectives (size-1 short circuit), the
in-jit psum plane over a shard_map'd mesh, and the optax
DistributedOptimizer in both planes."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import horovod_tpu.jax as hvd


@pytest.fixture(scope="module", autouse=True)
def init_hvd():
    hvd.init()
    yield


def test_rank_size():
    assert hvd.size() == 1
    assert hvd.rank() == 0


def test_host_allreduce():
    x = jnp.arange(10, dtype=jnp.float32)
    out = hvd.allreduce(x, average=False)
    assert np.allclose(out, x)
    out = hvd.allreduce(x, average=True)
    assert np.allclose(out, x)


def test_host_allgather_broadcast():
    x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    assert np.allclose(hvd.allgather(x), x)
    assert np.allclose(hvd.broadcast(x, 0), x)


def test_shutdown_reinit_cycles():
    """The core must survive init/shutdown/init cycles in one process
    (VERDICT round-1 lifecycle obligation; exercised by spark task reuse
    and notebook workflows)."""
    import horovod_tpu as hvd_core
    for cycle in range(2):
        hvd_core.init()
        assert hvd_core.is_initialized()
        out = hvd.allreduce(jnp.ones(3), average=False,
                            name="cycle.%d" % cycle)
        assert np.allclose(out, 1.0)
        hvd_core.shutdown()
        assert not hvd_core.is_initialized()
    hvd_core.init()  # leave initialized for the rest of the module


def test_scalar_shape_roundtrip():
    """0-d tensors must come back 0-d (ascontiguousarray promotes them
    to (1,) internally; the caller's shape wins)."""
    out = hvd.allreduce(jnp.float32(2.0), average=False)
    assert out.shape == (), out.shape
    out = hvd.broadcast(jnp.int32(5), 0)
    assert out.shape == (), out.shape


def test_host_allgather_empty():
    # Zero rows is legal (reference allgatherv semantics); the zero-copy
    # view path must not choke on the core's null empty-buffer pointer.
    out = hvd.allgather(jnp.zeros((0, 4), jnp.float32))
    assert out.shape[0] == 0 and out.shape[1:] == (4,)


def test_compression_fp16_roundtrip():
    x = jnp.arange(8, dtype=jnp.float32)
    out = hvd.allreduce(x, average=False, compression=hvd.Compression.fp16)
    assert out.dtype == jnp.float32
    assert np.allclose(out, x, atol=1e-2)


def test_injit_psum_plane():
    devices = jax.devices("cpu")
    assert len(devices) == 8, "conftest should provide 8 virtual devices"
    mesh = Mesh(np.array(devices), (hvd.AXIS_NAME,))

    def step(x):
        return hvd.allreduce(x, average=True)

    f = shard_map(step, mesh=mesh, in_specs=P(hvd.AXIS_NAME),
                  out_specs=P(hvd.AXIS_NAME))
    x = jnp.arange(16, dtype=jnp.float32).reshape(8, 2)
    out = jax.jit(f)(x)
    # Average over the mapped axis: every row becomes the column mean
    # broadcast back to its shard.
    expected_mean = x.reshape(8, 2).mean(axis=0)
    assert np.allclose(out, jnp.tile(expected_mean, (8, 1)))


def test_injit_allgather():
    devices = jax.devices("cpu")
    mesh = Mesh(np.array(devices), (hvd.AXIS_NAME,))
    f = shard_map(lambda x: hvd.allgather(x), mesh=mesh,
                  in_specs=P(hvd.AXIS_NAME), out_specs=P(),
                  check_rep=False)
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = jax.jit(f)(x)
    assert out.shape == (8, 1)
    assert np.allclose(out.ravel(), np.arange(8))


def test_injit_broadcast_pytree():
    """In-jit broadcast accepts a pytree and broadcasts leaf-wise (the
    masked-psum rewrite must not regress the tree-accepting API)."""
    devices = jax.devices("cpu")
    mesh = Mesh(np.array(devices), (hvd.AXIS_NAME,))

    def step(rank_arr):
        tree = {"w": rank_arr, "b": rank_arr * 2.0}
        return hvd.broadcast(tree, root_rank=3)

    f = shard_map(step, mesh=mesh, in_specs=P(hvd.AXIS_NAME),
                  out_specs=P(hvd.AXIS_NAME))
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = jax.jit(f)(x)
    # Every shard receives rank 3's values.
    assert np.allclose(out["w"].ravel(), 3.0)
    assert np.allclose(out["b"].ravel(), 6.0)


def test_distributed_optimizer_host():
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = {"w": jnp.ones(4), "b": jnp.zeros(2)}
    state = opt.init(params)
    grads = {"w": jnp.full(4, 2.0), "b": jnp.ones(2)}
    updates, state = opt.update(grads, state, params)
    new_params = optax.apply_updates(params, updates)
    assert np.allclose(new_params["w"], 1.0 - 0.1 * 2.0)
    assert np.allclose(new_params["b"], -0.1)


def test_distributed_optimizer_injit():
    devices = jax.devices("cpu")
    mesh = Mesh(np.array(devices), (hvd.AXIS_NAME,))
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    params = jnp.ones(4)
    state = opt.init(params)

    def step(params, state, grads):
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    f = shard_map(step, mesh=mesh,
                  in_specs=(P(), P(), P(hvd.AXIS_NAME)),
                  out_specs=(P(), P()))
    # Per-device gradients 0..7 -> average 3.5.
    grads = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) * jnp.ones((8, 4))
    grads = grads.reshape(8, 4)
    new_params, _ = jax.jit(f)(params, state, grads)
    assert np.allclose(new_params, 1.0 - 0.1 * 3.5)


def test_broadcast_parameters():
    params = {"w": jnp.arange(4, dtype=jnp.float32),
              "b": jnp.ones(2, dtype=jnp.bfloat16)}
    out = hvd.broadcast_parameters(params, root_rank=0)
    assert out["b"].dtype == jnp.bfloat16
    assert np.allclose(out["w"], params["w"])


def test_metric_average():
    assert hvd.metric_average(3.5) == 3.5


def test_plain_jit_single_process_identity():
    """Collectives inside plain jit (no shard_map axis) in a single
    process are identity — must NOT raise unbound-axis NameError."""
    import jax
    import jax.numpy as jnp
    import horovod_tpu.jax as hvd_jax

    @jax.jit
    def step(x):
        a = hvd_jax.allreduce(x, average=True)
        b = hvd_jax.broadcast(x, 0)
        g = hvd_jax.allgather(x)
        return a, b, g

    x = jnp.arange(6.0)
    a, b, g = step(x)
    assert jnp.allclose(a, x)
    assert jnp.allclose(b, x)
    assert jnp.allclose(g, x)


def test_w2v_sparse_step_matches_dense_mesh():
    """The bench's sparse (indices,values) allgather+scatter-add plane
    must produce bit-comparable tables to the dense psum path after
    multiple steps on a real 4-device mesh — pins the jax-plane
    IndexedSlices analogue end to end (duplicate ids accumulate, the
    cross-rank average matches, updates stay replicated)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from bench import w2v_make_step

    jax.config.update("jax_default_matmul_precision", "highest")
    n = 4
    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("dp",))
    V, D, B, K = 64, 16, 32, 8  # B/K divisible by n
    rng = np.random.RandomState(3)
    center = jnp.asarray(rng.randint(0, V, B).astype(np.int32))
    context = jnp.asarray(rng.randint(0, V, B).astype(np.int32))
    neg = jnp.asarray(rng.randint(0, V, K).astype(np.int32))

    def tables():
        r = np.random.RandomState(5)
        return (jnp.asarray(r.randn(V, D).astype(np.float32)),
                jnp.asarray(r.randn(V, D).astype(np.float32)),
                jnp.zeros((V,), jnp.float32))

    outs = {}
    for sparse in (True, False):
        # donate=False: old jaxlib CPU runtimes flakily recycle donated
        # buffers mid-scan (garbage outputs) — equivalence needs
        # deterministic inputs, and donation is a memory optimization,
        # not part of the semantics under test.
        step = w2v_make_step(mesh, n, sparse, num_iters=3, donate=False)
        outs[sparse] = step(*tables(), center, context, neg)

    for a, b, nm in zip(outs[True], outs[False],
                        ("emb", "nce_w", "nce_b", "loss")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6, err_msg=nm)
