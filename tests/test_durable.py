"""Durable elastic checkpoints (ISSUE 5 tentpole; docs/ELASTIC.md
"Durability").

Unit layer: manifest/shard round trip, CRC validation, torn-write and
bit-flip fallback to the newest VALID manifest, ENOSPC retry/degrade
(training never crashes on a storage fault), retention, stale-tmp
pruning, fault-spec grammar + determinism, and the pure-Python CRC32C
fallback's bit-parity with the native export.

E2E layer (``e2e`` marker, launcher-driven): SIGKILL every worker AND
the driver mid-training, relaunch, and training resumes from the last
durable commit with bitwise-identical state (CRC32C over the full state
bytes) — plus a shrink-resume variant at a smaller world size, a chaos
run with injected storage faults, and the driver's
``--restart-from-ckpt`` full-job restart when the world falls below
``--min-np``.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu.elastic import durable
from horovod_tpu.elastic.durable import (CkptFaultInjector,
                                         DurableCheckpointer,
                                         MANIFEST_NAME, apply_retention,
                                         last_durable_step,
                                         latest_valid_manifest,
                                         list_checkpoints,
                                         prune_stale_tmp,
                                         prune_unrestorable,
                                         validate_manifest)
from horovod_tpu.elastic.state import ElasticState

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_state(value=0.0, step=0):
    return ElasticState(w=np.full(8, value, np.float64), step=step,
                        nested={"a": np.arange(3.0), "b": [1, 2.5]})


def write_ckpt(directory, step, value=1.0, world_size=1):
    """Synchronously writes one complete checkpoint at `step` (all
    shards from this process) and returns the state that was saved."""
    state = make_state(value, step)
    ckpts = [DurableCheckpointer(directory, rank=r,
                                 world_size=world_size)
             for r in range(world_size)]
    state.save()
    # Enqueue ALL ranks before flushing any: rank 0's publisher blocks
    # until every sibling shard exists (exactly like a real job, where
    # the rank writers run concurrently).
    for ck in ckpts:
        ck.maybe_enqueue(state._committed, step)
    for ck in ckpts:
        assert ck.flush(timeout=60)
    return state


# ---------------------------------------------------------------------------
# CRC32C parity

def test_py_crc32c_known_answer_and_native_parity():
    # The iSCSI/RFC 3720 check value.
    assert durable._py_crc32c(b"123456789") == 0xE3069283
    # Incremental chaining must compose to the one-shot value.
    assert durable._py_crc32c(
        b"6789", durable._py_crc32c(b"12345")) == 0xE3069283
    from horovod_tpu.common.basics import get_basics
    native = get_basics().crc32c
    for blob in (b"", b"\x00" * 33, os.urandom(257), b"horovod_tpu"):
        assert native(blob) == durable._py_crc32c(blob), blob


# ---------------------------------------------------------------------------
# Manifest round trip + sharding

def test_roundtrip_single_rank(tmp_path):
    d = str(tmp_path)
    saved = write_ckpt(d, step=7, value=4.25)
    manifest, path = latest_valid_manifest(d)
    assert manifest is not None
    assert manifest["step"] == 7
    assert manifest["world_size"] == 1
    assert len(manifest["shards"]) == 1

    fresh = make_state()
    ck = DurableCheckpointer(d, rank=0, world_size=1)
    assert ck.restore_into(fresh) == 7
    assert np.array_equal(fresh.w, saved.w)
    assert fresh.step == 7
    assert fresh.nested["b"] == [1, 2.5]


def test_sharded_write_and_resharded_restore(tmp_path):
    """Two ranks each write only their shard; a single restoring rank
    (different world size) reads them all — the re-sharding path."""
    d = str(tmp_path)
    saved = write_ckpt(d, step=10, value=-2.5, world_size=2)
    manifest, path = latest_valid_manifest(d)
    assert manifest is not None and manifest["world_size"] == 2
    assert len(manifest["shards"]) == 2
    # Each shard holds a strict subset of the leaves.
    leaves = durable.load_leaves(manifest, path)
    import pickle
    for shard in manifest["shards"]:
        with open(os.path.join(path, shard["file"]), "rb") as f:
            part = pickle.loads(f.read())
        assert 0 < len(part) < len(leaves)

    fresh = make_state()
    ck = DurableCheckpointer(d, rank=0, world_size=1)
    assert ck.restore_into(fresh) == 10
    assert np.array_equal(fresh.w, saved.w)
    assert np.array_equal(fresh.nested["a"], np.arange(3.0))


def test_structural_mismatch_is_rejected(tmp_path):
    d = str(tmp_path)
    write_ckpt(d, step=3)
    other = ElasticState(q=np.zeros(2), step=0)  # different attributes
    ck = DurableCheckpointer(d, rank=0, world_size=1)
    assert ck.restore_into(other) is None  # warned, not raised
    assert np.array_equal(other.q, np.zeros(2))


def test_structural_mismatch_falls_back_to_matching_older(tmp_path):
    """A foreign-structure checkpoint as the NEWEST entry (another job
    sharing the dir, or a briefly-changed state registration) must not
    shadow an older checkpoint that matches this state exactly."""
    d = str(tmp_path)
    saved = write_ckpt(d, step=3, value=7.0)  # matches make_state
    foreign = ElasticState(qq=np.ones(4), step=9)
    ck_f = DurableCheckpointer(d, rank=0, world_size=1)
    foreign.save()
    ck_f.maybe_enqueue(foreign._committed, 9)
    assert ck_f.flush(timeout=60)
    assert latest_valid_manifest(d)[0]["step"] == 9  # newest is foreign

    fresh = make_state()
    ck = DurableCheckpointer(d, rank=0, world_size=1)
    assert ck.restore_into(fresh) == 3  # fell back past the mismatch
    assert np.array_equal(fresh.w, saved.w)


def test_sticky_snapshots_guarantee_durable_progress(tmp_path):
    """The deterministic 1-in-K sticky slot: under storage far slower
    than the commit cadence, sticky steps are never displaced by newer
    non-sticky snapshots (every rank writes them — the cross-rank
    convergence anchor), while the newest snapshot still lands via the
    second slot."""
    d = str(tmp_path)
    state = make_state()
    ck = DurableCheckpointer(
        d, rank=0, world_size=1,
        fault_spec="op=shard,prob=1.0,action=slowfsync,"
                   "delay_ms=250,count=-1")
    ck._sticky_every = 3  # due commits 0, 3, 6 are sticky
    state._durable = ck
    for step in range(9):
        state.step = step
        state.commit()  # never blocks
    assert ck.flush(timeout=60)
    steps = sorted(s for s, g, p in list_checkpoints(d))
    assert 0 in steps                  # first commit (sticky) landed
    assert steps[-1] == 8              # newest snapshot still wins
    assert 3 in steps or 6 in steps    # a mid-run sticky anchor landed


def test_every_n_commits_cadence(tmp_path):
    d = str(tmp_path)
    state = make_state()
    ck = DurableCheckpointer(d, every_n_commits=3, rank=0, world_size=1)
    state._durable = ck
    for step in range(7):
        state.step = step
        state.commit()
        # Flush each commit so the latest-wins pending slot (which may
        # otherwise skip an intermediate due snapshot when commits
        # outpace storage — by design) doesn't blur the cadence.
        assert ck.flush(timeout=60)
    steps = sorted(s for s, g, p in list_checkpoints(d))
    assert steps == [0, 3, 6]  # commits 0, 3, 6 of 0..6


def test_off_stride_commit_cadence_still_durable(tmp_path):
    """A commit cadence whose step values never hit a stride multiple
    (steps 3, 8, 13, ... with every_n_commits=10) must still produce
    durable checkpoints: the due rule fires on the first commit in each
    stride-sized step window, not on `step % stride == 0`."""
    d = str(tmp_path)
    state = make_state()
    ck = DurableCheckpointer(d, every_n_commits=10, rank=0,
                             world_size=1)
    state._durable = ck
    for step in (3, 8, 13, 18, 23):
        state.step = step
        state.commit()
        assert ck.flush(timeout=60)
    steps = sorted(s for s, g, p in list_checkpoints(d))
    assert steps == [3, 13, 23]


def test_storage_slower_than_commits_skips_to_newest(tmp_path):
    """When storage can't keep up, intermediate due snapshots are
    REPLACED by newer ones (never queued unboundedly) and the newest
    commit always lands."""
    d = str(tmp_path)
    state = make_state()
    ck = DurableCheckpointer(
        d, rank=0, world_size=1,
        fault_spec="op=shard,prob=1.0,action=slowfsync,"
                   "delay_ms=300,count=-1")
    state._durable = ck
    for step in range(5):
        state.step = step
        state.commit()  # never blocks, even at 300ms/write
    assert ck.flush(timeout=60)
    steps = sorted(s for s, g, p in list_checkpoints(d))
    assert steps[-1] == 4            # the newest commit is durable
    assert len(steps) < 5            # and some intermediates skipped


# ---------------------------------------------------------------------------
# Torn-write / bit-flip fallback (the acceptance property)

def test_fallback_skips_torn_shard(tmp_path):
    d = str(tmp_path)
    good = write_ckpt(d, step=5, value=1.0)
    write_ckpt(d, step=9, value=9.0)
    # Tear the NEWEST checkpoint's shard after the fact (as a crash
    # mid-write on a non-atomic store would): truncate to half.
    step9 = [p for s, g, p in list_checkpoints(d) if s == 9][0]
    shard = [n for n in os.listdir(step9) if n.startswith("shard-")][0]
    spath = os.path.join(step9, shard)
    data = open(spath, "rb").read()
    with open(spath, "wb") as f:
        f.write(data[:len(data) // 2])
    assert validate_manifest(step9) is None
    manifest, _ = latest_valid_manifest(d)
    assert manifest["step"] == 5  # silently fell back
    fresh = make_state()
    ck = DurableCheckpointer(d, rank=0, world_size=1)
    assert ck.restore_into(fresh) == 5
    assert np.array_equal(fresh.w, good.w)


def test_fallback_skips_bitflipped_shard(tmp_path):
    d = str(tmp_path)
    write_ckpt(d, step=2, value=1.0)
    write_ckpt(d, step=4, value=4.0)
    step4 = [p for s, g, p in list_checkpoints(d) if s == 4][0]
    shard = [n for n in os.listdir(step4) if n.startswith("shard-")][0]
    spath = os.path.join(step4, shard)
    data = bytearray(open(spath, "rb").read())
    data[len(data) // 3] ^= 0x01  # a single flipped bit
    with open(spath, "wb") as f:
        f.write(bytes(data))
    manifest, _ = latest_valid_manifest(d)
    assert manifest["step"] == 2


def test_fallback_skips_torn_manifest(tmp_path):
    d = str(tmp_path)
    write_ckpt(d, step=1, value=1.0)
    write_ckpt(d, step=6, value=6.0)
    step6 = [p for s, g, p in list_checkpoints(d) if s == 6][0]
    mpath = os.path.join(step6, MANIFEST_NAME)
    raw = open(mpath, "rb").read()
    with open(mpath, "wb") as f:
        f.write(raw[:len(raw) // 2])  # torn json
    manifest, _ = latest_valid_manifest(d)
    assert manifest["step"] == 1
    # A checkpoint dir with no manifest at all is also just skipped.
    os.remove(mpath)
    manifest, _ = latest_valid_manifest(d)
    assert manifest["step"] == 1


def test_injected_faults_produce_invalid_checkpoints(tmp_path):
    """The injector's torn/bitflip writes must be exactly the failures
    the validator rejects — proving detector and fault model agree."""
    d = str(tmp_path)
    state = make_state(1.0, 0)
    state.save()
    for step, spec in ((1, "op=shard,write=0,action=bitflip"),
                       (2, "op=shard,write=0,action=torn"),
                       (3, "op=manifest,write=0,action=torn")):
        ck = DurableCheckpointer(d, rank=0, world_size=1,
                                 fault_spec=spec)
        state.step = step
        ck.maybe_enqueue(state._committed, step)
        assert ck.flush(timeout=60)
        assert ck._injector.fires == 1
    # Every one of the three is invalid; nothing valid exists at all.
    assert all(validate_manifest(p) is None
               for _, _, p in list_checkpoints(d))
    assert latest_valid_manifest(d) == (None, None)
    # A clean write after the carnage is found immediately.
    write_ckpt(d, step=4, value=4.0)
    manifest, _ = latest_valid_manifest(d)
    assert manifest["step"] == 4


def test_enospc_degrades_to_warning_never_raises(tmp_path, capsys):
    """A persistently failing store exhausts the capped-backoff retries
    and degrades: the commit path never sees an exception, and the next
    healthy write succeeds."""
    d = str(tmp_path)
    state = make_state(1.0, 0)
    # Every attempt (first + 3 retries) hits ENOSPC.
    ck = DurableCheckpointer(d, rank=0, world_size=1,
                             fault_spec="op=shard,prob=1.0,"
                                        "action=enospc,count=-1")
    ck._retries = 2
    state.save()
    ck.maybe_enqueue(state._committed, 1)  # must not raise
    assert ck.flush(timeout=60)
    assert latest_valid_manifest(d) == (None, None)
    assert ck.last_durable_step == -1
    err = capsys.readouterr().err
    assert "FAILED after 3 attempts" in err
    # Storage recovers: the next durable commit lands.
    ck2 = DurableCheckpointer(d, rank=0, world_size=1)
    state.step = 2
    state.save()
    ck2.maybe_enqueue(state._committed, 2)
    assert ck2.flush(timeout=60)
    assert latest_valid_manifest(d)[0]["step"] == 2


class _Unpicklable:
    """deep-copyable (so commit() succeeds) but unpicklable (so the
    durable writer's serialization fails deterministically)."""

    def __deepcopy__(self, memo):
        return self

    def __reduce__(self):
        raise TypeError("deliberately unpicklable")


def test_unpicklable_state_degrades_and_writer_survives(tmp_path,
                                                        capsys):
    """A non-storage writer failure (unpicklable leaf) must degrade
    like a storage one — warning + failure metric — and must NOT kill
    the writer thread: later healthy snapshots still land."""
    d = str(tmp_path)
    bad = ElasticState(w=np.zeros(2), step=0, extra=_Unpicklable())
    ck = DurableCheckpointer(d, rank=0, world_size=1)
    bad._durable = ck
    bad.commit()  # must not raise
    assert ck.flush(timeout=60)
    assert latest_valid_manifest(d) == (None, None)
    assert "FAILED" in capsys.readouterr().err
    # Same checkpointer, now-picklable state: the thread is still alive.
    good = make_state(3.0, 4)
    good.save()
    ck.maybe_enqueue(good._committed, 4)
    assert ck.flush(timeout=60)
    assert latest_valid_manifest(d)[0]["step"] == 4


def test_auto_resume_in_run_wrapper(tmp_path, monkeypatch):
    """@elastic.run auto-enables durability from HVD_TPU_CKPT_DIR and
    restores the newest valid manifest before entering the function."""
    import horovod_tpu as hvd
    from horovod_tpu import elastic

    d = str(tmp_path)
    saved = write_ckpt(d, step=5, value=2.5)
    monkeypatch.setenv("HVD_TPU_CKPT_DIR", d)
    hvd.init()
    state = make_state()

    @elastic.run
    def train(st):
        return st.step

    assert train(state) == 5
    assert np.array_equal(state.w, saved.w)
    assert state._durable is not None  # auto-enabled


def test_prune_unrestorable_removes_crashed_leftovers(tmp_path):
    d = str(tmp_path)
    write_ckpt(d, step=3)
    # A crashed run renamed a shard but never published the manifest.
    orphan = os.path.join(d, "ckpt-%012d-g0" % 7)
    os.makedirs(orphan)
    payload = b"stale trajectory"
    name = "shard-00000-of-00001.%08x.%d.bin" % (durable.crc32c(payload),
                                                 len(payload))
    with open(os.path.join(orphan, name), "wb") as f:
        f.write(payload)
    assert prune_unrestorable(d) == ["ckpt-000000000007-g0"]
    # The valid checkpoint survives.
    assert latest_valid_manifest(d)[0]["step"] == 3


def test_publisher_refuses_ambiguous_duplicate_shards(tmp_path, capsys):
    """Two same-rank shards with different content in one checkpoint
    dir (a stale leftover colliding with a fresh write) must abandon
    the manifest — publishing would mix trajectories with every CRC
    valid."""
    import pickle

    d = str(tmp_path)
    ckdir = os.path.join(d, durable._ckpt_dirname(5, 0))
    os.makedirs(ckdir)
    stale = pickle.dumps({"stale": True})
    name = durable._shard_name(0, 1, durable.crc32c(stale), len(stale))
    with open(os.path.join(ckdir, name), "wb") as f:
        f.write(stale)

    state = make_state(1.0, 5)
    ck = DurableCheckpointer(d, rank=0, world_size=1)
    state.save()
    ck.maybe_enqueue(state._committed, 5)
    assert ck.flush(timeout=60)
    assert "ambiguous duplicate shard" in capsys.readouterr().err
    assert validate_manifest(ckdir) is None  # no manifest published


# ---------------------------------------------------------------------------
# Hygiene: tmp pruning + retention

def test_prune_stale_tmp(tmp_path):
    d = str(tmp_path)
    write_ckpt(d, step=1)
    ckpt_dir = list_checkpoints(d)[0][2]
    for name in ("shard-00001-of-00002.deadbeef.12.bin.tmp",
                 MANIFEST_NAME + ".tmp"):
        with open(os.path.join(ckpt_dir, name), "w") as f:
            f.write("partial")
    assert prune_stale_tmp(d) == 2
    assert not any(n.endswith(".tmp") for n in os.listdir(ckpt_dir))
    assert validate_manifest(ckpt_dir) is not None  # untouched


def test_retention_keeps_last_k_valid(tmp_path, monkeypatch):
    # High keep while writing (the publisher applies retention itself),
    # then tighten and apply.
    monkeypatch.setenv("HVD_TPU_CKPT_KEEP", "50")
    d = str(tmp_path)
    for step in range(6):
        write_ckpt(d, step=step, value=float(step))
    monkeypatch.setenv("HVD_TPU_CKPT_KEEP", "2")
    removed = apply_retention(d)
    steps = sorted(s for s, g, p in list_checkpoints(d))
    assert steps == [4, 5]
    assert len(removed) == 4
    # An abandoned invalid dir OLDER than the kept set is swept too.
    os.makedirs(os.path.join(d, "ckpt-%012d-g0" % 1))
    apply_retention(d)
    assert sorted(s for s, g, p in list_checkpoints(d)) == [4, 5]


def test_retention_runs_automatically_after_publish(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("HVD_TPU_CKPT_KEEP", "3")
    d = str(tmp_path)
    for step in range(5):
        write_ckpt(d, step=step)
    steps = sorted(s for s, g, p in list_checkpoints(d))
    assert steps == [2, 3, 4]  # publisher applied retention itself


def test_abandoned_publish_does_not_claim_durability(tmp_path, capsys):
    """Rank 0 whose manifest wait times out (a sibling shard never
    appeared) must NOT advance last_durable_step or the write counter —
    the step is unrestorable and the operator report must not name it
    as a recovery point."""
    d = str(tmp_path)
    state = make_state(1.0, 5)
    state.save()
    ck = DurableCheckpointer(d, rank=0, world_size=2,
                             publish_timeout=0.3)
    ck.maybe_enqueue(state._committed, 5)
    assert ck.flush(timeout=60)
    assert "abandoning manifest" in capsys.readouterr().err
    assert ck.last_durable_step == -1
    assert last_durable_step(d) == (None, None)


def test_last_durable_step_helper(tmp_path):
    d = str(tmp_path)
    assert last_durable_step(d) == (None, None)
    write_ckpt(d, step=11)
    step, path = last_durable_step(d)
    assert step == 11 and path is not None


# ---------------------------------------------------------------------------
# Fault-spec grammar

def test_fault_spec_parse_and_determinism():
    spec = ("seed=7;op=shard,prob=0.5,action=bitflip,count=-1;"
            "op=manifest,write=1,action=torn")
    a = CkptFaultInjector(spec, rank=1)
    b = CkptFaultInjector(spec, rank=1)
    seq_a = [a.on_write("shard")[0] for _ in range(32)]
    seq_b = [b.on_write("shard")[0] for _ in range(32)]
    assert seq_a == seq_b  # seeded: identical replay
    assert any(s == "bitflip" for s in seq_a)
    assert any(s is None for s in seq_a)
    # Different seed -> different sequence (32 coin flips: ~certain).
    c = CkptFaultInjector(spec.replace("seed=7", "seed=8"), rank=1)
    assert [c.on_write("shard")[0] for _ in range(32)] != seq_a
    # write= rules fire exactly at the Nth matching write, once.
    d = CkptFaultInjector(spec, rank=1)
    assert d.on_write("manifest") == (None, 0)
    assert d.on_write("manifest")[0] == "torn"
    assert d.on_write("manifest") == (None, 0)
    # rank filter: rules for rank 0 never fire on rank 1.
    e = CkptFaultInjector("rank=0,op=shard,write=0,action=torn", rank=1)
    assert e.on_write("shard") == (None, 0)


def test_fault_spec_rejects_garbage():
    for bad in ("op=shard,action=explode", "op=nope,action=torn",
                "op=shard", "op=shard,wat=1,action=torn"):
        with pytest.raises(ValueError):
            CkptFaultInjector(bad, rank=0)


# ---------------------------------------------------------------------------
# E2E: kill EVERYTHING, relaunch, resume bitwise-identically

COMMIT_LINE = re.compile(r"worker (\S+) commit step (\d+) crc ([0-9a-f]{8})")
START_LINE = re.compile(r"worker (\S+) start step (\d+) crc ([0-9a-f]{8}) "
                        r"size (\d+)")
DONE_LINE = re.compile(r"worker (\S+) done step (\d+) crc ([0-9a-f]{8})")


def _launch(ckpt_dir, np_, extra_env=None, extra_args=(), pid_dir=None,
            total=24, script="durable_worker.py"):
    from tests.conftest import clean_worker_env

    env = clean_worker_env(dict({
        "HVD_TPU_ELASTIC_COOLDOWN": "2",
        "HVD_TPU_ELASTIC_DISCOVERY_INTERVAL": "0.3",
        "HVD_TPU_START_TIMEOUT": "30",
        "DURABLE_TEST_TOTAL_STEPS": str(total),
        "DURABLE_TEST_STEP_SLEEP": "0.15",
    }, **(extra_env or {})))
    if pid_dir:
        env["DURABLE_TEST_PID_DIR"] = pid_dir
    cmd = [sys.executable, "-m", "horovod_tpu.run.run", "-np", str(np_),
           "--min-np", "1", "--ckpt-dir", ckpt_dir] + list(extra_args) + \
          ["--", sys.executable,
           os.path.join(REPO_ROOT, "tests", script)]
    return cmd, env


def _commit_crcs(out):
    """{step: crc} from a run's commit lines (identical across ranks —
    asserted)."""
    crcs = {}
    for wid, step, crc in COMMIT_LINE.findall(out):
        prev = crcs.setdefault(int(step), crc)
        assert prev == crc, ("ranks disagree at step %s: %s vs %s"
                             % (step, prev, crc))
    return crcs


@pytest.mark.e2e
def test_kill_everything_then_relaunch_resumes_bitwise(tmp_path):
    """SIGKILL every worker AND the driver mid-training; a relaunch
    must resume from the last durable commit with bitwise-identical
    state. Then the shrink variant: a second kill + relaunch at HALF
    the world size re-shards through rank-0-read + broadcast."""
    ckpt_dir = str(tmp_path / "ckpt")
    pid_dir = str(tmp_path / "pids")
    os.makedirs(pid_dir)

    # Run 1 gets a step budget it can never finish before the kill; the
    # relaunches run the normal 24 steps (the trajectory is identical
    # either way — total only bounds the loop).
    cmd, env = _launch(ckpt_dir, np_=2, pid_dir=pid_dir, total=200)
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    # Wait for a durable manifest covering a mid-training step.
    deadline = time.monotonic() + 120
    while True:
        manifest, _ = latest_valid_manifest(ckpt_dir)
        if manifest is not None and manifest["step"] >= 8:
            break
        assert proc.poll() is None, proc.communicate()
        assert time.monotonic() < deadline, "no durable manifest in 120s"
        time.sleep(0.1)

    # SIGKILL the driver (the launcher process group) and every worker
    # (their own sessions, via the pid files) — total job loss.
    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    for name in os.listdir(pid_dir):
        pid = int(open(os.path.join(pid_dir, name)).read())
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    out1, _ = proc.communicate(timeout=30)
    crcs1 = _commit_crcs(out1)
    assert crcs1, out1

    def relaunch_and_check(np_, prior_crcs):
        cmd, env = _launch(ckpt_dir, np_=np_)
        result = subprocess.run(cmd, env=env, timeout=240,
                                capture_output=True, text=True)
        out = result.stdout
        assert result.returncode == 0, (out, result.stderr)
        starts = [(int(s), crc, int(n))
                  for _, s, crc, n in START_LINE.findall(out)]
        resumed = [x for x in starts if x[0] > 0]
        assert resumed, ("relaunch did not resume from the durable "
                         "checkpoint", out)
        step0, crc0, size0 = resumed[0]
        assert size0 == np_
        # Bitwise-identical: the resumed state's CRC equals the CRC the
        # killed run printed when it committed that exact step.
        assert step0 in prior_crcs, (step0, sorted(prior_crcs))
        assert crc0 == prior_crcs[step0], "state corrupted across restart"
        done = DONE_LINE.findall(out)
        assert len(done) == np_ and all(int(s) == 24 for _, s, _ in done)
        return _commit_crcs(out)

    # Same-size relaunch resumes bitwise-identically...
    crcs2 = relaunch_and_check(2, crcs1)
    # ...then kill nothing further; third run at HALF the world size
    # must restore the checkpoints run 2 finished with (step 24) — the
    # saved world size (2) differs from the restoring one (1).
    crcs2.update(crcs1)
    relaunch_and_check(1, crcs2)


@pytest.mark.e2e
def test_sharded_update_kill_restore_half_and_double_world(tmp_path):
    """Sharded-update x durable (docs/ZERO.md acceptance): SIGKILL a
    2-rank sharded-update job mid-run, then resume it at HALF (1) and
    DOUBLE (4) the world size — the sharded Adam state rides the
    checkpoint in its world-independent full form and re-shards on
    restore, and the final parameters are BITWISE-identical to an
    uninterrupted 2-rank run's (the worker's gradient quantization
    makes the trajectory exactly world-size-independent)."""
    # Uninterrupted 2-rank reference run.
    ckpt_u = str(tmp_path / "ckpt_u")
    cmd, env = _launch(ckpt_u, np_=2, script="sharded_durable_worker.py",
                       extra_env={"DURABLE_TEST_STEP_SLEEP": "0.1"})
    ref = subprocess.run(cmd, env=env, timeout=240, capture_output=True,
                         text=True)
    assert ref.returncode == 0, (ref.stdout, ref.stderr)
    ref_crcs = _commit_crcs(ref.stdout)
    ref_done = DONE_LINE.findall(ref.stdout)
    assert len(ref_done) == 2 and all(int(s) == 24 for _, s, _ in ref_done)
    ref_final = ref_done[0][2]

    # Killed run: same trajectory, SIGKILLed once a mid-run manifest
    # exists.
    ckpt = str(tmp_path / "ckpt")
    pid_dir = str(tmp_path / "pids")
    os.makedirs(pid_dir)
    cmd, env = _launch(ckpt, np_=2, script="sharded_durable_worker.py",
                       pid_dir=pid_dir, total=200,
                       extra_env={"DURABLE_TEST_STEP_SLEEP": "0.1"})
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    deadline = time.monotonic() + 120
    while True:
        manifest, _ = latest_valid_manifest(ckpt)
        if manifest is not None and manifest["step"] >= 6:
            break
        assert proc.poll() is None, proc.communicate()
        assert time.monotonic() < deadline, "no durable manifest in 120s"
        time.sleep(0.1)
    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    for name in os.listdir(pid_dir):
        pid = int(open(os.path.join(pid_dir, name)).read())
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    out1, _ = proc.communicate(timeout=30)
    crcs1 = _commit_crcs(out1)
    assert crcs1, out1
    # The killed run's commits match the uninterrupted run's bitwise.
    for step, crc in crcs1.items():
        assert ref_crcs.get(step) == crc, (step, crc, ref_crcs.get(step))

    def resume(np_, total, prior_crcs):
        cmd, env = _launch(ckpt, np_=np_, total=total,
                           script="sharded_durable_worker.py",
                           extra_env={"DURABLE_TEST_STEP_SLEEP": "0.1"})
        result = subprocess.run(cmd, env=env, timeout=240,
                                capture_output=True, text=True)
        assert result.returncode == 0, (result.stdout, result.stderr)
        starts = [(int(s), crc, int(n))
                  for _, s, crc, n in START_LINE.findall(result.stdout)]
        resumed = [x for x in starts if x[0] > 0]
        assert resumed, ("no resume from the durable checkpoint",
                         result.stdout)
        step0, crc0, size0 = resumed[0]
        assert size0 == np_
        # Bitwise resume: params + re-shardable full Adam state.
        assert step0 in prior_crcs, (step0, sorted(prior_crcs))
        assert crc0 == prior_crcs[step0], \
            "sharded state corrupted across restart"
        done = DONE_LINE.findall(result.stdout)
        assert len(done) == np_ and all(int(s) == total
                                        for _, s, _ in done)
        return _commit_crcs(result.stdout), done[0][2]

    # HALF the world size (1): finishes step 16 on the reference
    # trajectory bitwise.
    half_crcs, _ = resume(1, 16, crcs1)
    for step, crc in half_crcs.items():
        assert ref_crcs.get(step) == crc, (step, crc)
    # DOUBLE the world size (4): resumes the 1-rank run's step-16
    # state, trains 8 more steps, and lands on the uninterrupted run's
    # final CRC exactly.
    all_crcs = dict(crcs1)
    all_crcs.update(half_crcs)
    _, final = resume(4, 24, all_crcs)
    assert final == ref_final, (final, ref_final)


@pytest.mark.e2e
def test_chaos_storage_faults_never_crash_and_restore_skips_invalid(
        tmp_path):
    """Acceptance: with torn writes and bit flips injected across the
    run, training completes (storage faults degrade, never kill), and a
    relaunch restores the newest CRC-valid manifest — proven by
    corrupting the newest valid checkpoint post-hoc and watching the
    resume land one valid checkpoint earlier."""
    ckpt_dir = str(tmp_path / "ckpt")
    spec = ("seed=3;op=shard,prob=0.25,action=bitflip,count=-1;"
            "op=manifest,prob=0.2,action=torn,count=-1;"
            "op=shard,prob=0.1,action=slowfsync,delay_ms=200,count=-1")
    cmd, env = _launch(ckpt_dir, np_=2,
                       extra_env={"HVD_TPU_CKPT_FAULT_SPEC": spec,
                                  "HVD_TPU_CKPT_KEEP": "50"})
    result = subprocess.run(cmd, env=env, timeout=240,
                            capture_output=True, text=True)
    assert result.returncode == 0, (result.stdout, result.stderr)
    crcs1 = _commit_crcs(result.stdout)
    done = DONE_LINE.findall(result.stdout)
    assert len(done) == 2, result.stdout

    # The faults fired: with p=0.25 per shard over ~12 checkpoints the
    # run must contain at least one invalid checkpoint directory.
    entries = list_checkpoints(ckpt_dir)
    validity = {p: validate_manifest(p) is not None
                for _, _, p in entries}
    assert any(not ok for ok in validity.values()), \
        "fault injection produced no invalid checkpoint — spec inert?"
    manifest, best = latest_valid_manifest(ckpt_dir)
    assert manifest is not None
    # Invariant: everything newer than the chosen manifest is invalid.
    for step, gen, path in entries:
        if (step, gen) > (manifest["step"], manifest["generation"]):
            assert not validity[path]

    # Corrupt the newest VALID one too; the restore must fall back to
    # the next-older valid manifest, never touch the corrupt ones.
    shard = [n for n in os.listdir(best) if n.startswith("shard-")][0]
    spath = os.path.join(best, shard)
    data = bytearray(open(spath, "rb").read())
    data[0] ^= 0xFF
    with open(spath, "wb") as f:
        f.write(bytes(data))
    manifest2, best2 = latest_valid_manifest(ckpt_dir)
    assert manifest2 is not None and best2 != best
    assert manifest2["step"] <= manifest["step"]

    cmd, env = _launch(ckpt_dir, np_=2)
    result2 = subprocess.run(cmd, env=env, timeout=240,
                             capture_output=True, text=True)
    assert result2.returncode == 0, (result2.stdout, result2.stderr)
    starts = [(int(s), crc) for _, s, crc, _ in
              START_LINE.findall(result2.stdout)]
    resumed = [x for x in starts if x[0] > 0]
    assert resumed, result2.stdout
    step0, crc0 = resumed[0]
    assert step0 == manifest2["step"]
    assert crcs1.get(step0) == crc0


@pytest.mark.e2e
def test_driver_restart_from_ckpt_below_min_np(tmp_path):
    """--restart-from-ckpt: both workers die in generation 0, the world
    cannot reach --min-np=2 (host blacklisted), and instead of tearing
    down the driver performs a full-job restart whose fresh cohort
    auto-resumes from the last durable commit and finishes."""
    ckpt_dir = str(tmp_path / "ckpt")
    cmd, env = _launch(
        ckpt_dir, np_=2,
        extra_env={"DURABLE_TEST_CRASH_STEP": "7",
                   "DURABLE_TEST_CRASH_WIDS": "0,1",
                   # Long cooldown: the blacklisted host cannot return
                   # on its own, so only the restart path can save the
                   # job.
                   "HVD_TPU_ELASTIC_COOLDOWN": "600",
                   "HVD_TPU_START_TIMEOUT": "15"})
    cmd = cmd[:cmd.index("--")] + ["--min-np", "2",
                                   "--restart-from-ckpt"] + \
        cmd[cmd.index("--"):]
    # The worker command's --min-np 1 from _launch is overridden by the
    # later --min-np 2 (argparse keeps the last occurrence).
    t0 = time.monotonic()
    result = subprocess.run(cmd, env=env, timeout=240,
                            capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    out, err = result.stdout, result.stderr
    assert result.returncode == 0, (out, err)
    assert out.count("crashing now") == 2, out
    assert "full-job restart 1/" in err, err
    crcs = _commit_crcs(out)
    starts = [(int(s), crc) for _, s, crc, _ in START_LINE.findall(out)]
    resumed = [x for x in starts if x[0] > 0]
    assert resumed, out
    step0, crc0 = resumed[0]
    # Crash at step 7, commits every 2: the restart resumes from the
    # step-6 durable commit, bitwise-identical.
    assert step0 == 6
    assert crcs[6] == crc0
    done = DONE_LINE.findall(out)
    assert len(done) == 2 and all(int(s) == 24 for _, s, _ in done)
    assert elapsed < 180, "restart recovery took %.0fs" % elapsed


@pytest.mark.e2e
def test_launcher_failure_summary_names_last_durable_step(tmp_path):
    """The static launcher's failure summary reports what a restart
    would recover when --ckpt-dir is set."""
    from tests.conftest import clean_worker_env

    ckpt_dir = str(tmp_path / "ckpt")
    write_ckpt(ckpt_dir, step=12)
    env = clean_worker_env()
    env["HVD_TPU_CKPT_DIR"] = ckpt_dir
    result = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "1", "--",
         sys.executable, "-c", "import sys; sys.exit(3)"],
        env=env, timeout=120, capture_output=True, text=True)
    assert result.returncode != 0
    assert "last durable checkpoint: step 12" in result.stderr, \
        result.stderr
