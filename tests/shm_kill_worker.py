"""Peer-death-mid-shm-hop worker (tests/test_shm.py): rank 1 SIGKILLs
itself in the middle of a stream of large allreduces (no orderly close —
the shm ring's closed flag is never set), and rank 0 must surface a
prompt recoverable CONNECTION_LOST instead of hanging: the liveness
probe on the shm leg's TCP socket (EOF) or the transport deadline is
what catches it."""

import os
import signal
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.ops import HorovodInternalError


def main():
    hvd.init()
    r = hvd.rank()
    x = np.ones(1 << 20, np.float32)
    try:
        for i in range(200):
            if r == 1 and i == 5:
                os.kill(os.getpid(), signal.SIGKILL)
            ops.allreduce(x, "kill.%d" % i)
    except HorovodInternalError as e:
        print("CONNLOST %s" % str(e)[:160], flush=True)
        return 7
    print("rank %d finished without peer loss" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
