"""Fused Pallas BatchNorm correctness, pinned against flax BatchNorm
(interpret mode on CPU; the kernels themselves run on v5e via
`bench.py --model resnet50pbn`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.batch_norm import (LeanBatchNorm, PallasBatchNorm,
                                        batch_norm_stats,
                                        batch_norm_grad_stats,
                                        bn_remat_policy,
                                        fused_batch_norm_train,
                                        lean_batch_norm_train)

jax.config.update("jax_default_matmul_precision", "highest")


def test_stats_kernel_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(512, 192).astype(np.float32)
    s, ss = batch_norm_stats(jnp.asarray(x), interpret=True)
    np.testing.assert_allclose(np.asarray(s), x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ss), (x * x).sum(0), rtol=1e-5)


def test_stats_kernel_bf16_read_f32_accumulate():
    rng = np.random.RandomState(1)
    x = rng.randn(2048, 128).astype(np.float32)
    xb = jnp.asarray(x).astype(jnp.bfloat16)
    s, ss = batch_norm_stats(xb, interpret=True)
    assert s.dtype == jnp.float32
    # Accumulation error must be f32-like (bf16 inputs, not bf16 sums).
    ref = np.asarray(xb.astype(jnp.float32)).sum(0)
    np.testing.assert_allclose(np.asarray(s), ref, rtol=1e-5, atol=1e-3)


def test_grad_stats_kernel_matches_numpy():
    rng = np.random.RandomState(2)
    x = rng.randn(256, 64).astype(np.float32)
    dy = rng.randn(256, 64).astype(np.float32)
    mean = x.mean(0)
    rstd = 1.0 / np.sqrt(x.var(0) + 1e-5)
    dbeta, dgamma = batch_norm_grad_stats(
        jnp.asarray(dy), jnp.asarray(x), jnp.asarray(mean),
        jnp.asarray(rstd), interpret=True)
    xhat = (x - mean) * rstd
    np.testing.assert_allclose(np.asarray(dbeta), dy.sum(0), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dgamma), (dy * xhat).sum(0),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("M,C", [(512, 128), (392, 64)])
def test_fused_bn_train_matches_flax(M, C):
    """Forward outputs, batch stats, AND gradients (x, gamma, beta)
    must match flax.linen.BatchNorm in training mode. M=392 = 8*49
    exercises the small-power-of-two block path."""
    import flax.linen as nn

    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(M, C).astype(np.float32)) * 2.0 + 0.5
    gamma = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(C).astype(np.float32))

    bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                      epsilon=1e-5)
    variables = {"params": {"scale": gamma, "bias": beta},
                 "batch_stats": {"mean": jnp.zeros(C),
                                 "var": jnp.ones(C)}}

    def flax_loss(x, gamma, beta):
        v = {"params": {"scale": gamma, "bias": beta},
             "batch_stats": variables["batch_stats"]}
        y, _ = bn.apply(v, x, mutable=["batch_stats"])
        return jnp.sum(y ** 2), y

    def fused_loss(x, gamma, beta):
        y, mean, var = fused_batch_norm_train(x, gamma, beta, 1e-5, True)
        return jnp.sum(y.astype(jnp.float32) ** 2), (y, mean, var)

    (l1, y1), g1 = jax.value_and_grad(flax_loss, argnums=(0, 1, 2),
                                      has_aux=True)(x, gamma, beta)
    (l2, (y2, mean, var)), g2 = jax.value_and_grad(
        fused_loss, argnums=(0, 1, 2), has_aux=True)(x, gamma, beta)

    np.testing.assert_allclose(np.asarray(y2), np.asarray(y1),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(x).mean(0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(var),
                               np.asarray(x).var(0), rtol=1e-4, atol=1e-4)
    for a, b, nm in zip(g2, g1, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=nm)


def test_pallas_bn_module_train_eval_roundtrip():
    """The flax module: training updates running stats like
    nn.BatchNorm; eval mode uses them identically."""
    import flax.linen as nn

    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 8, 8, 32).astype(np.float32))

    ours_t = PallasBatchNorm(use_running_average=False, momentum=0.9,
                             epsilon=1e-5, interpret=True)
    flax_t = nn.BatchNorm(use_running_average=False, momentum=0.9,
                          epsilon=1e-5)
    v0 = flax_t.init(jax.random.PRNGKey(0), x)
    y_f, upd_f = flax_t.apply(v0, x, mutable=["batch_stats"])
    y_o, upd_o = ours_t.apply(v0, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_o), np.asarray(y_f),
                               rtol=2e-4, atol=2e-4)
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(upd_o["batch_stats"][k]),
            np.asarray(upd_f["batch_stats"][k]), rtol=1e-4, atol=1e-5)

    ours_e = PallasBatchNorm(use_running_average=True, epsilon=1e-5)
    flax_e = nn.BatchNorm(use_running_average=True, epsilon=1e-5)
    v1 = {"params": v0["params"], "batch_stats": upd_f["batch_stats"]}
    np.testing.assert_allclose(
        np.asarray(ours_e.apply(v1, x)),
        np.asarray(flax_e.apply(v1, x)), rtol=2e-4, atol=2e-4)


def test_sync_bn_matches_global_batch():
    """axis_name sync BN over a 4-way sharded batch must equal plain BN
    over the concatenated batch under the canonical DP loss contract
    (each shard computes a LOCAL loss; total = implicit sum over
    shards; param grads are per-shard contributions the gradient
    allreduce completes): outputs, batch stats, dx per shard, and
    summed dgamma/dbeta must all match the global-batch run. No
    explicit loss psum — under check_vma=False its transpose is
    another psum, which would scale every cotangent by n."""
    from jax.sharding import Mesh, PartitionSpec as P

    n, M, C = 4, 64, 32
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(n * M, C).astype(np.float32)) * 1.5 + 0.3
    # Random linear loss weights: sum(y*w) has a non-degenerate dx
    # (sum(y^2)'s dx is ~1e-5 — BN outputs are nearly invariant to
    # input perturbations — and would vacuously pass any atol).
    w = jnp.asarray(rng.randn(n * M, C).astype(np.float32))
    gamma = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(C).astype(np.float32))
    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("dp",))

    def global_loss(x, gamma, beta):
        y, mean, var = fused_batch_norm_train(x, gamma, beta, 1e-5, True)
        return jnp.sum(y * w), (mean, var)

    def sharded_loss(xs, gamma, beta, ws):
        y, mean, var = fused_batch_norm_train(
            xs, gamma, beta, 1e-5, True, "dp")
        return jnp.sum(y * ws), (mean, var)

    (l_g, (mean_g, var_g)), g_g = jax.value_and_grad(
        global_loss, argnums=(0, 1, 2), has_aux=True)(x, gamma, beta)

    fwd = jax.jit(jax.shard_map(
        lambda xs, gamma, beta: fused_batch_norm_train(
            xs, gamma, beta, 1e-5, True, "dp"),
        mesh=mesh, in_specs=(P("dp"), P(), P()),
        out_specs=(P("dp"), P(None), P(None)), check_vma=False))
    y_s, mean_s, var_s = fwd(x, gamma, beta)

    grad = jax.jit(jax.shard_map(
        jax.grad(lambda *a: sharded_loss(*a)[0], argnums=(0, 1, 2)),
        mesh=mesh, in_specs=(P("dp"), P(), P(), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp")), check_vma=False))
    dx_s, dgamma_s, dbeta_s = grad(x, gamma, beta, w)

    np.testing.assert_allclose(float(jnp.sum(y_s * w)), float(l_g),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(mean_s), np.asarray(mean_g),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(var_s), np.asarray(var_g),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx_s), np.asarray(g_g[0]),
                               rtol=1e-4, atol=1e-5)
    # Per-shard param-grad contributions; their sum (the gradient
    # allreduce) equals the global-batch parameter gradient.
    np.testing.assert_allclose(
        np.asarray(dgamma_s).reshape(n, C).sum(0), np.asarray(g_g[1]),
        rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(dbeta_s).reshape(n, C).sum(0), np.asarray(g_g[2]),
        rtol=1e-4, atol=1e-4)


def test_resnet_sync_bn_wiring():
    """ResNet(bn_axis_name='dp'): training forward over a 4-way
    sharded batch produces the same outputs and running-stat updates
    as the unsharded model (sync BN sees the global batch either
    way). Covers the model-level wiring of both norm paths' axis_name
    plumb-through (the pallas module falls back to XLA stats off-TPU
    but keeps the psum)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.models.resnet import ResNet, BottleneckBlock

    n = 4
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, 16, 16, 3).astype(np.float32))
    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("dp",))

    def build(axis):
        return ResNet(stage_sizes=[1], block_cls=BottleneckBlock,
                      num_classes=5, num_filters=8, dtype=jnp.float32,
                      norm="pallas", bn_axis_name=axis)

    variables = build(None).init(jax.random.PRNGKey(0), x, train=False)
    y_ref, upd_ref = build(None).apply(
        variables, x, train=True, mutable=["batch_stats"])

    model = build("dp")

    def shard_fwd(xs):
        y, upd = model.apply(variables, xs, train=True,
                             mutable=["batch_stats"])
        return y, upd["batch_stats"]

    f = jax.jit(jax.shard_map(
        shard_fwd, mesh=mesh, in_specs=(P("dp"),),
        out_specs=(P("dp"), P(None)), check_vma=False))
    y_s, stats_s = f(x)

    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-4)
    ref_stats = upd_ref["batch_stats"]
    flat_s = jax.tree_util.tree_leaves_with_path(stats_s)
    flat_r = dict(jax.tree_util.tree_leaves_with_path(ref_stats))
    assert flat_s
    for path, leaf in flat_s:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_r[path]),
            rtol=1e-4, atol=1e-5, err_msg=str(path))


def test_resnet_pallas_variant_one_step():
    """ResNet50PBN: one train step runs, loss finite, batch_stats
    update present (CPU falls back to the plain-XLA stats path via the
    same fused_batch_norm_train custom-VJP)."""
    from horovod_tpu.models import ResNet50PBN

    model = ResNet50PBN(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)

    def loss_fn(params):
        logits, upd = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean(logits ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)


def test_inception_pallas_variant_one_step():
    """InceptionV3 with norm='pallas' (the zoo's most BN-bound model):
    one train step, finite loss and grads."""
    from horovod_tpu.models import InceptionV3

    model = InceptionV3(norm="pallas", num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 96, 96, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)

    def loss_fn(params):
        logits, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(1)})
        return jnp.mean(logits ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree_util.tree_leaves(grads))


# --- round 10: the traffic-lean graph-level BN -----------------------------

@pytest.mark.parametrize("shape", [(512, 128), (392, 64), (96, 12),
                                   (6, 5, 7, 13)])
def test_lean_bn_matches_flax(shape):
    """Outputs, batch stats, and all three gradients of the lean
    custom-VJP path vs flax.linen.BatchNorm, 2-D and 4-D, odd shapes
    included (no power-of-two or lane constraints — the lean path is
    pure XLA)."""
    import flax.linen as nn

    C = shape[-1]
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32)) * 2.0 + 0.5
    gamma = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(C).astype(np.float32))
    w = jnp.asarray(rng.randn(*shape).astype(np.float32))

    bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                      epsilon=1e-5)
    stats0 = {"mean": jnp.zeros(C), "var": jnp.ones(C)}

    def flax_loss(x, gamma, beta):
        v = {"params": {"scale": gamma, "bias": beta},
             "batch_stats": stats0}
        y, _ = bn.apply(v, x, mutable=["batch_stats"])
        return jnp.sum(y * w), y

    def lean_loss(x, gamma, beta):
        y, mean, var = lean_batch_norm_train(x, gamma, beta, 1e-5)
        return jnp.sum(y * w), (y, mean, var)

    (l1, y1), g1 = jax.value_and_grad(flax_loss, argnums=(0, 1, 2),
                                      has_aux=True)(x, gamma, beta)
    (l2, (y2, mean, var)), g2 = jax.value_and_grad(
        lean_loss, argnums=(0, 1, 2), has_aux=True)(x, gamma, beta)

    np.testing.assert_allclose(np.asarray(y2), np.asarray(y1),
                               rtol=2e-4, atol=2e-4)
    flat = np.asarray(x).reshape(-1, C)
    np.testing.assert_allclose(np.asarray(mean), flat.mean(0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(var), flat.var(0),
                               rtol=1e-4, atol=1e-5)
    for a, b, nm in zip(g2, g1, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=nm)


def test_lean_bn_fused_relu_matches_flax_plus_relu():
    """relu=True: y = max(bn(x), 0) with the backward mask recomputed
    from the pre-activation sign (never stored) must equal
    relu(flax_bn(x)) in value AND all three gradients."""
    import flax.linen as nn

    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(8, 6, 6, 24).astype(np.float32))
    C = x.shape[-1]
    gamma = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(C).astype(np.float32))
    w = jnp.asarray(rng.randn(*x.shape).astype(np.float32))
    bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                      epsilon=1e-5)
    stats0 = {"mean": jnp.zeros(C), "var": jnp.ones(C)}

    def flax_loss(x, gamma, beta):
        v = {"params": {"scale": gamma, "bias": beta},
             "batch_stats": stats0}
        y, _ = bn.apply(v, x, mutable=["batch_stats"])
        return jnp.sum(jax.nn.relu(y) * w)

    def lean_loss(x, gamma, beta):
        y, _, _ = lean_batch_norm_train(x, gamma, beta, 1e-5, True)
        return jnp.sum(y * w)

    l1, g1 = jax.value_and_grad(flax_loss, argnums=(0, 1, 2))(
        x, gamma, beta)
    l2, g2 = jax.value_and_grad(lean_loss, argnums=(0, 1, 2))(
        x, gamma, beta)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-5)
    for a, b, nm in zip(g2, g1, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=nm)


def test_lean_ghost_bn_matches_per_group_flax():
    """groups=G (ghost BN): each virtual batch normalized independently
    must equal flax BN applied per slice — values, (G, C) stats, and
    gradients (dgamma/dbeta summed over groups)."""
    import flax.linen as nn

    G, M, C = 4, 32, 12
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(M, 5, C).astype(np.float32)) * 1.5
    gamma = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(C).astype(np.float32))
    w = jnp.asarray(rng.randn(*x.shape).astype(np.float32))
    bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                      epsilon=1e-5)
    stats0 = {"mean": jnp.zeros(C), "var": jnp.ones(C)}

    def ref_loss(x, gamma, beta):
        v = {"params": {"scale": gamma, "bias": beta},
             "batch_stats": stats0}
        ys = []
        for i in range(G):
            y, _ = bn.apply(v, x[i * (M // G):(i + 1) * (M // G)],
                            mutable=["batch_stats"])
            ys.append(y)
        return jnp.sum(jnp.concatenate(ys) * w)

    def ghost_loss(x, gamma, beta):
        y, mean, var = lean_batch_norm_train(x, gamma, beta, 1e-5,
                                             False, G)
        return jnp.sum(y * w), (mean, var)

    l1, g1 = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        x, gamma, beta)
    (l2, (mean, var)), g2 = jax.value_and_grad(
        lambda *a: ghost_loss(*a), argnums=(0, 1, 2),
        has_aux=True)(x, gamma, beta)
    assert mean.shape == (G, C) and var.shape == (G, C)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-5)
    for i in range(G):
        sl = np.asarray(x)[i * (M // G):(i + 1) * (M // G)].reshape(-1, C)
        np.testing.assert_allclose(np.asarray(mean)[i], sl.mean(0),
                                   rtol=1e-5, atol=1e-6)
    for a, b, nm in zip(g2, g1, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=nm)


def test_lean_module_train_eval_roundtrip_and_ghost():
    """LeanBatchNorm: training updates running stats like nn.BatchNorm
    (same variables dict — param names match), eval mode uses them
    identically, fuse_relu eval clamps, and virtual_batch_size updates
    running stats with the mean of the group statistics."""
    import flax.linen as nn

    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, 4, 4, 16).astype(np.float32))

    ours_t = LeanBatchNorm(momentum=0.9, epsilon=1e-5)
    flax_t = nn.BatchNorm(use_running_average=False, momentum=0.9,
                          epsilon=1e-5)
    v0 = flax_t.init(jax.random.PRNGKey(0), x)
    y_f, upd_f = flax_t.apply(v0, x, mutable=["batch_stats"])
    y_o, upd_o = ours_t.apply(v0, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_o), np.asarray(y_f),
                               rtol=2e-4, atol=2e-4)
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(upd_o["batch_stats"][k]),
            np.asarray(upd_f["batch_stats"][k]), rtol=1e-4, atol=1e-5)

    ours_e = LeanBatchNorm(use_running_average=True, epsilon=1e-5)
    flax_e = nn.BatchNorm(use_running_average=True, epsilon=1e-5)
    v1 = {"params": v0["params"], "batch_stats": upd_f["batch_stats"]}
    np.testing.assert_allclose(
        np.asarray(ours_e.apply(v1, x)),
        np.asarray(flax_e.apply(v1, x)), rtol=2e-4, atol=2e-4)
    # fuse_relu in eval mode clamps exactly like a separate relu.
    np.testing.assert_allclose(
        np.asarray(LeanBatchNorm(use_running_average=True,
                                 fuse_relu=True).apply(v1, x)),
        np.asarray(jax.nn.relu(flax_e.apply(v1, x))),
        rtol=2e-4, atol=2e-4)

    # Ghost running stats: mean over the per-group statistics.
    ghost = LeanBatchNorm(momentum=0.9, virtual_batch_size=2)
    _, upd_g = ghost.apply(v0, x, mutable=["batch_stats"])
    flat = np.asarray(x)
    means = np.stack([flat[i * 2:(i + 1) * 2].reshape(-1, 16).mean(0)
                      for i in range(4)])
    np.testing.assert_allclose(
        np.asarray(upd_g["batch_stats"]["mean"]),
        0.9 * 0.0 + 0.1 * means.mean(0), rtol=1e-4, atol=1e-5)

    # virtual_batch_size must divide the batch.
    with pytest.raises(ValueError):
        LeanBatchNorm(virtual_batch_size=3).apply(
            v0, x, mutable=["batch_stats"])


def test_lean_bn_remat_policy_grads_match():
    """bn_remat_policy: gradients through jax.checkpoint with the
    BN-scoped policy (normalize outputs recomputed, everything else
    saved) match the un-remat'd gradients exactly."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(6, 4, 4, 8).astype(np.float32))
    mod = LeanBatchNorm(momentum=0.9)
    import flax.linen as nn
    v0 = nn.BatchNorm(use_running_average=False).init(
        jax.random.PRNGKey(0), x)

    def f(x):
        y, _ = mod.apply(v0, x, mutable=["batch_stats"])
        return jnp.sum(y ** 2)

    g_plain = jax.grad(f)(x)
    g_remat = jax.grad(jax.checkpoint(f, policy=bn_remat_policy()))(x)
    np.testing.assert_allclose(np.asarray(g_remat), np.asarray(g_plain),
                               rtol=1e-5, atol=1e-6)


def test_lean_sync_bn_matches_global_batch():
    """axis_name (in-jit) sync for the lean path over a 4-way sharded
    batch equals plain lean BN over the concatenated batch under the
    canonical DP loss contract (cf. test_sync_bn_matches_global_batch
    for the Pallas path)."""
    from jax.sharding import Mesh, PartitionSpec as P

    n, M, C = 4, 64, 32
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(n * M, C).astype(np.float32)) * 1.5 + 0.3
    w = jnp.asarray(rng.randn(n * M, C).astype(np.float32))
    gamma = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(C).astype(np.float32))
    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("dp",))

    def global_loss(x, gamma, beta):
        y, mean, var = lean_batch_norm_train(x, gamma, beta, 1e-5)
        return jnp.sum(y * w), (mean, var)

    (l_g, (mean_g, var_g)), g_g = jax.value_and_grad(
        global_loss, argnums=(0, 1, 2), has_aux=True)(x, gamma, beta)

    def sharded_loss(xs, gamma, beta, ws):
        y, mean, var = lean_batch_norm_train(
            xs, gamma, beta, 1e-5, False, 1, "dp")
        return jnp.sum(y * ws)

    fwd = jax.jit(jax.shard_map(
        lambda xs, gamma, beta: lean_batch_norm_train(
            xs, gamma, beta, 1e-5, False, 1, "dp"),
        mesh=mesh, in_specs=(P("dp"), P(), P()),
        out_specs=(P("dp"), P(None), P(None)), check_vma=False))
    y_s, mean_s, var_s = fwd(x, gamma, beta)

    grad = jax.jit(jax.shard_map(
        jax.grad(sharded_loss, argnums=(0, 1, 2)),
        mesh=mesh, in_specs=(P("dp"), P(), P(), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp")), check_vma=False))
    dx_s, dgamma_s, dbeta_s = grad(x, gamma, beta, w)

    np.testing.assert_allclose(np.asarray(mean_s), np.asarray(mean_g),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(var_s), np.asarray(var_g),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(jnp.sum(y_s * w)), float(l_g),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dx_s), np.asarray(g_g[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(dgamma_s).reshape(n, C).sum(0), np.asarray(g_g[1]),
        rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(dbeta_s).reshape(n, C).sum(0), np.asarray(g_g[2]),
        rtol=1e-4, atol=1e-4)


def _rename_bn(tree, a="BatchNorm", b="LeanBatchNorm"):
    if isinstance(tree, dict):
        return {k.replace(a, b) if k.startswith(a) else k:
                _rename_bn(v, a, b) for k, v in tree.items()}
    return tree


def test_lean_resnet_matches_stock_resnet():
    """ResNet(norm='lean') with flax-BN params transplanted (module
    class names differ; structure and call order do not) produces the
    same outputs, running-stat updates, and parameter gradients as the
    stock norm='batch' model — the model-level wiring proof, fused
    norm+relu pairs included."""
    from horovod_tpu.models.resnet import ResNet, BottleneckBlock

    def build(norm):
        return ResNet(stage_sizes=[1], block_cls=BottleneckBlock,
                      num_classes=5, num_filters=8, dtype=jnp.float32,
                      norm=norm)

    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randn(4, 16, 16, 3).astype(np.float32))
    v_b = build("batch").init(jax.random.PRNGKey(0), x, train=False)
    v_l = {"params": _rename_bn(v_b["params"]),
           "batch_stats": _rename_bn(v_b["batch_stats"])}
    v_l_check = build("lean").init(jax.random.PRNGKey(0), x, train=False)
    assert jax.tree_util.tree_structure(v_l["params"]) == \
        jax.tree_util.tree_structure(v_l_check["params"])

    y_b, upd_b = build("batch").apply(v_b, x, train=True,
                                      mutable=["batch_stats"])
    y_l, upd_l = build("lean").apply(v_l, x, train=True,
                                     mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y_l), np.asarray(y_b),
                               rtol=2e-4, atol=2e-4)
    stats_l = dict(jax.tree_util.tree_leaves_with_path(
        _rename_bn(upd_l["batch_stats"], "LeanBatchNorm", "BatchNorm")))
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            upd_b["batch_stats"]):
        np.testing.assert_allclose(np.asarray(stats_l[path]),
                                   np.asarray(leaf), rtol=1e-4,
                                   atol=1e-5, err_msg=str(path))

    def loss(model, variables, params):
        vv = {"params": params, "batch_stats": variables["batch_stats"]}
        y, _ = model.apply(vv, x, train=True, mutable=["batch_stats"])
        return jnp.sum(y ** 2)

    g_b = jax.grad(lambda p: loss(build("batch"), v_b, p))(v_b["params"])
    g_l = jax.grad(lambda p: loss(build("lean"), v_l, p))(v_l["params"])
    g_l_cmp = dict(jax.tree_util.tree_leaves_with_path(
        _rename_bn(g_l, "LeanBatchNorm", "BatchNorm")))
    for path, leaf in jax.tree_util.tree_leaves_with_path(g_b):
        np.testing.assert_allclose(np.asarray(g_l_cmp[path]),
                                   np.asarray(leaf), rtol=5e-3,
                                   atol=5e-3, err_msg=str(path))


def test_resnet_lean_variant_one_step():
    """ResNet50Lean end to end: one train step, finite loss and grads
    (the zoo variant bench.py measures as resnet50lean)."""
    from horovod_tpu.models import ResNet50Lean

    model = ResNet50Lean(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)

    def loss_fn(params):
        logits, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return jnp.mean(logits ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree_util.tree_leaves(grads))


@pytest.mark.e2e
def test_sync_bn_host_plane_2rank_bitwise(run_launcher):
    """2-rank e2e: lean BN with host-collective stats sync (plain jit,
    ordered io_callback plane). Stats equal the global batch AND are
    bitwise rank-identical; the backward's dx matches the global-batch
    reference."""
    result = run_launcher(2, "bn_sync_worker.py",
                          extra_env={"JAX_PLATFORMS": "cpu",
                                     "BN_SYNC_MODE": "world"},
                          timeout=600)
    assert result.returncode == 0, result.stdout + result.stderr
    for marker in ("PASS world_stats_global_and_bitwise",
                   "PASS world_backward_global_dx",
                   "PASS bn_sync_worker_done"):
        assert marker in result.stdout, (marker, result.stdout)


@pytest.mark.e2e
def test_sync_bn_group_scoped_2x2_mesh(run_launcher):
    """4-rank e2e under hvd.init(model_parallel=2): sync BN scoped to
    the batch group of the 2-D mesh (docs/GROUPS.md composition). Stats
    are bitwise identical WITHIN each batch group, equal that group's
    global batch, and DIFFER across groups."""
    result = run_launcher(4, "bn_sync_worker.py",
                          extra_env={"JAX_PLATFORMS": "cpu",
                                     "BN_SYNC_MODE": "mesh"},
                          timeout=600)
    assert result.returncode == 0, result.stdout + result.stderr
    for marker in ("PASS mesh_group_scoped_sync_bn",
                   "PASS bn_sync_worker_done"):
        assert marker in result.stdout, (marker, result.stdout)


def test_sync_batch_norm_stats_wrapper():
    """hvd.jax.sync_batch_norm_stats: the jax-wrapper plumbing under
    sync BN — partial (sum, sumsq) in, (mean, var, global_count) out.
    Single-process world: the host allreduce is identity, so the
    result must equal the local statistics exactly."""
    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax

    hvd.init()
    rng = np.random.RandomState(11)
    x = rng.randn(64, 8).astype(np.float32)
    s = jnp.asarray(x.sum(0))
    ss = jnp.asarray((x * x).sum(0))
    mean, var, count = hvd_jax.sync_batch_norm_stats(s, ss, x.shape[0],
                                                     name="t_sync_bn")
    assert count == x.shape[0] * hvd.size()
    np.testing.assert_allclose(np.asarray(mean), x.mean(0), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(var), x.var(0), rtol=1e-4,
                               atol=1e-5)


def test_pallas_ghost_bn_degenerate_single_group():
    """PallasBatchNorm(virtual_batch_size == batch): one ghost group is
    plain BN — running stats must stay (C,)-shaped and match flax (a
    groups==1 path once collapsed them to a cross-channel scalar)."""
    import flax.linen as nn

    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randn(4, 4, 4, 16).astype(np.float32))
    flax_t = nn.BatchNorm(use_running_average=False, momentum=0.9,
                          epsilon=1e-5)
    v0 = flax_t.init(jax.random.PRNGKey(0), x)
    _, upd_f = flax_t.apply(v0, x, mutable=["batch_stats"])
    mod = PallasBatchNorm(use_running_average=False, momentum=0.9,
                          epsilon=1e-5, virtual_batch_size=4,
                          interpret=True)
    _, upd_o = mod.apply(v0, x, mutable=["batch_stats"])
    for k in ("mean", "var"):
        got = np.asarray(upd_o["batch_stats"][k])
        assert got.shape == (16,), got.shape
        np.testing.assert_allclose(
            got, np.asarray(upd_f["batch_stats"][k]),
            rtol=1e-4, atol=1e-5)
