"""Multi-process collective tests: launch real 2- and 4-rank jobs on
localhost via the launcher (no mocked collectives, mirroring the reference CI
strategy in SURVEY.md §4)."""

import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def run_launcher(np_, script, extra_env=None, timeout=180):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The workers should run plain CPU numpy; don't inherit test JAX flags.
    env.pop("JAX_PLATFORMS", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", str(np_), "--",
         sys.executable, os.path.join(HERE, script)],
        env=env, timeout=timeout, capture_output=True, text=True)


@pytest.mark.parametrize("np_", [2, 4])
def test_distributed_ops(np_):
    proc = run_launcher(np_, "distributed_ops_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(np_):
        assert ("rank %d: all distributed op tests passed" % r) in \
            proc.stdout, proc.stdout + proc.stderr


def test_single_process_short_circuit():
    proc = run_launcher(1, "single_proc_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cycle_time_env():
    proc = run_launcher(2, "distributed_ops_worker.py",
                        extra_env={"HVD_TPU_CYCLE_TIME": "1"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cache_disabled():
    proc = run_launcher(2, "distributed_ops_worker.py",
                        extra_env={"HVD_TPU_CACHE_CAPACITY": "0"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
