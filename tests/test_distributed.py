"""Multi-process collective tests: launch real 2- and 4-rank jobs on
localhost via the launcher (no mocked collectives, mirroring the reference CI
strategy in SURVEY.md §4). The `run_launcher` harness lives in conftest.py."""

import pytest

pytestmark = pytest.mark.e2e


@pytest.mark.parametrize("np_", [2, 4])
def test_distributed_ops(run_launcher, np_):
    proc = run_launcher(np_, "distributed_ops_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(np_):
        assert ("rank %d: all distributed op tests passed" % r) in \
            proc.stdout, proc.stdout + proc.stderr


def test_single_process_short_circuit(run_launcher):
    proc = run_launcher(1, "single_proc_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cycle_time_env(run_launcher):
    proc = run_launcher(2, "distributed_ops_worker.py",
                        extra_env={"HVD_TPU_CYCLE_TIME": "1"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cache_disabled(run_launcher):
    # Deliberately includes the plain-jit io_callback plane: the host
    # core must stay correct with the response cache off.
    proc = run_launcher(2, "distributed_ops_worker.py",
                        extra_env={"HVD_TPU_CACHE_CAPACITY": "0"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
