"""Worker for bench.py --model-parallel (BENCH_r09): the process-group
wire-bytes and step-time A/B on the host control plane.

Forms the (batch, model) mesh via hvd.init(model_parallel=K), then
measures per-rank socket bytes (net_ring_bytes_sent_total deltas) and
latency for:
  * a full-world allreduce of the payload tensor (the pure-DP baseline);
  * a MODEL-group allreduce of the SAME tensor (the tensor-parallel
    activation reduction — the acceptance's wire-ratio numerator);
  * a BATCH-group allreduce of the same tensor (the mesh's gradient
    path: same bytes class as the world ring but over N/K members).

numpy+ctypes only — spawned by bench.py _spawn_local_workers."""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


def ring_sent():
    return hvd.metrics()["counters"]["net_ring_bytes_sent_total"]


def main():
    k = int(os.environ.get("HVD_TPU_BENCH_MODEL_PARALLEL", "2"))
    mb = float(os.environ.get("HVD_TPU_BENCH_PAYLOAD_MB", "1"))
    iters = int(os.environ.get("HVD_TPU_BENCH_ITERS", "20"))
    hvd.init(model_parallel=k)
    r, n = hvd.rank(), hvd.size()
    bg, mg = hvd.mesh_groups()
    elems = int(mb * (1 << 20) / 4)
    x = np.full(elems, float(r + 1), np.float32)

    # Warm-up: settle negotiation, build both group rings.
    ops.allreduce(x, "warm.world")
    ops.allreduce(x, "warm.model", group=mg)
    ops.allreduce(x, "warm.batch", group=bg)

    def measure(tag, group):
        b0 = ring_sent()
        t0 = time.perf_counter()
        for i in range(iters):
            out = ops.allreduce(x, "%s.%d" % (tag, i), group=group)
        dt_us = (time.perf_counter() - t0) / iters * 1e6
        per_iter = (ring_sent() - b0) / iters
        expect = (sum(m + 1 for m in group.ranks) if group is not None
                  else n * (n + 1) / 2)
        assert np.allclose(out, expect), (tag, out[0], expect)
        return {"bytes_per_iter": per_iter, "us_per_iter": dt_us}

    world = measure("bw.world", None)
    model = measure("bw.model", mg)
    batch = measure("bw.batch", bg)

    print("GB_RESULT " + json.dumps({
        "rank": r, "world_size": n, "model_parallel": k,
        "payload_mb": mb, "iters": iters,
        "world": world, "model_group": model, "batch_group": batch,
        "groups": hvd.metrics()["gauges"]["groups"],
        "group_tensors": hvd.metrics()["counters"]["group_tensors_total"],
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
