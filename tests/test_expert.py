"""Expert parallelism (Switch MoE + ep all_to_all): routing semantics,
dense equivalence, sharded-vs-unsharded equality, gradients, and the
MoeMlp module (virtual 8-device CPU mesh)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.expert import (MoeMlp, ep_param_specs,
                                         moe_capacity, moe_ffn,
                                         switch_dispatch)

jax.config.update("jax_default_matmul_precision", "highest")


def test_switch_dispatch_routing_and_capacity():
    # 4 tokens, 2 experts: tokens 0,1,3 -> expert 1; token 2 -> expert 0.
    logits = jnp.asarray([[0.0, 2.0],
                          [0.0, 3.0],
                          [4.0, 0.0],
                          [0.0, 1.0]], jnp.float32)
    dispatch, combine, aux = switch_dispatch(logits, capacity=2)
    d = np.asarray(dispatch)
    # Expert 1 queue: token0 -> slot0, token1 -> slot1, token3 DROPPED
    # (capacity 2 full).
    assert d[0, 1, 0] == 1 and d[1, 1, 1] == 1
    assert d[3].sum() == 0
    assert d[2, 0, 0] == 1
    # Combine carries the softmax gate of the chosen expert.
    probs = np.asarray(jax.nn.softmax(logits, -1))
    np.testing.assert_allclose(np.asarray(combine)[0, 1, 0], probs[0, 1],
                               rtol=1e-6)
    assert float(aux) > 0


def test_moe_ffn_matches_per_token_expert_computation():
    """With capacity >= T (no drops), the einsum dispatch must equal
    computing each token through its argmax expert, scaled by gate."""
    rng = np.random.RandomState(0)
    T, D, F, E = 32, 16, 24, 4
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    router = jnp.asarray(rng.randn(D, E).astype(np.float32) * 0.3)
    w_in = jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.2)
    w_out = jnp.asarray(rng.randn(E, F, D).astype(np.float32) * 0.2)

    y, aux = moe_ffn(x, router, w_in, w_out,
                     capacity_factor=float(E))  # C = T: nothing dropped
    probs = jax.nn.softmax(x @ router, -1)
    idx = np.asarray(jnp.argmax(probs, -1))
    import flax.linen as nn
    expect = np.zeros((T, D), np.float32)
    for t in range(T):
        e = idx[t]
        h = np.asarray(nn.silu(x[t] @ w_in[e]))
        expect[t] = float(probs[t, e]) * np.asarray(h @ w_out[e])
    np.testing.assert_allclose(np.asarray(y), expect, rtol=2e-4, atol=2e-4)


def _mesh_dp_ep(dp, ep):
    devs = np.array(jax.devices("cpu")[:dp * ep]).reshape(dp, ep)
    return Mesh(devs, ("dp", "ep"))


def test_ep_sharded_matches_unsharded():
    """(dp=2 x ep=4): tokens sharded over BOTH axes (each rank routes
    its own T/8 tokens), experts sharded over ep — output must equal
    the single-device moe_ffn on each token shard."""
    rng = np.random.RandomState(1)
    T, D, F, E = 64, 16, 24, 8
    x = rng.randn(T, D).astype(np.float32)
    router = rng.randn(D, E).astype(np.float32) * 0.3
    w_in = rng.randn(E, D, F).astype(np.float32) * 0.2
    w_out = rng.randn(E, F, D).astype(np.float32) * 0.2
    cf = float(E)  # no drops, so shard/unshard routing agrees exactly

    mesh = _mesh_dp_ep(2, 4)

    def sharded(x, router, w_in, w_out):
        y, aux = moe_ffn(x, router, w_in, w_out, capacity_factor=cf,
                         ep_axis="ep")
        return y, lax_pmean_all(aux)

    from jax import lax

    def lax_pmean_all(v):
        return lax.pmean(lax.pmean(v, "ep"), "dp")

    mapped = jax.jit(jax.shard_map(
        sharded, mesh=mesh,
        in_specs=(P(("dp", "ep")), P(), P("ep"), P("ep")),
        out_specs=(P(("dp", "ep")), P()),
        check_vma=False))
    y_sharded, aux_sharded = mapped(x, router, w_in, w_out)

    # Reference: same per-shard computation, serially.
    shards = x.reshape(8, T // 8, D)
    y_ref = np.concatenate([
        np.asarray(moe_ffn(jnp.asarray(s), jnp.asarray(router),
                           jnp.asarray(w_in), jnp.asarray(w_out),
                           capacity_factor=cf)[0])
        for s in shards])
    np.testing.assert_allclose(np.asarray(y_sharded), y_ref,
                               rtol=2e-4, atol=2e-4)


def test_ep_sharded_gradients_match():
    """Expert-weight gradients through the all_to_all path must match
    the unsharded computation (summed over token shards)."""
    rng = np.random.RandomState(2)
    T, D, F, E = 32, 8, 12, 4
    x = rng.randn(T, D).astype(np.float32)
    router = rng.randn(D, E).astype(np.float32) * 0.3
    w_in = rng.randn(E, D, F).astype(np.float32) * 0.2
    w_out = rng.randn(E, F, D).astype(np.float32) * 0.2
    cf = float(E)
    mesh = _mesh_dp_ep(2, 2)

    from jax import lax

    from horovod_tpu.parallel.expert import ep_grad_sync

    def loss_sharded(w_in, w_out, x, router):
        # LOCAL loss — no psum: psum's transpose is psum, so a
        # replicated psum'd loss would scale every grad by the rank
        # count. ep_grad_sync's contract is raw local-loss grads.
        y, _ = moe_ffn(x, router, w_in, w_out, capacity_factor=cf,
                       ep_axis="ep")
        return jnp.sum(y ** 2)

    def grads_fn(w_in, w_out, x, router):
        g_in, g_out = jax.grad(loss_sharded, argnums=(0, 1))(
            w_in, w_out, x, router)
        # Expert-sharded grads carry only THIS rank's token shard:
        # sync over the data axes (the library rule, ep_grad_sync).
        return ep_grad_sync({"w_in": g_in, "w_out": g_out},
                            ep_axis="ep", dp_axis="dp")

    grads_sh = jax.jit(jax.shard_map(
        grads_fn, mesh=mesh,
        in_specs=(P("ep"), P("ep"), P(("dp", "ep")), P()),
        out_specs={"w_in": P("ep"), "w_out": P("ep")},
        check_vma=False))(w_in, w_out, x, router)
    grads_sh = (grads_sh["w_in"], grads_sh["w_out"])

    def loss_ref(w_in, w_out):
        total = 0.0
        for s in x.reshape(4, T // 4, D):
            y, _ = moe_ffn(jnp.asarray(s), jnp.asarray(router), w_in,
                           w_out, capacity_factor=cf)
            total = total + jnp.sum(y ** 2)
        return total

    grads_ref = jax.grad(loss_ref, argnums=(0, 1))(jnp.asarray(w_in),
                                                   jnp.asarray(w_out))
    for a, b in zip(grads_sh, grads_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_moe_mlp_module_and_param_specs():
    """MoeMlp init/apply, aux-loss sowing, and ep_param_specs placing
    only expert weights on the ep axis."""
    model = MoeMlp(num_experts=4, mlp_dim=32, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 16)
                    .astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), x)
    y, state = model.apply(variables, x, mutable=["intermediates"])
    assert y.shape == x.shape
    aux = state["intermediates"]["moe_aux_loss"][0]
    assert float(aux) > 0
    specs = ep_param_specs(variables["params"], "ep")
    assert specs["w_in"] == P("ep") and specs["w_out"] == P("ep")
    assert specs["router"] == P()


def test_capacity_helper():
    assert moe_capacity(64, 8, 1.0) == 8
    assert moe_capacity(64, 8, 1.25) == 10
    assert moe_capacity(3, 8, 1.0) == 1


def test_moe_transformer_train_step_dp_ep():
    """Full (dp=2 x ep=4) MoE-transformer train step: every other block
    swaps its MLP for the expert-parallel MoeMlp; expert weights
    sharded P('ep'), tokens over (dp, ep); one optimizer step with
    ep_grad_sync'd gradients."""
    import dataclasses

    import optax

    from horovod_tpu.models import Transformer, TransformerConfig

    base = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                             embed_dim=32, mlp_dim=64, moe_experts=4,
                             moe_every=2, moe_capacity_factor=2.0,
                             dtype=jnp.float32)
    cfg = dataclasses.replace(base, ep_axis="ep", ep_size=4)
    model = Transformer(cfg)
    tokens = jnp.asarray(
        np.random.RandomState(7).randint(0, 64, size=(8, 16)))
    # Init with the ep_axis-free twin (identical param structure; the
    # axis name only exists inside shard_map).
    variables = Transformer(base).init(jax.random.PRNGKey(0), tokens[:1])
    params = variables["params"]
    specs = ep_param_specs(params, "ep")
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)

    from horovod_tpu.parallel.expert import ep_grad_sync

    mesh = _mesh_dp_ep(2, 4)

    def loss_fn(params, tokens):
        # mutable=["intermediates"] surfaces the sown Switch aux loss;
        # without it the load-balancing pressure is silently dropped
        # (the canonical expert-collapse failure).
        logits, state = model.apply({"params": params}, tokens,
                                    mutable=["intermediates"])
        tgt = jnp.roll(tokens, -1, axis=1)
        logp = jax.nn.log_softmax(logits)
        xent = -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))
        aux = sum(jax.tree_util.tree_leaves(state["intermediates"]))
        return xent + 0.01 * aux

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        grads = ep_grad_sync(grads, "ep", dp_axis="dp", average=True)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        from jax import lax
        return params, opt_state, lax.pmean(lax.pmean(loss, "ep"), "dp")

    # SGD state is empty; replicate it.
    opt_specs = jax.tree_util.tree_map(lambda _: P(), opt_state)

    params_p = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs, opt_specs, P(("dp", "ep"))),
        out_specs=(specs, opt_specs, P()),
        check_vma=False))
    new_params, _, loss = mapped(params_p, opt_state, tokens)
    assert np.isfinite(float(loss))
    # The MoE expert weights moved.
    moved = np.abs(
        np.asarray(new_params["block_1"]["moe_mlp"]["w_in"]) -
        np.asarray(params["block_1"]["moe_mlp"]["w_in"])).max()
    assert moved > 0


def test_moe_with_ring_attention_sp_ep_mesh():
    """ep and sp compose on one mesh: batch sharded over ep (MoE
    all_to_all dispatch inside each sp group), sequence sharded over
    sp (ring attention inside each ep group) — output still matches
    the full unsharded MoE model (capacity high enough that routing
    grouping is irrelevant)."""
    import dataclasses

    from horovod_tpu.models import Transformer, TransformerConfig

    ep, sp = 2, 2
    base = TransformerConfig(vocab_size=97, num_layers=2, num_heads=4,
                             embed_dim=32, mlp_dim=64, moe_experts=4,
                             moe_every=2, moe_capacity_factor=4.0,
                             dtype=jnp.float32)
    full = Transformer(base)
    rng = np.random.RandomState(13)
    tokens = jnp.asarray(rng.randint(0, 97, (2, 32)))
    params = full.init(jax.random.PRNGKey(17), tokens)["params"]
    expected = full.apply({"params": params}, tokens)

    sharded_cfg = dataclasses.replace(base, attention="ring",
                                      sp_axis="sp", ep_axis="ep",
                                      ep_size=ep)
    local = Transformer(sharded_cfg)
    mesh = Mesh(np.array(jax.devices("cpu")[:ep * sp]).reshape(ep, sp),
                ("ep", "sp"))
    specs = ep_param_specs(params, "ep")
    params_p = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)

    def run(p, tokens):
        L = tokens.shape[1]
        positions = jnp.broadcast_to(
            jax.lax.axis_index("sp") * L +
            jnp.arange(L, dtype=jnp.int32)[None], tokens.shape)
        return local.apply({"params": p}, tokens, positions)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(specs, P("ep", "sp")),
        out_specs=P("ep", "sp"), check_vma=False))(params_p, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_top2_dispatch_routing():
    """Top-2: both chosen experts get slots, gates renormalize to 1,
    second choices queue after ALL first choices (GShard ordering)."""
    from horovod_tpu.parallel.expert import topk_dispatch

    logits = jnp.asarray([[3.0, 2.0, -5.0],
                          [2.5, 3.5, -5.0]], jnp.float32)
    dispatch, combine, aux = topk_dispatch(logits, capacity=4, k=2)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # First choices: t0 -> e0 slot0, t1 -> e1 slot0.
    assert d[0, 0, 0] == 1 and d[1, 1, 0] == 1
    # Second choices enqueue after first-round counts: t0 -> e1 gets
    # slot 1 (e1 already has t1's first choice), t1 -> e0 slot 1.
    assert d[0, 1, 1] == 1 and d[1, 0, 1] == 1
    # Gates renormalized per token: the two combine weights sum to 1.
    np.testing.assert_allclose(c[0].sum(), 1.0, rtol=1e-6)
    np.testing.assert_allclose(c[1].sum(), 1.0, rtol=1e-6)
    assert float(aux) > 0


def test_top2_moe_ffn_matches_per_token():
    """Top-2 with ample capacity == per-token sum of the two chosen
    experts weighted by renormalized gates."""
    import flax.linen as nn

    rng = np.random.RandomState(5)
    T, D, F, E = 16, 8, 12, 4
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    router = jnp.asarray(rng.randn(D, E).astype(np.float32) * 0.5)
    w_in = jnp.asarray(rng.randn(E, D, F).astype(np.float32) * 0.2)
    w_out = jnp.asarray(rng.randn(E, F, D).astype(np.float32) * 0.2)

    y, _ = moe_ffn(x, router, w_in, w_out, capacity_factor=2.0 * E,
                   top_k=2)
    probs = np.asarray(jax.nn.softmax(x @ router, -1))
    expect = np.zeros((T, D), np.float32)
    for t in range(T):
        order = np.argsort(-probs[t])
        g = probs[t, order[:2]]
        g = g / g.sum()
        for e, gate in zip(order[:2], g):
            h = np.asarray(nn.silu(x[t] @ w_in[e]))
            expect[t] += gate * np.asarray(h @ w_out[e])
    np.testing.assert_allclose(np.asarray(y), expect, rtol=2e-4,
                               atol=2e-4)


def test_top2_ep_sharded_matches_unsharded():
    """Top-2 routing through the ep all_to_all: sharded == per-shard
    unsharded."""
    rng = np.random.RandomState(6)
    T, D, F, E = 32, 8, 12, 4
    x = rng.randn(T, D).astype(np.float32)
    router = rng.randn(D, E).astype(np.float32) * 0.4
    w_in = rng.randn(E, D, F).astype(np.float32) * 0.2
    w_out = rng.randn(E, F, D).astype(np.float32) * 0.2
    cf = 2.0 * E
    mesh = _mesh_dp_ep(2, 2)

    def sharded(x, router, w_in, w_out):
        y, _ = moe_ffn(x, router, w_in, w_out, capacity_factor=cf,
                       ep_axis="ep", top_k=2)
        return y

    y_sh = jax.jit(jax.shard_map(
        sharded, mesh=mesh,
        in_specs=(P(("dp", "ep")), P(), P("ep"), P("ep")),
        out_specs=P(("dp", "ep")), check_vma=False))(x, router, w_in,
                                                     w_out)
    y_ref = np.concatenate([
        np.asarray(moe_ffn(jnp.asarray(s), jnp.asarray(router),
                           jnp.asarray(w_in), jnp.asarray(w_out),
                           capacity_factor=cf, top_k=2)[0])
        for s in x.reshape(4, T // 4, D)])
    np.testing.assert_allclose(np.asarray(y_sh), y_ref, rtol=2e-4,
                               atol=2e-4)


def test_moe_with_ulysses_attention_sp_ep_mesh():
    """Same composition as the ring variant but with Ulysses attention:
    TWO different all_to_alls (sequence<->heads over sp, tokens<->
    experts over ep) in one compiled program, matching the unsharded
    model."""
    import dataclasses

    from horovod_tpu.models import Transformer, TransformerConfig

    ep, sp = 2, 2
    base = TransformerConfig(vocab_size=97, num_layers=2, num_heads=4,
                             embed_dim=32, mlp_dim=64, moe_experts=4,
                             moe_every=2, moe_capacity_factor=4.0,
                             dtype=jnp.float32)
    full = Transformer(base)
    rng = np.random.RandomState(21)
    tokens = jnp.asarray(rng.randint(0, 97, (2, 32)))
    params = full.init(jax.random.PRNGKey(23), tokens)["params"]
    expected = full.apply({"params": params}, tokens)

    local = Transformer(dataclasses.replace(
        base, attention="ulysses", sp_axis="sp", ep_axis="ep",
        ep_size=ep))
    mesh = Mesh(np.array(jax.devices("cpu")[:ep * sp]).reshape(ep, sp),
                ("ep", "sp"))
    specs = ep_param_specs(params, "ep")
    params_p = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)

    def run(p, tokens):
        L = tokens.shape[1]
        positions = jnp.broadcast_to(
            jax.lax.axis_index("sp") * L +
            jnp.arange(L, dtype=jnp.int32)[None], tokens.shape)
        return local.apply({"params": p}, tokens, positions)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(specs, P("ep", "sp")),
        out_specs=P("ep", "sp"), check_vma=False))(params_p, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
