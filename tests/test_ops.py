"""Pallas kernel tests. The kernel itself runs in interpret mode on the
CPU backend (exactly the code path the TPU compiles); numerical ground
truth is dense attention."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

jax.config.update("jax_default_matmul_precision", "highest")


def _dense(q, k, v, causal):
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * D ** -0.5
    if causal:
        L = s.shape[-1]
        mask = np.tril(np.ones((L, L), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


def _rand_qkv(B, L, H, D, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_interpret_matches_dense(causal):
    from horovod_tpu.ops.flash_attention import _pallas_forward
    B, L, H, D = 2, 256, 2, 64  # L multiple of BLOCK_Q=128
    q, k, v = _rand_qkv(B, L, H, D)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _pallas_forward(qt, kt, vt, D ** -0.5, causal,
                          interpret=True).transpose(0, 2, 1, 3)
    expected = _dense(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_flash_fallback_and_grads():
    """Public API on CPU uses the blockwise fallback; values and grads
    must match dense attention."""
    from horovod_tpu.ops import flash_attention
    B, L, H, D = 1, 64, 1, 8
    q, k, v = _rand_qkv(B, L, H, D, seed=3)

    out = flash_attention(q, k, v, causal=True)
    expected = _dense(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("bq,bk", [(256, 512), (128, 128)])
def test_flash_kernel_block_shapes_interpret(bq, bk):
    """(256, 512): the production default's unequal q/k tiling, where
    every visible causal block straddles the diagonal. (128, 128): equal
    tiling at L=512 has fully-below-diagonal blocks, exercising the
    mask-skip (straddles=False) branch the default tiling never hits."""
    from horovod_tpu.ops.flash_attention import _pallas_forward
    B, L, H, D = 1, 512, 1, 32
    q, k, v = _rand_qkv(B, L, H, D, seed=7)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _pallas_forward(qt, kt, vt, D ** -0.5, True, interpret=True,
                          block_q=bq, block_k=bk).transpose(0, 2, 1, 3)
    expected = _dense(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal,bq,bk", [
    (True, None, None),    # production tiling via the public custom-VJP
    (False, None, None),
    (True, 128, 128),      # equal tiling: exercises straddles=False in
                           # both backward kernels (fully-visible blocks)
])
def test_flash_pallas_backward_interpret(causal, bq, bk):
    """The Pallas backward kernels (dQ / dK+dV, used on TPU) must match
    dense-attention gradients; exercised in interpret mode."""
    from horovod_tpu.ops.flash_attention import (
        _flash, _pallas_backward, _pallas_forward_lse)
    B, L, H, D = 1, 512, 1, 32
    q, k, v = _rand_qkv(B, L, H, D, seed=11)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    w = jnp.asarray(np.random.RandomState(12).randn(B, H, L, D),
                    jnp.float32)

    if bq is None:
        def loss_flash(qt, kt, vt):
            return jnp.sum(_flash(qt, kt, vt, D ** -0.5, causal, True) * w)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(qt, kt, vt)
    else:
        out, lse = _pallas_forward_lse(qt, kt, vt, D ** -0.5, causal,
                                       True, block_q=bq, block_k=bk)
        g_flash = _pallas_backward(qt, kt, vt, out, lse, w, D ** -0.5,
                                   causal, True, block_q=bq, block_k=bk)

    def loss_dense(q, k, v):
        return jnp.sum(
            _dense(q, k, v, causal).transpose(0, 2, 1, 3) * w)

    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd.transpose(0, 2, 1, 3)),
            rtol=2e-4, atol=2e-4)


def test_flash_default_block_policy():
    """Pins the swept block-preference table (_default_blocks) and the
    invariant that the chosen blocks always divide L: D- and L-aware
    (L=8192 sweeps: bigger q blocks at L>=4096), one definition for
    plain and ring paths."""
    from horovod_tpu.ops.flash_attention import (_default_blocks,
                                                 _pick_block)
    # (D, L, backward) -> swept preference
    assert _default_blocks(64, 2048) == (256, 1024)
    assert _default_blocks(64, 2048, backward=True) == (512, 1024)
    assert _default_blocks(64, 8192) == (512, 1024)
    assert _default_blocks(64, 8192, backward=True) == (1024, 1024)
    assert _default_blocks(128, 2048) == (256, 512)
    assert _default_blocks(128, 8192) == (512, 512)
    assert _default_blocks(128, 8192, backward=True) == (512, 1024)
    # L unknown (ring callers pass shard length; None = conservative)
    assert _default_blocks(64) == (256, 1024)
    # The picked block always divides L, falling back down the ladder.
    for D in (64, 128):
        for L in (256, 384, 2048, 4096, 8192, 12288):
            for backward in (False, True):
                pq, pk = _default_blocks(D, L, backward)
                for pref in (pq, pk):
                    b = _pick_block(L, pref)
                    assert b is not None and L % b == 0 and b <= pref


def test_flash_fallback_tail_block():
    """L not a multiple of BLOCK_Q (160 = 128 + 32 tail): the blockwise
    fallback must cover the remainder, full shape, values AND grads."""
    from horovod_tpu.ops import flash_attention
    B, L, H, D = 1, 160, 1, 8
    q, k, v = _rand_qkv(B, L, H, D, seed=5)

    out = flash_attention(q, k, v, causal=True)
    assert out.shape == (B, L, H, D)
    expected = _dense(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)

    g_flash = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(
        q, k, v)
    g_dense = jax.grad(lambda q, k, v: jnp.sum(
        _dense(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_ring_step_carry(causal):
    """Two chained `flash_ring_step` calls (q at global offset Lq, k/v
    blocks arriving diagonal-first, the ring order) must equal dense
    attention of the q shard over the concatenated sequence — validates
    the carried online-softmax state and global-offset masking."""
    from horovod_tpu.ops.flash_attention import flash_ring_step
    BH, Lq, D = 2, 256, 32
    rng = np.random.RandomState(21)
    q = jnp.asarray(rng.randn(BH, Lq, D), jnp.float32)
    k_blocks = [jnp.asarray(rng.randn(BH, Lq, D), jnp.float32)
                for _ in range(2)]
    v_blocks = [jnp.asarray(rng.randn(BH, Lq, D), jnp.float32)
                for _ in range(2)]
    scale = D ** -0.5

    o = jnp.zeros((BH, Lq, D), jnp.float32)
    m = jnp.full((BH, Lq, 8), -jnp.inf, jnp.float32)
    l = jnp.zeros((BH, Lq, 8), jnp.float32)
    # q is the SECOND shard (offset Lq); ring delivers own (diagonal)
    # k/v block first, then the previous shard's.
    for kv_idx in (1, 0):
        o, m, l = flash_ring_step(
            q, k_blocks[kv_idx], v_blocks[kv_idx], o, m, l,
            q_offset=jnp.int32(Lq), kv_offset=jnp.int32(kv_idx * Lq),
            causal=causal, scale=scale, interpret=True)
    l1 = l[:, :, :1]
    out = o / jnp.where(l1 == 0.0, 1.0, l1)

    k_full = jnp.concatenate(k_blocks, axis=1)
    v_full = jnp.concatenate(v_blocks, axis=1)
    s = jnp.einsum("bqd,bkd->bqk", q, k_full) * scale
    if causal:
        rows = Lq + np.arange(Lq)[:, None]
        cols = np.arange(2 * Lq)[None, :]
        s = jnp.where(jnp.asarray(rows >= cols)[None], s, -jnp.inf)
    expected = jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, axis=-1),
                          v_full)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_transformer_flash_matches_dense():
    from horovod_tpu.models import Transformer, TransformerConfig
    base = dict(vocab_size=64, num_layers=2, num_heads=2, embed_dim=32,
                mlp_dim=64, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    dense_model = Transformer(TransformerConfig(**base))
    flash_model = Transformer(TransformerConfig(attention="flash", **base))
    variables = dense_model.init(jax.random.PRNGKey(0), tokens)
    expected = dense_model.apply(variables, tokens)
    out = flash_model.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# GQA/MQA (grouped kv heads) + fused rotary


def _ref_rotary(x, base=10000.0):
    """Independent outside-the-kernel rotary reference: the production
    model path (`models.transformer._rotary`), positions 0..L-1, over
    [B, L, H, D]. The kernels' in-block rotation must agree with it."""
    from horovod_tpu.models.transformer import _rotary
    B, L = x.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None], (B, L))
    return _rotary(x, pos, base)


def _dense_gqa(q, k, v, causal, rotary_base=None):
    """Dense reference for q [B,L,H,D], k/v [B,L,G,D]: rotate outside,
    repeat kv across each query-head group."""
    H, G = q.shape[2], k.shape[2]
    if rotary_base is not None:
        q = _ref_rotary(q, rotary_base)
        k = _ref_rotary(k, rotary_base)
    if H != G:
        k = jnp.repeat(k, H // G, axis=2)
        v = jnp.repeat(v, H // G, axis=2)
    return _dense(q, k, v, causal)


def _rand_gqa(B, L, H, G, D, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("G,causal", [(2, True), (2, False), (1, True)])
def test_flash_gqa_interpret_matches_dense(G, causal):
    """Grouped-rows GQA kernel layout (G=1 is MQA: every query head on
    one kv head) must match dense attention with repeated kv."""
    from horovod_tpu.ops.flash_attention import _pallas_forward
    B, L, H, D = 2, 256, 4, 32
    q, k, v = _rand_gqa(B, L, H, G, D, seed=5)
    out = _pallas_forward(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), D ** -0.5, causal,
                          interpret=True).transpose(0, 2, 1, 3)
    expected = _dense_gqa(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("G,rotary", [(2, None), (4, 10000.0),
                                      (2, 10000.0), (1, 10000.0)])
def test_flash_gqa_rotary_backward_interpret(G, rotary):
    """Values AND all three gradients of the Pallas path (custom VJP,
    interpret mode) for grouped kv heads and fused rotary, against
    dense attention that rotates outside and repeats kv. Pins: the
    in-kernel dK/dV group reduction, the rotated-space dQ/dK
    accumulation with finalize counter-rotation, and the grouped
    causal masks."""
    from horovod_tpu.ops.flash_attention import _flash
    B, L, H, D = 1, 512, 4, 32
    q, k, v = _rand_gqa(B, L, H, G, D, seed=9)
    w = jnp.asarray(np.random.RandomState(10).randn(B, L, H, D),
                    jnp.float32)

    def loss_flash(q, k, v):
        out = _flash(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                     v.transpose(0, 2, 1, 3), D ** -0.5, True, True,
                     rotary).transpose(0, 2, 1, 3)
        return jnp.sum(out * w), out

    def loss_dense(q, k, v):
        return jnp.sum(_dense_gqa(q, k, v, True, rotary) * w)

    (_, out), g_flash = jax.value_and_grad(
        loss_flash, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense_gqa(q, k, v, True, rotary)),
        rtol=2e-5, atol=2e-5)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, nm in zip(g_flash, g_dense, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4, err_msg=nm)


def test_flash_attention_gqa_fallback_and_validation():
    """Public API on CPU (blockwise fallback): GQA + fused rotary
    values/grads match dense; mismatched head counts raise."""
    from horovod_tpu.ops import flash_attention
    B, L, H, G, D = 1, 48, 4, 2, 16  # L not 128-aligned -> fallback
    q, k, v = _rand_gqa(B, L, H, G, D, seed=13)

    out = flash_attention(q, k, v, causal=True, rotary_base=10000.0)
    expected = _dense_gqa(q, k, v, True, 10000.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)

    g_flash = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, rotary_base=10000.0) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(lambda q, k, v: jnp.sum(
        _dense_gqa(q, k, v, True, 10000.0) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4)

    with pytest.raises(ValueError, match="num_kv_heads"):
        flash_attention(q, k[:, :, :1].repeat(3, 2), v, causal=True)


def test_pick_rows_block_policy():
    """Grouped row-block picking: bqp positions * group rows stays at
    or under the swept row preference, bqp | L, and group=1 defers to
    the plain picker."""
    from horovod_tpu.ops.flash_attention import (_pick_block,
                                                 _pick_rows_block)
    assert _pick_rows_block(8192, 512, 1) == _pick_block(8192, 512) == 512
    assert _pick_rows_block(8192, 512, 2) == 512      # 256 pos x 2
    assert _pick_rows_block(8192, 512, 3) == 384      # 128 pos x 3
    assert _pick_rows_block(8192, 512, 6) == 384      # 64 pos x 6
    assert _pick_rows_block(8192, 512, 12) == 384     # 32 pos x 12
    assert _pick_rows_block(8192, 1024, 4) == 1024    # 256 pos x 4
    assert _pick_rows_block(256, 512, 2) == 512       # 256 pos x 2


def test_transformer_gqa_flash_matches_dense():
    """Transformer with grouped kv heads: the flash path (fallback on
    CPU) must match the dense path on the same params, with rope_fused
    exercising the kernel-side rotary against the model-side one; the
    kv projections must actually shrink to G heads."""
    from horovod_tpu.models import Transformer, TransformerConfig
    base = dict(vocab_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, embed_dim=32, mlp_dim=64,
                dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    dense_model = Transformer(TransformerConfig(**base))
    flash_model = Transformer(TransformerConfig(
        attention="flash", rope_fused=True, **base))
    variables = dense_model.init(jax.random.PRNGKey(0), tokens)
    key_kernel = variables["params"]["block_0"]["attn"]["key"]["kernel"]
    assert key_kernel.shape == (32, 2, 8)  # (embed, G, head_dim)
    expected = dense_model.apply(variables, tokens)
    out = flash_model.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
