"""Pipelined-ring parity worker: runs allreduce / reduce-scatter /
allgather over payloads whose final pipeline segment is UNEVEN, under
every wire-compression mode, and prints a CRC digest of every result.

The test launches this twice — once with HVD_TPU_PIPELINE_CHUNK_BYTES=0
(unsliced hops) and once with a small chunk (many segments per hop) —
and asserts the digests match bitwise: slicing a hop into
double-buffered segments must be a pure transport optimization. int8
segments align to the quantization block (native SegmentElems), so even
the lossy codec's values are bitwise-stable across slicings.

Ops run strictly one-at-a-time (enqueue -> synchronize) so tensor fusion
cannot group them differently between the two runs — a fused buffer has
different ring partition boundaries, which legitimately changes f32
summation order.
"""

import json
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


# Element counts chosen so chunks are uneven across ranks AND the final
# pipeline segment is partial: primes, a sub-block tail, sub-segment
# payloads, and a multi-segment payload.
SIZES = [1, 255, 785, 3 * 256 + 17, 99991, (1 << 18) + 3]
MODES = ["none", "bf16", "int8"]


def fill(size, rank, mode):
    if mode == "int8":
        # Constant fills quantize exactly (scale = c/127, q = 127), so
        # the cross-run digest ALSO equals the exact expected sum.
        return np.full(size, float(rank + 1), np.float32)
    i = np.arange(size, dtype=np.float32)
    # Small integers: exact in f32 and in bf16 rounding (< 256).
    return np.asarray((i % 13) + rank + 1, np.float32)


def crc(arr):
    return hvd.get_basics().crc32c(np.ascontiguousarray(arr).tobytes())


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    digests = {}
    for mode in MODES:
        for size in SIZES:
            name = "parity.%s.%d" % (mode, size)
            x = fill(size, r, mode)
            out = ops.allreduce(x, name + ".ar", compression=mode)
            # Exact even under the lossy codecs: int8 constant fills
            # quantize exactly, bf16 small integers round-trip exactly.
            expected = sum(fill(size, rr, mode) for rr in range(n))
            assert np.array_equal(out, expected), (mode, size)
            digests[name + ".ar"] = crc(out)

            shard = ops.reduce_scatter(x, name + ".rs", compression=mode)
            counts, offsets = ops.shard_partition(size, n)
            want = expected[offsets[r]:offsets[r] + counts[r]]
            assert np.array_equal(shard, want), (mode, size)
            digests[name + ".rs"] = crc(shard)

        # Allgather rides the uncompressed block circulation; cover it
        # once per mode loop for the digest set anyway.
        g = ops.allgather(fill(1024 + r, r, "none"), "parity.ag.%s" % mode)
        digests["parity.ag.%s" % mode] = crc(g)

    print("PARITY_DIGESTS %s" % json.dumps(digests, sort_keys=True),
          flush=True)
    snap = hvd.metrics()
    print("PARITY_METRICS %s" % json.dumps({
        "pipeline_segments_total":
            snap["counters"]["pipeline_segments_total"],
        "reduce_scatter_total":
            snap["counters"]["reduce_scatter_total"],
    }), flush=True)
    print("rank %d parity done" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
