import numpy as np

import horovod_tpu as hvd

hvd.init()
for i in range(5):
    hvd.allreduce(np.arange(16, dtype=np.float32), "tl")
    hvd.allgather(np.arange(4, dtype=np.float32), "tl_ag.%d" % i)
hvd.shutdown()
print("rank done")
