"""hvd-verify: the symbolic collective-schedule verifier (docs/LINT.md).

Covers: schedule-extraction goldens (helper inlining, loop unrolling,
group-membership branches), one failing example per verifier finding
class with its clean twin, suppression/CLI/SARIF integration, finding
fingerprints surviving line shifts, the static-vs-runtime e2e (the same
divergent script test_divergence.py proves hangs-then-errors at
runtime must be flagged BEFORE launch), and the native lock-order
audit (`make check-lockorder`): clean on the real native tree, firing
on synthetic cycle / guard-violation fixtures.
"""

import json
import os
import re
import textwrap

import pytest

from horovod_tpu.lint import RULES, lint_source, verify_source
from horovod_tpu.lint.cli import main as lint_main
from horovod_tpu.lint.report import fingerprint
from horovod_tpu.lint.schedule import extract_schedules
from horovod_tpu.native import lockorder

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(source):
    return [f.rule for f in verify_source(textwrap.dedent(source),
                                          path="verify_case.py")]


def schedules_of(source, world=2):
    sched = extract_schedules("golden_case.py",
                              source=textwrap.dedent(source),
                              world=world)
    return [[(e.kind, e.name) for e in events if e.collective]
            for events in sched.per_rank]


# --- schedule-extraction goldens --------------------------------------------

def test_golden_straight_line_schedule():
    per_rank = schedules_of("""
        import horovod_tpu as hvd
        hvd.init()
        hvd.broadcast(x, 0, "init.w")
        hvd.allreduce(x, "grad.w")
        hvd.allgather(x, "metrics")
    """)
    expected = [("broadcast", "init.w"), ("allreduce", "grad.w"),
                ("allgather", "metrics")]
    assert per_rank == [expected, expected]


def test_golden_helper_inlined_with_chain():
    """Collectives inside user helpers land in the schedule with the
    full call chain (entry call site -> helper site)."""
    sched = extract_schedules("golden_case.py", source=textwrap.dedent("""
        import horovod_tpu as hvd

        def reduce_all(x, tag):
            return hvd.allreduce(x, name="g." + tag)

        def train_step(x):
            return reduce_all(x, "w")

        hvd.init()
        train_step(1)
    """), world=2)
    events = [e for e in sched.per_rank[0] if e.collective]
    assert [(e.kind, e.name) for e in events] == [("allreduce", "g.w")]
    # chain: top-level call -> train_step's call -> the collective
    assert len(events[0].chain) == 3
    assert events[0].chain[0][2] == "<module>"
    assert events[0].chain[1][2] == "train_step"
    assert events[0].chain[2][2] == "reduce_all"


def test_golden_loop_unrolled_names():
    per_rank = schedules_of("""
        import horovod_tpu as hvd
        hvd.init()
        for i in range(3):
            hvd.allreduce(x, name="g.%d" % i)
    """)
    expected = [("allreduce", "g.0"), ("allreduce", "g.1"),
                ("allreduce", "g.2")]
    assert per_rank == [expected, expected]


def test_golden_group_branch_membership():
    """A group collective correctly guarded by membership appears only
    in member ranks' schedules — and that asymmetry is NOT a
    divergence, because non-members never join that negotiation."""
    src = """
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 1])
        if hvd.rank() in (0, 1):
            hvd.allreduce(x, "model.grad", group=g)
        hvd.allreduce(x, "batch.grad")
    """
    per_rank = schedules_of(src, world=4)
    assert per_rank[0] == [("new_group", "new_group[0,1]"),
                           ("allreduce", "model.grad"),
                           ("allreduce", "batch.grad")]
    assert per_rank[3] == [("new_group", "new_group[0,1]"),
                           ("allreduce", "batch.grad")]
    assert rules_of(src) == []


def test_golden_local_import_and_helper(tmp_path):
    """Local imports are followed: a helper module's collectives are
    part of the entry script's schedule, and a rank-guarded collective
    INSIDE the helper is still found (the lexical rules cannot see
    this)."""
    (tmp_path / "helpers.py").write_text(textwrap.dedent("""
        import horovod_tpu as hvd

        def reduce_all(x, tag):
            return hvd.allreduce(x, name="h." + tag)

        def maybe_extra(x):
            if hvd.rank() == 0:
                hvd.allreduce(x, name="h.extra")
    """))
    entry = tmp_path / "train.py"
    entry.write_text(textwrap.dedent("""
        import horovod_tpu as hvd
        from helpers import maybe_extra, reduce_all

        hvd.init()
        maybe_extra(1)
        reduce_all(2, "loss")
    """))
    from horovod_tpu.lint import verify_paths
    findings, checked = verify_paths([str(entry)])
    assert checked == 1
    assert [f.rule for f in findings] == ["verify-divergent-schedule"]
    # Both call-site chains are named, through the helper file.
    assert "helpers.py" in findings[0].message
    assert "rank 0 call chain" in findings[0].message
    assert "rank 1 call chain" in findings[0].message


# --- one failing example per finding class, with its clean twin -------------

BAD = {
    "verify-divergent-schedule": """
        import horovod_tpu as hvd

        def log_helper(x):
            hvd.allreduce(x, "log.extra")

        hvd.init()
        if hvd.rank() == 0:
            log_helper(1)
        hvd.allreduce(2, "grad.w")
    """,
    "verify-kind-mismatch": """
        import horovod_tpu as hvd
        hvd.init()
        if flag:
            hvd.allreduce(x, "t")
        else:
            hvd.allgather(x, "t")
    """,
    "verify-non-member-group-call": """
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 1])
        hvd.allreduce(x, "grad", group=g)
    """,
    "verify-mixed-modes": """
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() < 2:
            hvd.allreduce(x, "g", compression="int8")
        else:
            hvd.allreduce(x, "g", compression="none")
    """,
    "verify-missing-restore-broadcast": """
        import horovod_tpu as hvd
        from horovod_tpu import elastic
        hvd.init()
        state = elastic.ElasticState(step=0)
        ck = hvd.elastic.DurableCheckpointer("/ckpt")
        ck.restore_into(state)
        hvd.allreduce(grads, "grads")
    """,
}

GOOD = {
    "verify-divergent-schedule": """
        import horovod_tpu as hvd

        def log_helper(x):
            hvd.allreduce(x, "log.extra")

        hvd.init()
        log_helper(1)
        hvd.allreduce(2, "grad.w")
        if hvd.rank() == 0:
            print("logged")
    """,
    "verify-kind-mismatch": """
        import horovod_tpu as hvd
        hvd.init()
        if flag:
            hvd.allreduce(x, "t.reduce")
        else:
            hvd.allgather(x, "t.gather")
    """,
    "verify-non-member-group-call": """
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 1])
        if hvd.rank() in (0, 1):
            hvd.allreduce(x, "grad", group=g)
    """,
    "verify-mixed-modes": """
        import horovod_tpu as hvd
        hvd.init()
        hvd.allreduce(x, "g", compression="int8")
    """,
    "verify-missing-restore-broadcast": """
        import horovod_tpu as hvd
        from horovod_tpu import elastic
        hvd.init()
        state = elastic.ElasticState(step=0)
        ck = hvd.elastic.DurableCheckpointer("/ckpt")
        ck.restore_into(state)
        state.sync()
        hvd.allreduce(grads, "grads")
    """,
}


@pytest.mark.parametrize("rule", sorted(BAD))
def test_verify_bad_flags(rule):
    assert rule in rules_of(BAD[rule])


@pytest.mark.parametrize("rule", sorted(BAD))
def test_verify_bad_names_both_chains(rule):
    """Acceptance: every verifier finding names BOTH conflicting
    call-site chains (mirroring the runtime divergence report)."""
    findings = [f for f in verify_source(textwrap.dedent(BAD[rule]),
                                         path="verify_case.py")
                if f.rule == rule]
    assert findings, rule
    assert findings[0].message.count("chain") >= 2, findings[0].message


@pytest.mark.parametrize("rule", sorted(GOOD))
def test_verify_good_clean(rule):
    assert rules_of(GOOD[rule]) == []


def test_group_rank_method_membership_guard():
    # `if g.rank() >= 0:` — the ProcessGroup API's own membership test.
    assert rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([1, 2])
        if g.rank() >= 0:
            hvd.allreduce(x, "grad", group=g)
    """) == []


def test_uniform_unknown_branches_do_not_diverge():
    # Every rank makes the same (unknowable) choice: both arms'
    # collectives surface, but identically on all ranks -> clean.
    assert rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        if flag:
            hvd.allreduce(x, "a")
        else:
            hvd.allreduce(x, "b")
    """) == []


def test_rank_dependent_name_diverges_interprocedurally():
    found = rules_of("""
        import horovod_tpu as hvd

        def reduce_mine(x):
            hvd.allreduce(x, name="grad.%d" % hvd.rank())

        hvd.init()
        reduce_mine(1)
    """)
    assert "verify-divergent-schedule" in found


def test_rank_taint_through_opaque_data_splits_world():
    """Rank-dependence surviving an opaque lookup: `table[hvd.rank()]`
    is undecidable but rank-derived, so the symbolic world splits and
    a branch-only collective is a proven divergence."""
    assert "verify-divergent-schedule" in rules_of("""
        import horovod_tpu as hvd

        def probe(x):
            hvd.allreduce(x, "probe")

        hvd.init()
        if table[hvd.rank()] > 0:
            probe(1)
        hvd.allreduce(2, "grad")
    """)


def test_tuple_unpack_does_not_smear_rank_taint():
    """`r, n = hvd.rank(), hvd.size()` taints r but NOT n — a
    world-size condition stays uniform."""
    assert rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        if n > 1:
            hvd.allreduce(x, "t")
    """) == []


def test_helper_toplevel_collective_anchors_at_import(tmp_path):
    """A divergence at an imported module's TOP LEVEL anchors at the
    entry file's import line, where a suppression can reach it."""
    (tmp_path / "sidefx.py").write_text(textwrap.dedent("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.allreduce(1, "import.time")
    """))
    entry = tmp_path / "train.py"
    entry.write_text(textwrap.dedent("""
        import horovod_tpu as hvd
        import sidefx
        hvd.init()
        hvd.allreduce(2, "grad")
    """))
    from horovod_tpu.lint import verify_paths
    findings, _ = verify_paths([str(entry)])
    assert [f.rule for f in findings] == ["verify-divergent-schedule"]
    entry_lines = entry.read_text().splitlines()
    assert findings[0].line <= len(entry_lines)
    assert "import sidefx" in entry_lines[findings[0].line - 1]


def test_distinct_optimizer_prefixes_do_not_collide():
    """Two optimizers with DISTINCT explicit name_prefix= values
    negotiate disjoint names at runtime — no mixed-modes report; with
    the default prefix they genuinely alias and the report stands."""
    assert rules_of("""
        import horovod_tpu.jax as hvd_jax
        import horovod_tpu as hvd
        hvd.init()
        opt_a = hvd_jax.DistributedOptimizer(
            inner, sharded_update=True, name_prefix="a")
        opt_b = hvd_jax.DistributedOptimizer(inner, name_prefix="b")
        p = hvd_jax.broadcast_parameters(p, root_rank=0)
        opt_a.update(g, s, p)
        opt_b.update(g, s, p)
    """) == []
    assert "verify-mixed-modes" in rules_of("""
        import horovod_tpu.jax as hvd_jax
        import horovod_tpu as hvd
        hvd.init()
        opt_a = hvd_jax.DistributedOptimizer(inner, sharded_update=True)
        opt_b = hvd_jax.DistributedOptimizer(inner)
        p = hvd_jax.broadcast_parameters(p, root_rank=0)
        opt_a.update(g, s, p)
        opt_b.update(g, s, p)
    """)


def test_boolop_returns_operand_not_bool():
    """`args.name or "grad.w"` evaluates to an operand (Python
    semantics), never the literal True — two such defaults must not
    collide under one name."""
    assert rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        hvd.allreduce(x, name=args.name or "grad.w")
        hvd.allgather(y, name=args.tag or "metrics")
    """) == []


def test_group_rank_taint_through_opaque_data():
    """g.rank() carries the rank taint like hvd.rank(): opaque lookups
    fed by a group position still split the symbolic world."""
    assert "verify-kind-mismatch" not in rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 1, 2, 3])
        if table[g.rank()]:
            hvd.allreduce(x, "a")
        else:
            hvd.allgather(x, "b")
    """)  # split world: per-rank choice, divergence owns the report
    assert "verify-divergent-schedule" in rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 1, 2, 3])
        if table[g.rank()] > 0:
            hvd.allreduce(x, "extra")
        hvd.allreduce(x, "grad")
    """)


def test_new_group_keyword_spelling():
    """new_group(ranks=[0, 1]) keeps the literal member list — the
    non-member check must not be disabled by an argument spelling."""
    assert "verify-non-member-group-call" in rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group(ranks=[0, 1])
        hvd.allreduce(x, "grad", group=g)
    """)


def test_collective_inside_name_expression_counted_once():
    sched = extract_schedules("golden_case.py", source=textwrap.dedent("""
        import horovod_tpu as hvd

        def mkname():
            hvd.allreduce(1, "probe")
            return "grad.w"

        hvd.init()
        hvd.allreduce(x, name=mkname())
    """), world=2)
    names = [e.name for e in sched.per_rank[0] if e.collective]
    assert names == ["probe", "grad.w"]


def test_divergence_not_masked_by_unrelated_mode_finding():
    """A rank-divergent collective must be reported even when the
    event it happens to align against carries its own (unrelated)
    mixed-modes finding."""
    found = rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() == 0:
            hvd.allreduce(x, "extra")
        if flag:
            hvd.allreduce(x, "m", compression="int8")
        else:
            hvd.allreduce(x, "m", compression="none")
    """)
    assert "verify-mixed-modes" in found
    assert "verify-divergent-schedule" in found


def test_reduce_scatter_in_schedule():
    """reduce_scatter is a negotiated collective (ZeRO's core op) and
    must appear in schedules: a rank-guarded one is a divergence."""
    per_rank = schedules_of("""
        import horovod_tpu as hvd
        from horovod_tpu.common import ops
        hvd.init()
        ops.reduce_scatter(x, "rs.grad")
    """)
    assert per_rank[0] == [("reducescatter", "rs.grad")]
    assert "verify-divergent-schedule" in rules_of("""
        import horovod_tpu as hvd
        from horovod_tpu.common import ops
        hvd.init()
        if hvd.rank() == 0:
            ops.reduce_scatter(x, "rs.only0")
        hvd.allreduce(x, "grad")
    """)


def test_try_else_clause_is_executed():
    """try/except/else: the else clause runs on the normal path — the
    path the executor models — so a divergent collective there is
    found."""
    assert "verify-divergent-schedule" in rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        try:
            x = load()
        except ValueError:
            x = None
        else:
            if hvd.rank() == 0:
                hvd.allreduce(x, "only0")
        hvd.allreduce(x, "grad")
    """)


def test_second_unsynced_restore_is_found():
    """Every restore site is audited, not just the first: a later
    restore without a sync is the classic elastic re-init bug."""
    assert "verify-missing-restore-broadcast" in rules_of("""
        import horovod_tpu as hvd
        from horovod_tpu import elastic
        hvd.init()
        state = elastic.ElasticState(step=0)
        ck = hvd.elastic.DurableCheckpointer("/ckpt")
        ck.restore_into(state)
        state.sync()
        hvd.allreduce(g, "g1")
        ck.restore_into(state)
        hvd.allreduce(g, "g2")
    """)


def test_preflight_world_matches_num_proc(tmp_path, capsys):
    """--lint=verify verifies at the job's -np: a group of [0, 1] is
    world-covering at -np 2 (launch allowed) but not at -np 4
    (refused)."""
    import io
    from horovod_tpu.run.run import lint_preflight
    script = tmp_path / "pair.py"
    script.write_text(textwrap.dedent("""
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 1])
        hvd.allreduce(x, "grad", group=g)
    """))
    buf = io.StringIO()
    assert lint_preflight(["python", str(script)], "verify", out=buf,
                          num_proc=2) is True
    buf = io.StringIO()
    assert lint_preflight(["python", str(script)], "verify", out=buf,
                          num_proc=4) is False
    assert "verify-non-member-group-call" in buf.getvalue()


def test_unknown_membership_group_guard_is_clean():
    """The guard docs/LINT.md recommends for implicit mesh groups —
    `if g.rank() >= 0:` — must verify clean even though the
    membership is unknowable statically."""
    assert rules_of("""
        import horovod_tpu as hvd
        hvd.init(model_parallel=2)
        g = hvd.model_group()
        if g.rank() >= 0:
            hvd.allreduce(x, "mg.grad", group=g)
        hvd.allreduce(x, "dp.grad")
    """) == []


def test_short_circuited_collective_is_rank_divergent():
    """A collective behind a rank-decidable short-circuit runs on some
    ranks only — the boolean operands must evaluate lazily."""
    assert "verify-divergent-schedule" in rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() != 0 and bool(hvd.allreduce(x, "only_nonzero")):
            pass
        hvd.allreduce(x, "grad")
    """)


def test_same_members_different_registrations_diverge():
    """Two new_group registrations with identical member lists are two
    distinct runtime groups: one name negotiated under gA by half the
    ranks and gB by the rest is a mixed-group divergence."""
    assert "verify-divergent-schedule" in rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        gA = hvd.new_group([0, 1, 2, 3])
        gB = hvd.new_group([0, 1, 2, 3])
        if hvd.rank() < 2:
            hvd.allreduce(x, "t", group=gA)
        else:
            hvd.allreduce(x, "t", group=gB)
    """)


def test_sharded_mixed_via_helper():
    found = rules_of("""
        import horovod_tpu.jax as hvd_jax
        import horovod_tpu as hvd

        def make_opt(inner):
            if hvd.rank() < 2:
                return hvd_jax.DistributedOptimizer(
                    inner, sharded_update=True)
            return hvd_jax.DistributedOptimizer(inner)

        hvd.init()
        opt = make_opt(inner)
        p = hvd_jax.broadcast_parameters(p, root_rank=0)
        opt.update(g, s, p)
    """)
    assert "verify-mixed-modes" in found


def test_verify_suppression():
    assert rules_of("""
        import horovod_tpu as hvd
        hvd.init()
        g = hvd.new_group([0, 1])
        hvd.allreduce(x, "grad", group=g)  # hvd-lint: disable=verify-non-member-group-call
    """) == []


def test_verify_rules_registered():
    for rule in ("verify-divergent-schedule", "verify-kind-mismatch",
                 "verify-non-member-group-call", "verify-mixed-modes",
                 "verify-missing-restore-broadcast"):
        assert rule in RULES
        assert RULES[rule].default_severity == "error"


def test_syntax_error_left_to_lexical_pass():
    assert verify_source("def broken(:\n", path="x.py") == []
    assert [f.rule for f in lint_source("def broken(:\n")] == \
        ["parse-error"]


# --- CLI / reporters --------------------------------------------------------

def _write(tmp_path, name, source):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return str(path)


def test_cli_verify_exit_codes(tmp_path):
    bad = _write(tmp_path, "bad.py", BAD["verify-non-member-group-call"])
    good = _write(tmp_path, "good.py",
                  GOOD["verify-non-member-group-call"])
    assert lint_main([bad]) == 0          # lexical alone: clean
    assert lint_main(["--verify", bad]) == 1
    assert lint_main(["--verify", good]) == 0
    assert lint_main(["--verify", "--disable",
                      "verify-non-member-group-call", bad]) == 0


def test_cli_verify_json_carries_fingerprint(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", BAD["verify-kind-mismatch"])
    assert lint_main(["--verify", "--format", "json", bad]) == 1
    payload = json.loads(capsys.readouterr().out)
    rules = {f["rule"] for f in payload["findings"]}
    assert "verify-kind-mismatch" in rules
    for f in payload["findings"]:
        assert re.match(r"^[0-9a-f]{16}$", f["fingerprint"])


def test_cli_sarif_format(tmp_path, capsys):
    bad = _write(tmp_path, "bad.py", BAD["verify-divergent-schedule"])
    assert lint_main(["--verify", "--format", "sarif", bad]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == "2.1.0"
    run = payload["runs"][0]
    assert run["tool"]["driver"]["name"] == "hvd-lint"
    results = run["results"]
    assert any(r["ruleId"] == "verify-divergent-schedule"
               for r in results)
    for r in results:
        assert "hvdLintFingerprint/v1" in r["partialFingerprints"]
        region = r["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1
    # every ruleId is declared in the driver's rule table
    declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {r["ruleId"] for r in results} <= declared


def test_fingerprint_survives_line_shift(tmp_path):
    """The suppression/baseline id must not change when unrelated lines
    are inserted above the finding."""
    src = textwrap.dedent(BAD["verify-non-member-group-call"])
    shifted = "# leading comment\n# another\n\n" + src
    a = verify_source(src, path=str(tmp_path / "a.py"))
    b = verify_source(shifted, path=str(tmp_path / "a.py"))
    assert len(a) == len(b) == 1
    assert a[0].line != b[0].line  # the line DID shift...
    fa = fingerprint(a[0], source_lines=src.splitlines())
    fb = fingerprint(b[0], source_lines=shifted.splitlines())
    assert fa == fb               # ...the fingerprint did not


# --- static-vs-runtime e2e --------------------------------------------------

def test_verifier_flags_the_runtime_divergence_script():
    """tests/test_divergence.py proves divergence_worker.py (mode
    cross_stall) hangs-then-errors at RUNTIME via the coordinator's
    digest cross-check; the verifier must prove the same bug BEFORE
    launch. The shipped worker carries intentional suppressions (it is
    the runtime fixture); stripping them restores the finding."""
    path = os.path.join(REPO_ROOT, "tests", "divergence_worker.py")
    with open(path) as fh:
        source = fh.read()
    unsuppressed = source.replace("# hvd-lint: disable", "# stripped")
    findings = verify_source(unsuppressed, path=path)
    rules = [f.rule for f in findings]
    assert "verify-divergent-schedule" in rules, rules
    diverge = [f for f in findings
               if f.rule == "verify-divergent-schedule"][0]
    # Both sides of the divergence are named, like the runtime error.
    assert "diverged.0" in diverge.message
    assert "diverged.1" in diverge.message
    # ...and the suppressed shipped fixture stays quiet (self-lint).
    assert [f.rule for f in verify_source(source, path=path)] == []


# --- native lock-order audit ------------------------------------------------

CYCLE_CC = """
#include <mutex>
class Pool {
 public:
  void Fill() {
    std::lock_guard<std::mutex> lk(mu_a_);
    std::lock_guard<std::mutex> lk2(mu_b_);
  }
  void Drain() {
    std::lock_guard<std::mutex> lk(mu_b_);
    std::lock_guard<std::mutex> lk2(mu_a_);
  }
 private:
  // lockorder: allow(mutex-without-guarded-fields)
  std::mutex mu_a_;
  std::mutex mu_b_;
};
"""

CALL_CYCLE_CC = """
#include <mutex>
class Router {
 public:
  void TakeBoth() {
    std::lock_guard<std::mutex> lk(first_);
    AcquireSecondOnly();
  }
  void AcquireSecondOnly() {
    std::lock_guard<std::mutex> lk(second_);
  }
  void Reversed() {
    std::lock_guard<std::mutex> lk(second_);
    std::lock_guard<std::mutex> lk2(first_);
  }
 private:
  std::mutex first_, second_;  // lockorder: allow(mutex-without-guarded-fields)
};
"""

GUARD_CC = """
#include <mutex>
class Table {
 public:
  int Get() {
    std::lock_guard<std::mutex> lk(mu_);
    return count_;
  }
  void Bump() { count_++; }
  Table() { count_ = 0; }
 private:
  std::mutex mu_;
  int count_ = 0;  // guarded_by(mu_)
};
"""

NESTED_OK_CC = """
#include <mutex>
class Ok {
 public:
  void Consistent() {
    std::lock_guard<std::mutex> lk(mu_a_);
    std::lock_guard<std::mutex> lk2(mu_b_);
  }
  void AlsoConsistent() {
    std::lock_guard<std::mutex> lk(mu_a_);
    std::lock_guard<std::mutex> lk2(mu_b_);
  }
 private:
  std::mutex mu_a_, mu_b_;  // lockorder: allow(mutex-without-guarded-fields)
};
"""


def _lockorder_on(tmp_path, name, source):
    path = tmp_path / name
    path.write_text(source)
    findings, stats = lockorder.analyze_files([str(path)])
    return findings, stats


def test_lockorder_flags_synthetic_cycle(tmp_path):
    findings, stats = _lockorder_on(tmp_path, "cycle.cc", CYCLE_CC)
    assert [f.rule for f in findings] == ["lock-order-cycle"]
    # Both acquisition sites are named (the "both call sites" format).
    assert "Pool::mu_a_ -> Pool::mu_b_" in findings[0].message
    assert "Pool::mu_b_ -> Pool::mu_a_" in findings[0].message
    assert stats["edges"] == 2


def test_lockorder_flags_cycle_through_call(tmp_path):
    findings, _ = _lockorder_on(tmp_path, "call.cc", CALL_CYCLE_CC)
    assert [f.rule for f in findings] == ["lock-order-cycle"]
    assert "calls AcquireSecondOnly" in findings[0].message


def test_lockorder_flags_guarded_field_violation(tmp_path):
    findings, stats = _lockorder_on(tmp_path, "guard.cc", GUARD_CC)
    assert [f.rule for f in findings] == ["guarded-field-unlocked"]
    assert "Table::count_" in findings[0].message
    assert stats["guarded_fields"] == 1
    # the constructor's unlocked init is exempt: exactly ONE finding
    assert len(findings) == 1


def test_lockorder_consistent_order_is_clean(tmp_path):
    findings, stats = _lockorder_on(tmp_path, "ok.cc", NESTED_OK_CC)
    assert findings == []
    assert stats["edges"] == 1


def test_lockorder_native_tree_is_clean():
    """`make check-lockorder` over the real native core: clean, with a
    meaningful amount audited (acquisitions scanned, annotated fields
    covered)."""
    native = os.path.join(REPO_ROOT, "horovod_tpu", "native")
    files = list(lockorder.iter_sources([native]))
    assert len(files) > 30
    findings, stats = lockorder.analyze_files(files)
    assert findings == [], "\n".join(
        "%s:%d %s" % (f.path, f.line, f.message) for f in findings)
    assert stats["functions"] > 300
    assert stats["guarded_fields"] >= 7


def test_lockorder_cli(tmp_path, capsys):
    path = tmp_path / "cycle.cc"
    path.write_text(CYCLE_CC)
    assert lockorder.main([str(path)]) == 1
    out = capsys.readouterr().out
    assert "lock-order-cycle" in out
    ok = tmp_path / "ok.cc"
    ok.write_text(NESTED_OK_CC)
    assert lockorder.main([str(ok)]) == 0
