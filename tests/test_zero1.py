"""ZeRO-1 optimizer-state sharding: numerically identical to plain DP
for elementwise optimizers, with per-device optimizer state n-fold
smaller (reduce_scatter grads -> shard-local update -> all_gather
params)."""

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

jax.config.update("jax_default_matmul_precision", "highest")

from horovod_tpu.parallel import data_parallel_mesh, make_train_step  # noqa: E402


def _problem():
    rng = np.random.RandomState(0)
    params = {
        "w": jnp.asarray(rng.randn(13, 7).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(7).astype(np.float32)),
        "scalarish": jnp.asarray(rng.randn(3).astype(np.float32)),
    }
    x = jnp.asarray(rng.randn(32, 13).astype(np.float32))
    y = jnp.asarray(rng.randn(32, 7).astype(np.float32))

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"] + \
            jnp.sum(params["scalarish"] ** 2)
        return jnp.mean((pred - batch["y"]) ** 2)

    return params, {"x": x, "y": y}, loss_fn


def test_zero1_matches_plain_dp_adam():
    """3 Adam steps: zero1 params == plain params (the odd-sized leaves
    13x7 / 7 / 3 exercise the flatten+pad path on 8 shards)."""
    params, batch, loss_fn = _problem()
    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    opt = optax.adam(1e-2)

    plain = make_train_step(loss_fn, opt, mesh, donate=False)
    p1, s1, b1 = plain.place(params, opt.init(params), batch)
    z = make_train_step(loss_fn, opt, mesh, donate=False, zero1=True)
    p2, s2, b2 = z.place(params, None, batch)

    for _ in range(3):
        p1, s1, loss1 = plain(p1, s1, b1)
        p2, s2, loss2 = z(p2, s2, b2)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    for k in params:
        np.testing.assert_allclose(np.asarray(p2[k]), np.asarray(p1[k]),
                                   rtol=2e-5, atol=2e-5, err_msg=k)


def test_zero1_state_is_sharded():
    """Each device holds 1/n of every Adam moment (the memory claim),
    and the moment shards match a replicated run's moments."""
    params, batch, loss_fn = _problem()
    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    n = len(jax.devices("cpu"))
    opt = optax.adam(1e-2)
    z = make_train_step(loss_fn, opt, mesh, donate=False, zero1=True)
    p, s, b = z.place(params, None, batch)

    mu = s[0].mu
    for k, leaf in mu.items():
        total = int(np.prod(params[k].shape))
        padded = total + (-total) % n
        assert leaf.shape == (padded,), (k, leaf.shape)
        assert leaf.sharding.spec == P("hvd"), (k, leaf.sharding.spec)
        shard_bytes = leaf.addressable_shards[0].data.size
        assert shard_bytes == padded // n

    p, s, _ = z(p, s, b)
    # Moments equal the full-tree Adam moments, flattened+padded.
    plain = make_train_step(loss_fn, opt, mesh, donate=False)
    p1, s1, b1 = plain.place(params, opt.init(params), batch)
    p1, s1, _ = plain(p1, s1, b1)
    for k in params:
        full = np.zeros(int(np.prod(params[k].shape)) +
                        (-int(np.prod(params[k].shape))) % n, np.float32)
        full[:params[k].size] = np.asarray(s1[0].mu[k]).ravel()
        np.testing.assert_allclose(np.asarray(s[0].mu[k]), full,
                                   rtol=2e-5, atol=1e-7, err_msg=k)


def test_zero1_rejects_compression():
    import pytest

    from horovod_tpu import jax as hvd_jax

    params, batch, loss_fn = _problem()
    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    with pytest.raises(ValueError, match="legacy codec"):
        make_train_step(loss_fn, optax.sgd(0.1), mesh, zero1=True,
                        compression=hvd_jax.Compression.fp16)
