"""Chunked LM cross entropy: identical values and gradients to the
dense log_softmax form (the streaming loss is a memory optimization,
not an approximation)."""

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_default_matmul_precision", "highest")

from horovod_tpu.ops.losses import chunked_softmax_cross_entropy  # noqa: E402


def _dense_loss(hidden, kernel, targets):
    logits = (hidden @ kernel).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(
        logp, targets[..., None], axis=-1))


def test_chunked_xent_matches_dense_values_and_grads():
    B, L, D, V = 2, 64, 16, 50
    rng = np.random.RandomState(0)
    hidden = jnp.asarray(rng.randn(B, L, D), jnp.float32)
    kernel = jnp.asarray(rng.randn(D, V) * 0.1, jnp.float32)
    targets = jnp.asarray(rng.randint(0, V, (B, L)))

    for chunk in (16, 32, 64):
        loss = chunked_softmax_cross_entropy(hidden, kernel, targets,
                                             chunk=chunk)
        dense = _dense_loss(hidden, kernel, targets)
        np.testing.assert_allclose(float(loss), float(dense), rtol=1e-6)

    g_c = jax.grad(
        lambda h, k: chunked_softmax_cross_entropy(h, k, targets,
                                                   chunk=16),
        argnums=(0, 1))(hidden, kernel)
    g_d = jax.grad(_dense_loss, argnums=(0, 1))(hidden, kernel, targets)
    for got, exp in zip(g_c, g_d):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=1e-5, atol=1e-6)


def test_chunked_xent_rejects_indivisible_chunk():
    hidden = jnp.zeros((1, 10, 4))
    kernel = jnp.zeros((4, 7))
    targets = jnp.zeros((1, 10), jnp.int32)
    try:
        chunked_softmax_cross_entropy(hidden, kernel, targets, chunk=3)
    except ValueError as e:
        assert "divisible" in str(e)
    else:
        raise AssertionError("expected ValueError")
