"""Launcher unit tests: host parsing, slot allocation (rank/local/cross
topology), hostfile parsing. Reference analogue: the allocation logic of
gloo_run.py:51-109."""

import pytest

from horovod_tpu.run import util


def test_parse_hosts():
    hosts = util.parse_hosts("a:2,b:3,c")
    assert [(h.hostname, h.slots) for h in hosts] == [("a", 2), ("b", 3),
                                                      ("c", 1)]


def test_parse_hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text("hosta slots=2\n# comment\nhostb slots=4\nhostc\n")
    hosts = util.parse_hostfile(str(f))
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("hosta", 2), ("hostb", 4), ("hostc", 1)]


def test_allocate_slots_single_host():
    slots = util.allocate_slots(util.parse_hosts("localhost:4"), 4)
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [s.local_rank for s in slots] == [0, 1, 2, 3]
    assert all(s.local_size == 4 for s in slots)
    assert all(s.cross_size == 1 for s in slots)
    assert all(s.cross_rank == 0 for s in slots)


def test_allocate_slots_two_hosts():
    slots = util.allocate_slots(util.parse_hosts("a:2,b:2"), 4)
    assert [(s.hostname, s.rank, s.local_rank, s.cross_rank) for s in slots] \
        == [("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)]
    assert all(s.local_size == 2 and s.cross_size == 2 for s in slots)


def test_allocate_heterogeneous():
    slots = util.allocate_slots(util.parse_hosts("a:1,b:2"), 3)
    assert [(s.hostname, s.local_rank, s.local_size) for s in slots] == [
        ("a", 0, 1), ("b", 0, 2), ("b", 1, 2)]
    # local_rank 1 exists only on b.
    assert slots[2].cross_size == 1 and slots[2].cross_rank == 0


def test_allocate_too_many():
    with pytest.raises(ValueError):
        util.allocate_slots(util.parse_hosts("a:1"), 2)


def test_reserve_port_valid():
    from horovod_tpu.run import rendezvous
    ports = {rendezvous.reserve_port() for _ in range(4)}
    assert all(0 < p < 65536 for p in ports)


def test_reference_capability_probes():
    """Migration shims (reference basics.py:117-191): gloo-role probes
    track the TCP build; MPI/NCCL-family probes are honestly False."""
    import horovod_tpu as hvd
    assert hvd.gloo_built() and hvd.gloo_enabled()
    assert not hvd.mpi_built() and not hvd.mpi_enabled()
    assert not hvd.mpi_threads_supported()
    assert not hvd.nccl_built() and not hvd.ddl_built() \
        and not hvd.mlsl_built()


def test_ssh_remote_branch_e2e():
    """Drives the launcher's REMOTE branch end to end (ssh fan-out,
    connect-back preflight, stdin secret piping, env-export filter,
    remote middleman wrapping) with a fake ssh that execs locally —
    two fake "hosts", one slot each, running the real distributed
    collective worker (reference analogue: run/run.py:109-186 remote
    launch + test/test_run.py's mocked-shell strategy)."""
    import os
    import pathlib
    import subprocess
    import sys

    from conftest import clean_worker_env

    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    fake_ssh = os.path.join(repo_root, "tests", "fake_ssh.py")
    worker = os.path.join(repo_root, "tests", "distributed_ops_worker.py")
    env = clean_worker_env({
        "HVD_TPU_SSH_CMD": "%s %s" % (sys.executable, fake_ssh),
        "HVD_TPU_REMOTE_PYTHON": sys.executable,
    })
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "2",
         "-H", "fakehost-a:1,fakehost-b:1", "--",
         sys.executable, worker],
        env=env, timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


def test_check_build_matrix():
    """`horovodrun_tpu --check-build` prints the capability matrix with
    every data plane and kernel row this build provides (reference:
    run.py:262-298)."""
    import subprocess
    import sys

    from conftest import clean_worker_env

    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "--check-build"],
        env=clean_worker_env(), timeout=240, capture_output=True,
        text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    for row in ("[X] JAX", "[X] PyTorch", "[X] TensorFlow",
                "[X] TCP (dynamic rendezvous)",
                "[X] CPU (TCP ring + hierarchical)",
                "[X] XLA/ICI (in-jit)",
                "[X] Torch C-extension glue (zero-copy)",
                "[X] flash attention / ring attention",
                "[X] fused BatchNorm statistics"):
        assert row in out, (row, out)


def test_preflight_cache_roundtrip(tmp_path):
    """The on-disk host-check cache (reference run/util/cache.py):
    fresh-entry hit, TTL expiry miss, parameters-hash invalidation,
    and corrupt-file self-heal."""
    from horovod_tpu.run.cache import Cache

    c = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p1")
    assert c.get("ssh://a") is None
    c.put("ssh://a", True)
    assert c.get("ssh://a") is True
    # Same params, new instance: persisted.
    c2 = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p1")
    assert c2.get("ssh://a") is True
    # Different params: whole store invalidated.
    c3 = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p2")
    assert c3.get("ssh://a") is None
    # TTL zero: entries immediately stale.
    c4 = Cache(str(tmp_path), staleness_minutes=0, parameters_hash="p1")
    c4.put("ssh://b", True)
    import time as _t
    _t.sleep(0.01)
    assert c4.get("ssh://b") is None
    # Corrupt file self-heals to empty.
    (tmp_path / "cache.json").write_text("{not json")
    c5 = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p1")
    assert c5.get("ssh://a") is None
    c5.put("ssh://a", True)
    assert c5.get("ssh://a") is True


def test_preflight_cache_put_merges_concurrent_writers(tmp_path):
    """Two launchers sharing one cache file: a put() merges the on-disk
    entries written since load instead of clobbering them."""
    from horovod_tpu.run.cache import Cache

    c1 = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p")
    c2 = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p")
    c1.put("ssh://a", True)
    c2.put("ssh://b", True)  # must not wipe c1's entry
    c3 = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p")
    assert c3.get("ssh://a") is True
    assert c3.get("ssh://b") is True


def test_preflight_cache_put_prunes_expired(tmp_path):
    """Expired entries are dropped at write time (they already read as
    misses; pruning keeps the file from growing forever)."""
    import json

    from horovod_tpu.run.cache import Cache

    c = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p")
    c.put("ssh://old", True)
    # Age the entry on disk beyond the TTL, then trigger a new put.
    path = tmp_path / "cache.json"
    content = json.loads(path.read_text())
    content["entries"]["ssh://old"][0] -= 3601.0
    path.write_text(json.dumps(content))
    c2 = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p")
    c2.put("ssh://new", True)
    stored = json.loads(path.read_text())["entries"]
    assert "ssh://new" in stored and "ssh://old" not in stored


def test_preflight_cache_put_best_effort(tmp_path):
    """A cache directory that turns unwritable after construction must
    not raise from put() — the cache only saves re-probing."""
    import os
    import stat

    from horovod_tpu.run.cache import Cache

    c = Cache(str(tmp_path), staleness_minutes=60, parameters_hash="p")
    if os.geteuid() == 0:
        # Root ignores mode bits; simulate the failure by replacing the
        # folder with a file so open(tmp) raises instead.
        import shutil
        shutil.rmtree(str(tmp_path))
        (tmp_path.parent / tmp_path.name).write_text("not a dir")
    else:
        os.chmod(str(tmp_path), stat.S_IRUSR | stat.S_IXUSR)
    c.put("ssh://a", True)  # must not raise
    assert c.get("ssh://a") is True  # still served from memory


def test_ssh_preflight_uses_cache(tmp_path, monkeypatch):
    """A cached success skips the probe subprocess entirely; a cache
    miss probes and records the success (only successes are stored —
    failures re-probe next run)."""
    import sys as _sys

    from horovod_tpu.run.cache import Cache
    from horovod_tpu.run.run import ssh_preflight

    calls = tmp_path / "calls"
    calls.mkdir()
    probe_script = tmp_path / "counting_ssh.py"
    probe_script.write_text(
        "import os, sys, uuid\n"
        "open(os.path.join(%r, str(uuid.uuid4())), 'w').close()\n"
        "sys.exit(0)\n" % str(calls))
    monkeypatch.setenv("HVD_TPU_SSH_CMD",
                       "%s %s" % (_sys.executable, probe_script))

    cache = Cache(str(tmp_path / "store"), staleness_minutes=60,
                  parameters_hash="t")
    ssh_preflight(["hostA", "hostB"], fn_cache=cache)
    assert len(list(calls.iterdir())) == 2
    assert cache.get("ssh://hostA") and cache.get("ssh://hostB")
    # Second preflight: fully served from cache, zero probes.
    ssh_preflight(["hostA", "hostB"], fn_cache=cache)
    assert len(list(calls.iterdir())) == 2
    # A new host probes; the cached two still don't.
    ssh_preflight(["hostA", "hostB", "hostC"], fn_cache=cache)
    assert len(list(calls.iterdir())) == 3
