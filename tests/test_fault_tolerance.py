"""Failure fan-out e2e: when one rank dies, the launcher must kill the
survivors and report failure promptly (reference: run.py's
one-failed-rank teardown; SURVEY §5.3 failure-detection obligations)."""

import time

import pytest

pytestmark = pytest.mark.e2e


def test_worker_crash_tears_down_job(run_launcher):
    t0 = time.monotonic()
    # Tight stall timers so the survivors' pending collective is also
    # bounded if teardown were to miss them.
    result = run_launcher(3, "crash_worker.py", extra_env={
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "5",
        "HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS": "60",
    }, timeout=120)
    elapsed = time.monotonic() - t0
    assert result.returncode != 0, "job must fail when a rank dies"
    assert "rank 1 crashing now" in result.stdout
    # Teardown must come from the launcher's failure fan-out (seconds),
    # not from the workers' own 300s sleep or the stall shutdown.
    assert elapsed < 60, "teardown took %.0fs - failure fan-out broken" \
        % elapsed
