"""Failure fan-out e2e: when one rank dies, the launcher must kill the
survivors, report failure promptly, and name the root cause — the
first-failing rank, its exit status, and its tee'd log (reference:
run.py's one-failed-rank teardown; SURVEY §5.3 failure-detection
obligations)."""

import os
import re
import time

import pytest

pytestmark = pytest.mark.e2e


def test_worker_crash_tears_down_job(run_launcher):
    t0 = time.monotonic()
    # Stall shutdown is pushed OUT to 240s so it cannot be what ends the
    # job: within the 120s subprocess budget, only the launcher's
    # failure fan-out can terminate the 300s-sleeping survivors. (An
    # earlier version asserted elapsed < 60 with a 60s stall shutdown,
    # which was flaky under parallel-suite load: worker startup alone
    # can eat tens of seconds.)
    result = run_launcher(3, "crash_worker.py", extra_env={
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "30",
        "HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS": "240",
    }, timeout=120)
    elapsed = time.monotonic() - t0
    assert result.returncode != 0, "job must fail when a rank dies"
    assert "rank 1 crashing now" in result.stdout
    assert elapsed < 115, "teardown took %.0fs - failure fan-out broken" \
        % elapsed

    # Failure summary: the launcher must name the FIRST failing rank
    # (the root cause — rank 1, which crashed — not the teardown
    # collateral), its exit status, and the tee'd per-rank log, which
    # must contain that rank's output.
    m = re.search(r"first failing rank was rank (\d+) \(([^)]*)\); "
                  r"worker log: (\S+)", result.stderr)
    assert m, result.stderr
    assert m.group(1) == "1", result.stderr
    assert "exit code" in m.group(2) or "killed by" in m.group(2)
    log_path = m.group(3)
    assert os.path.exists(log_path), result.stderr
    with open(log_path) as f:
        assert "rank 1 crashing now" in f.read()


def test_torch_cext_crash_surfaces_error(run_launcher):
    """Peer failure through the C-extension zero-copy path: the
    surviving rank's in-flight allreduce raises HorovodInternalError
    via cext wait (or launcher teardown) — no hang, no silent
    success."""
    t0 = time.monotonic()
    result = run_launcher(3, "torch_crash_worker.py", extra_env={
        "HVD_TPU_REQUIRE_CEXT": "1",
        "HVD_TPU_STALL_CHECK_TIME_SECONDS": "30",
        "HVD_TPU_STALL_SHUTDOWN_TIME_SECONDS": "240",
    }, timeout=120)
    elapsed = time.monotonic() - t0
    assert result.returncode != 0, "job must fail when a rank dies"
    assert "rank 1 crashing now" in result.stdout
    assert elapsed < 115, "teardown took %.0fs" % elapsed
