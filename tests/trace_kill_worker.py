"""Flight-recorder e2e worker: rank HVD_TPU_KILL_RANK SIGKILLs itself —
no cleanup, no goodbye frame — while the survivors are mid-negotiation
on the "doomed" tensor. Every survivor must leave a post-mortem bundle
(HVD_TPU_BUNDLE_DIR): the coordinator's via the connection-lost dump
(pending table naming the missing rank and the in-flight tensor), the
rest via connection-lost cascade or the launcher-teardown SIGTERM hook.
With HVD_TPU_TIMELINE set, the test also proves rank 0's timeline file
is a complete JSON array afterwards (the emergency-finalize hook)."""

import os
import signal
import sys
import time

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    kill_rank = int(os.environ.get("HVD_TPU_KILL_RANK", "1"))
    assert 0 < kill_rank < n, "kill a NON-zero rank (timeline lives on 0)"

    out = hvd.allreduce(np.ones(4, np.float32), "pre_kill")
    assert np.allclose(out, n), out

    if r == kill_rank:
        # A beat so the survivors get "doomed" into the coordinator's
        # pending table first — the bundle must name it as in-flight.
        time.sleep(1.0)
        print("rank %d: SIGKILL now" % r, flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    try:
        hvd.allreduce(np.ones(4, np.float32), "doomed")
    except Exception as e:
        print("rank %d: collective failed after kill: %s" % (r, e),
              flush=True)
        return 1
    # The collective can never complete; wait for the launcher teardown
    # (its SIGTERM is itself a bundle trigger) instead of exiting on our
    # own, which would make the survivor-bundle assertion vacuous.
    time.sleep(300)
    return 0


if __name__ == "__main__":
    sys.exit(main())
