"""Serve-churn smoke for the sanitizer gates (`make check-tsan` /
`check-asan` in native/Makefile; docs/SERVE.md).

A 2-replica serve pool under seeded open-loop load, churned with the
two events the serving plane must absorb without lying to a client:

* a seeded SIGKILL of one replica mid-request (the elastic driver
  respawns it; the client re-queues to the survivor), and
* a CONCURRENT rolling weight swap (a newer durable checkpoint lands
  while the kill is being absorbed).

The invariant is the serving contract end to end: every request gets a
correct answer — verified against the numpy forward of the weight set
its response fingerprint names — or a prompt cause-named error; never
a hang, never a wrong answer, never a silent drop. Exits 0 iff the
contract held and the pool drained to EXIT_DRAINED.

Usage::

    python tests/serve_churn.py [--preload LIBSAN.SO] [ENV=VALUE...]

``--preload`` prefixes the REPLICA command with ``env LD_PRELOAD=...``
(plus any trailing ENV=VALUE args, e.g. TSAN_OPTIONS) — the sanitizer
runtime must be preloaded into the replica pythons only; the
supervisor/driver process forks and stays unpreloaded (see the
Makefile's launch notes).
"""

import os
import random
import signal
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

from horovod_tpu.elastic.state import EXIT_DRAINED  # noqa: E402
from horovod_tpu.serve import model as smodel  # noqa: E402
from horovod_tpu.serve.loadgen import run_load  # noqa: E402
from horovod_tpu.serve.supervisor import ServeSupervisor  # noqa: E402
from horovod_tpu.serve.swap import publish_leaves  # noqa: E402

DIM = 8
SEED = 31


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    preload, extra_env = "", []
    while argv:
        arg = argv.pop(0)
        if arg == "--preload":
            preload = argv.pop(0)
        elif "=" in arg:
            extra_env.append(arg)
        else:
            sys.stderr.write(__doc__)
            return 2

    command = []
    if preload:
        command += ["env", "LD_PRELOAD=%s" % preload] + extra_env
    command += [sys.executable, "-m", "horovod_tpu.serve.replica"]

    ckpt = tempfile.mkdtemp(prefix="hvd-serve-churn-")
    old = smodel.init_leaves("affine", DIM, seed=1)
    new = smodel.init_leaves("affine", DIM, seed=2)
    crc_old, crc_new = smodel.fingerprint(old), smodel.fingerprint(new)
    publish_leaves(ckpt, 10, old)

    rng = random.Random(SEED)
    port_base = rng.randint(21000, 55000)
    env = dict(os.environ)
    env.update({
        "HVD_TPU_SERVE_JIT": "0",
        "HVD_TPU_SERVE_MODEL": "affine",
        "HVD_TPU_SERVE_DIM": str(DIM),
        "HVD_TPU_SERVE_PORT": str(port_base),
        "HVD_TPU_SERVE_SWAP_INTERVAL": "0.1",
        "HVD_TPU_SERVE_SWAP_STAGGER": "0.3",
        "HVD_TPU_CKPT_DIR": ckpt,
    })
    # A SIGKILLed replica must respawn within the churn window.
    os.environ["HVD_TPU_ELASTIC_COOLDOWN"] = "1"

    sup = ServeSupervisor(command, {"localhost": 2}, min_replicas=1,
                          max_replicas=2, np_initial=2,
                          port_base=port_base, env=env, verbose=True)
    rc_box = {}
    thread = threading.Thread(
        target=lambda: rc_box.update(
            rc=sup.driver.run(install_signal_handlers=False)),
        daemon=True)
    thread.start()

    def healthy():
        return sum(1 for v in sup.replica_views(timeout=1.0)
                   if v.get("state") == "serving")

    deadline = time.monotonic() + 60
    while healthy() < 2:
        if time.monotonic() > deadline:
            sys.stderr.write("serve_churn: pool never became healthy\n")
            return 1
        time.sleep(0.2)
    print("serve_churn: 2 replicas serving on ports %d-%d"
          % (port_base, port_base + 1))

    by_crc = {crc_old: old, crc_new: new}
    result_box = {}

    def load():
        result_box["r"], result_box["wall"] = run_load(
            sup.endpoints, rate=25, duration=6.0, dim=DIM, seed=SEED,
            leaves_by_crc=by_crc, workers=4, total_deadline=15.0)

    loader = threading.Thread(target=load)
    loader.start()

    # Churn event 1 (seeded): SIGKILL one replica mid-request.
    time.sleep(1.5)
    victim = rng.choice(sup.driver.live_workers())
    pid = sup.driver.worker_pid(victim)
    print("serve_churn: SIGKILL replica %d (pid %d)" % (victim, pid))
    os.kill(pid, signal.SIGKILL)

    # Churn event 2, CONCURRENT with the kill's absorption: a newer
    # checkpoint lands and the rolling swap flips the survivors.
    time.sleep(0.5)
    publish_leaves(ckpt, 20, new)
    print("serve_churn: published step 20 (weights %s)" % crc_new)

    loader.join(timeout=120)
    if loader.is_alive():
        sys.stderr.write("serve_churn: load generator hung\n")
        return 1
    res = result_box["r"]
    total = res.ok + len(res.errors)
    print("serve_churn: %d ok, %d errors, %d mismatches, by_crc=%s"
          % (res.ok, len(res.errors), len(res.mismatches),
             dict(res.by_crc)))
    if res.mismatches:
        sys.stderr.write("serve_churn: WRONG ANSWERS: %s\n"
                         % res.mismatches[:5])
        return 1
    if total != 150:
        sys.stderr.write("serve_churn: %d/150 requests unaccounted "
                         "for (silent drop)\n" % (150 - total))
        return 1
    bad = [e for e in res.errors
           if e[1] not in ("replica-lost", "draining", "overload",
                           "deadline")]
    if bad:
        sys.stderr.write("serve_churn: unnamed failure causes: %s\n"
                         % bad[:5])
        return 1
    if res.ok < 120:
        sys.stderr.write("serve_churn: only %d/150 answered — the "
                         "pool did not absorb the churn\n" % res.ok)
        return 1
    if res.by_crc.get(crc_new, 0) < 1:
        sys.stderr.write("serve_churn: no response carried the swapped "
                         "weights %s (by_crc=%s)\n"
                         % (crc_new, dict(res.by_crc)))
        return 1

    sup.driver.request_drain("all")
    thread.join(timeout=90)
    if thread.is_alive():
        sys.stderr.write("serve_churn: drain hung\n")
        return 1
    if rc_box.get("rc") != EXIT_DRAINED:
        sys.stderr.write("serve_churn: driver rc %r (want EXIT_DRAINED "
                         "%d)\n" % (rc_box.get("rc"), EXIT_DRAINED))
        return 1
    print("serve_churn: contract held through kill + concurrent swap; "
          "pool drained clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
