"""Autotune A/B worker: steady-state throughput of a gradient-bucket
workload under whatever knob env the caller set.

One "step" allreduces AB_TENSORS gradients of AB_ELEMS f32 each (the
many-small-tensors shape where fusion and cycle pacing actually govern
throughput — reference rationale: parameter_manager score = bytes/us,
`/root/reference/horovod/common/parameter_manager.cc:136-160`). In
autotune mode (HVD_TPU_AUTOTUNE=1) the worker first trains until the
tuner converges (`autotune_params()["active"]` goes False — the
coordinator adopts the best knobs and re-syncs every rank), so the
measured window is steady state under the TUNED knobs, not the
sampling transient. Rank 0 prints one `AB_RESULT {json}` line.
"""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    r = hvd.rank()
    k = int(os.environ.get("AB_TENSORS", "48"))
    elems = int(os.environ.get("AB_ELEMS", "32768"))  # 128 KB each
    grads = [np.full(elems, float(i % 7), np.float32) for i in range(k)]
    names = ["ab.layer%03d.grad" % i for i in range(k)]

    def step():
        hs = [hvd.allreduce_async(g, nm) for g, nm in zip(grads, names)]
        for h in hs:
            hvd.synchronize(h)

    tune_steps = 0
    if os.environ.get("HVD_TPU_AUTOTUNE") == "1":
        deadline = time.time() + float(
            os.environ.get("AB_TUNE_TIMEOUT", "300"))
        max_steps = int(os.environ.get("AB_TUNE_MAX_STEPS", "0"))
        while True:
            step()
            tune_steps += 1
            # Every rank must exit this loop at the SAME step: the
            # `active` flip reaches ranks at different cycle
            # boundaries (and per-rank deadlines skew), and ranks
            # leaving at different counts desynchronize the collective
            # sequence (shutdown error / hang). Rank 0 alone decides —
            # converged (its tuner view is canonical), step-capped, or
            # timed out — and broadcasts one verdict per step.
            verdict = 1.0
            if r == 0:
                if not hvd.get_basics().autotune_params()["active"]:
                    verdict = 0.0
                elif max_steps and tune_steps >= max_steps:
                    verdict = 0.0
                elif time.time() > deadline:
                    verdict = -1.0
            verdict = float(hvd.broadcast(
                np.array([verdict]), 0,
                "ab.tune_verdict.%d" % tune_steps)[0])
            if verdict == 0.0:
                break
            if verdict < 0.0:
                print("AUTOTUNE_TIMEOUT after %d steps" % tune_steps)
                return 1
    else:
        for _ in range(20):
            step()

    iters = int(os.environ.get("AB_ITERS", "80"))
    # Job-total CPU per step alongside wall steps/s: on an oversubscribed
    # 1-core host, wall clock measures the hypervisor (steal/scheduler
    # modes swing runs +/-15%) while CPU time measures the framework —
    # same rationale as the negotiation microbench's rusage window.
    import resource
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    dt = time.perf_counter() - t0
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    cpu_s = ((ru1.ru_utime - ru0.ru_utime) +
             (ru1.ru_stime - ru0.ru_stime))
    job_cpu_s = float(hvd.allreduce(np.array([cpu_s], np.float64),
                                    "ab.cpu_total", average=False)[0])
    bytes_per_step = k * elems * 4
    if r == 0:
        out = {
            "steps_per_s": round(iters / dt, 2),
            "ms_per_step": round(dt / iters * 1e3, 3),
            "cpu_ms_per_step_job": round(job_cpu_s / iters * 1e3, 3),
            "mb_per_step": round(bytes_per_step / 1e6, 3),
            "bytes_per_us": round(bytes_per_step * iters / (dt * 1e6), 2),
            "tune_steps": tune_steps,
            "params": hvd.get_basics().autotune_params(),
        }
        print("AB_RESULT %s" % json.dumps(out))
    print("rank %d done" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
