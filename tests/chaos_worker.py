"""Chaos e2e worker: a stream of allreduces with known-correct expected
values, run under an injected transport fault (HVD_TPU_FAULT_SPEC set by
the test). The contract being proved (docs/CHAOS.md):

* every synchronize() that RETURNS returned the numerically correct
  result — an injected corrupt frame may abort the op but must never
  produce wrong gradients;
* when the transport dies, the error is the recoverable connection-lost
  kind and NAMES a transport-level cause;
* with a recoverable fault (control close + reconnect), the whole
  stream completes and the job exits 0.

Prints "chaos: connection lost surfaced cleanly" and exits 0 when the
fault surfaced as the expected error, so the test can distinguish a
clean detected failure from a crash or a silent wrong answer.
"""

import os
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.ops import HorovodInternalError


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    steps = int(os.environ.get("HVD_TPU_CHAOS_STEPS", "30"))
    expect_failure = os.environ.get("HVD_TPU_CHAOS_EXPECT_FAILURE") == "1"

    completed = 0
    try:
        for i in range(steps):
            # 64 KiB per step so corrupt/close faults land mid-payload,
            # not only in tiny headers.
            arr = np.full((128, 128), float(r + 1 + i), np.float32)
            out = ops.synchronize(
                ops.allreduce_async(arr, "chaos.%d" % i))
            expected = sum(rr + 1 + i for rr in range(n))
            # THE invariant: a result that comes back is correct. A
            # corrupted frame must be a detected error, never this
            # assert firing.
            assert np.allclose(out, expected), (
                "SILENT CORRUPTION at step %d: got %r want %r"
                % (i, out.flat[0], expected))
            completed += 1
    except HorovodInternalError as e:
        msg = str(e)
        print("rank %d failed at step %d: %s" % (r, completed, msg),
              flush=True)
        assert "connection" in msg.lower(), (
            "transport fault surfaced as the wrong error: %s" % msg)
        print("chaos: connection lost surfaced cleanly", flush=True)
        return 0
    print("rank %d completed all %d steps" % (r, steps), flush=True)
    if expect_failure:
        # The fault spec should have killed this stream; finishing means
        # the injection missed — fail loudly so the test's spec gets
        # fixed rather than silently passing.
        print("chaos: expected a transport failure but none occurred",
              flush=True)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
