"""Autotune coverage: the Bayesian-optimization math (unit) and a live
HVD_TPU_AUTOTUNE=1 job (e2e). Reference semantics: ParameterManager
warmup/sample/score flow (`/root/reference/horovod/common/parameter_manager.cc:27-30`)
+ BayesianOptimization (`common/optim/bayesian_optimization.cc`)."""

import ctypes
import json
import os
import re

import numpy as np
import pytest

from horovod_tpu.common import get_basics

FUSION_LO, FUSION_HI = 0.0, 64.0
CYCLE_LO, CYCLE_HI = 1.0, 100.0


def _bo(lo0, hi0, lo1, hi1, seed):
    lib = get_basics().lib
    lib.horovod_tpu_bo_create.restype = ctypes.c_void_p
    lib.horovod_tpu_bo_create.argtypes = [
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_uint64]
    lib.horovod_tpu_bo_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double)]
    lib.horovod_tpu_bo_add.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_double]
    lib.horovod_tpu_bo_best.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double)]
    lib.horovod_tpu_bo_destroy.argtypes = [ctypes.c_void_p]
    return lib, lib.horovod_tpu_bo_create(lo0, hi0, lo1, hi1, seed)


def test_bayesian_optimizer_finds_optimum_2d():
    """EI over the GP surrogate must localize the optimum of a smooth
    2-D function within the sample budget the autotuner actually uses
    (kSamplesPerCombo=10 per categorical combo, up to kMaxSamples=40) —
    and never propose points outside the bounds."""
    lib, bo = _bo(FUSION_LO, FUSION_HI, CYCLE_LO, CYCLE_HI, seed=7)
    opt_x, opt_y = 20.0, 70.0

    def f(x, y):
        return -((x - opt_x) / (FUSION_HI - FUSION_LO)) ** 2 \
            - ((y - opt_y) / (CYCLE_HI - CYCLE_LO)) ** 2

    try:
        pt = (ctypes.c_double * 2)()
        for _ in range(25):
            lib.horovod_tpu_bo_next(bo, pt)
            x, y = pt[0], pt[1]
            assert FUSION_LO <= x <= FUSION_HI, x
            assert CYCLE_LO <= y <= CYCLE_HI, y
            lib.horovod_tpu_bo_add(bo, pt, f(x, y))
        best_y = ctypes.c_double()
        lib.horovod_tpu_bo_best(bo, pt, ctypes.byref(best_y))
        # Within ~15% of each axis of the true optimum, and a function
        # value close to the max of 0.
        assert abs(pt[0] - opt_x) < 0.15 * (FUSION_HI - FUSION_LO), pt[0]
        assert abs(pt[1] - opt_y) < 0.15 * (CYCLE_HI - CYCLE_LO), pt[1]
        assert best_y.value > -0.05, best_y.value
    finally:
        lib.horovod_tpu_bo_destroy(bo)


def test_bayesian_optimizer_survives_many_samples():
    """100 samples (beyond kMaxSamples) on a noisy constant function:
    the Cholesky must stay finite (no NaN proposals) even with
    near-duplicate inputs."""
    lib, bo = _bo(FUSION_LO, FUSION_HI, CYCLE_LO, CYCLE_HI, seed=3)
    rng = np.random.RandomState(0)
    try:
        pt = (ctypes.c_double * 2)()
        for i in range(100):
            lib.horovod_tpu_bo_next(bo, pt)
            assert np.isfinite(pt[0]) and np.isfinite(pt[1]), (i, pt[0],
                                                              pt[1])
            assert FUSION_LO <= pt[0] <= FUSION_HI
            assert CYCLE_LO <= pt[1] <= CYCLE_HI
            lib.horovod_tpu_bo_add(bo, pt, 1.0 + 1e-3 * rng.randn())
    finally:
        lib.horovod_tpu_bo_destroy(bo)


@pytest.mark.e2e
def test_autotune_e2e(run_launcher, tmp_path):
    """A 2-rank job with autotuning live: collectives must stay correct
    while the coordinator re-tunes fusion/cycle/cache knobs under the
    running job (cross-rank agreement is implicit — a desynchronized
    cache or fusion config deadlocks negotiation and the run times
    out), the CSV log must be well-formed with >= warmup + 2 samples,
    and every sampled/final knob must lie inside the search bounds."""
    log = tmp_path / "autotune.csv"
    proc = run_launcher(2, "autotune_worker.py",
                        extra_env={"HVD_TPU_AUTOTUNE": "1",
                                   "HVD_TPU_AUTOTUNE_LOG": str(log)},
                        timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "MISMATCH" not in proc.stdout, proc.stdout

    # Every rank reports synchronized params inside the search bounds.
    params = [json.loads(m) for m in
              re.findall(r"AUTOTUNE_PARAMS (\{.*?\})", proc.stdout)]
    assert len(params) == 2, proc.stdout
    for p in params:
        assert FUSION_LO <= p["fusion_mb"] <= FUSION_HI, p
        assert CYCLE_LO <= p["cycle_time_ms"] <= CYCLE_HI, p

    # CSV: header + >= 2 post-warmup samples, all rows in bounds.
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith("fusion_mb,cycle_time_ms,cache_enabled"), \
        lines[0]
    rows = [line.split(",") for line in lines[1:]]
    assert len(rows) >= 2, lines
    for row in rows:
        assert len(row) == 6, row
        fusion, cycle = float(row[0]), float(row[1])
        assert FUSION_LO <= fusion <= FUSION_HI, row
        assert CYCLE_LO <= cycle <= CYCLE_HI, row
        assert row[2] in ("0", "1") and row[3] in ("0", "1") \
            and row[4] in ("0", "1"), row
        assert np.isfinite(float(row[5])), row


@pytest.mark.e2e
def test_autotune_ab_worker_symmetric_exit(run_launcher):
    """The A/B worker's broadcast-gated tune loop (SCALING.md §2.2):
    rank 0 alone decides exit (converged / step-capped / timed out)
    and broadcasts the verdict, so every rank leaves at the SAME step
    — per-rank polling of `active` exits ranks at different collective
    counts and desynchronizes shutdown (the race the A/B experiment
    hit live). Pins: clean exit at the step cap while tuning is still
    active, identical tune_steps on the reporting rank, and a
    well-formed AB_RESULT."""
    result = run_launcher(2, "autotune_ab_worker.py",
                          extra_env={"HVD_TPU_AUTOTUNE": "1",
                                     "AB_TUNE_MAX_STEPS": "25",
                                     "AB_ITERS": "10",
                                     "AB_TENSORS": "8",
                                     "AB_ELEMS": "4096"},
                          timeout=600)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "AUTOTUNE_TIMEOUT" not in result.stdout, result.stdout
    marker = result.stdout.find("AB_RESULT ")
    assert marker >= 0, result.stdout
    # raw_decode: another rank's output can interleave after the
    # JSON object on the same line.
    res = json.JSONDecoder().raw_decode(
        result.stdout[marker + len("AB_RESULT "):])[0]
    assert res["tune_steps"] == 25, res
    assert res["steps_per_s"] > 0, res
