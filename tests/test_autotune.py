"""Autotune coverage: the Bayesian-optimization math (unit) and a live
HVD_TPU_AUTOTUNE=1 job (e2e). Reference semantics: ParameterManager
warmup/sample/score flow (`/root/reference/horovod/common/parameter_manager.cc:27-30`)
+ BayesianOptimization (`common/optim/bayesian_optimization.cc`)."""

import ctypes
import json
import os
import re

import numpy as np
import pytest

from horovod_tpu.common import get_basics

FUSION_LO, FUSION_HI = 0.0, 64.0
CYCLE_LO, CYCLE_HI = 1.0, 100.0
# Pipelined-ring chunk bounds of the UNCOMPRESSED profile — the e2e's
# workload (parameter_manager.cc; compressed jobs search the tighter
# [16, 1024] instead).
CHUNK_LO_KB, CHUNK_HI_KB = 64.0, 4096.0

# Fast-convergence env for the closed-loop e2es: 2 cycles per sample,
# 6 samples, 1 warmup — the tuner converges in ~14 work cycles.
FAST_TUNE_ENV = {
    "HVD_TPU_AUTOTUNE": "1",
    "HVD_TPU_AUTOTUNE_CYCLES_PER_SAMPLE": "2",
    "HVD_TPU_AUTOTUNE_MAX_SAMPLES": "6",
    "HVD_TPU_AUTOTUNE_WARMUP": "1",
}


def _bo(lo0, hi0, lo1, hi1, seed):
    lib = get_basics().lib
    lib.horovod_tpu_bo_create.restype = ctypes.c_void_p
    lib.horovod_tpu_bo_create.argtypes = [
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_uint64]
    lib.horovod_tpu_bo_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double)]
    lib.horovod_tpu_bo_add.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_double]
    lib.horovod_tpu_bo_best.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double)]
    lib.horovod_tpu_bo_destroy.argtypes = [ctypes.c_void_p]
    return lib, lib.horovod_tpu_bo_create(lo0, hi0, lo1, hi1, seed)


def test_bayesian_optimizer_finds_optimum_2d():
    """EI over the GP surrogate must localize the optimum of a smooth
    2-D function within the sample budget the autotuner actually uses
    (kSamplesPerCombo=10 per categorical combo, up to kMaxSamples=40) —
    and never propose points outside the bounds."""
    lib, bo = _bo(FUSION_LO, FUSION_HI, CYCLE_LO, CYCLE_HI, seed=7)
    opt_x, opt_y = 20.0, 70.0

    def f(x, y):
        return -((x - opt_x) / (FUSION_HI - FUSION_LO)) ** 2 \
            - ((y - opt_y) / (CYCLE_HI - CYCLE_LO)) ** 2

    try:
        pt = (ctypes.c_double * 2)()
        for _ in range(25):
            lib.horovod_tpu_bo_next(bo, pt)
            x, y = pt[0], pt[1]
            assert FUSION_LO <= x <= FUSION_HI, x
            assert CYCLE_LO <= y <= CYCLE_HI, y
            lib.horovod_tpu_bo_add(bo, pt, f(x, y))
        best_y = ctypes.c_double()
        lib.horovod_tpu_bo_best(bo, pt, ctypes.byref(best_y))
        # Within ~15% of each axis of the true optimum, and a function
        # value close to the max of 0.
        assert abs(pt[0] - opt_x) < 0.15 * (FUSION_HI - FUSION_LO), pt[0]
        assert abs(pt[1] - opt_y) < 0.15 * (CYCLE_HI - CYCLE_LO), pt[1]
        assert best_y.value > -0.05, best_y.value
    finally:
        lib.horovod_tpu_bo_destroy(bo)


def test_bayesian_optimizer_survives_many_samples():
    """100 samples (beyond kMaxSamples) on a noisy constant function:
    the Cholesky must stay finite (no NaN proposals) even with
    near-duplicate inputs."""
    lib, bo = _bo(FUSION_LO, FUSION_HI, CYCLE_LO, CYCLE_HI, seed=3)
    rng = np.random.RandomState(0)
    try:
        pt = (ctypes.c_double * 2)()
        for i in range(100):
            lib.horovod_tpu_bo_next(bo, pt)
            assert np.isfinite(pt[0]) and np.isfinite(pt[1]), (i, pt[0],
                                                              pt[1])
            assert FUSION_LO <= pt[0] <= FUSION_HI
            assert CYCLE_LO <= pt[1] <= CYCLE_HI
            lib.horovod_tpu_bo_add(bo, pt, 1.0 + 1e-3 * rng.randn())
    finally:
        lib.horovod_tpu_bo_destroy(bo)


@pytest.mark.e2e
def test_autotune_e2e(run_launcher, tmp_path):
    """A 2-rank job with autotuning live: collectives must stay correct
    while the coordinator re-tunes fusion/cycle/cache knobs under the
    running job (cross-rank agreement is implicit — a desynchronized
    cache or fusion config deadlocks negotiation and the run times
    out), the CSV log must be well-formed with >= warmup + 2 samples,
    and every sampled/final knob must lie inside the search bounds."""
    log = tmp_path / "autotune.csv"
    proc = run_launcher(2, "autotune_worker.py",
                        extra_env=dict(FAST_TUNE_ENV,
                                       HVD_TPU_AUTOTUNE_LOG=str(log)),
                        timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "MISMATCH" not in proc.stdout, proc.stdout

    # Every rank reports synchronized params inside the search bounds.
    params = [json.loads(m) for m in
              re.findall(r"AUTOTUNE_PARAMS (\{.*?\})", proc.stdout)]
    assert len(params) == 2, proc.stdout
    for p in params:
        assert FUSION_LO <= p["fusion_mb"] <= FUSION_HI, p
        assert CYCLE_LO <= p["cycle_time_ms"] <= CYCLE_HI, p

    # CSV: header + >= 2 post-warmup samples, all rows in bounds. Format
    # (docs/AUTOTUNE.md): the three continuous knobs, the five
    # categorical knobs (cache, the three hierarchicals, shm_transport),
    # the score, and the row's event (sample/converged/rearm reason).
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith(
        "fusion_mb,cycle_time_ms,pipeline_chunk_kb,cache_enabled"), lines[0]
    assert "shm_transport" in lines[0], lines[0]
    rows = [line.split(",") for line in lines[1:]]
    assert len(rows) >= 2, lines
    assert any(row[9] == "converged" for row in rows), lines
    for row in rows:
        assert len(row) == 10, row
        fusion, cycle, chunk = float(row[0]), float(row[1]), float(row[2])
        assert FUSION_LO <= fusion <= FUSION_HI, row
        assert CYCLE_LO <= cycle <= CYCLE_HI, row
        assert CHUNK_LO_KB <= chunk <= CHUNK_HI_KB, row
        for cat in row[3:8]:
            assert cat in ("0", "1"), row
        assert np.isfinite(float(row[8])), row
        assert row[9], row


@pytest.mark.e2e
def test_autotune_ab_worker_symmetric_exit(run_launcher):
    """The A/B worker's broadcast-gated tune loop (SCALING.md §2.2):
    rank 0 alone decides exit (converged / step-capped / timed out)
    and broadcasts the verdict, so every rank leaves at the SAME step
    — per-rank polling of `active` exits ranks at different collective
    counts and desynchronizes shutdown (the race the A/B experiment
    hit live). Pins: clean exit at the step cap while tuning is still
    active, identical tune_steps on the reporting rank, and a
    well-formed AB_RESULT."""
    result = run_launcher(2, "autotune_ab_worker.py",
                          extra_env={"HVD_TPU_AUTOTUNE": "1",
                                     "AB_TUNE_MAX_STEPS": "25",
                                     "AB_ITERS": "10",
                                     "AB_TENSORS": "8",
                                     "AB_ELEMS": "4096"},
                          timeout=600)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "AUTOTUNE_TIMEOUT" not in result.stdout, result.stdout
    marker = result.stdout.find("AB_RESULT ")
    assert marker >= 0, result.stdout
    # raw_decode: another rank's output can interleave after the
    # JSON object on the same line.
    res = json.JSONDecoder().raw_decode(
        result.stdout[marker + len("AB_RESULT "):])[0]
    assert res["tune_steps"] == 25, res
    assert res["steps_per_s"] > 0, res


@pytest.mark.e2e
def test_autotune_drift_rearm(run_launcher):
    """Closed loop (docs/AUTOTUNE.md): after convergence on a small
    workload, an 8x payload shift must trip the drift watch — the tuner
    re-arms (rearms_total bumps, a new epoch rides the ResponseList
    bootstrap) on EVERY rank, with rank 0 naming workload-shift as the
    reason."""
    result = run_launcher(
        2, "autotune_drift_worker.py",
        extra_env=dict(FAST_TUNE_ENV,
                       HVD_TPU_AUTOTUNE_DRIFT_WINDOW="8",
                       HVD_TPU_AUTOTUNE_DRIFT="2.0"),
        timeout=600)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "DRIFT_TIMEOUT" not in result.stdout, result.stdout
    rearmed = [json.loads(m) for m in
               re.findall(r"DRIFT_REARMED (\{.*?\})", result.stdout)]
    assert len(rearmed) == 2, result.stdout  # both ranks re-entered tuning
    assert all(r["rearms"] >= 1 for r in rearmed), rearmed
    assert all(r["epoch"] >= 1 for r in rearmed), rearmed
    assert any(r["reason"] == "workload-shift" for r in rearmed), rearmed


@pytest.mark.e2e
def test_autotune_rearm_across_elastic_resize():
    """Acceptance e2e: the tuner converges in generation 0, RE-ARMS when
    worker 1 dies (shrink 3->2), converges again under the new world
    size with different knobs, survives the regrow to 3, and step time
    recovers to the converged-regime envelope instead of sticking at
    sampling-transient pacing."""
    import statistics
    import subprocess
    import sys
    import time as _time

    from tests.conftest import clean_worker_env

    env = clean_worker_env(dict(
        FAST_TUNE_ENV,
        HVD_TPU_ELASTIC_COOLDOWN="2",
        HVD_TPU_ELASTIC_DISCOVERY_INTERVAL="0.3",
        HVD_TPU_START_TIMEOUT="30",
    ))
    result = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.run", "-np", "3",
         "--min-np", "1", "--",
         sys.executable, os.path.join(os.path.dirname(__file__),
                                      "autotune_elastic_worker.py")],
        env=env, timeout=420, capture_output=True, text=True)
    out = result.stdout
    assert result.returncode == 0, (out, result.stderr)
    assert "worker 1 crashing now" in out

    line = re.compile(
        r"TUNE worker (\S+) gen (\d+) step (\d+) size (\d+) active (\d) "
        r"epoch (\d+) rearms (\d+) fusion ([0-9.]+) cycle ([0-9.]+) "
        r"chunk ([0-9.]+) ms ([0-9.]+)")
    rows = [dict(worker=m[0], gen=int(m[1]), step=int(m[2]),
                 size=int(m[3]), active=int(m[4]), epoch=int(m[5]),
                 rearms=int(m[6]), fusion=float(m[7]), cycle=float(m[8]),
                 chunk=float(m[9]), ms=float(m[10]))
            for m in line.findall(out)]
    gen0 = [r for r in rows if r["gen"] == 0]
    shrunk = [r for r in rows if r["gen"] >= 1 and r["size"] == 2]
    assert gen0 and shrunk, out

    # Generation 0 converged before the crash...
    gen0_converged = [r for r in gen0 if r["active"] == 0]
    assert gen0_converged, "tuner never converged in gen 0:\n" + out
    # ...and the resize RE-ARMED it: the shrunk generation starts with
    # the tuner actively sampling again.
    assert any(r["active"] == 1 for r in shrunk), \
        "tuner did not re-arm after the shrink:\n" + out
    # Post-resize the tuner converges AGAIN (the shrunk generation may
    # regrow before its pass finishes — the regrown generation re-arms
    # once more and finishes there) on knobs that differ from the
    # pre-shrink ones: each pass explores generation-salted sample
    # points, so an identical point would mean the re-tune never ran.
    shrunk_converged = [r for r in rows
                        if r["gen"] >= 1 and r["active"] == 0]
    assert shrunk_converged, "tuner never re-converged post-resize:\n" + out
    pre, post = gen0_converged[-1], shrunk_converged[-1]
    assert (abs(pre["fusion"] - post["fusion"]) > 1e-9 or
            abs(pre["cycle"] - post["cycle"]) > 1e-9 or
            abs(pre["chunk"] - post["chunk"]) > 1e-9), (pre, post)

    # The job regrew to 3 and finished on every worker.
    assert any(r["size"] == 3 and r["gen"] >= 1 for r in rows), out
    assert len(re.findall(r"tune train done", out)) == 3, out

    # Throughput recovers: converged step time after the resize stays in
    # the same envelope as generation 0's converged regime (generous 4x
    # bound — the point is it does NOT stick at sampling-transient
    # pacing, e.g. a 100ms-cycle probe).
    pre_ms = statistics.median(r["ms"] for r in gen0_converged[-5:])
    post_ms = statistics.median(r["ms"] for r in shrunk_converged[-5:])
    assert post_ms <= 4 * pre_ms + 50, (pre_ms, post_ms)


# --- hvd-top `tun` column tolerance -----------------------------------------


def _job(per_rank):
    return {"size": len(per_rank), "generation": 1,
            "per_rank": per_rank,
            "age_seconds": {r: 0.0 for r in per_rank},
            "rank_lag_seconds": [0.0] * len(per_rank)}


def test_hvd_top_tun_column_and_mixed_version_tolerance():
    """The `tun` column renders tuning posture + re-arm count, and a
    mixed-version job (rank 1's summary predates the autotune fields)
    shows '-' in the same column span without shifting anything."""
    from horovod_tpu.run import top

    new_worker = {"cycles_total": 100.0, "cycle_seconds_sum": 1.0,
                  "cache_hit_total": 5, "cache_miss_total": 5,
                  "autotune_active": 1.0, "autotune_rearms_total": 2.0}
    old_worker = {"cycles_total": 90.0, "cycle_seconds_sum": 1.0,
                  "cache_hit_total": 5, "cache_miss_total": 5}
    frame = top.render(_job({"0": new_worker, "1": old_worker}), None, 0.0,
                       "test:0")
    lines = frame.splitlines()
    rows = [ln for ln in lines if ln.strip().startswith(("0", "1"))]
    assert len(rows) == 2, frame
    header = next(ln for ln in lines if " tun" in ln)
    tun_col = header.index(" tun")
    assert "tun/2" in rows[0], frame
    assert rows[1][tun_col:tun_col + 5].strip() == "-", frame
    assert all(len(r) == len(rows[0]) for r in rows), frame
    # Converged posture with no re-arms renders plain 'cvg'.
    cvg = dict(new_worker, autotune_active=0.0, autotune_rearms_total=0.0)
    assert "cvg" in top.render(_job({"0": cvg}), None, 0.0, "t"), "cvg"
