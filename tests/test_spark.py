"""Spark integration tests (reference analogue: test/test_spark.py, which
mocks the shell layer; pyspark is absent here so the barrier-task body is
tested with a fake BarrierTaskContext and real multi-process rendezvous)."""

import threading

import pytest

from horovod_tpu.spark import _task_topology_env, run


def test_topology_single_host():
    hp = ["nodeA:100", "nodeA:101", "nodeA:102"]
    env = _task_topology_env(1, hp)
    assert env["HVD_TPU_RANK"] == "1"
    assert env["HVD_TPU_SIZE"] == "3"
    assert env["HVD_TPU_LOCAL_RANK"] == "1"
    assert env["HVD_TPU_LOCAL_SIZE"] == "3"
    assert env["HVD_TPU_CROSS_RANK"] == "0"
    assert env["HVD_TPU_CROSS_SIZE"] == "1"
    assert env["HVD_TPU_ADDRS"] == ",".join(hp)


def test_topology_two_hosts():
    hp = ["nodeA:1", "nodeA:2", "nodeB:3", "nodeB:4"]
    envs = [_task_topology_env(r, hp) for r in range(4)]
    assert [e["HVD_TPU_LOCAL_RANK"] for e in envs] == ["0", "1", "0", "1"]
    assert [e["HVD_TPU_CROSS_RANK"] for e in envs] == ["0", "0", "1", "1"]
    assert all(e["HVD_TPU_CROSS_SIZE"] == "2" for e in envs)
    assert all(e["HVD_TPU_LOCAL_SIZE"] == "2" for e in envs)


def test_topology_uneven_hosts():
    hp = ["nodeA:1", "nodeA:2", "nodeB:3"]
    env = _task_topology_env(1, hp)  # nodeA local_rank 1
    assert env["HVD_TPU_CROSS_SIZE"] == "1"  # only nodeA has local_rank 1
    assert env["HVD_TPU_CROSS_RANK"] == "0"


def test_run_without_pyspark():
    with pytest.raises(ImportError, match="pyspark"):
        run(lambda: 1, num_proc=2)


class _FakeBarrierContext:
    """Stands in for pyspark.BarrierTaskContext: allGather implemented
    with a shared barrier across threads."""

    def __init__(self, rank, world, store, barrier):
        self._rank = rank
        self._world = world
        self._store = store
        self._barrier = barrier

    def partitionId(self):
        return self._rank

    def allGather(self, message):
        self._store[self._rank] = message
        self._barrier.wait(timeout=30)
        return [self._store[r] for r in range(self._world)]


def test_barrier_task_end_to_end():
    """Two threads -> two fake barrier tasks -> real hvd.init rendezvous
    in subprocesses is NOT possible in-process (one core per process), so
    run the task body up to the env computation with init stubbed."""
    from horovod_tpu import spark as hvd_spark

    import horovod_tpu as hvd

    world = 2
    store = {}
    barrier = threading.Barrier(world)
    results = {}

    def fake_task(rank):
        ctx = _FakeBarrierContext(rank, world, store, barrier)
        r, out = hvd_spark._barrier_task(
            lambda x: x * 10, (rank,), {}, None, context=ctx)
        results[r] = out

    # Patch init/shutdown once: one process owns one core runtime, so the
    # collective rendezvous itself is covered by the launcher tests.
    orig_init, orig_shutdown = hvd.init, hvd.shutdown
    hvd.init = lambda: None
    hvd.shutdown = lambda: None
    try:
        threads = [threading.Thread(target=fake_task, args=(r,))
                   for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    finally:
        hvd.init, hvd.shutdown = orig_init, orig_shutdown
    assert results == {0: 0, 1: 10}


def test_spark_run_e2e_fake_pyspark():
    """Drives `spark.run()` ITSELF — SparkSession.builder ->
    parallelize -> barrier -> mapPartitions -> collect — through the
    fake pyspark package (tests/fake_pyspark), with each barrier task
    forked as a real OS process doing a genuine hvd.init() rendezvous
    and allreduce. Runs in a clean interpreter so the forked children
    hold no pre-initialized native runtime (reference analogue:
    test/test_spark.py:51-91)."""
    import os
    import pathlib
    import subprocess
    import sys

    from conftest import clean_worker_env

    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo_root, "tests",
                                      "spark_run_worker.py")],
        env=clean_worker_env(), timeout=240, capture_output=True, text=True)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "spark run ok" in proc.stdout, proc.stdout
