"""hvd-model: the explicit-state protocol model checker (docs/MODEL.md).

Three layers:

1. engine unit tests — freeze/canon, BFS trace minimality, deadlock /
   livelock detection, symmetry reduction, the state budget;
2. golden seeded-bug regressions — every historical bug encoded in the
   protocol models must be re-found with its exact minimal
   counterexample length (and, for the shm missed wake, the exact
   interleaving), so a model edit that loses a regression fails here
   before it ships;
3. CLI: default run clean under the CI budget, --bug mode, JSON and
   SARIF output, and the model-regression-missed tripwire.
"""

import io
import json
import time

import pytest

from horovod_tpu.lint.model import cli
from horovod_tpu.lint.model.dsl import (Action, Invariant, Model,
                                        default_permute, freeze)
from horovod_tpu.lint.model.explore import (BudgetExceeded, explore,
                                            replay)
from horovod_tpu.lint.model.protocols import MODELS, BugSpec, ModelSpec


# --- engine -----------------------------------------------------------------

def test_freeze_canonicalizes_nested_state():
    a = {"t": {1: [1, 2], 0: {"x"}}, "u": (3, {"k": 4})}
    b = {"u": (3, {"k": 4}), "t": {0: {"x"}, 1: [1, 2]}}
    assert freeze(a) == freeze(b)
    assert hash(freeze(a)) == hash(freeze(b))
    assert freeze({"t": {1: [1, 2]}}) != freeze({"t": {1: [2, 1]}})


def test_default_permute_rekeys_int_dicts_at_any_depth():
    state = {"phase": {0: "a", 1: "b"}, "misc": {"n": 3},
             "nested": {"by_rank": {0: [1], 1: [2]}}}
    swapped = default_permute(state, {0: 1, 1: 0})
    assert swapped["phase"] == {1: "a", 0: "b"}
    assert swapped["nested"]["by_rank"] == {1: [1], 0: [2]}
    assert swapped["misc"] == {"n": 3}  # string keys untouched


def _counter_model(limit, bug_at=None):
    """x counts 0..limit via two interleaved incrementers; optionally an
    invariant that trips at x == bug_at."""
    invs = []
    if bug_at is not None:
        invs.append(Invariant("x-below-%d" % bug_at,
                              lambda s: s["x"] < bug_at))

    def inc(s):
        s["x"] += 1

    return Model(
        "counter",
        {"x": 0},
        [Action("a.inc", lambda s: s["x"] < limit, inc, progress=True),
         Action("b.inc", lambda s: s["x"] < limit, inc, progress=True)],
        invs,
        done=lambda s: s["x"] == limit)


def test_bfs_trace_is_minimal_by_construction():
    result = explore(_counter_model(10, bug_at=3))
    (v,) = result.violations
    assert v.kind == "invariant"
    # shortest path to x==3 is exactly 3 increments, never more
    assert len(v.trace) == 3
    assert v.state["x"] == 3


def test_deadlock_and_clean_termination():
    # done==limit: terminal state accepted, no violations
    assert explore(_counter_model(4)).violations == []
    # done never true: the same terminal state is now a deadlock
    wedge = _counter_model(4)
    wedge.done = lambda s: False
    (v,) = explore(wedge).violations
    assert v.kind == "deadlock"
    assert len(v.trace) == 4


def test_livelock_needs_a_progress_free_cycle():
    def spin(s):
        s["t"] = (s["t"] + 1) % 2

    def mk(progress):
        return Model(
            "spinner", {"t": 0, "done": False},
            [Action("tick", lambda s: True, spin, progress=progress)],
            done=lambda s: s["done"])

    (v,) = explore(mk(progress=False)).violations
    assert v.kind == "livelock"
    assert v.cycle  # the repeating suffix is reported
    # the same cycle made of `progress` edges is not a livelock
    assert explore(mk(progress=True)).violations == []


def test_budget_exceeded_raises():
    with pytest.raises(BudgetExceeded):
        explore(_counter_model(100), max_states=5)


def test_replay_rejects_disabled_step():
    model = _counter_model(2)
    with pytest.raises(ValueError):
        replay(model, ["a.inc", "a.inc", "a.inc"])  # third is disabled


# --- symmetry reduction -----------------------------------------------------

def test_symmetry_reduction_shrinks_the_state_space():
    """The drain model declares all ranks interchangeable; stripping the
    declaration must explore strictly more canonical states while
    reaching the same verdict."""
    sym = MODELS["drain"].build(3)
    nosym = MODELS["drain"].build(3)
    nosym.symmetry = []
    r_sym = explore(sym)
    r_nosym = explore(nosym)
    assert r_sym.violations == [] and r_nosym.violations == []
    assert r_sym.num_states < r_nosym.num_states
    # pinned: the golden counts the CLI run reports
    assert r_sym.num_states == 52


def test_canon_is_invariant_under_rank_permutation():
    model = MODELS["cache_bits"].build(3)
    state = model.init
    for mapping in model.permutations():
        assert model.canon(model.permute(state, mapping)) == \
            model.canon(state)


# --- clean explorations (golden state counts) -------------------------------

GOLDEN_CLEAN = {
    # (model, ranks, sub-model index) -> canonical states
    ("cache_bits", 2, 0): 21,
    ("cache_bits", 3, 0): 36,
    ("cache_bits", 4, 0): 56,
    ("drain", 2, 0): 30,
    ("drain", 2, 1): 15,   # drain[sticky]
    ("drain", 3, 0): 52,
    ("drain", 3, 1): 35,
    ("drain", 4, 0): 84,
    ("drain", 4, 1): 70,
    ("rendezvous", 2, 0): 9,
    ("rendezvous", 3, 0): 21,
    ("shm_ring", 2, 0): 274,
    ("group_ring", 3, 0): 45,
}


@pytest.mark.parametrize("name,ranks,idx", sorted(GOLDEN_CLEAN))
def test_shipped_models_explore_clean(name, ranks, idx):
    spec = MODELS[name]
    model = spec.clean_builds(ranks)[idx]
    result = explore(model)
    assert result.complete
    assert result.violations == [], [
        (v.kind, v.trace) for v in result.violations]
    # Pinned canonical state counts: a drop means the model lost
    # behaviors (under-approximation hides bugs); a jump means symmetry
    # reduction broke (CI budget erodes).
    assert result.num_states == GOLDEN_CLEAN[(name, ranks, idx)]


# --- golden seeded-bug regressions ------------------------------------------

GOLDEN_BUGS = [
    # (model, bug, violation kind, minimal counterexample length)
    ("cache_bits", "late_registration", "deadlock", 5),
    ("cache_bits", "no_foreign", "invariant", 13),
    ("cache_bits", "rearm_no_force", "livelock", 14),
    ("drain", "local_poll", "deadlock", 5),
    ("drain", "sticky_displacement", "invariant", 9),
    ("rendezvous", "ungated_growth", "invariant", 5),
    ("shm_ring", "missed_wake", "deadlock", 12),
    ("shm_ring", "no_close_wake", "deadlock", 13),
    ("group_ring", "no_stash", "deadlock", 8),
    ("group_ring", "reconnect_drop", "deadlock", 10),
]


def test_every_registered_bug_has_a_golden_entry():
    registered = {(name, bug) for name, spec in MODELS.items()
                  for bug in spec.bugs}
    assert registered == {(n, b) for n, b, _, _ in GOLDEN_BUGS}


@pytest.mark.parametrize("name,bug,kind,steps", GOLDEN_BUGS)
def test_seeded_bug_refound_with_minimal_trace(name, bug, kind, steps):
    spec = MODELS[name]
    assert spec.bugs[bug].kind == kind
    model = spec.build(ranks=None, bug=bug)
    result = explore(model)
    hits = [v for v in result.violations if v.kind == kind]
    assert hits, [v.kind for v in result.violations]
    v = hits[0]
    # BFS makes the first hit minimal; these lengths are golden — a
    # longer trace means the model grew noise steps, a shorter one
    # means the bug got easier (the abstraction drifted).
    assert len(v.trace) == steps
    # every counterexample replays from init (guards stay consistent)
    states = replay(model, v.trace)
    assert freeze(states[-1]) == freeze(v.state)


def test_shm_missed_wake_exact_interleaving():
    """The missed-wake counterexample IS the historical bug: the writer
    loads the waiters flag BEFORE bumping data_seq (the relaxed-order
    reverted variant), the reader parks in the window, and both sides
    end up in FutexWait — the exact interleaving the seq_cst pairing in
    shm_context.cc:296-305/:364-376 forbids."""
    model = MODELS["shm_ring"].build(bug="missed_wake")
    (v,) = explore(model).violations
    assert v.kind == "deadlock"
    assert v.trace == [
        "w.stale_waiter_load",
        "r.set_read_waiters",
        "r.load_data_seq",
        "r.recheck_empty",
        "w.publish",
        "r.futex_wait_data",
        "w.bump_data_seq",
        "w.wake_if_stale_saw_waiter",
        "w.set_write_waiters",
        "w.load_space_seq",
        "w.recheck_space",
        "w.futex_wait_space",
    ]


# --- CLI --------------------------------------------------------------------

def test_cli_default_run_is_clean_and_inside_ci_budget(capsys):
    start = time.monotonic()
    assert cli.main([]) == 0
    elapsed = time.monotonic() - start
    out = capsys.readouterr().out
    assert "10 seeded bugs re-found" in out
    assert "0 problem(s)" in out
    # `make check-model` gates check-tsan/check-asan: the full pass must
    # stay far below the CI cap (it runs in well under five seconds).
    assert elapsed < 120, "model checking no longer fits the CI budget"


def test_cli_list_names_models_and_bugs(capsys):
    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in MODELS:
        assert name in out
    assert "missed_wake" in out and "deadlock" in out


def test_cli_bug_mode_prints_counterexample(capsys):
    assert cli.main(["--model", "shm_ring", "--bug", "missed_wake"]) == 0
    out = capsys.readouterr().out
    assert "re-found deadlock" in out
    assert "w.futex_wait_space" in out   # the trace is printed
    assert "final state:" in out


def test_cli_json_format(capsys):
    assert cli.main(["--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"] == []


def test_cli_sarif_format(capsys):
    assert cli.main(["--format", "sarif", "--model", "rendezvous"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == "2.1.0"
    (run,) = payload["runs"]
    assert run["tool"]["driver"]["name"] == "hvd-model"
    assert run["tool"]["driver"]["informationUri"] == "docs/MODEL.md"
    assert run["results"] == []


def test_sarif_findings_carry_stable_fingerprints():
    """A violation rendered through the shared reporter gets the same
    partialFingerprints scheme hvd-lint uses, so SARIF consumers can
    diff model regressions across commits."""
    from horovod_tpu.lint.report import format_sarif

    spec = MODELS["rendezvous"]
    model = spec.build(bug="ungated_growth")
    (v,) = explore(model).violations
    finding = cli._violation_finding(spec, model, v)
    assert finding.rule == "model-invariant"
    assert finding.path.endswith("rendezvous.py")
    buf = io.StringIO()
    format_sarif([finding], 1, buf, tool_name="hvd-model",
                 information_uri="docs/MODEL.md")
    payload = json.loads(buf.getvalue())
    (result,) = payload["runs"][0]["results"]
    fp = result["partialFingerprints"]["hvdLintFingerprint/v1"]
    assert len(fp) == 16
    assert result["ruleId"] == "model-invariant"


def test_cli_flags_a_missed_regression(capsys, monkeypatch):
    """A seeded bug whose variant explores clean is a LOST regression:
    the checker must fail CI, not silently shrink its coverage."""
    def build(ranks=None, bug=None):
        return _counter_model(2)  # "bug" variant is accidentally clean

    fake = ModelSpec(
        name="fake", build=build,
        clean_builds=lambda ranks=None: [build(ranks)],
        bugs={"lost": BugSpec("deadlock", "regression that vanished")},
        default_ranks=2, rank_range=(2, 2), description="test double")
    monkeypatch.setitem(cli.MODELS, "fake", fake)
    assert cli.main(["--model", "fake"]) == 1
    out = capsys.readouterr().out
    assert "model-regression-missed" in out
    assert "NOT re-found" in out


def test_cli_budget_finding(capsys):
    assert cli.main(["--model", "shm_ring", "--no-bugs",
                     "--max-states", "10"]) == 1
    out = capsys.readouterr().out
    assert "model-budget" in out
