"""Self-verifying TensorFlow-binding test, run under the launcher with
N >= 2 ranks (reference analogue: test/test_tensorflow.py — dense +
IndexedSlices collectives, DistributedGradientTape, broadcast_variables,
Keras optimizer wrapper + callbacks)."""

import os
import sys

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def test_allreduce_dense(r, n):
    for dtype in (tf.int32, tf.int64, tf.float32, tf.float64):
        x = tf.cast(tf.reshape(tf.range(12), (3, 4)), dtype) + r
        out = hvd.allreduce(x, average=False, name="tf_ar.%s" % dtype.name)
        exp = sum(tf.cast(tf.reshape(tf.range(12), (3, 4)), dtype) + rr
                  for rr in range(n))
        assert np.allclose(out.numpy(), exp.numpy()), (dtype, out, exp)


def test_allreduce_average(r, n):
    x = tf.ones((5,)) * (r + 1)
    out = hvd.allreduce(x, average=True, name="tf_avg")
    exp = sum(rr + 1 for rr in range(n)) / n
    assert np.allclose(out.numpy(), exp), out


def test_allreduce_in_tf_function(r, n):
    @tf.function
    def fn(x):
        return hvd.allreduce(x, average=False, name="tf_fn_ar")

    x = tf.ones((4,)) * (r + 1)
    for _ in range(2):  # retrace/cached-graph second call
        out = fn(x)
        exp = float(sum(rr + 1 for rr in range(n)))
        assert np.allclose(out.numpy(), exp), out


def test_gradients_through_collectives(r, n):
    """Collectives are graph-real: differentiable under tf.function via
    the registered gradients (reference: tensorflow/mpi_ops.py:89-180)."""
    if not hvd.native_ops_available():
        if r == 0:
            print("SKIP test_gradients_through_collectives (no native ops)")
        return

    # allreduce: y = mean_r(x_r); dL/dx_r with L = sum(y * (r+1)) is
    # mean_r(r+1) on every rank (the grad itself is allreduced).
    @tf.function
    def grad_allreduce(x):
        with tf.GradientTape() as tape:
            tape.watch(x)
            y = hvd.allreduce(x, average=True, name="tf_gar")
            loss = tf.reduce_sum(y) * (r + 1)
        return tape.gradient(loss, x)

    g = grad_allreduce(tf.ones((3,)))
    exp = sum(rr + 1 for rr in range(n)) / n
    assert np.allclose(g.numpy(), exp), g

    # allgather with unequal first dims: rank r contributes r+1 rows of
    # value r; every rank computes L_r = sum over gathered rows of
    # per-row weight w_i. The registered gradient sums the upstream
    # grads (the objective is implicitly sum_r L_r, the reference's
    # convention) then slices this rank's segment: with identical L_r
    # here, that is n * w over my rows.
    @tf.function
    def grad_allgather(x):
        with tf.GradientTape() as tape:
            tape.watch(x)
            y = hvd.allgather(x, name="tf_gag")
            w = tf.cast(tf.range(tf.shape(y)[0]) + 1, tf.float32)
            loss = tf.reduce_sum(y[:, 0] * w)
        return tape.gradient(loss, x)

    x = tf.fill((r + 1, 2), float(r))
    g = grad_allgather(x)
    assert g.shape == x.shape
    offset = sum(rr + 1 for rr in range(r))
    exp_rows = (np.arange(offset, offset + r + 1) + 1) * n
    assert np.allclose(g.numpy()[:, 0], exp_rows), (g.numpy(), exp_rows)
    assert np.allclose(g.numpy()[:, 1], 0.0)

    # broadcast: every rank's output grad (ones) sums onto the root's
    # input; non-roots get zeros.
    @tf.function
    def grad_broadcast(x):
        with tf.GradientTape() as tape:
            tape.watch(x)
            y = hvd.broadcast(x, root_rank=0, name="tf_gbc")
            loss = tf.reduce_sum(y)
        return tape.gradient(loss, x)

    g = grad_broadcast(tf.ones((4,)) * (r + 1))
    exp = float(n) if r == 0 else 0.0
    assert np.allclose(g.numpy(), exp), g


def test_allreduce_indexed_slices(r, n):
    values = tf.ones((2, 4)) * (r + 1)
    indices = tf.constant([r, r + 1], dtype=tf.int64)
    slices = tf.IndexedSlices(values, indices,
                              dense_shape=tf.constant([n + 1, 4]))
    out = hvd.allreduce(slices, average=True, name="tf_sparse")
    assert isinstance(out, tf.IndexedSlices)
    assert out.indices.shape[0] == 2 * n
    # densify and check: row i touched by ranks {i-1, i} (within bounds)
    dense = tf.math.unsorted_segment_sum(
        out.values, tf.cast(out.indices, tf.int32), n + 1).numpy()
    expected = np.zeros((n + 1, 4))
    for rr in range(n):
        expected[rr] += (rr + 1) / n
        expected[rr + 1] += (rr + 1) / n
    assert np.allclose(dense, expected), (dense, expected)


def test_allreduce_sparse_as_dense(r, n):
    values = tf.ones((1, 3)) * (r + 1)
    indices = tf.constant([0], dtype=tf.int64)
    slices = tf.IndexedSlices(values, indices,
                              dense_shape=tf.constant([2, 3]))
    out = hvd.allreduce(slices, average=False, name="tf_sad",
                        sparse_as_dense=True)
    assert not isinstance(out, tf.IndexedSlices)
    exp = np.zeros((2, 3))
    exp[0] = sum(rr + 1 for rr in range(n))
    assert np.allclose(out.numpy(), exp), out


def test_allgather(r, n):
    x = tf.fill((r + 1, 2), float(r))
    out = hvd.allgather(x, name="tf_ag")
    assert out.shape[0] == sum(rr + 1 for rr in range(n))


def test_broadcast_variables(r, n):
    v1 = tf.Variable(tf.ones((3,)) * (r + 1))
    v2 = tf.Variable(tf.ones((2, 2)) * (10 * r))
    hvd.broadcast_variables([v1, v2], root_rank=0)
    assert np.allclose(v1.numpy(), 1.0), v1
    assert np.allclose(v2.numpy(), 0.0), v2


def test_distributed_gradient_tape(r, n):
    w = tf.Variable([2.0, 3.0])
    with hvd.DistributedGradientTape() as tape:
        loss = tf.reduce_sum(w * (r + 1))
    grad = tape.gradient(loss, w)
    exp = sum(rr + 1 for rr in range(n)) / n
    assert np.allclose(grad.numpy(), exp), grad


def test_keras_distributed_optimizer(r, n):
    import keras
    import horovod_tpu.keras as hvd_keras

    keras.utils.set_random_seed(0)
    model = keras.Sequential([keras.layers.Input((4,)),
                              keras.layers.Dense(3),
                              keras.layers.Dense(1)])
    opt = hvd_keras.DistributedOptimizer(keras.optimizers.SGD(0.05))
    model.compile(optimizer=opt, loss="mse")
    hvd_keras.broadcast_model_weights(model, root_rank=0)
    rng = np.random.RandomState(100 + r)  # different data per rank
    x = rng.randn(16, 4).astype(np.float32)
    y = rng.randn(16, 1).astype(np.float32)
    model.fit(x, y, epochs=1, batch_size=8, verbose=0)
    for i, wt in enumerate(model.get_weights()):
        avg = np.asarray(hvd.allreduce(
            tf.constant(wt), average=True, name="tf_kw.%d" % i))
        assert np.allclose(avg, wt, atol=1e-6), i


def test_tensorflow_keras_alias(r, n):
    """horovod_tpu.tensorflow.keras is the same shell as .keras
    (reference import-path parity: horovod.tensorflow.keras)."""
    import horovod_tpu.keras as hk
    import horovod_tpu.tensorflow.keras as htk

    assert htk.DistributedOptimizer is hk.DistributedOptimizer
    assert htk.callbacks.MetricAverageCallback \
        is hk.callbacks.MetricAverageCallback
    assert htk.rank() == r and htk.size() == n


def test_keras_callbacks(r, n):
    import keras
    import horovod_tpu.keras as hvd_keras

    keras.utils.set_random_seed(r)  # different init per rank
    model = keras.Sequential([keras.layers.Input((4,)),
                              keras.layers.Dense(1)])
    model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
    cbs = [hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
           hvd_keras.callbacks.MetricAverageCallback(),
           hvd_keras.callbacks.LearningRateWarmupCallback(warmup_epochs=2)]
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)
    model.fit(x, y, epochs=1, batch_size=8, verbose=0, callbacks=cbs)
    # After broadcast + identical data, weights must agree across ranks.
    for i, wt in enumerate(model.get_weights()):
        avg = np.asarray(hvd.allreduce(
            tf.constant(wt), average=True, name="tf_cb.%d" % i))
        assert np.allclose(avg, wt, atol=1e-6), i


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2
    tests = [v for k, v in sorted(globals().items())
             if k.startswith("test_")]
    for t in tests:
        t(r, n)
        if r == 0:
            print("PASS %s" % t.__name__)
    print("rank %d: all tensorflow tests passed" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
