"""hvd-fleet tests (ISSUE 7; docs/FLEET.md).

Unit layer: the shared placement library (plan_spawns + PlacementPool
lease ledger), voluntary-release vs failure-blacklist semantics, the
fleet chaos grammar, fleet metrics rendering, and the controller's
admission / preemption / grow planning against fake drivers.

E2E layer: ``--drain-grace`` SIGTERM drains a static job through a
durable commit of exactly the drained step (resume verified at equal
AND smaller world size); a fleet preemption drains, reclaims, and
restores a job observably (/fleet + hvd-top --fleet + fleet_*
counters); and the seeded chaos schedule (arrivals + SIGKILLs +
preemption over 3 concurrent jobs) upholds the lineage invariant:
every job completes or resumes bitwise-consistently with a state it
committed, and no host is ever oversubscribed.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.elastic.discovery import FixedHosts, HostManager
from horovod_tpu.elastic.state import EXIT_DRAINED
from horovod_tpu.fleet.chaos import FleetChaos, FleetChaosError
from horovod_tpu.fleet.controller import (DRAINING, PENDING, RUNNING,
                                          FleetController, JobSpec)
from horovod_tpu.fleet.metrics import FleetMetrics, render_prometheus
from horovod_tpu.fleet.placement import PlacementPool, plan_spawns

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Placement library

def test_plan_spawns_fills_free_slots_in_sorted_host_order():
    plan = plan_spawns({"b": 2, "a": 2}, {"a": 1}, room=10)
    assert plan == ["a", "b", "b"]


def test_plan_spawns_respects_room_and_zero():
    assert plan_spawns({"a": 4}, {}, room=2) == ["a", "a"]
    assert plan_spawns({"a": 4}, {}, room=0) == []
    assert plan_spawns({}, {}, room=3) == []


def test_plan_spawns_ignores_overfull_hosts():
    # More live workers than slots (mid-drain overlap) must not
    # produce a negative contribution.
    assert plan_spawns({"a": 1, "b": 1}, {"a": 3}, room=2) == ["b"]


def test_plan_spawns_spread_balances_occupancy():
    # Spread: each worker lands on the least-occupied host (ties by
    # name) — serve replicas want failure-domain diversity.
    assert plan_spawns({"a": 4, "b": 4}, {}, room=4,
                       placement="spread") == ["a", "b", "a", "b"]
    # Existing occupancy is honored: "a" already carries 2, so "b"
    # catches up before the round-robin resumes.
    assert plan_spawns({"a": 4, "b": 4}, {"a": 2}, room=3,
                       placement="spread") == ["b", "b", "a"]
    # Full hosts drop out; a full pool stops the plan short.
    assert plan_spawns({"a": 1, "b": 2}, {"a": 1}, room=3,
                       placement="spread") == ["b", "b"]


def test_plan_spawns_rejects_unknown_placement():
    with pytest.raises(ValueError):
        plan_spawns({"a": 1}, {}, room=1, placement="sprinkle")


def test_pool_spread_lease_round_robins_hosts():
    pool = PlacementPool(FixedHosts({"a": 2, "b": 2}))
    pool.refresh()
    # Pack (default) fills "a" densely; spread alternates hosts.
    assert pool.lease("packed", 2) == {"a": 2}
    pool.release("packed")
    grant = pool.lease("spread", 2, placement="spread")
    assert grant == {"a": 1, "b": 1}
    pool.release("spread")
    # Want > one-per-host: the round-robin wraps for the remainder.
    assert pool.lease("big", 3, placement="spread") == {"a": 2, "b": 1}
    with pytest.raises(ValueError):
        pool.lease("x", 1, placement="sprinkle")


def test_jobspec_kind_defaults():
    train = JobSpec("t", ["true"], np=2)
    assert train.kind == "train"
    assert train.placement == "pack"
    assert train.start_timeout == 60
    serve = JobSpec("s", ["true"], np=2, kind="serve")
    assert serve.placement == "spread"  # failure-domain diversity
    assert serve.start_timeout == 2  # growth gate unsticks by stalling
    # Either default is overridable per job.
    pinned = JobSpec("p", ["true"], np=2, kind="serve",
                     placement="pack", start_timeout=30)
    assert pinned.placement == "pack" and pinned.start_timeout == 30
    with pytest.raises(ValueError):
        JobSpec("x", ["true"], np=2, kind="batch")
    with pytest.raises(ValueError):
        JobSpec("x", ["true"], np=2, placement="sprinkle")
    via_dict = JobSpec.from_dict(
        {"name": "d", "command": ["true"], "np": 1, "kind": "serve",
         "placement": "spread"})
    assert via_dict.kind == "serve"


def test_pool_gang_lease_all_or_nothing():
    pool = PlacementPool(FixedHosts({"a": 2, "b": 2}))
    pool.refresh()
    assert pool.free_slots() == 4
    grant = pool.lease("j1", 3)
    assert sum(grant.values()) == 3
    # j2 wants a gang of 2 but only 1 slot is free: NOTHING is leased.
    assert pool.lease("j2", 2) == {}
    assert pool.free_slots() == 1
    # min_slots relaxes the gang: 1 of 2 is acceptable.
    assert sum(pool.lease("j2", 2, min_slots=1).values()) == 1
    assert pool.free_slots() == 0


def test_pool_release_reenters_immediately():
    pool = PlacementPool(FixedHosts({"a": 2}))
    pool.refresh()
    pool.lease("j1", 2)
    assert pool.free_slots() == 0
    pool.release("j1", "a", 1)
    assert pool.free_slots() == 1  # no cooldown on voluntary release
    pool.release("j1")
    assert pool.free_slots() == 2
    assert pool.lease_of("j1") == {}


def test_pool_refuses_oversubscription():
    pool = PlacementPool(FixedHosts({"a": 2}))
    pool.refresh()
    assert sum(pool.lease("j1", 2).values()) == 2
    assert pool.lease("j2", 1) == {}
    assert pool.leased_slots_of("j2") == 0


def test_pool_occupancy_invariant_uses_raw_inventory():
    pool = PlacementPool(FixedHosts({"a": 2, "b": 1}))
    pool.refresh()
    assert pool.check_occupancy({"j1": {"a": 2}, "j2": {"b": 1}}) == []
    assert pool.check_occupancy({"j1": {"a": 2}, "j2": {"a": 1}}) == ["a"]
    # Blacklisting a host must not turn its still-draining workers into
    # a false violation: capacity reference is the RAW inventory.
    pool.record_failure("a")
    assert pool.check_occupancy({"j1": {"a": 2}}) == []


def test_pool_host_states():
    pool = PlacementPool(FixedHosts({"a": 2, "b": 2, "c": 1}))
    pool.refresh()
    pool.lease("j1", 2)  # lands on "a" (sorted order)
    pool.record_failure("c")
    states = pool.host_states()
    assert states["a"]["state"] == "leased"
    assert states["a"]["by_job"] == {"j1": 2}
    assert states["b"]["state"] == "free"
    assert states["c"]["state"] == "blacklisted"


# ---------------------------------------------------------------------------
# Voluntary release vs failure blacklist (satellite fix)

def test_record_release_never_blacklists():
    mgr = HostManager(FixedHosts({"a": 2}), cooldown=10.0,
                      clock=lambda: 100.0)
    mgr.refresh()
    mgr.record_release("a")
    assert not mgr.is_blacklisted("a")
    assert mgr.available_hosts_and_slots() == {"a": 2}


def test_record_release_keeps_existing_failure_streak():
    clock = {"t": 0.0}
    mgr = HostManager(FixedHosts({"a": 1}), cooldown=10.0,
                      clock=lambda: clock["t"])
    mgr.refresh()
    mgr.record_failure("a")
    assert mgr.is_blacklisted("a")
    # A planned drain on a flaky host must not launder the blacklist.
    mgr.record_release("a")
    assert mgr.is_blacklisted("a")
    clock["t"] = 5.0
    mgr.record_failure("a")  # second consecutive failure: 2x backoff
    assert mgr.blacklisted_until("a") == pytest.approx(25.0)


# ---------------------------------------------------------------------------
# Chaos grammar

def test_chaos_spec_parse():
    c = FleetChaos("seed=7;job=b,at=3,action=arrive;"
                   "job=a,at=5,action=kill,count=2,every=2;"
                   "at=8,action=preempt")
    assert c.seed == 7
    assert c.arrival_override("b") == 3.0
    assert c.arrival_override("a") is None
    assert [e.action for e in c.due(5.9)] == ["kill"]
    assert [e.action for e in c.due(8.5)] == ["kill", "preempt"]
    assert c.due(100.0) == []  # counts exhausted


def test_chaos_pick_is_seed_deterministic():
    picks1 = [FleetChaos("seed=3;at=0,action=kill").pick(["a", "b", "c"])
              for _ in range(1)]
    picks2 = [FleetChaos("seed=3;at=0,action=kill").pick(["c", "b", "a"])
              for _ in range(1)]
    assert picks1 == picks2  # candidates sorted; same seed, same pick


@pytest.mark.parametrize("spec", [
    "garbage",
    "action=explode",
    "seed=x",
    "job=a",  # no action
    "at=-1,action=kill",
    "action=kill,count=0",
    "action=arrive",  # arrive needs an explicit job
    "action=kill,frobnicate=1",
])
def test_chaos_spec_rejects_garbage(spec):
    with pytest.raises(FleetChaosError):
        FleetChaos(spec)


# ---------------------------------------------------------------------------
# Fleet metrics

def test_fleet_metrics_snapshot_and_prometheus():
    m = FleetMetrics()
    m.inc("fleet_admissions_total")
    m.inc("fleet_preemptions_total", 2)
    m.set_gauge("fleet_jobs_running", 3)
    m.observe("fleet_drain_seconds", 0.7)
    snap = m.snapshot()
    assert snap["counters"]["fleet_admissions_total"] == 1
    assert snap["counters"]["fleet_preemptions_total"] == 2
    assert snap["gauges"]["fleet_jobs_running"] == 3
    h = snap["histograms"]["fleet_drain_seconds"]
    assert h["count"] == 1 and h["sum"] == pytest.approx(0.7)
    text = render_prometheus(m)
    assert "hvdtpu_fleet_admissions_total 1" in text
    assert "hvdtpu_fleet_drain_seconds_bucket" in text
    assert 'le="+Inf"' in text


# ---------------------------------------------------------------------------
# JobSpec / controller planning (fake drivers, no processes)

def test_jobspec_validation():
    with pytest.raises(ValueError):
        JobSpec("j", ["x"], np=1, min_np=2)
    with pytest.raises(ValueError):
        JobSpec.from_dict({"name": "j", "command": "x", "np": 1,
                           "bogus": True})
    spec = JobSpec.from_dict({"name": "j", "command": "python t.py",
                              "np": 2})
    assert spec.command == ["python", "t.py"]
    assert spec.max_np == 2


class _FakeDriver:
    """Controller-facing surface of ElasticDriver, slot-accurate."""

    def __init__(self, pool, job_name, np_now):
        self._pool = pool
        self._job = job_name
        self._wids = list(range(np_now))
        self.max_np = np_now
        self.drain_requests = []
        self._draining = False

    def live_per_host(self):
        out, left = {}, len(self._wids)
        for host, slots in sorted(self._pool.lease_of(self._job).items()):
            take = min(slots, left)
            if take:
                out[host] = take
                left -= take
        return out

    def live_workers(self):
        return sorted(self._wids)

    def worker_pid(self, wid):
        return None

    def resize(self, max_np):
        self.max_np = max_np

    def request_drain(self, victims, grace=None):
        self.drain_requests.append((victims, grace))
        if victims == "all":
            self._wids = []
        else:
            self._wids = [w for w in self._wids
                          if str(w) not in [str(v) for v in victims]]
        self._draining = False  # fake: drain completes instantly

    def draining(self):
        return self._draining

    def terminate(self):
        pass


def _fake_controller(hosts, monkeypatch):
    controller = FleetController(FixedHosts(hosts))
    controller._start = time.monotonic()
    controller.pool.refresh()

    def fake_start(job, granted):
        job.driver = _FakeDriver(controller.pool, job.name,
                                 sum(granted.values()))

    monkeypatch.setattr(controller, "_start_driver", fake_start)
    return controller


def test_gang_admission_and_backoff(monkeypatch):
    controller = _fake_controller({"h": 4}, monkeypatch)
    a = controller.submit(JobSpec("a", ["x"], np=3, min_np=3))
    b = controller.submit(JobSpec("b", ["x"], np=2, min_np=2))
    now = time.monotonic()
    assert controller._try_admit(a, now)
    assert a.state == RUNNING
    assert controller.pool.leased_slots_of("a") == 3
    # b's gang of 2 cannot fit into the single free slot: nothing
    # leased, backoff armed, retry counter bumped.
    assert not controller._try_admit(b, now)
    assert b.state == PENDING
    assert controller.pool.leased_slots_of("b") == 0
    assert b.next_try > now
    assert controller.metrics.get("fleet_admission_retries_total") == 1


def test_preemption_prefers_shrink_over_kill(monkeypatch):
    controller = _fake_controller({"h": 4}, monkeypatch)
    a = controller.submit(JobSpec("a", ["x"], np=4, min_np=1,
                                  priority=0))
    b = controller.submit(JobSpec("b", ["x"], np=2, min_np=2,
                                  priority=5))
    now = time.monotonic()
    assert controller._try_admit(a, now)
    assert controller._preempt_for(b)
    # a was SHRUNK (drain of its 2 youngest workers), not killed.
    assert a.state == RUNNING
    assert a.driver.drain_requests[0][0] == [2, 3]
    assert a.driver.max_np == 2
    # The fake drain completed instantly; reconciliation frees slots.
    controller._finish_shrinks(time.monotonic())
    assert controller.pool.free_slots() == 2
    assert controller._try_admit(b, time.monotonic())
    assert controller.metrics.get("fleet_shrinks_total") == 1


def test_preemption_full_when_shrink_cannot_cover(monkeypatch):
    controller = _fake_controller({"h": 2}, monkeypatch)
    a = controller.submit(JobSpec("a", ["x"], np=2, min_np=2,
                                  priority=0))
    b = controller.submit(JobSpec("b", ["x"], np=2, min_np=2,
                                  priority=5))
    now = time.monotonic()
    assert controller._try_admit(a, now)
    assert controller._preempt_for(b)
    assert a.state == DRAINING
    assert a.driver.drain_requests[0][0] == "all"


def test_no_preemption_of_equal_or_higher_priority(monkeypatch):
    controller = _fake_controller({"h": 2}, monkeypatch)
    a = controller.submit(JobSpec("a", ["x"], np=2, min_np=1,
                                  priority=5))
    b = controller.submit(JobSpec("b", ["x"], np=2, min_np=1,
                                  priority=5))
    now = time.monotonic()
    assert controller._try_admit(a, now)
    assert not controller._preempt_for(b)
    assert a.state == RUNNING and not a.driver.drain_requests


def test_no_grow_while_higher_priority_waits(monkeypatch):
    controller = _fake_controller({"h": 4}, monkeypatch)
    a = controller.submit(JobSpec("a", ["x"], np=4, min_np=1,
                                  priority=0))
    b = controller.submit(JobSpec("b", ["x"], np=4, min_np=4,
                                  priority=5))
    now = time.monotonic()
    assert controller._try_admit(a, now)
    controller._shrink(a, 1, b)
    controller._finish_shrinks(time.monotonic())
    assert controller.pool.free_slots() == 3
    # b (min_np=4) still cannot fit, but it outranks a: a must NOT eat
    # the free slots back while b waits.
    controller._grow_running(time.monotonic())
    assert controller.pool.leased_slots_of("a") == 1
    # Once b is gone (failed/done), a grows back toward max_np.
    b.state = "failed"
    controller._grow_running(time.monotonic())
    assert controller.pool.leased_slots_of("a") == 4
    assert a.driver.max_np == 4
    assert controller.metrics.get("fleet_grows_total") == 3


def test_reap_mid_shrink_clears_stale_shrink_state(monkeypatch):
    # A job that dies (or is fully drained) while a partial shrink is
    # still pending must not carry shrink_target into its next
    # incarnation: a stale target would make _finish_shrinks release
    # slots freshly leased to the restarted driver.
    controller = _fake_controller({"h": 4}, monkeypatch)
    a = controller.submit(JobSpec("a", ["x"], np=4, min_np=1,
                                  priority=0, max_restarts=1))
    b = controller.submit(JobSpec("b", ["x"], np=2, min_np=2,
                                  priority=5))
    now = time.monotonic()
    assert controller._try_admit(a, now)
    controller._shrink(a, 2, b)
    assert a.shrink_target == 2 and a.drain_started is not None
    # a dies mid-shrink (driver thread finished with rc=1).
    a.rc = 1
    a.thread = type("T", (), {"join": lambda self, timeout=None: None,
                              "is_alive": lambda self: False})()
    controller._reap_job(a, time.monotonic())
    assert a.state == PENDING and a.restarts == 1
    assert a.shrink_target is None and a.drain_started is None
    # Restarted at full size: _finish_shrinks must not steal the fresh
    # lease out from under the new driver.
    assert controller._try_admit(a, time.monotonic())
    controller._finish_shrinks(time.monotonic())
    assert controller.pool.leased_slots_of("a") == 4
    assert controller.metrics.get("fleet_shrinks_total") == 0


# ---------------------------------------------------------------------------
# E2E helpers

LOG_COMMIT = re.compile(
    r"job (\S+) worker (\S+) commit step (\d+) crc ([0-9a-f]{8})")
LOG_START = re.compile(
    r"job (\S+) worker (\S+) start step (\d+) crc ([0-9a-f]{8}) size (\d+)")
LOG_DONE = re.compile(
    r"job (\S+) worker (\S+) done step (\d+) crc ([0-9a-f]{8})")


def _fleet_env(extra=None):
    from tests.conftest import clean_worker_env
    env = clean_worker_env(extra)
    return env


def _wait_for(predicate, timeout, what, poll=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(poll)
    raise AssertionError("timed out after %ss waiting for %s"
                         % (timeout, what))


def _read(path):
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return ""


def assert_lineage_consistent(out):
    """The chaos/restore invariant: every (re)entry at step > 0 must
    carry the crc of a state that job COMMITTED earlier — bitwise
    consistency with the checkpoint lineage."""
    committed = {}  # (job, step) -> set of crcs
    checked = 0
    for line in out.splitlines():
        m = LOG_COMMIT.search(line)
        if m:
            committed.setdefault((m.group(1), int(m.group(3))),
                                 set()).add(m.group(4))
            continue
        m = LOG_START.search(line)
        if m and int(m.group(3)) > 0:
            job, step, crc = m.group(1), int(m.group(3)), m.group(4)
            assert crc in committed.get((job, step), set()), (
                "job %s resumed at step %d with crc %s, which was "
                "never committed (lineage: %s)"
                % (job, step, crc,
                   sorted(k for k in committed if k[0] == job)))
            checked += 1
    return checked


# ---------------------------------------------------------------------------
# E2E: --drain-grace SIGTERM drains through a durable commit of the
# drained step, and the job resumes from it at equal AND smaller size
# (satellites 2 + 3)

@pytest.mark.e2e
def test_drain_grace_durable_commit_and_resume(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    log = str(tmp_path / "out.log")
    env = _fleet_env({
        "HVD_TPU_CKPT_DIR": ckpt,
        # Sparse durable cadence: only the very first commit would be
        # durable on its own, so the manifest for the DRAINED step can
        # only exist if the drain force-wrote it (not an older sticky
        # anchor).
        "HVD_TPU_CKPT_EVERY_N_COMMITS": "1000",
        "FLEET_TEST_JOB": "s",
        "FLEET_TEST_TOTAL_STEPS": "500",
        "FLEET_TEST_STEP_SLEEP": "0.1",
    })
    with open(log, "w") as logf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.run.run", "-np", "2",
             "--drain-grace", "30", "--",
             sys.executable,
             os.path.join(REPO_ROOT, "tests", "fleet_worker.py")],
            env=env, stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True)
    try:
        _wait_for(
            lambda: len(LOG_COMMIT.findall(_read(log))) >= 10,
            timeout=90, what="10 commits before the drain")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
    finally:
        if proc.poll() is None:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait(timeout=10)
    out = _read(log)
    assert rc == EXIT_DRAINED, (rc, out)
    assert "drain requested" in out
    assert "exiting with EXIT_DRAINED" in out
    # Escalation must NOT have fired: the workers drained voluntarily.
    assert "escalating" not in out

    from horovod_tpu.elastic.durable import last_durable_step
    drained_step, _ = last_durable_step(ckpt)
    commits = [(int(s), c) for _, _, s, c in LOG_COMMIT.findall(out)]
    max_commit = max(s for s, _ in commits)
    # The durable manifest is for the DRAINED step — the step the
    # workers were at when the drain landed — not the step-1 anchor the
    # sparse cadence would have left behind.
    assert drained_step == max_commit, (drained_step, max_commit)
    drained_crcs = {c for s, c in commits if s == drained_step}

    # Resume at EQUAL world size (2) and SMALLER world size (1): both
    # start bitwise-identically from the drained commit. Each resume
    # gets a pristine copy of the drained lineage — a resumed run
    # writes its own fresh durable anchor, which would otherwise leak
    # into the next resume's view.
    import shutil
    for np_resume in (2, 1):
        ckpt_copy = str(tmp_path / ("ckpt-resume-%d" % np_resume))
        shutil.copytree(ckpt, ckpt_copy)
        resume_env = dict(env)
        resume_env["HVD_TPU_CKPT_DIR"] = ckpt_copy
        resume_env["FLEET_TEST_TOTAL_STEPS"] = str(drained_step + 3)
        result = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run.run",
             "-np", str(np_resume), "--",
             sys.executable,
             os.path.join(REPO_ROOT, "tests", "fleet_worker.py")],
            env=resume_env, timeout=120, capture_output=True, text=True)
        assert result.returncode == 0, (np_resume, result.stdout,
                                        result.stderr)
        starts = LOG_START.findall(result.stdout)
        assert starts, result.stdout
        for _, _, step, crc, size in starts:
            assert int(step) == drained_step, (np_resume, starts)
            assert crc in drained_crcs, (np_resume, starts, drained_crcs)
            assert int(size) == np_resume


# ---------------------------------------------------------------------------
# E2E: fleet preemption — drain, reclaim, restore, all observable
# (tentpole acceptance: /fleet + hvd-top --fleet + fleet_* metrics)

@pytest.mark.e2e
def test_fleet_preempt_reclaim_restore_observable(tmp_path):
    jobfile = {
        "hosts": "localhost:2",
        "drain_grace": 30,
        "jobs": [
            # min_np == np == pool size: the only way to fit "hi" is a
            # WHOLE-JOB preemption of "lo", and the only way to finish
            # "lo" afterwards is a full restore from its lineage.
            {"name": "lo", "command":
                "%s %s" % (sys.executable,
                           os.path.join(REPO_ROOT, "tests",
                                        "fleet_worker.py")),
             "np": 2, "min_np": 2, "priority": 0,
             "ckpt_dir": str(tmp_path / "ckpt-lo"),
             "env": {"FLEET_TEST_JOB": "lo",
                     "FLEET_TEST_TOTAL_STEPS": "60",
                     "FLEET_TEST_STEP_SLEEP": "0.2"}},
            {"name": "hi", "command":
                "%s %s" % (sys.executable,
                           os.path.join(REPO_ROOT, "tests",
                                        "fleet_worker.py")),
             "np": 2, "min_np": 2, "priority": 10, "arrival": 6.0,
             "ckpt_dir": str(tmp_path / "ckpt-hi"),
             "env": {"FLEET_TEST_JOB": "hi",
                     "FLEET_TEST_TOTAL_STEPS": "8",
                     "FLEET_TEST_STEP_SLEEP": "0.2"}},
        ],
    }
    jobfile_path = tmp_path / "jobs.json"
    jobfile_path.write_text(json.dumps(jobfile))
    log = str(tmp_path / "fleet.log")
    env = _fleet_env({"HVD_TPU_ELASTIC_COOLDOWN": "2"})
    with open(log, "w") as logf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.fleet.cli",
             "--port", "0", str(jobfile_path)],
            env=env, stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True)
    try:
        port = int(_wait_for(
            lambda: (re.search(r"metrics at http://localhost:(\d+)",
                               _read(log)) or [None, None])[1],
            timeout=30, what="controller metrics port"))

        def fleet_view():
            with urllib.request.urlopen(
                    "http://localhost:%d/fleet" % port,
                    timeout=5) as resp:
                return json.loads(resp.read().decode())

        # The drain → reclaim cycle is OBSERVABLE: at some poll, job lo
        # is draining or already preempted while hi holds/waits for the
        # slots.
        seen_states = set()

        def lo_preempted():
            view = fleet_view()
            seen_states.add(view["jobs"]["lo"]["state"])
            return ("preempted" in seen_states
                    or "draining" in seen_states)

        _wait_for(lo_preempted, timeout=90,
                  what="job lo draining/preempted in /fleet")

        # hvd-top --fleet renders the cross-job view against the live
        # endpoint.
        top = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bin", "hvd-top"),
             "--fleet", "--once", "localhost:%d" % port],
            env=env, timeout=30, capture_output=True, text=True)
        assert top.returncode == 0, (top.stdout, top.stderr)
        assert "lo" in top.stdout and "hi" in top.stdout
        assert "preempted" in top.stdout or "draining" in top.stdout

        # The fleet_* Prometheus plane records the drain cycle live.
        with urllib.request.urlopen(
                "http://localhost:%d/metrics" % port, timeout=5) as resp:
            prom = resp.read().decode()
        assert "hvdtpu_fleet_drains_requested_total" in prom
        assert re.search(
            r"hvdtpu_fleet_drains_requested_total \d", prom), prom
        assert "hvdtpu_fleet_drain_seconds" in prom
        assert "hvdtpu_fleet_jobs_preempted" in prom

        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait(timeout=10)
    out = _read(log)
    assert rc == 0, out
    # Both jobs completed; lo was preempted and restored.
    assert len(LOG_DONE.findall(out)) >= 2, out
    assert "preempting job lo" in out
    assert "job lo preempted" in out
    assert "job lo restored" in out
    # The restore resumed bitwise-consistently with the lineage.
    assert assert_lineage_consistent(out) >= 1
    # fleet_* metrics recorded the full cycle (the controller logs its
    # counters through /metrics; check the final ones via the log's
    # Prometheus scrape is gone with the process, so re-derive from
    # events above plus the drain/restore latency histograms having
    # been observed — asserted through the controller's own summary).
    assert "fleet finished: all 2 job(s) completed" in out


# ---------------------------------------------------------------------------
# E2E: serve/train co-tenancy (ISSUE 16 acceptance) — a serving job is
# a first-class JobSpec: it preempts lower-priority training via the
# same graceful drain, answers traffic from its checkpoint lineage
# while it holds the chips, and when traffic subsides (replicas exit 0)
# the training job restores from its durable lineage.

@pytest.mark.e2e
def test_fleet_serve_cotenancy_preempts_and_training_restores(tmp_path):
    from horovod_tpu.serve import model as smodel
    from horovod_tpu.serve.client import ServeClient
    from horovod_tpu.serve.swap import publish_leaves
    from tests.test_serve import _free_port_base

    dim = 4
    leaves = smodel.init_leaves("affine", dim, seed=11)
    crc = smodel.fingerprint(leaves)
    serve_ckpt = str(tmp_path / "ckpt-serve")
    publish_leaves(serve_ckpt, 10, leaves)
    port_base = _free_port_base(2)

    jobfile = {
        "hosts": "localhost:2",
        "drain_grace": 30,
        "jobs": [
            # min_np == np == pool size: serving can only be admitted
            # by a whole-job preemption of the training gang.
            {"name": "train0", "command":
                "%s %s" % (sys.executable,
                           os.path.join(REPO_ROOT, "tests",
                                        "fleet_worker.py")),
             "np": 2, "min_np": 2, "priority": 0,
             "ckpt_dir": str(tmp_path / "ckpt-train"),
             "env": {"FLEET_TEST_JOB": "train0",
                     "FLEET_TEST_TOTAL_STEPS": "60",
                     "FLEET_TEST_STEP_SLEEP": "0.2"}},
            {"name": "serve0", "kind": "serve",
             "command": "%s -m horovod_tpu.serve.replica"
                        % sys.executable,
             "np": 2, "min_np": 2, "priority": 10, "arrival": 4.0,
             "ckpt_dir": serve_ckpt,
             "env": {"HVD_TPU_SERVE_JIT": "0",
                     "HVD_TPU_SERVE_MODEL": "affine",
                     "HVD_TPU_SERVE_DIM": str(dim),
                     "HVD_TPU_SERVE_PORT": str(port_base),
                     # "Traffic subsides": replicas retire after 6s of
                     # serving and exit 0, completing the job.
                     "HVD_TPU_SERVE_EXIT_AFTER": "6"}},
        ],
    }
    jobfile_path = tmp_path / "jobs.json"
    jobfile_path.write_text(json.dumps(jobfile))
    log = str(tmp_path / "fleet.log")
    env = _fleet_env({"HVD_TPU_ELASTIC_COOLDOWN": "2"})
    with open(log, "w") as logf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.fleet.cli",
             "--port", "0", str(jobfile_path)],
            env=env, stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True)
    try:
        port = int(_wait_for(
            lambda: (re.search(r"metrics at http://localhost:(\d+)",
                               _read(log)) or [None, None])[1],
            timeout=30, what="controller metrics port"))

        def fleet_view():
            with urllib.request.urlopen(
                    "http://localhost:%d/fleet" % port,
                    timeout=5) as resp:
                return json.loads(resp.read().decode())

        # The serving job carries its kind/placement through /fleet.
        view = _wait_for(lambda: fleet_view(), timeout=10,
                         what="/fleet view")
        assert view["jobs"]["serve0"]["kind"] == "serve"
        assert view["jobs"]["serve0"]["placement"] == "spread"
        assert view["jobs"]["train0"]["kind"] == "train"

        # While the serving job holds the chips, it ANSWERS — with the
        # weights of its published lineage (fingerprint-checked).
        endpoints = ["127.0.0.1:%d" % (port_base + wid)
                     for wid in (0, 1)]
        client = ServeClient(endpoints, total_deadline=45.0,
                             attempt_timeout=3.0)
        x = np.arange(dim, dtype=np.float32)
        doc = client.infer(x, rid="cotenancy")
        assert doc["weights_crc"] == crc, doc
        assert doc["model_step"] == 10
        assert np.allclose(doc["y"],
                           smodel.forward("affine", leaves, x),
                           atol=1e-4)

        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait(timeout=10)
    out = _read(log)
    assert rc == 0, out
    # The serving job preempted training via the graceful drain...
    assert "preempting job train0" in out
    assert "job train0 preempted" in out
    # ...and training restored from its durable lineage after traffic
    # subsided, resuming bitwise-consistently.
    assert "job train0 restored" in out
    assert assert_lineage_consistent(out) >= 1
    assert "fleet finished: all 2 job(s) completed" in out


# ---------------------------------------------------------------------------
# E2E: seeded fleet chaos — arrivals + random SIGKILLs + forced
# preemption over 3 concurrent jobs (acceptance criterion; slow tier)

@pytest.mark.e2e
@pytest.mark.slow
def test_fleet_chaos_schedule(tmp_path):
    worker = os.path.join(REPO_ROOT, "tests", "fleet_worker.py")

    def job(name, np_, min_np, priority, arrival=0.0, steps=40,
            sleep=0.15):
        return {"name": name,
                "command": "%s %s" % (sys.executable, worker),
                "np": np_, "min_np": min_np, "priority": priority,
                "arrival": arrival,
                "ckpt_dir": str(tmp_path / ("ckpt-%s" % name)),
                "env": {"FLEET_TEST_JOB": name,
                        "FLEET_TEST_TOTAL_STEPS": str(steps),
                        "FLEET_TEST_STEP_SLEEP": str(sleep)}}

    jobfile = {
        "hosts": "localhost:4",
        "drain_grace": 30,
        "jobs": [
            job("a", 2, 1, priority=0, steps=60),
            job("b", 2, 1, priority=3, steps=40),
            job("c", 2, 2, priority=8, steps=25),
        ],
    }
    jobfile_path = tmp_path / "jobs.json"
    jobfile_path.write_text(json.dumps(jobfile))
    log = str(tmp_path / "fleet.log")
    env = _fleet_env({
        "HVD_TPU_ELASTIC_COOLDOWN": "2",
        # Seeded schedule: b arrives at t=4, c at t=8 (its gang of 2
        # with min_np=2 forces preemption pressure), a random worker of
        # a is SIGKILLed twice, and b eats one forced preemption at t=6
        # — while its ~6s of stepping is guaranteed still in flight
        # (a late preempt would be dropped against an already-done b).
        "HVD_TPU_FLEET_CHAOS_SPEC":
            "seed=1702;job=b,at=4,action=arrive;"
            "job=c,at=8,action=arrive;"
            "job=a,at=6,action=kill,count=2,every=5;"
            "job=b,at=6,action=preempt",
    })
    with open(log, "w") as logf:
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.fleet.cli",
             "--port", "0", "--timeout", "420", str(jobfile_path)],
            env=env, stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True)
    try:
        rc = proc.wait(timeout=480)
    finally:
        if proc.poll() is None:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait(timeout=10)
    out = _read(log)
    assert rc == 0, out[-4000:]
    # Every job completed...
    for name in ("a", "b", "c"):
        assert re.search(r"job %s worker \S+ done step" % name, out), (
            "job %s never printed done\n%s" % (name, out[-4000:]))
    # ...the chaos actually happened...
    assert out.count("chaos: SIGKILL") >= 1, out
    assert "chaos: forced preemption of job b" in out
    # ...every resume was bitwise-consistent with the lineage...
    assert assert_lineage_consistent(out) >= 1
    # ...and the pool never double-assigned a host.
    assert "OCCUPANCY VIOLATION" not in out
    assert "fleet finished: all 3 job(s) completed" in out
