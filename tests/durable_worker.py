"""Durable-checkpoint e2e worker: deterministic quadratic training with
durable commits (docs/ELASTIC.md "Durability").

Run under the launcher with ``HVD_TPU_CKPT_DIR`` (``--ckpt-dir``) set;
``@elastic.run`` auto-enables durable commits and auto-resumes from the
newest valid manifest. Every durable commit prints a CRC32C fingerprint
of the full state, and the first line inside ``train()`` prints the
state the run STARTED from — so the kill-everything tests can assert a
relaunch resumes bitwise-identically to what was committed.

Knobs (env):
  DURABLE_TEST_TOTAL_STEPS  total optimization steps        (default 24)
  DURABLE_TEST_COMMIT_EVERY commit cadence in steps         (default 2)
  DURABLE_TEST_STEP_SLEEP   per-step sleep seconds          (default 0.1)
  DURABLE_TEST_CRASH_STEP   step at which crashers exit(31) (-1 = never)
  DURABLE_TEST_CRASH_WIDS   csv of worker ids that crash (generation 0
                            only, so restarted/resumed runs never
                            re-crash)
  DURABLE_TEST_PID_DIR      write pid.<wid> files here so a test can
                            SIGKILL the worker processes directly
"""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic
from horovod_tpu.elastic import durable

TOTAL_STEPS = int(os.environ.get("DURABLE_TEST_TOTAL_STEPS", "24"))
COMMIT_EVERY = int(os.environ.get("DURABLE_TEST_COMMIT_EVERY", "2"))
STEP_SLEEP = float(os.environ.get("DURABLE_TEST_STEP_SLEEP", "0.1"))
CRASH_STEP = int(os.environ.get("DURABLE_TEST_CRASH_STEP", "-1"))
CRASH_WIDS = set(
    w for w in os.environ.get("DURABLE_TEST_CRASH_WIDS", "").split(",")
    if w)
LR = 0.05
TARGET = 3.0

WID = os.environ.get("HVD_TPU_WORKER_ID", "?")


def state_crc(state):
    """CRC32C over the full state bytes — bitwise identity check."""
    crc = durable.crc32c(np.ascontiguousarray(state.w).tobytes())
    return durable.crc32c(("step=%d" % state.step).encode(), crc)


@elastic.run
def train(state):
    print("worker %s start step %d crc %08x size %d"
          % (WID, state.step, state_crc(state), hvd.size()), flush=True)
    while state.step < TOTAL_STEPS:
        gen = int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)
        grad_local = 2.0 * (state.w - TARGET)
        grad = np.asarray(hvd.allreduce(grad_local, "grad", average=True))
        state.w = state.w - LR * grad
        state.step += 1
        if WID in CRASH_WIDS and gen == 0 and state.step == CRASH_STEP:
            # Drain the async writer first so the LAST durable commit is
            # deterministic for the driver-restart test's exact-step
            # assertion (crash-mid-write atomicity is covered separately
            # by the SIGKILL-everything test, where the kill is external
            # and the restore may legitimately land on an older valid
            # manifest).
            if state.durable is not None:
                state.durable.flush(timeout=60)
            print("worker %s crashing now" % WID, flush=True)
            os._exit(31)
        if state.step % COMMIT_EVERY == 0:
            state.commit()
            print("worker %s commit step %d crc %08x"
                  % (WID, state.step, state_crc(state)), flush=True)
        time.sleep(STEP_SLEEP)
    return float(np.sum((state.w - TARGET) ** 2))


def main():
    pid_dir = os.environ.get("DURABLE_TEST_PID_DIR")
    if pid_dir:
        with open(os.path.join(pid_dir, "pid.%s" % WID), "w") as f:
            f.write(str(os.getpid()))
    state = elastic.ElasticState(w=np.zeros(4, np.float64), step=0)
    final_loss = train(state)
    if final_loss is None:  # job finished before this worker could join
        print("worker %s superseded (job already complete)" % WID,
              flush=True)
        return 0
    print("worker %s done step %d crc %08x loss %.6f"
          % (WID, state.step, state_crc(state), final_loss), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
