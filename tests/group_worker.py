"""Worker for the process-group e2e suite (test_groups.py).

Modes (GROUP_MODE env):
  ops — 4 ranks: disjoint groups {0,2}/{1,3} run every collective kind
      with rank remapping, the SAME tensor name active in both groups
      concurrently (the 2-D mesh's per-column shape), plus a nontrivial
      whole-world group; asserts exact values and group metrics.
  cache — repeated steps in a 2-group job must HIT the response cache in
      both groups (fast-path cycles), and re-scoping a cached name to a
      DIFFERENT group must read INVALID -> renegotiate (membership
      change semantics, like a compression-mode change).
  wire — measures per-collective socket bytes: a model-group allreduce
      must move <= (group/world + 5%%) of the same tensor's full-world
      allreduce (summed across ranks; the BENCH_r09 acceptance).
  reject — non-member submission fails immediately at enqueue; ranks
      that created the same group id with DIFFERENT member lists are
      rejected at negotiation naming the mixed membership.
"""

import os
import signal
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.ops import HorovodInternalError


def alarm(signum, frame):
    sys.stderr.write("watchdog fired: job deadlocked\n")
    sys.exit(3)


signal.signal(signal.SIGALRM, alarm)
signal.alarm(150)

mode = os.environ.get("GROUP_MODE", "ops")
hvd.init()
r, n = hvd.rank(), hvd.size()


def ring_bytes():
    c = hvd.metrics()["counters"]
    return c["net_ring_bytes_sent_total"]


if mode == "ops":
    assert n == 4
    g_even = hvd.new_group([0, 2])
    g_odd = hvd.new_group([1, 3])
    g_all = hvd.new_group(range(n))
    mine = g_even if r % 2 == 0 else g_odd
    members = list(mine.ranks)
    assert mine.rank() == members.index(r)
    assert mine.size() == 2

    # Same tensor NAME in two disjoint groups concurrently.
    out = ops.allreduce(np.full(7, float(r + 1), np.float32), "grad.0",
                        group=mine)
    assert np.allclose(out, sum(m + 1 for m in members)), (r, out)

    # Broadcast: root is a WORLD rank, remapped to the group ring.
    root = members[1]
    out = ops.broadcast(np.full(3, float(r), np.float32), root, "bc.0",
                        group=mine)
    assert np.allclose(out, float(root)), (r, out)

    # Allgather: blocks in group order, uneven first dims.
    out = ops.allgather(np.full((r + 1, 2), float(r), np.float32), "ag.0",
                        group=mine)
    exp = np.concatenate([np.full((m + 1, 2), float(m), np.float32)
                          for m in members])
    assert out.shape == exp.shape and np.allclose(out, exp), (r, out.shape)

    # Reduce-scatter: shard i to group member i.
    t = np.arange(10, dtype=np.float32) + r
    out = ops.reduce_scatter(t, "rs.0", group=mine)
    counts, offsets = ops.shard_partition(10, 2)
    gr = mine.rank()
    full = sum(np.arange(10, dtype=np.float32) + m for m in members)
    exp = full[offsets[gr]:offsets[gr] + counts[gr]]
    assert np.allclose(out, exp), (r, out, exp)

    # Average divides by the GROUP size.
    out = ops.allreduce(np.full(4, float(r), np.float32), "avg.0",
                        average=True, group=mine)
    assert np.allclose(out, sum(members) / 2.0), (r, out)

    # A whole-world group with a NONTRIVIAL id behaves like the world.
    out = ops.allreduce(np.ones(5, np.float32), "world.0", group=g_all)
    assert np.allclose(out, n), (r, out)

    m = hvd.metrics()
    assert m["gauges"]["groups"] == 3, m["gauges"]
    assert m["counters"]["group_tensors_total"] >= 6, m["counters"]
    if r == 0:
        # Coordinator-side group-labeled negotiation counters.
        per_group = m.get("per_group", {})
        assert per_group and all(int(v["negotiated_total"]) > 0
                                 for v in per_group.values()), per_group
    print("rank %d group ops ok" % r, flush=True)

elif mode == "cache":
    assert n == 4
    g_even = hvd.new_group([0, 2])
    g_odd = hvd.new_group([1, 3])
    mine = g_even if r % 2 == 0 else g_odd
    steps = 8
    for step in range(steps):
        out = ops.allreduce(np.full(64, float(r), np.float32), "c.t",
                            group=mine)
        assert np.allclose(out, sum(mine.ranks)), (r, step, out)
    c = hvd.metrics()["counters"]
    # Steps 2.. must ride the cached fast path in BOTH groups.
    assert c["cache_hit_total"] >= steps - 2, c
    assert c["cycles_fast_total"] >= 1, c
    hits_before = c["cache_hit_total"]

    # Membership change: the same tensor name re-scoped to a NEW group
    # id must read INVALID (erase + renegotiate), not silently reuse the
    # old group's cached response.
    g_new = hvd.new_group([0, 1, 2, 3])
    out = ops.allreduce(np.full(64, float(r), np.float32), "c.t",  # hvd-lint: disable=duplicate-collective-name
                        group=g_new)
    assert np.allclose(out, sum(range(n))), (r, out)
    c = hvd.metrics()["counters"]
    assert c["cache_invalid_total"] >= 1, c
    # And the new scope caches again.
    for step in range(3):
        out = ops.allreduce(np.full(64, float(r), np.float32), "c.t",  # hvd-lint: disable=duplicate-collective-name
                            group=g_new)
        assert np.allclose(out, sum(range(n))), (r, step, out)
    c = hvd.metrics()["counters"]
    assert c["cache_hit_total"] > hits_before, c
    print("rank %d group cache ok (hits=%d invalid=%d)"
          % (r, c["cache_hit_total"], c["cache_invalid_total"]), flush=True)

elif mode == "wire":
    assert n == 4
    group = hvd.new_group([0, 1])  # the "model group" of the A/B
    elems = 1 << 18  # 1 MiB f32 payload: frame headers are noise
    x = np.full(elems, float(r + 1), np.float32)

    # Warm-up builds rings and settles negotiation so the measured
    # deltas are pure collective traffic.
    ops.allreduce(x, "warm.world")
    if r in group.ranks:
        ops.allreduce(x, "warm.grp", group=group)

    b0 = ring_bytes()
    ops.allreduce(x, "wire.world")
    b1 = ring_bytes()
    if r in group.ranks:
        ops.allreduce(x, "wire.grp", group=group)
    b2 = ring_bytes()
    print("rank %d wire world=%d group=%d" % (r, b1 - b0, b2 - b1),
          flush=True)

elif mode == "reject":
    assert n == 2
    g0 = hvd.new_group([0])
    # Non-member submission fails at enqueue, naming rank and group.
    if r == 1:
        try:
            ops.allreduce(np.ones(3, np.float32), "nm.0", group=g0)  # hvd-lint: disable=verify-non-member-group-call
            raise AssertionError("non-member allreduce did not fail")
        except HorovodInternalError as e:
            assert "not a member" in str(e), e
    # Unknown group id.
    try:
        ops.allreduce(np.ones(3, np.float32), "ug.0", group=999)
        raise AssertionError("unknown-group allreduce did not fail")
    except HorovodInternalError as e:
        assert "unknown process group" in str(e), e
    # Mixed membership: both ranks create group id 2, with DIFFERENT
    # member lists (a new_group discipline violation). Rank 1's
    # announcement carries a digest that disagrees with the
    # coordinator's registry and is rejected by name.
    # id 2 everywhere; members differ!
    # hvd-lint: disable=verify-divergent-schedule
    g2 = hvd.new_group([r])
    if r == 0:
        # The coordinator's registry says {0}. Depending on announcement
        # order, rank 0's own submission either completes alone (its
        # announcement formed a fresh pending entry) or is failed
        # together with rank 1's colliding one — either way the error
        # NAMES the mixed membership; a hang is the only wrong outcome.
        try:
            out = ops.allreduce(np.ones(3, np.float32), "mm.0", group=g2)
            assert np.allclose(out, 1.0), out
        except HorovodInternalError as e:
            assert "Mixed membership" in str(e), e
    else:
        try:
            ops.allreduce(np.ones(3, np.float32), "mm.0", group=g2)  # hvd-lint: disable=duplicate-collective-name
            raise AssertionError("mixed-membership allreduce did not fail")
        except HorovodInternalError as e:
            assert "Mixed membership" in str(e) or "not a member" in \
                str(e), e
    print("rank %d group reject ok" % r, flush=True)

elif mode == "unknown":
    # Registration-order divergence: rank 1 creates (and uses) a group
    # the COORDINATOR never registered. The late-registration sweep can
    # never resolve it, so past the grace window the divergence detector
    # must error naming the unregistered group — not hang.
    assert n == 2
    import time
    if r == 1:
        g = hvd.new_group([1])  # rank 0 skips this call — the bug
        try:
            ops.allreduce(np.ones(4, np.float32), "ur.0", group=g)
            raise AssertionError("unregistered-group allreduce did not "
                                 "fail")
        except HorovodInternalError as e:
            assert "never registered that group" in str(e), e
            print("rank %d unregistered group reported" % r, flush=True)
    else:
        time.sleep(8)  # outlive rank 1's grace window
        print("rank %d coordinator survived" % r, flush=True)

else:
    raise SystemExit("unknown GROUP_MODE %r" % mode)
