"""Pipeline parallelism: the GPipe schedule over pp-sharded transformer
block stages must reproduce the unsharded model exactly (forward and
gradients), embedding/head computed outside the pipelined region."""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

jax.config.update("jax_default_matmul_precision", "highest")

from horovod_tpu.models import Transformer, TransformerConfig  # noqa: E402
from horovod_tpu.models.transformer import Block  # noqa: E402
from horovod_tpu.parallel.pipeline import (  # noqa: E402
    pipeline_apply, stack_block_params)

CFG = TransformerConfig(vocab_size=89, num_layers=4, num_heads=4,
                        embed_dim=32, mlp_dim=64, dtype=jnp.float32)
PP = 2             # stages
MB = 2             # microbatches
B, L = 4, 16       # global batch (split into MB microbatches), seq len


def _setup():
    model = Transformer(CFG)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, CFG.vocab_size, (B, L)))
    params = model.init(jax.random.PRNGKey(3), tokens)["params"]
    return model, params, tokens


def _pipeline_forward(params, tokens, mesh):
    """Embed everywhere -> pipelined blocks -> norm/head everywhere."""
    import flax.linen as nn

    block = Block(CFG)
    stacked = stack_block_params(params, CFG.num_layers)
    layers_per_stage = CFG.num_layers // PP
    # [num_layers, ...] -> [PP, layers_per_stage, ...], stage dim
    # sharded over pp.
    staged = jax.tree_util.tree_map(
        lambda x: x.reshape((PP, layers_per_stage) + x.shape[1:]),
        stacked)
    specs = jax.tree_util.tree_map(lambda _: P("pp"), staged)
    staged = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        staged, specs)

    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 (B // MB, L))

    def stage_fn(stage_params, x):
        # One stage = its group of blocks, scanned over the layer dim.
        def layer(x, p):
            return block.apply({"params": p}, x, positions), None

        y, _ = lax.scan(layer, x, stage_params)
        return y

    def run(staged_local, embed_p, norm_p, head_p, tokens):
        # staged_local arrives as [1, layers_per_stage, ...]: this
        # shard's stage.
        local = jax.tree_util.tree_map(lambda x: x[0], staged_local)
        emb = nn.Embed(CFG.vocab_size, CFG.embed_dim,
                       param_dtype=jnp.float32, dtype=CFG.dtype)
        x = emb.apply({"params": embed_p}, tokens)
        x_mb = x.reshape((MB, B // MB) + x.shape[1:])
        y_mb = pipeline_apply(stage_fn, local, x_mb, "pp")
        y = y_mb.reshape((B,) + y_mb.shape[2:])
        norm = nn.RMSNorm(dtype=CFG.dtype, param_dtype=jnp.float32)
        y = norm.apply({"params": norm_p}, y)
        logits = y @ head_p["kernel"].astype(y.dtype)
        return logits.astype(jnp.float32)

    fwd = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(specs, P(), P(), P(), P()),
        out_specs=P(), check_vma=False))
    return fwd, staged, positions


def test_pipeline_forward_matches_full_model():
    model, params, tokens = _setup()
    expected = model.apply({"params": params}, tokens)
    mesh = Mesh(np.array(jax.devices("cpu")[:PP]), ("pp",))
    fwd, staged, _ = _pipeline_forward(params, tokens, mesh)
    out = fwd(staged, params["embed"], params["norm_f"],
              params["lm_head"], tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_flow():
    """Autodiff through the schedule: gradients w.r.t. the staged block
    params must match the full model's (stacked the same way)."""
    model, params, tokens = _setup()

    def full_loss(p):
        return jnp.mean(model.apply({"params": p}, tokens) ** 2)

    g_full = jax.grad(full_loss)(params)
    g_full_stacked = stack_block_params(g_full, CFG.num_layers)

    mesh = Mesh(np.array(jax.devices("cpu")[:PP]), ("pp",))
    fwd, staged, _ = _pipeline_forward(params, tokens, mesh)

    def loss(staged):
        out = fwd(staged, params["embed"], params["norm_f"],
                  params["lm_head"], tokens)
        return jnp.mean(out ** 2)

    g = jax.grad(loss)(staged)
    g_flat = jax.tree_util.tree_flatten_with_path(g)[0]
    e_flat = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_flatten_with_path(g_full_stacked)[0]}
    layers_per_stage = CFG.num_layers // PP
    for path, got in g_flat:
        exp = e_flat[jax.tree_util.keystr(path)]
        exp = exp.reshape((PP, layers_per_stage) + exp.shape[1:])
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_pipeline_composes_with_dp():
    """(dp=2 x pp=2): batch sharded over dp, stages over pp — each dp
    row runs the GPipe schedule on its shard; output matches the full
    model. Completes the composition matrix (tp x sp, sp x ep, dp x pp
    all pinned)."""
    import flax.linen as nn

    model, params, tokens = _setup()
    expected = model.apply({"params": params}, tokens)
    dp = 2
    mesh = Mesh(np.array(jax.devices("cpu")[:dp * PP]).reshape(dp, PP),
                ("dp", "pp"))

    block = Block(CFG)
    stacked = stack_block_params(params, CFG.num_layers)
    layers_per_stage = CFG.num_layers // PP
    staged = jax.tree_util.tree_map(
        lambda x: x.reshape((PP, layers_per_stage) + x.shape[1:]),
        stacked)
    specs = jax.tree_util.tree_map(lambda _: P("pp"), staged)
    staged = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        staged, specs)

    B_local = B // dp
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 (B_local // MB, L))

    def stage_fn(stage_params, x):
        def layer(x, p):
            return block.apply({"params": p}, x, positions), None
        return lax.scan(layer, x, stage_params)[0]

    def run(staged_local, embed_p, norm_p, head_p, tokens):
        local = jax.tree_util.tree_map(lambda x: x[0], staged_local)
        emb = nn.Embed(CFG.vocab_size, CFG.embed_dim,
                       param_dtype=jnp.float32, dtype=CFG.dtype)
        x = emb.apply({"params": embed_p}, tokens)
        x_mb = x.reshape((MB, B_local // MB) + x.shape[1:])
        y_mb = pipeline_apply(stage_fn, local, x_mb, "pp")
        y = y_mb.reshape((B_local,) + y_mb.shape[2:])
        norm = nn.RMSNorm(dtype=CFG.dtype, param_dtype=jnp.float32)
        y = norm.apply({"params": norm_p}, y)
        logits = y @ head_p["kernel"].astype(y.dtype)
        return logits.astype(jnp.float32)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(specs, P(), P(), P(), P("dp")),
        out_specs=P("dp"), check_vma=False))(
            staged, params["embed"], params["norm_f"],
            params["lm_head"], tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_inprocess_grad_sync_contract():
    """Training INSIDE shard_map (local loss per rank): the output
    collection is a psum, whose transpose SUMS every rank's identical
    loss cotangent — pipeline-internal cotangents arrive pp-fold. The
    clean contract: scale the local loss by 1/pp; then staged block
    grads are complete as-is and every non-staged param (embed before
    the pipeline, norm/head after) needs a psum over pp. This test
    pins that rule against the full model's gradients."""
    import flax.linen as nn

    model, params, tokens = _setup()

    def full_loss(p):
        return jnp.mean(model.apply({"params": p}, tokens) ** 2)

    g_full = jax.grad(full_loss)(params)

    mesh = Mesh(np.array(jax.devices("cpu")[:PP]), ("pp",))
    block = Block(CFG)
    stacked = stack_block_params(params, CFG.num_layers)
    layers_per_stage = CFG.num_layers // PP
    staged = jax.tree_util.tree_map(
        lambda x: x.reshape((PP, layers_per_stage) + x.shape[1:]),
        stacked)
    specs = jax.tree_util.tree_map(lambda _: P("pp"), staged)
    staged = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        staged, specs)
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 (B // MB, L))

    def stage_fn(stage_params, x):
        def layer(x, p):
            return block.apply({"params": p}, x, positions), None
        return lax.scan(layer, x, stage_params)[0]

    def grads_fn(staged_local, embed_p, norm_p, head_p, tokens):
        def local_loss(staged_local, embed_p, norm_p, head_p):
            local = jax.tree_util.tree_map(lambda x: x[0], staged_local)
            emb = nn.Embed(CFG.vocab_size, CFG.embed_dim,
                           param_dtype=jnp.float32, dtype=CFG.dtype)
            x = emb.apply({"params": embed_p}, tokens)
            x_mb = x.reshape((MB, B // MB) + x.shape[1:])
            y_mb = pipeline_apply(stage_fn, local, x_mb, "pp")
            y = y_mb.reshape((B,) + y_mb.shape[2:])
            norm = nn.RMSNorm(dtype=CFG.dtype, param_dtype=jnp.float32)
            y = norm.apply({"params": norm_p}, y)
            logits = (y @ head_p["kernel"].astype(y.dtype)) \
                .astype(jnp.float32)
            # THE CONTRACT part 1: scale the local loss by 1/pp (the
            # collection psum's transpose sums pp identical cotangents).
            return jnp.mean(logits ** 2) / lax.psum(1, "pp")

        g_staged, g_embed, g_norm, g_head = jax.grad(
            local_loss, argnums=(0, 1, 2, 3))(
                staged_local, embed_p, norm_p, head_p)
        # THE CONTRACT part 2: staged grads complete; every non-staged
        # param psums over pp.
        g_embed, g_norm, g_head = jax.tree_util.tree_map(
            lambda g: lax.psum(g, "pp"), (g_embed, g_norm, g_head))
        return g_staged, g_embed, g_norm, g_head

    g_staged, g_embed, g_norm, g_head = jax.jit(jax.shard_map(
        grads_fn, mesh=mesh,
        in_specs=(specs, P(), P(), P(), P()),
        out_specs=(specs, P(), P(), P()),
        check_vma=False))(staged, params["embed"], params["norm_f"],
                          params["lm_head"], tokens)

    np.testing.assert_allclose(
        np.asarray(g_embed["embedding"]),
        np.asarray(g_full["embed"]["embedding"]), rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(
        np.asarray(g_head["kernel"]),
        np.asarray(g_full["lm_head"]["kernel"]), rtol=5e-5, atol=5e-5)
    np.testing.assert_allclose(
        np.asarray(g_norm["scale"]),
        np.asarray(g_full["norm_f"]["scale"]), rtol=5e-5, atol=5e-5)
    # Staged block grads match the full model's, stage-stacked.
    g_full_stacked = stack_block_params(g_full, CFG.num_layers)
    e_flat = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_flatten_with_path(g_full_stacked)[0]}
    for path, got in jax.tree_util.tree_flatten_with_path(g_staged)[0]:
        exp = e_flat[jax.tree_util.keystr(path)].reshape(
            (PP, layers_per_stage) +
            e_flat[jax.tree_util.keystr(path)].shape[1:])
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_pipeline_remat_matches():
    """remat=True (checkpointed stages — the 1F1B-class activation
    footprint) must not change forward values or gradients."""
    import flax.linen as nn

    model, params, tokens = _setup()
    mesh = Mesh(np.array(jax.devices("cpu")[:PP]), ("pp",))
    block = Block(CFG)
    stacked = stack_block_params(params, CFG.num_layers)
    staged = jax.tree_util.tree_map(
        lambda x: x.reshape((PP, CFG.num_layers // PP) + x.shape[1:]),
        stacked)
    specs = jax.tree_util.tree_map(lambda _: P("pp"), staged)
    staged = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        staged, specs)
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 (B // MB, L))

    def stage_fn(stage_params, x):
        def layer(x, p):
            return block.apply({"params": p}, x, positions), None
        return lax.scan(layer, x, stage_params)[0]

    def run(remat):
        def fwd(staged_local, embed_p, tokens):
            local = jax.tree_util.tree_map(lambda x: x[0], staged_local)
            emb = nn.Embed(CFG.vocab_size, CFG.embed_dim,
                           param_dtype=jnp.float32, dtype=CFG.dtype)
            x = emb.apply({"params": embed_p}, tokens)
            x_mb = x.reshape((MB, B // MB) + x.shape[1:])
            y = pipeline_apply(stage_fn, local, x_mb, "pp", remat=remat)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        f = jax.jit(jax.shard_map(
            fwd, mesh=mesh, in_specs=(specs, P(), P()), out_specs=P(),
            check_vma=False))
        val = f(staged, params["embed"], tokens)
        g = jax.grad(lambda s: f(s, params["embed"], tokens))(staged)
        return val, g

    v0, g0 = run(False)
    v1, g1 = run(True)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-6)
    for (p0, a), (p1, b) in zip(
            jax.tree_util.tree_flatten_with_path(g0)[0],
            jax.tree_util.tree_flatten_with_path(g1)[0]):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=jax.tree_util.keystr(p0))
