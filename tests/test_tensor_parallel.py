"""Tensor parallelism: a tp-sharded transformer must compute exactly
what the unsharded model computes (forward AND gradients), with the
full-size params placed by tp_param_specs and the local module built
from cfg.local(tp). Runs on the virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

jax.config.update("jax_default_matmul_precision", "highest")

from horovod_tpu.models import Transformer, TransformerConfig  # noqa: E402
from horovod_tpu.parallel import tp_grad_sync, tp_param_specs  # noqa: E402
from horovod_tpu.parallel.tensor_parallel import is_tp_sharded  # noqa: E402

BASE = dict(vocab_size=97, num_layers=2, num_heads=4, embed_dim=32,
            mlp_dim=64, dtype=jnp.float32)


def _mesh(n, name):
    return Mesh(np.array(jax.devices("cpu")[:n]), (name,))


def _setup(tp):
    cfg = TransformerConfig(**BASE)
    model = Transformer(cfg)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 97, (2, 16)))
    params = model.init(jax.random.PRNGKey(3), tokens)["params"]
    local = Transformer(TransformerConfig(tp_axis="tp", **BASE).local(tp))
    return model, local, params, tokens


def test_tp_forward_matches_full_model():
    tp = 4
    model, local, params, tokens = _setup(tp)
    expected = model.apply({"params": params}, tokens)

    mesh = _mesh(tp, "tp")
    specs = tp_param_specs(params, "tp")
    params_p = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)

    fwd = jax.jit(jax.shard_map(
        lambda p, t: local.apply({"params": p}, t),
        mesh=mesh, in_specs=(specs, P()), out_specs=P(),
        check_vma=False))
    out = fwd(params_p, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_tp_gradients_match_full_model():
    """tp_grad_sync must reproduce the unsharded gradients: sharded
    leaves hold their slice of the full grad, replicated leaves the
    full (tp-psummed) grad."""
    tp = 2
    model, local, params, tokens = _setup(tp)
    tgt = jnp.roll(tokens, -1, axis=1)

    def full_loss(p):
        logits = model.apply({"params": p}, tokens)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))

    expected = jax.grad(full_loss)(params)

    mesh = _mesh(tp, "tp")
    specs = tp_param_specs(params, "tp")
    params_p = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)

    def shard_grads(p, t):
        def loss(p):
            logits = local.apply({"params": p}, t)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(
                logp, jnp.roll(t, -1, axis=1)[..., None], -1))

        return tp_grad_sync(jax.grad(loss)(p), "tp")

    g = jax.jit(jax.shard_map(
        shard_grads, mesh=mesh, in_specs=(specs, P()), out_specs=specs,
        check_vma=False))(params_p, tokens)

    flat_g = jax.tree_util.tree_flatten_with_path(g)[0]
    flat_e = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_flatten_with_path(expected)[0]}
    for path, got in flat_g:
        exp = flat_e[jax.tree_util.keystr(path)]
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=jax.tree_util.keystr(path))


def test_tp_with_flash_attention_path():
    """TP composes with the flash-attention config (each shard runs
    flash over its local heads; the blockwise fallback covers non-TPU
    backends) — values still match the full dense model."""
    tp = 2
    base = dict(BASE, attention="flash")
    cfg = TransformerConfig(**base)
    model = Transformer(TransformerConfig(**dict(BASE)))
    rng = np.random.RandomState(5)
    tokens = jnp.asarray(rng.randint(0, 97, (2, 128)))  # flash-aligned L
    params = model.init(jax.random.PRNGKey(7), tokens)["params"]
    expected = model.apply({"params": params}, tokens)

    local = Transformer(TransformerConfig(tp_axis="tp", **base).local(tp))
    mesh = _mesh(tp, "tp")
    specs = tp_param_specs(params, "tp")
    params_p = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)
    out = jax.jit(jax.shard_map(
        lambda p, t: local.apply({"params": p}, t),
        mesh=mesh, in_specs=(specs, P()), out_specs=P(),
        check_vma=False))(params_p, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_tp_with_ring_attention_sp_mesh():
    """tp and sp compose on one mesh: heads sharded over tp, sequence
    sharded over sp with ring attention inside each tp group — output
    still matches the full dense model."""
    tp, sp = 2, 2
    base = dict(BASE, attention="ring", sp_axis="sp")
    model = Transformer(TransformerConfig(**dict(BASE)))
    rng = np.random.RandomState(9)
    tokens = jnp.asarray(rng.randint(0, 97, (2, 32)))
    params = model.init(jax.random.PRNGKey(11), tokens)["params"]
    expected = model.apply({"params": params}, tokens)

    local = Transformer(TransformerConfig(tp_axis="tp", **base).local(tp))
    mesh = Mesh(np.array(jax.devices("cpu")[:tp * sp]).reshape(tp, sp),
                ("tp", "sp"))
    specs = tp_param_specs(params, "tp")
    params_p = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)

    def run(p, tokens):
        L = tokens.shape[1]
        positions = jnp.broadcast_to(
            jax.lax.axis_index("sp") * L +
            jnp.arange(L, dtype=jnp.int32)[None], tokens.shape)
        return local.apply({"params": p}, tokens, positions)

    out = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(specs, P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))(params_p, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_tp_local_config_validation():
    cfg = TransformerConfig(**BASE)
    with pytest.raises(ValueError):
        cfg.local(3)  # 4 heads not divisible by 3
    assert cfg.local(2).num_heads == 2
    assert cfg.local(2).mlp_dim == 32


def test_tp_spec_classification():
    _, _, params, _ = _setup(2)
    specs = tp_param_specs(params, "tp")
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    sharded = {jax.tree_util.keystr(k) for k, s in flat if s != P()}
    assert any("query" in s for s in sharded)
    assert any("mlp_out" in s for s in sharded)
    assert not any("embed" in s for s in sharded)
    assert not any("norm" in s for s in sharded)
    for path, _ in flat:
        assert is_tp_sharded(path) == (jax.tree_util.keystr(path)
                                       in sharded)
