"""Protocol-counter worker: runs repeated same-name collectives and
prints this rank's control-plane accounting as one JSON line, so the
test (and bench.py --scaling) can compare the response-cache fast path
against full negotiation at the PROTOCOL level — bytes and cycle
kinds, independent of wall clock (the fast path's design goal;
reference: response_cache.cc:308-409).

Env: HVD_TPU_CACHE_CAPACITY=0 disables the cache (full round trip per
cycle); default leaves it on.
"""

import json
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops
from horovod_tpu.common.basics import get_basics


def main():
    hvd.init()
    basics = get_basics()
    r = hvd.rank()

    # A deliberately long tensor name: the uncached path serializes one
    # Request (name + shape + dtype + op) per op per worker per cycle,
    # so name length is visible in bytes/op; the cached path sends a
    # fixed-width bit vector regardless.
    name = "protocol_counters.the_quick_brown_fox_gradient_block_%04d"

    # Warmup: populates the response cache (first sight of a name is
    # always a full negotiation) and lets autotune warmup cycles pass.
    for i in range(8):
        ops.allreduce(np.ones(16, np.float32), name % 0)  # hvd-lint: disable=loop-auto-name

    basics.protocol_counters_reset()
    n_ops = 64
    for i in range(n_ops):
        ops.allreduce(np.ones(16, np.float32), name % 0)  # hvd-lint: disable=loop-auto-name
    counters = basics.protocol_counters()
    counters["ops"] = n_ops
    counters["rank"] = r
    print("COUNTERS %s" % json.dumps(counters))
    return 0


if __name__ == "__main__":
    sys.exit(main())
