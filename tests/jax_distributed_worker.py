"""Self-verifying multi-process jax.distributed bootstrap test: 2 ranks
initialize jax's distributed runtime from horovod_tpu topology, see each
other's devices as one global mesh, and run a cross-process psum."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import horovod_tpu.jax as hvd


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2

    hvd.init_distributed()
    hvd.init_distributed()  # idempotent: second call is a no-op

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == n, jax.process_count()
    assert jax.process_index() == r, (jax.process_index(), r)
    local = jax.local_device_count()
    assert jax.device_count() == n * local, (jax.device_count(), n, local)
    if r == 0:
        print("PASS global_device_view (%d devices over %d processes)"
              % (jax.device_count(), n), flush=True)

    # Cross-process collective through the global runtime: every process
    # contributes its rank; psum must see them all.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    local_vals = [jnp.full((1,), float(r) + 1.0)
                  for _ in range(local)]
    arr = jax.make_array_from_single_device_arrays(
        (jax.device_count(),), sharding,
        [jax.device_put(v, d)
         for v, d in zip(local_vals, jax.local_devices())])

    @jax.jit
    def total(x):
        return jnp.sum(x)

    result = float(total(arr))
    expected = sum((rr + 1.0) * local for rr in range(n))
    assert abs(result - expected) < 1e-6, (result, expected)
    if r == 0:
        print("PASS cross_process_sum", flush=True)

    # FULL flagship train step over the multi-process global mesh: the
    # same make_train_step the single-process path uses, with the
    # gradient psum now crossing process boundaries (the DCN-plane
    # analogue of the reference's multi-host NCCL allreduce). Every
    # process supplies the identical global batch; jax slices each
    # process's addressable shards.
    import optax

    from horovod_tpu.parallel import data_parallel_mesh, make_train_step
    from horovod_tpu.parallel.train import cross_entropy_loss

    gmesh = data_parallel_mesh(devices=jax.devices())
    rngs = np.random.RandomState(0)
    w0 = jnp.asarray(rngs.randn(16, 8).astype(np.float32) * 0.1)

    def loss_fn(params, batch):
        logits = batch["x"] @ params
        return cross_entropy_loss(logits, batch["y"])

    opt = optax.sgd(0.1)
    step = make_train_step(loss_fn, opt, gmesh, donate=False)
    total_batch = 2 * jax.device_count()
    batch = {
        "x": jnp.asarray(rngs.randn(total_batch, 16).astype(np.float32)),
        "y": jnp.asarray(rngs.randint(0, 8, size=total_batch)),
    }
    params_p, opt_state, batch_p = step.place(w0, opt.init(w0), batch)
    losses = []
    for _ in range(3):
        params_p, opt_state, loss = step(params_p, opt_state, batch_p)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # The replicated loss must agree across processes (allgather the
    # final loss through the host core to check).
    gathered = hvd.allgather(np.asarray([losses[-1]], np.float64),
                             name="jd_final_loss")
    assert np.allclose(np.asarray(gathered), losses[-1], atol=1e-9), \
        gathered
    if r == 0:
        print("PASS cross_process_train_step", flush=True)

    # FSDP over the same multi-process global mesh: params/state
    # sharded across PROCESS boundaries, GSPMD's gathers riding the
    # distributed runtime.
    from horovod_tpu.parallel import make_fsdp_train_step

    fparams = {"w": w0, "w2": jnp.asarray(
        rngs.randn(8, 16).astype(np.float32) * 0.1)}

    def floss(params, b):
        h = jnp.tanh(b["x"] @ params["w"])
        logits = h @ params["w2"]
        return cross_entropy_loss(logits, b["y"] % 16)

    fstep = make_fsdp_train_step(floss, opt, gmesh, donate=False,
                                 min_size=32)
    fp, fs, fb = fstep.place(fparams, batch=batch)
    flosses = []
    for _ in range(3):
        fp, fs, floss_v = fstep(fp, fs, fb)
        flosses.append(float(floss_v))
    assert flosses[-1] < flosses[0], flosses
    from jax.sharding import PartitionSpec as PS
    assert fp["w"].sharding.spec == PS("hvd"), fp["w"].sharding
    gathered_f = hvd.allgather(np.asarray([flosses[-1]], np.float64),
                               name="jd_fsdp_loss")
    assert np.allclose(np.asarray(gathered_f), flosses[-1], atol=1e-9)
    if r == 0:
        print("PASS cross_process_fsdp_step", flush=True)

    jax.distributed.shutdown()
    print("rank %d: jax.distributed bootstrap tests passed" % r,
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
