"""Self-verifying multi-process jax.distributed bootstrap test: 2 ranks
initialize jax's distributed runtime from horovod_tpu topology, see each
other's devices as one global mesh, and run a cross-process psum."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import horovod_tpu.jax as hvd


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2

    hvd.init_distributed()
    hvd.init_distributed()  # idempotent: second call is a no-op

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == n, jax.process_count()
    assert jax.process_index() == r, (jax.process_index(), r)
    local = jax.local_device_count()
    assert jax.device_count() == n * local, (jax.device_count(), n, local)
    if r == 0:
        print("PASS global_device_view (%d devices over %d processes)"
              % (jax.device_count(), n), flush=True)

    # Cross-process collective through the global runtime: every process
    # contributes its rank; psum must see them all.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    local_vals = [jnp.full((1,), float(r) + 1.0)
                  for _ in range(local)]
    arr = jax.make_array_from_single_device_arrays(
        (jax.device_count(),), sharding,
        [jax.device_put(v, d)
         for v, d in zip(local_vals, jax.local_devices())])

    @jax.jit
    def total(x):
        return jnp.sum(x)

    try:
        result = float(total(arr))
    except Exception as e:  # jaxlib.xla_extension.XlaRuntimeError
        if "Multiprocess computations aren't implemented" in str(e):
            # Old jaxlib: the CPU backend has no cross-process
            # collective runtime (landed later). The bootstrap itself
            # (device view above) worked; report a capability skip so
            # the test can distinguish "unsupported here" from broken.
            print("SKIP multiprocess_cpu_unsupported", flush=True)
            return 0
        raise
    expected = sum((rr + 1.0) * local for rr in range(n))
    assert abs(result - expected) < 1e-6, (result, expected)
    if r == 0:
        print("PASS cross_process_sum", flush=True)

    # FULL flagship train step over the multi-process global mesh: the
    # same make_train_step the single-process path uses, with the
    # gradient psum now crossing process boundaries (the DCN-plane
    # analogue of the reference's multi-host NCCL allreduce). Every
    # process supplies the identical global batch; jax slices each
    # process's addressable shards.
    import optax

    from horovod_tpu.parallel import data_parallel_mesh, make_train_step
    from horovod_tpu.parallel.train import cross_entropy_loss

    gmesh = data_parallel_mesh(devices=jax.devices())
    rngs = np.random.RandomState(0)
    w0 = jnp.asarray(rngs.randn(16, 8).astype(np.float32) * 0.1)

    def loss_fn(params, batch):
        logits = batch["x"] @ params
        return cross_entropy_loss(logits, batch["y"])

    opt = optax.sgd(0.1)
    step = make_train_step(loss_fn, opt, gmesh, donate=False)
    total_batch = 2 * jax.device_count()
    batch = {
        "x": jnp.asarray(rngs.randn(total_batch, 16).astype(np.float32)),
        "y": jnp.asarray(rngs.randint(0, 8, size=total_batch)),
    }
    params_p, opt_state, batch_p = step.place(w0, opt.init(w0), batch)
    losses = []
    for _ in range(3):
        params_p, opt_state, loss = step(params_p, opt_state, batch_p)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # The replicated loss must agree across processes (allgather the
    # final loss through the host core to check).
    gathered = hvd.allgather(np.asarray([losses[-1]], np.float64),
                             name="jd_final_loss")
    assert np.allclose(np.asarray(gathered), losses[-1], atol=1e-9), \
        gathered
    if r == 0:
        print("PASS cross_process_train_step", flush=True)

    # FSDP over the same multi-process global mesh: params/state
    # sharded across PROCESS boundaries, GSPMD's gathers riding the
    # distributed runtime.
    from horovod_tpu.parallel import make_fsdp_train_step

    fparams = {"w": w0, "w2": jnp.asarray(
        rngs.randn(8, 16).astype(np.float32) * 0.1)}

    def floss(params, b):
        h = jnp.tanh(b["x"] @ params["w"])
        logits = h @ params["w2"]
        return cross_entropy_loss(logits, b["y"] % 16)

    fstep = make_fsdp_train_step(floss, opt, gmesh, donate=False,
                                 min_size=32)
    fp, fs, fb = fstep.place(fparams, batch=batch)
    flosses = []
    for _ in range(3):
        fp, fs, floss_v = fstep(fp, fs, fb)
        flosses.append(float(floss_v))
    assert flosses[-1] < flosses[0], flosses
    from jax.sharding import PartitionSpec as PS
    assert fp["w"].sharding.spec == PS("hvd"), fp["w"].sharding
    gathered_f = hvd.allgather(np.asarray([flosses[-1]], np.float64),
                               name="jd_fsdp_loss")
    assert np.allclose(np.asarray(gathered_f), flosses[-1], atol=1e-9)
    if r == 0:
        print("PASS cross_process_fsdp_step", flush=True)

    # Hierarchical (dp_cross x dp_local) train step over the global
    # mesh — the two-level ICI/DCN reduction the reference implements
    # as hierarchical NCCL allreduce (reference
    # horovod/common/ops/nccl_operations.cc:150-346: intra-node reduce,
    # inter-node allreduce, intra-node bcast). Here the mesh axes
    # encode the split (trailing axis = devices within a process) and
    # the program reduces in two explicit levels.
    from jax import lax
    from jax.sharding import PartitionSpec

    if local >= 2 and n >= 2:
        from horovod_tpu.parallel import hybrid_mesh

        hmesh = hybrid_mesh((n, local), ("dp_cross", "dp_local"),
                            devices=jax.devices())
        lr = 0.1
        N = n * local

        def hier_local(w, x, y):
            def lf(w):
                return cross_entropy_loss(x @ w, y)
            loss, g = jax.value_and_grad(lf)(w)
            # Level 1: reduce within the process (ICI analogue);
            # level 2: across processes (DCN analogue).
            g = lax.psum(g, "dp_local")
            g = lax.psum(g, "dp_cross")
            loss = lax.pmean(lax.pmean(loss, "dp_local"), "dp_cross")
            return w - lr * (g / N), loss

        hstep = jax.jit(jax.shard_map(
            hier_local, mesh=hmesh,
            in_specs=(PartitionSpec(),
                      PartitionSpec(("dp_cross", "dp_local")),
                      PartitionSpec(("dp_cross", "dp_local"))),
            out_specs=(PartitionSpec(), PartitionSpec()),
            check_vma=False))
        hw = w0
        hlosses = []
        for _ in range(3):
            hw, hloss = hstep(hw, batch["x"], batch["y"])
            hlosses.append(float(hloss))
        assert hlosses[-1] < hlosses[0], hlosses
        gathered_h = hvd.allgather(np.asarray([hlosses[-1]], np.float64),
                                   name="jd_hier_loss")
        assert np.allclose(np.asarray(gathered_h), hlosses[-1],
                           atol=1e-9), gathered_h
        if r == 0:
            print("PASS cross_process_hierarchical_step", flush=True)

    # Pipeline parallelism ACROSS process boundaries: pp stages on the
    # leading (cross-process) axis, dp on the per-process devices —
    # activations ppermute between processes every microbatch tick.
    if n >= 2:
        from horovod_tpu.parallel import hybrid_mesh, pipeline_apply

        ppmesh = hybrid_mesh((n, local), ("pp", "dp"),
                             devices=jax.devices())
        d, B_pp, M = 16, 4 * local * 2, 4
        rng2 = np.random.RandomState(7)
        stage_w = jnp.asarray(
            rng2.randn(n, 1, d, d).astype(np.float32) * (1.0 / d ** 0.5))
        xs = jnp.asarray(rng2.randn(B_pp, d).astype(np.float32))
        ys = jnp.asarray(rng2.randn(B_pp, d).astype(np.float32))
        lr = 0.2

        def stage_fn(sp, x):
            def layer(x, w):
                return jnp.tanh(x @ w), None
            return lax.scan(layer, x, sp)[0]

        def pp_local(stage_local, x, y):
            def local_loss(sl):
                sl0 = jax.tree_util.tree_map(lambda v: v[0], sl)
                x_mb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
                out = pipeline_apply(stage_fn, sl0, x_mb, "pp")
                out = out.reshape(x.shape)
                # Pipeline grad contract (test_pipeline.py): local
                # loss scaled by 1/pp; staged grads then complete.
                return jnp.mean((out - y) ** 2) / lax.psum(1, "pp")
            loss, g = jax.value_and_grad(local_loss)(stage_local)
            # dp axis: plain data-parallel gradient average.
            g = jax.tree_util.tree_map(
                lambda v: lax.psum(v, "dp") / lax.psum(1, "dp"), g)
            loss = lax.pmean(lax.pmean(loss, "dp"), "pp") * n
            new = jax.tree_util.tree_map(lambda w, gv: w - lr * gv,
                                         stage_local, g)
            return new, loss

        pstep = jax.jit(jax.shard_map(
            pp_local, mesh=ppmesh,
            in_specs=(PartitionSpec("pp"), PartitionSpec("dp"),
                      PartitionSpec("dp")),
            out_specs=(PartitionSpec("pp"), PartitionSpec()),
            check_vma=False))
        sw = stage_w
        plosses = []
        for _ in range(4):
            sw, ploss = pstep(sw, xs, ys)
            plosses.append(float(ploss))
        assert plosses[-1] < plosses[0], plosses
        gathered_p = hvd.allgather(np.asarray([plosses[-1]], np.float64),
                                   name="jd_pp_loss")
        assert np.allclose(np.asarray(gathered_p), plosses[-1],
                           atol=1e-9), gathered_p
        if r == 0:
            print("PASS cross_process_pp_step", flush=True)

    jax.distributed.shutdown()
    print("rank %d: jax.distributed bootstrap tests passed" % r,
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
