"""Self-verifying multi-process jax.distributed bootstrap test: 2 ranks
initialize jax's distributed runtime from horovod_tpu topology, see each
other's devices as one global mesh, and run a cross-process psum."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import horovod_tpu.jax as hvd


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2

    hvd.init_distributed()
    hvd.init_distributed()  # idempotent: second call is a no-op

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == n, jax.process_count()
    assert jax.process_index() == r, (jax.process_index(), r)
    local = jax.local_device_count()
    assert jax.device_count() == n * local, (jax.device_count(), n, local)
    if r == 0:
        print("PASS global_device_view (%d devices over %d processes)"
              % (jax.device_count(), n), flush=True)

    # Cross-process collective through the global runtime: every process
    # contributes its rank; psum must see them all.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    local_vals = [jnp.full((1,), float(r) + 1.0)
                  for _ in range(local)]
    arr = jax.make_array_from_single_device_arrays(
        (jax.device_count(),), sharding,
        [jax.device_put(v, d)
         for v, d in zip(local_vals, jax.local_devices())])

    @jax.jit
    def total(x):
        return jnp.sum(x)

    result = float(total(arr))
    expected = sum((rr + 1.0) * local for rr in range(n))
    assert abs(result - expected) < 1e-6, (result, expected)
    if r == 0:
        print("PASS cross_process_sum", flush=True)

    jax.distributed.shutdown()
    print("rank %d: jax.distributed bootstrap tests passed" % r,
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
