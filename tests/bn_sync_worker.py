"""Self-verifying distributed sync-BN worker (test_batch_norm.py e2e).

Modes (BN_SYNC_MODE env):
  world — 2 ranks: lean BN with host-plane stats sync over the world.
      Verifies (a) the synced statistics equal the GLOBAL-batch
      statistics computed locally from the full data, (b) the stats
      bytes are BITWISE identical across ranks (the ring computes each
      chunk's total once and distributes the same bytes), and (c) the
      custom-VJP backward runs through the same host plane (plain jit,
      ordered io_callback) with rank-identical dx-relevant reductions.
  mesh — 4 ranks under hvd.init(model_parallel=2): sync BN scoped to
      hvd.batch_group() on the 2x2 (batch x model) mesh. Ranks in the
      SAME batch group (columns {0,2} and {1,3}) must hold bitwise
      identical stats equal to their group-global batch; the two
      groups' stats must differ (they saw different data) — proving
      the group= scoping actually scopes.
"""

import os
import signal
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


def alarm(signum, frame):
    sys.stderr.write("watchdog fired: job deadlocked\n")
    sys.exit(3)


signal.signal(signal.SIGALRM, alarm)
signal.alarm(240)

mode = os.environ.get("BN_SYNC_MODE", "world")

import jax
import jax.numpy as jnp

from horovod_tpu.ops.batch_norm import lean_batch_norm_train

M, C = 16, 8
EPS = 1e-5


def shard_for(rank):
    r = np.random.RandomState(100 + rank)
    return r.randn(M, C).astype(np.float32) * (1.0 + 0.25 * rank) + rank


def stats_of(x):
    return x.mean(0), x.var(0)


def check_bitwise(tag, arr, group=None):
    """Allgathers `arr` (within `group`) and asserts every rank
    contributed BITWISE identical bytes."""
    gathered = np.asarray(ops.allgather(
        np.asarray(arr, np.float32)[None], "bitwise.%s" % tag,
        group=group))
    for row in range(1, gathered.shape[0]):
        assert np.array_equal(gathered[row], gathered[0]), (
            tag, gathered[row] - gathered[0])
    return gathered[0]


if mode == "world":
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2
    xs = jnp.asarray(shard_for(r))
    gamma = jnp.linspace(0.5, 1.5, C, dtype=jnp.float32)
    beta = jnp.linspace(-1.0, 1.0, C, dtype=jnp.float32)

    # Plain jit, no mapped axis: the stats allreduce rides the host
    # core through the ordered io_callback plane — the designed path
    # for eager/host training loops.
    @jax.jit
    def fwd(xs, gamma, beta):
        return lean_batch_norm_train(xs, gamma, beta, EPS, False, 1,
                                     None, "world", "bn_e2e")

    y, mean, var = fwd(xs, gamma, beta)

    x_all = np.concatenate([shard_for(i) for i in range(n)])
    mean_ref, var_ref = stats_of(x_all)
    np.testing.assert_allclose(np.asarray(mean), mean_ref, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(var), var_ref, rtol=1e-4,
                               atol=1e-5)
    # Bitwise rank-identity of the synced statistics.
    check_bitwise("mean", np.asarray(mean))
    check_bitwise("var", np.asarray(var))
    if r == 0:
        print("PASS world_stats_global_and_bitwise", flush=True)

    # Backward through the same plane: the dx psum-equivalents must be
    # computed from rank-identical global reductions. dx itself is
    # per-shard; the VJP's synced (dbeta_g, dgamma_g) being identical
    # shows through a deterministic function of dx.
    w = jnp.asarray(np.random.RandomState(7).randn(M, C).astype(np.float32))

    @jax.jit
    def loss_grads(xs, gamma, beta):
        def f(xs, gamma, beta):
            y, _, _ = lean_batch_norm_train(xs, gamma, beta, EPS, False,
                                            1, None, "world", "bn_e2e_g")
            return jnp.sum(y * w)
        return jax.grad(f, argnums=(0, 1, 2))(xs, gamma, beta)

    dx, dgamma, dbeta = loss_grads(xs, gamma, beta)
    assert np.all(np.isfinite(np.asarray(dx)))

    # Reference: global-batch dx for THIS rank's shard, computed
    # locally from the full data (per-shard loss weights w are the
    # same array on both ranks by construction).
    def ref_grads():
        x = x_all
        mean, var = stats_of(x)
        rstd = 1.0 / np.sqrt(var + EPS)
        xhat = (x - mean) * rstd
        # Both ranks use the SAME per-shard loss weights w, so the
        # global cotangent is w stacked per shard.
        gy = np.concatenate([np.asarray(w)] * n)
        Mg = x.shape[0]
        db = gy.sum(0)
        dg = (gy * xhat).sum(0)
        dx_all = (np.asarray(gamma) * rstd) * (
            gy - db / Mg - xhat * (dg / Mg))
        return dx_all[r * M:(r + 1) * M]

    np.testing.assert_allclose(np.asarray(dx), ref_grads(), rtol=1e-4,
                               atol=1e-5)
    if r == 0:
        print("PASS world_backward_global_dx", flush=True)

elif mode == "mesh":
    hvd.init(model_parallel=2)
    r, n = hvd.rank(), hvd.size()
    assert n == 4
    bg = hvd.batch_group()
    # Column c = ranks {c, c+2}: make the DATA a function of the batch
    # group so the two groups see different batches.
    col = r % 2
    xs = jnp.asarray(shard_for(10 * col + (r // 2)))
    gamma = jnp.ones(C, jnp.float32)
    beta = jnp.zeros(C, jnp.float32)

    @jax.jit
    def fwd(xs, gamma, beta):
        return lean_batch_norm_train(xs, gamma, beta, EPS, False, 1,
                                     None, bg, "bn_mesh")

    y, mean, var = fwd(xs, gamma, beta)

    group_all = np.concatenate([shard_for(10 * col + row)
                                for row in range(2)])
    mean_ref, var_ref = stats_of(group_all)
    np.testing.assert_allclose(np.asarray(mean), mean_ref, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(var), var_ref, rtol=1e-4,
                               atol=1e-5)
    # Bitwise identical WITHIN the batch group (same tensor name active
    # in both disjoint groups concurrently — the PR 10 cache/negotiation
    # shape, exercised again here).
    mine = check_bitwise("mean", np.asarray(mean), group=bg)
    # ...and different ACROSS groups (they saw different data): gather
    # each group's representative over the world and compare.
    world_rows = np.asarray(ops.allgather(
        mine[None], "bn_mesh.groups"))
    assert world_rows.shape[0] == 4
    col0 = world_rows[0]
    col1 = world_rows[1]
    assert not np.allclose(col0, col1), (
        "batch groups produced identical stats for different data — "
        "group scoping is not scoping")
    if r == 0:
        print("PASS mesh_group_scoped_sync_bn", flush=True)
else:
    raise SystemExit("unknown BN_SYNC_MODE %r" % mode)

hvd.shutdown()
if r == 0:
    print("PASS bn_sync_worker_done", flush=True)
