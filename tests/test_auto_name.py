"""Auto-generated collective names must be deterministic across ranks and
generations: the jax binding's counter resets on every init(), so a
survivor of an elastic shrink/regrow and a freshly spawned worker generate
identical names for the same call sites (a diverged counter produces
mismatched names — the exact hang the divergence cross-check reports)."""

import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvd_core
import horovod_tpu.jax as hvd_jax


def test_auto_name_counter_resets_on_reinit():
    hvd_core.init()
    start = hvd_jax._name_counter[0]
    hvd_jax.allreduce(jnp.ones(3), average=False)
    assert hvd_jax._name_counter[0] == start + 1

    # Simulate a surviving elastic member whose counter drifted during the
    # failed generation (calls that newly spawned peers never made).
    hvd_jax._name_counter[0] += 1000
    hvd_core.shutdown()
    hvd_core.init()
    assert hvd_jax._name_counter[0] == 0

    # First auto-named collective of the new generation: same name a
    # fresh process would generate.
    out = hvd_jax.allreduce(jnp.ones(3), average=False)
    assert np.allclose(out, 1.0)
    assert hvd_jax._name_counter[0] == 1


def test_auto_names_deterministic_sequence():
    hvd_core.init()
    hvd_core.shutdown()
    hvd_core.init()
    assert hvd_jax._auto_name("allreduce") == "allreduce.1"
    assert hvd_jax._auto_name("broadcast") == "broadcast.2"
    hvd_core.shutdown()
    hvd_core.init()
    # Identical call pattern after re-init -> identical names.
    assert hvd_jax._auto_name("allreduce") == "allreduce.1"
    assert hvd_jax._auto_name("broadcast") == "broadcast.2"
