"""Control-plane negotiation microbenchmark worker: times synchronous tiny
allreduces, whose cost is dominated by the per-cycle coordinator negotiation
(gather/bcast or the cached bit-sync), not data movement. Run with
HVD_TPU_CYCLE_TIME=0 so the cycle pacing sleep doesn't mask the control
plane. Prints `NEGOTIATION_US_PER_OP <us>` on rank 0."""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    r = hvd.rank()
    # Zero-element tensor: the negotiation/cycle machinery runs in full but
    # the ring data phase is skipped, isolating control-plane latency (a
    # payload allreduce would add the ring's inherent Theta(n) hop latency).
    x = np.zeros(0, dtype=np.float32)
    iters = int(os.environ.get("HVD_TPU_BENCH_ITERS", "200"))
    for i in range(20):  # warmup; also populates the response cache
        hvd.allreduce(x, "nb")
    t0 = time.perf_counter()
    for i in range(iters):
        hvd.allreduce(x, "nb")
    dt = time.perf_counter() - t0
    if r == 0:
        print("NEGOTIATION_US_PER_OP %.1f" % (dt / iters * 1e6))
    print("rank %d done" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
