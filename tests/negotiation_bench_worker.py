"""Control-plane negotiation microbenchmark worker: times synchronous tiny
allreduces, whose cost is dominated by the per-cycle coordinator negotiation
(gather/bcast or the cached bit-sync), not data movement. Run with
HVD_TPU_CYCLE_TIME=0 so the cycle pacing sleep doesn't mask the control
plane. Prints `NEGOTIATION_US_PER_OP <us>` on rank 0."""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common.basics import get_basics


def main():
    hvd.init()
    r = hvd.rank()
    basics = get_basics()
    # Zero-element tensor: the negotiation/cycle machinery runs in full but
    # the ring data phase is skipped, isolating control-plane latency (a
    # payload allreduce would add the ring's inherent Theta(n) hop latency).
    x = np.zeros(0, dtype=np.float32)
    iters = int(os.environ.get("HVD_TPU_BENCH_ITERS", "200"))
    # HVD_TPU_BENCH_TENSORS > 1 simulates one training step's gradient
    # bucket: k async ops with realistic long names negotiated together.
    # Uncached negotiation traffic scales with k x name length; the
    # cached bit vector doesn't — the fast path's actual win.
    k = int(os.environ.get("HVD_TPU_BENCH_TENSORS", "1"))
    if k > 1:
        names = ["nb.layer%03d.weight_gradient_accumulator" % i
                 for i in range(k)]
    else:
        names = ["nb"]
    from horovod_tpu.common import ops

    def step():
        handles = [ops.allreduce_async(x, nm) for nm in names]
        for h in handles:
            ops.synchronize(h)

    # Warmup (populates the response cache); tunable because at the
    # 1024-rank oversubscribed sweep every step costs a full fleet
    # round-robin on one core.
    warmup = int(os.environ.get("HVD_TPU_BENCH_WARMUP", "20"))
    for i in range(warmup):
        step()
    basics.protocol_counters_reset()
    # Coordinator CPU time (user+sys of THIS process, coordinator
    # thread included) over the measured window: wall clock on a
    # 1-core host measures the OS scheduler, CPU time measures the
    # protocol. cpu_us / work cycles = the per-cycle coordinator cost
    # whose O(n) constant SCALING.md §2.3 pins.
    import resource
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    t0 = time.perf_counter()
    for i in range(iters):
        step()
    dt = time.perf_counter() - t0
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    cpu_us = ((ru1.ru_utime - ru0.ru_utime) +
              (ru1.ru_stime - ru0.ru_stime)) * 1e6
    counters = basics.protocol_counters()
    counters.update(rank=r, iters=iters, tensors_per_step=k,
                    cpu_us=round(cpu_us, 1))
    # Ranks 0 (coordinator, O(n) traffic) and 1 (representative worker,
    # O(1) traffic) carry the protocol-cost evidence.
    if r <= 1:
        print("PROTOCOL_COUNTERS %s" % json.dumps(counters))
    if r == 0:
        # Per OP also in bucket mode (k ops ride each step).
        print("NEGOTIATION_US_PER_OP %.1f" % (dt / (iters * k) * 1e6))
        # Live-metrics snapshot for the BENCH json (docs/METRICS.md):
        # the cycle-time histogram, fused-bytes total, and cache hit
        # rate of this run's coordinator.
        m = hvd.metrics()
        c = m["counters"]
        looked_up = c["cache_hit_total"] + c["cache_miss_total"]
        print("METRICS_SNAPSHOT %s" % json.dumps({
            "cycle_seconds": m["histograms"]["cycle_seconds"],
            "fused_bytes_total": c["fused_bytes_total"],
            "fused_tensors_total": c["fused_tensors_total"],
            "cache_hit_rate": round(c["cache_hit_total"] / looked_up, 4)
            if looked_up else None,
        }))
        # Trace-recorder counters for bench.py --trace-overhead: the A/B
        # there asserts spans flowed when tracing was on AND nothing was
        # dropped at the default ring size.
        print("TRACE_COUNTERS %s" % json.dumps(basics.trace_counters()))
    print("rank %d done" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
