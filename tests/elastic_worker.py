"""Elastic e2e worker: deterministic quadratic training with commits.

Run under the elastic launcher (`-np 3 --min-np 1`). Worker id 1 kills
itself mid-generation-0; the survivors must roll back to the last commit
and continue at size 2, and a respawned worker must be absorbed later
(size 3 again) — all without the surviving processes restarting.

Training: gradient descent on ||w - target||^2 with the gradient
allreduce-averaged across ranks (every rank computes the same gradient,
so the averaged step is identical and the loss decreases strictly —
letting the test assert "loss keeps decreasing" across membership
changes).
"""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic

TOTAL_STEPS = int(os.environ.get("ELASTIC_TEST_TOTAL_STEPS", "30"))
COMMIT_EVERY = int(os.environ.get("ELASTIC_TEST_COMMIT_EVERY", "5"))
CRASH_STEP = int(os.environ.get("ELASTIC_TEST_CRASH_STEP", "7"))
STEP_SLEEP = float(os.environ.get("ELASTIC_TEST_STEP_SLEEP", "0.25"))
LR = 0.05
TARGET = 3.0

WID = os.environ.get("HVD_TPU_WORKER_ID", "?")


@elastic.run
def train(state):
    while state.step < TOTAL_STEPS:
        gen = int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)
        grad_local = 2.0 * (state.w - TARGET)
        grad = np.asarray(hvd.allreduce(grad_local, "grad",
                                        average=True))
        state.w = state.w - LR * grad
        state.step += 1
        loss = float(np.sum((state.w - TARGET) ** 2))
        print("worker %s gen %d step %d size %d loss %.6f"
              % (WID, gen, state.step, hvd.size(), loss), flush=True)
        if WID == "1" and gen == 0 and state.step == CRASH_STEP:
            print("worker 1 crashing now", flush=True)
            os._exit(23)
        if state.step % COMMIT_EVERY == 0:
            state.commit()
        time.sleep(STEP_SLEEP)
    return float(np.sum((state.w - TARGET) ** 2))


def main():
    state = elastic.ElasticState(w=np.zeros(4, np.float64), step=0)
    final_loss = train(state)
    if final_loss is None:  # job finished before this worker could join
        print("worker %s superseded (job already complete)" % WID,
              flush=True)
        return 0
    print("worker %s train done step %d loss %.6f"
          % (WID, state.step, final_loss), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
