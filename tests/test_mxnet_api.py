"""MXNet binding tests. MXNet itself is EOL and absent from this
environment, so the numpy bridge is exercised with an NDArray test
double (asnumpy / in-place [:] assignment — the only NDArray surface
the in-place ops touch) over a real size-1 core init; the lazy-import
gate and the optimizer proxy are covered directly."""

import numpy as np
import pytest

import horovod_tpu as hvd
import horovod_tpu.mxnet as hvd_mx


class FakeNDArray:
    """The slice of the mx.nd.NDArray API the in-place ops use."""

    def __init__(self, arr):
        self.arr = np.asarray(arr, dtype=np.float32)
        self.context = "cpu(0)"

    def asnumpy(self):
        return self.arr.copy()

    def __setitem__(self, key, value):
        self.arr[key] = value


@pytest.fixture
def single_proc_init():
    for key in ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_ADDRS",
                "HVD_TPU_RENDEZVOUS_ADDR"):
        import os
        os.environ.pop(key, None)
    hvd.init()
    yield
    hvd.shutdown()


def test_lazy_import_gate():
    with pytest.raises(ImportError) as e:
        hvd_mx._mx()
    assert "MXNet" in str(e.value)
    assert "horovod_tpu.jax" in str(e.value)  # actionable alternative


def test_inplace_allreduce_broadcast(single_proc_init):
    x = FakeNDArray([1.0, 2.0, 3.0])
    out = hvd_mx.allreduce_(x, average=True, name="mx_ar")
    assert out is x
    np.testing.assert_allclose(x.arr, [1.0, 2.0, 3.0])  # size-1 identity

    y = FakeNDArray([[5.0, 6.0]])
    out = hvd_mx.broadcast_(y, root_rank=0, name="mx_bc")
    assert out is y
    np.testing.assert_allclose(y.arr, [[5.0, 6.0]])


def test_distributed_optimizer_proxy(single_proc_init):
    calls = []

    class FakeOpt:
        learning_rate = 0.5

        def update(self, index, weight, grad, state):
            calls.append(("update", index))

        def update_multi_precision(self, index, weight, grad, state):
            calls.append(("ump", index))

        def set_learning_rate(self, lr):
            calls.append(("lr", lr))

    opt = hvd_mx.DistributedOptimizer(FakeOpt())
    assert opt.learning_rate == 0.5  # attribute proxying
    g = FakeNDArray([1.0])
    opt.update(0, None, g, None)          # size-1: allreduce shortcut
    opt.update_multi_precision([1, 2], None, [g, g], None)
    opt.set_learning_rate(0.1)
    assert calls == [("update", 0), ("ump", [1, 2]), ("lr", 0.1)]


def test_broadcast_parameters_plain_dict(single_proc_init):
    params = {"w": FakeNDArray([1.0, 2.0]), "b": FakeNDArray([0.5])}
    hvd_mx.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(params["w"].arr, [1.0, 2.0])

    with pytest.raises(ValueError):
        hvd_mx.broadcast_parameters([1, 2, 3])
