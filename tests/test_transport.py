"""Transport-hardening unit tests (docs/CHAOS.md satellites): CRC32C
known answers, frame round-trip + detected corruption, recv-deadline
expiry, oversize-frame rejection, handshake-timeout accept, stale
generation rejection, and fault-spec determinism. All run in-process
against the native lib's selftest C API — no multi-process job, CPU
only, tier-1 safe."""

import ctypes
import os

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lib():
    native_dir = os.environ.get("HVD_TPU_NATIVE_DIR") or os.path.join(
        REPO_ROOT, "horovod_tpu", "native")
    lib = ctypes.CDLL(os.path.join(native_dir, "libhorovod_tpu.so"))
    lib.horovod_tpu_crc32c.restype = ctypes.c_uint32
    lib.horovod_tpu_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.horovod_tpu_crc32c_extend.restype = ctypes.c_uint32
    lib.horovod_tpu_crc32c_extend.argtypes = [
        ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint64]
    lib.horovod_tpu_net_selftest.restype = ctypes.c_int
    lib.horovod_tpu_net_selftest.argtypes = [ctypes.c_char_p]
    return lib


def test_crc32c_known_answers():
    lib = _lib()
    # The canonical CRC32C check vector (RFC 3720 appendix B.4 et al).
    assert lib.horovod_tpu_crc32c(b"123456789", 9) == 0xE3069283
    assert lib.horovod_tpu_crc32c(b"", 0) == 0
    # 32 zero bytes — second known vector (iSCSI test pattern).
    assert lib.horovod_tpu_crc32c(b"\x00" * 32, 32) == 0x8A9136AA


def test_crc32c_incremental_matches_oneshot():
    lib = _lib()
    data = bytes(range(256)) * 17 + b"tail-bytes"
    want = lib.horovod_tpu_crc32c(data, len(data))
    for split in (0, 1, 7, 64, 255, len(data) - 1):
        crc = lib.horovod_tpu_crc32c(data[:split], split)
        crc = lib.horovod_tpu_crc32c_extend(crc, data[split:],
                                            len(data) - split)
        assert crc == want, split


def test_crc32c_detects_single_bit_flip():
    lib = _lib()
    data = bytearray(b"G" * 4096)
    want = lib.horovod_tpu_crc32c(bytes(data), len(data))
    data[1000] ^= 0x1
    assert lib.horovod_tpu_crc32c(bytes(data), len(data)) != want


@pytest.mark.parametrize("scenario", [
    "crc_roundtrip",           # frame survives the wire and verifies
    "crc_corrupt_detected",    # flipped payload byte -> CRC error, not data
    "recv_deadline",           # silent peer trips SO_RCVTIMEO promptly
    "max_frame",               # corrupt length field rejected, not OOM'd
    "handshake_timeout",       # silent client can't wedge the accept loop
    "stale_generation",        # old-generation peer rejected at accept
    "fault_spec",              # injector parse + seeded determinism
    # Shared-memory data plane (docs/TRANSPORT.md):
    "shm_roundtrip",           # SPSC ring round-trip incl. wrap + EOF
    "shm_corrupt_detected",    # in-segment flip -> CRC error, not data
    "shm_fallback",            # bad name / bad header refuse -> TCP path
    "shm_closed_wakes_peer",   # Close wakes a futex-parked reader promptly
])
def test_net_selftest(scenario):
    assert _lib().horovod_tpu_net_selftest(scenario.encode()) == 1, scenario


def test_net_selftest_unknown_name():
    assert _lib().horovod_tpu_net_selftest(b"no_such_scenario") == -1


def test_no_tracked_native_binaries():
    """Guard: no build artifact (*.so / *.o / *.d) under horovod_tpu/
    may ever be git-tracked again — a stale prebuilt .so shadowing fresh
    sources has produced phantom test failures before (a tracked one
    would pin that hazard into every checkout). Skips gracefully when
    git is unavailable (e.g. an exported tarball)."""
    import subprocess
    try:
        out = subprocess.run(
            ["git", "ls-files", "horovod_tpu/"], cwd=REPO_ROOT,
            capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired):
        pytest.skip("git unavailable")
    if out.returncode != 0:
        pytest.skip("not a git checkout")
    tracked = [f for f in out.stdout.splitlines()
               if f.endswith((".so", ".o", ".d", ".a", ".dylib"))]
    assert tracked == [], (
        "build artifacts are git-tracked (git rm --cached them): %s"
        % tracked)
