"""Shared-memory intra-host data plane e2es (docs/TRANSPORT.md).

What must hold, per the PR's acceptance criteria:
  - same-host pairs negotiate shm and ALL data-ring bytes ride it;
  - shm and TCP runs are bitwise identical under none/bf16/int8 wire
    codecs including uneven pipelined chunks (per-rank result digests);
  - a mixed job (one rank with HVD_TPU_SHM=0) completes correctly with
    every pair transparently on TCP, and pairs with distinct host keys
    never attach a segment;
  - on a forced 2x2 topology only the intra-host legs ride shm;
  - a uniform-grid SUBGROUP's reduce-scatter/allreduce take the
    hierarchical path (reduce_scatter_hierarchical_total moves) with
    exact shard values, while a ragged subgroup stays on the flat ring;
  - a peer SIGKILLed mid-shm-hop surfaces a prompt recoverable
    CONNECTION_LOST on the survivor — no hang.
"""

import json
import os
import re
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Small pipelined chunks: every SIZES entry in shm_worker.py then slices
# into multiple segments with ragged tails — the "uneven pipelined
# chunks" half of the parity claim.
BASE_ENV = {
    "HVD_TPU_PIPELINE_CHUNK_BYTES": "2048",
    "HVD_TPU_SKIP_JIT_TEST": "1",
    # Deterministic transport selection: the live tuner samples the
    # hierarchical and shm_transport knobs mid-run, which would make the
    # per-leg byte accounting below run-dependent.
    "HVD_TPU_AUTOTUNE": "0",
}


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def run_workers(script, n, common_env=None, rank_env=None, topology=None,
                timeout=300):
    """Launches `n` copies of tests/`script` on localhost with per-rank
    env overrides (`rank_env[r]`). `topology="2x2"` forces the 2-host x
    2-slot grid (rank r = slot r%2 on "host" r//2)."""
    from horovod_tpu.run.util import cpu_worker_env
    ports = _free_ports(n)
    addrs = ",".join("127.0.0.1:%d" % p for p in ports)
    procs = []
    for r in range(n):
        env = cpu_worker_env(repo_root=REPO)
        env.update(BASE_ENV)
        env.update({
            "HVD_TPU_RANK": str(r),
            "HVD_TPU_SIZE": str(n),
            "HVD_TPU_ADDRS": addrs,
        })
        if topology == "2x2":
            assert n == 4
            env.update({
                "HVD_TPU_LOCAL_RANK": str(r % 2),
                "HVD_TPU_LOCAL_SIZE": "2",
                "HVD_TPU_CROSS_RANK": str(r // 2),
                "HVD_TPU_CROSS_SIZE": "2",
            })
        if common_env:
            env.update(common_env)
        if rank_env and r in rank_env:
            env.update(rank_env[r])
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    return procs, outs


def _metrics(outs, marker="SHM_METRICS"):
    by_rank = {}
    for out in outs:
        for m in re.findall(r"%s (\{.*?\})" % marker, out):
            d = json.loads(m)
            by_rank[d["rank"]] = d
    return by_rank


def _digests(outs):
    return [re.search(r"SHM_DIGEST ([0-9a-f]{8})", out).group(1)
            for out in outs]


def test_shm_engages_and_is_bitwise_identical_to_tcp():
    """Same-host 2-rank job: shm carries EVERY data-ring byte
    (shm_sent == ring_sent, 2 live segments per rank), and the per-rank
    result digests are bitwise identical to a TCP-forced run across
    none/bf16/int8 with uneven pipelined chunks."""
    procs, outs = run_workers("shm_worker.py", 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
    shm = _metrics(outs)
    for r in (0, 1):
        assert shm[r]["segments"] == 2, shm
        assert shm[r]["shm_sent"] > 0, shm
        assert shm[r]["shm_sent"] == shm[r]["ring_sent"], shm
    shm_digests = _digests(outs)

    procs, outs = run_workers("shm_worker.py", 2,
                              common_env={"HVD_TPU_SHM": "0"})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
    tcp = _metrics(outs)
    for r in (0, 1):
        assert tcp[r]["segments"] == 0, tcp
        assert tcp[r]["shm_sent"] == 0, tcp
    assert _digests(outs) == shm_digests  # bitwise parity, per rank


def test_mixed_job_single_rank_opt_out_falls_back_to_tcp():
    """One rank launched with HVD_TPU_SHM=0: the capability negotiation
    nacks every pair touching it and the job completes correctly on
    plain TCP — zero segments anywhere, results identical."""
    procs, outs = run_workers("shm_worker.py", 2,
                              rank_env={1: {"HVD_TPU_SHM": "0"}})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
    m = _metrics(outs)
    for r in (0, 1):
        assert m[r]["segments"] == 0, m
        assert m[r]["shm_sent"] == 0, m


def test_distinct_host_keys_never_attach():
    """Per-rank HVD_TPU_HOST_KEY overrides that differ: the acceptor's
    authoritative key comparison nacks the attach, so 'cross-host' pairs
    never ride shm even on one physical box."""
    procs, outs = run_workers(
        "shm_worker.py", 2,
        rank_env={0: {"HVD_TPU_HOST_KEY": "hostA"},
                  1: {"HVD_TPU_HOST_KEY": "hostB"}})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
    m = _metrics(outs)
    for r in (0, 1):
        assert m[r]["segments"] == 0, m
        assert m[r]["shm_sent"] == 0, m


def test_forced_2x2_topology_shm_on_intra_host_legs_only():
    """Forced 2-host x 2-slot grid on localhost: the host key carries
    the cross index, so exactly the intra-host legs (global-ring
    neighbor on the same 'host' + both local-ring legs = 3 segments per
    rank) ride shm while every cross-host leg stays TCP. With the
    hierarchical composites pinned on, every rank moves bytes on BOTH
    its local (shm) and cross (TCP) legs: 0 < shm_sent < ring_sent."""
    procs, outs = run_workers(
        "shm_worker.py", 4, topology="2x2",
        common_env={"HVD_TPU_HIERARCHICAL_ALLREDUCE": "1",
                    "HVD_TPU_HIERARCHICAL_REDUCESCATTER": "1"})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
    m = _metrics(outs)
    for r in range(4):
        assert m[r]["segments"] == 3, m
        assert 0 < m[r]["shm_sent"] < m[r]["ring_sent"], m


def test_subgroup_uniform_grid_takes_hierarchical_path():
    """A subgroup forming a uniform 2x2 grid: its reduce-scatter and
    allreduce ride the hierarchical composites (counter-proved — 3
    codecs x 3 sizes = 9 hierarchical reduce-scatters) with exact shard
    values, its intra-host sub-ring legs ride shm, and a ragged group
    {0,1,3} stays on the flat ring (zero counter movement)."""
    procs, outs = run_workers(
        "group_hier_worker.py", 4, topology="2x2",
        common_env={"HVD_TPU_HIERARCHICAL_REDUCESCATTER": "1",
                    "HVD_TPU_HIERARCHICAL_ALLREDUCE": "1"})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
    m = _metrics(outs, marker="GHIER_METRICS")
    for r in range(4):
        assert m[r]["grid_hier"] == 9, m
        assert m[r]["ragged_hier"] == 0, m
        # init legs (3) + grid group's local sub-ring legs (2) at least;
        # flat group rings add more on some ranks.
        assert m[r]["segments"] >= 5, m
        assert m[r]["shm_sent"] > 0, m


def test_peer_death_mid_shm_hop_prompt_connection_lost():
    """SIGKILL a rank mid-stream (no orderly ring close): the survivor
    must fail its collective with the recoverable CONNECTION_LOST
    within the transport deadline — never hang. (The elastic layer's
    shrink rides exactly this error; test_elastic proves that end to
    end and runs over the same default-on shm plane.)"""
    procs, outs = run_workers(
        "shm_kill_worker.py", 2,
        common_env={"HVD_TPU_NET_TIMEOUT_SECONDS": "4",
                    "HVD_TPU_CONTROL_POLL_TIMEOUT_SECONDS": "6",
                    "HVD_TPU_RECONNECT_SECONDS": "2"},
        timeout=120)
    # Rank 1 died by SIGKILL.
    assert procs[1].returncode in (-9, 137), procs[1].returncode
    # Rank 0 exited promptly with the recoverable, cause-named error.
    assert procs[0].returncode == 7, "rank 0:\n%s" % outs[0]
    assert "CONNLOST" in outs[0], outs[0]
    assert "connection" in outs[0].lower(), outs[0]
