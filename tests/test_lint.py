"""hvd-lint: corpus of known-bad / known-good snippets (one per rule),
suppression handling, CLI exit-code semantics — and the repo self-lint:
the shipped examples and models must stay clean, so a divergence hazard
introduced into them fails tier-1."""

import json
import os
import textwrap

import pytest

from horovod_tpu.lint import RULES, lint_paths, lint_source
from horovod_tpu.lint.cli import main as lint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(source):
    return [f.rule for f in lint_source(textwrap.dedent(source))]


# --- known-bad corpus: one snippet per rule ---------------------------------

BAD_CORPUS = {
    "rank-conditional-collective": """
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() == 0:
            hvd.allreduce(x, "t")
    """,
    "missing-initial-broadcast": """
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(opt)
    """,
    "missing-bn-stats-broadcast": """
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(opt)
        params = hvd_jax.broadcast_parameters(variables["params"],
                                              root_rank=0)
        logits, upd = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
    """,
    "unordered-name-iteration": """
        import horovod_tpu as hvd
        for key in {"w", "b"}:
            hvd.allreduce(x, name="grad.%s" % key)
    """,
    "rank-dependent-name": """
        import horovod_tpu as hvd
        hvd.allreduce(x, name="grad.%d" % hvd.rank())
    """,
    "loop-auto-name": """
        import horovod_tpu as hvd
        for step in range(100):
            hvd.allreduce(x)
    """,
    "duplicate-collective-name": """
        import horovod_tpu as hvd
        hvd.allreduce(x, name="g")
        hvd.allreduce(y, name="g")
    """,
    "name-attr-mismatch": """
        import horovod_tpu.jax as hj
        hj.allreduce(x, name="g", average=True)
        hj.allreduce(y, name="g", average=False)
    """,
    "checkpoint-in-rank-guard": """
        import horovod_tpu.jax as hvd
        from horovod_tpu.jax import checkpoint
        if hvd.rank() == 0:
            checkpoint.save("/ckpt", tree, step=5)
    """,
    "compression-on-integer-tensor": """
        import horovod_tpu.jax as hvd
        ids = tokens.astype(jnp.int32)
        hvd.allreduce(ids, name="ids", compression="int8")
    """,
    "sharded-update-rank-local-param-read": """
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(inner, sharded_update=True)
        params = hvd_jax.broadcast_parameters(params, root_rank=0)
        state = opt.init(params)
        mu = state["inner"][0].mu
    """,
    "collective-in-serve-handler": """
        import horovod_tpu.jax as hvd
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                y = self._pool_mean()
                self.wfile.write(y)

            def _pool_mean(self):
                return refresh_stats(1.0)

        def refresh_stats(x):
            return hvd.allreduce(x, average=True, name="serve.stats")
    """,
    "thread-shared-mutable-without-lock": """
        import threading

        class Pump:
            def __init__(self):
                self.moved = 0
                self._stop = False
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                while not self._stop:
                    self.moved += 1

            def progress(self):
                return self.moved
    """,
}

# --- known-good twins: the corrected version of each snippet ----------------

GOOD_CORPUS = {
    "rank-conditional-collective": """
        import horovod_tpu as hvd
        hvd.init()
        loss = hvd.allreduce(x, "t")
        if hvd.rank() == 0:
            print(loss)
    """,
    "missing-initial-broadcast": """
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(opt)
        params = hvd_jax.broadcast_parameters(params, root_rank=0)
    """,
    "missing-bn-stats-broadcast": """
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(opt)
        params = hvd_jax.broadcast_parameters(variables["params"],
                                              root_rank=0)
        stats = hvd_jax.broadcast_parameters(variables["batch_stats"],
                                             root_rank=0)
        logits, upd = model.apply(
            {"params": params, "batch_stats": stats},
            x, train=True, mutable=["batch_stats"])
    """,
    "unordered-name-iteration": """
        import horovod_tpu as hvd
        for key in sorted({"w", "b"}):
            hvd.allreduce(x, name="grad.%s" % key)
    """,
    "rank-dependent-name": """
        import horovod_tpu as hvd
        hvd.allreduce(x, name="grad.dense0")
    """,
    "loop-auto-name": """
        import horovod_tpu as hvd
        for step in range(100):
            hvd.allreduce(x, name="grad.dense0")
    """,
    "duplicate-collective-name": """
        import horovod_tpu as hvd
        hvd.allreduce(x, name="g.x")
        hvd.allreduce(y, name="g.y")
    """,
    "name-attr-mismatch": """
        import horovod_tpu.jax as hj
        hj.allreduce(x, name="g.sum", average=False)
        hj.allreduce(y, name="g.mean", average=True)
    """,
    "checkpoint-in-rank-guard": """
        import horovod_tpu.jax as hvd
        from horovod_tpu.jax import checkpoint
        checkpoint.save("/ckpt", tree, step=5)
        if hvd.rank() == 0:
            print("saved")
    """,
    "compression-on-integer-tensor": """
        import horovod_tpu.jax as hvd
        grads = jax.grad(loss)(params)
        hvd.allreduce(grads, name="g", compression="int8")
    """,
    "sharded-update-rank-local-param-read": """
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(inner, sharded_update=True)
        params = hvd_jax.broadcast_parameters(params, root_rank=0)
        state = opt.init(params)
        full = hvd_jax.sharded_state_full(state)
        mu = full["inner"][0].mu
    """,
    "collective-in-serve-handler": """
        import horovod_tpu.jax as hvd
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                self.wfile.write(b"ok")

        def pool_mean(x):
            return hvd.allreduce(x, name="serve.stats")
    """,
    "thread-shared-mutable-without-lock": """
        import threading

        class Pump:
            def __init__(self):
                self.moved = 0
                self._stop = False
                self._mu = threading.Lock()
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                while not self._stop:
                    with self._mu:
                        self.moved += 1

            def progress(self):
                with self._mu:
                    return self.moved
    """,
}


@pytest.mark.parametrize("rule", sorted(BAD_CORPUS))
def test_known_bad_flags(rule):
    assert rule in rules_of(BAD_CORPUS[rule])


@pytest.mark.parametrize("rule", sorted(GOOD_CORPUS))
def test_known_good_clean(rule):
    assert rules_of(GOOD_CORPUS[rule]) == []


def test_thread_shared_mutable_edges():
    """Constant flags are the blessed signaling idiom (not flagged);
    the mutation is caught through a helper the thread reaches
    transitively; an inline suppression quiets the WARNING."""
    base = """
        import threading

        class Pump:
            def __init__(self):
                self.moved = 0
                self._stop = False
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                while not self._stop:
                    self._step()

            def _step(self):
                self.moved += 1{suffix}

            def stop(self):
                self._stop = True

            def progress(self):
                return self.moved
    """
    findings = lint_source(textwrap.dedent(base.format(suffix="")))
    assert [f.rule for f in findings] == \
        ["thread-shared-mutable-without-lock"]
    # anchored at the mutation inside the transitively-reached helper
    assert "Pump._step" in findings[0].message
    assert findings[0].severity == "warning"
    # `self._stop = True` (constant flag) was NOT flagged
    assert "moved" in findings[0].message
    suppressed = base.format(
        suffix="  # hvd-lint: disable=thread-shared-mutable-without-lock")
    assert rules_of(suppressed) == []


def test_sharded_state_read_variants():
    # torch style: `.state` on the sharded wrapper is empty by design.
    assert "sharded-update-rank-local-param-read" in rules_of("""
        import horovod_tpu.torch as hvd_torch
        opt = hvd_torch.DistributedOptimizer(sgd, sharded_update=True)
        buf = opt.state[p]["momentum_buffer"]
    """)
    # The re-bound state from update() keeps the taint.
    assert "sharded-update-rank-local-param-read" in rules_of("""
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(inner, sharded_update=True)
        s = opt.init(params)
        u, s = opt.update(grads, s, params)
        nu = s["inner"][0].nu
    """)
    # A dynamic sharded_update= counts (may be True at run time).
    assert "sharded-update-rank-local-param-read" in rules_of("""
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(inner, sharded_update=flag)
        s = opt.init(params)
        moments = s["inner"]
    """)
    # Replicated optimizers are untouched...
    assert rules_of("""
        import horovod_tpu.torch as hvd_torch
        opt = hvd_torch.DistributedOptimizer(sgd)
        params = hvd_torch.broadcast_parameters(params, root_rank=0)
        buf = opt.state[p]["momentum_buffer"]
    """) == []
    # ...as is an explicit sharded_update=False.
    assert rules_of("""
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(inner, sharded_update=False)
        params = hvd_jax.broadcast_parameters(params, root_rank=0)
        s = opt.init(params)
        moments = s["inner"]
    """) == []
    # Metadata keys on the sharded state stay clean.
    assert rules_of("""
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(inner, sharded_update=True)
        params = hvd_jax.broadcast_parameters(params, root_rank=0)
        s = opt.init(params)
        w = s["world"]
    """) == []


def test_bn_stats_broadcast_variants():
    # torch: BN buffers live in state_dict(), not parameters() — the
    # parameters() broadcast leaves running stats per-rank.
    assert "missing-bn-stats-broadcast" in rules_of("""
        import torch.nn as nn
        import horovod_tpu.torch as hvd
        model = nn.Sequential(nn.Conv2d(3, 8, 3), nn.BatchNorm2d(8))
        opt = hvd.DistributedOptimizer(
            sgd, named_parameters=model.named_parameters())
        hvd.broadcast_parameters(model.parameters(), root_rank=0)
    """)
    assert rules_of("""
        import torch.nn as nn
        import horovod_tpu.torch as hvd
        model = nn.Sequential(nn.Conv2d(3, 8, 3), nn.BatchNorm2d(8))
        opt = hvd.DistributedOptimizer(
            sgd, named_parameters=model.named_parameters())
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    """) == []
    # Broadcasting the WHOLE flax variables dict covers the stats.
    assert rules_of("""
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(opt)
        variables = hvd_jax.broadcast_parameters(variables, root_rank=0)
        logits, upd = model.apply(
            {"params": variables["params"],
             "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
    """) == []
    # Sync BN keeps every rank's statistics identical by construction.
    assert rules_of("""
        import horovod_tpu.jax as hvd_jax
        from horovod_tpu.ops.batch_norm import LeanBatchNorm
        opt = hvd_jax.DistributedOptimizer(opt)
        params = hvd_jax.broadcast_parameters(variables["params"],
                                              root_rank=0)
        norm = LeanBatchNorm(axis_name="hvd")
        logits, upd = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
    """) == []
    # No mutable BN state in the file: the rule stays silent (the base
    # missing-initial-broadcast rule owns the no-broadcast case).
    assert "missing-bn-stats-broadcast" not in rules_of("""
        import horovod_tpu.jax as hvd_jax
        opt = hvd_jax.DistributedOptimizer(opt)
        params = hvd_jax.broadcast_parameters(params, root_rank=0)
    """)


def test_compression_on_embedding_lookup_is_warning():
    findings = lint_source(textwrap.dedent("""
        import horovod_tpu.jax as hvd
        rows = jnp.take(emb, token_ids, axis=0)
        hvd.allreduce(rows, name="emb", compression="bf16")
    """))
    ours = [f for f in findings
            if f.rule == "compression-on-integer-tensor"]
    assert len(ours) == 1 and ours[0].severity == "warning", findings


def test_compression_integer_via_dataflow_and_dtype_kwarg():
    # One-level dataflow: the int provenance survives the assignment.
    assert "compression-on-integer-tensor" in rules_of("""
        import horovod_tpu as hvd
        mask = np.zeros(100, dtype=np.int64)
        hvd.allreduce(mask, name="m", compression="int8")
    """)
    # Float tensors with compression are clean...
    assert rules_of("""
        import horovod_tpu as hvd
        g = np.zeros(100, dtype=np.float32)
        hvd.allreduce(g, name="g", compression="int8")
    """) == []
    # ...and compression='none' on an integer tensor is clean too.
    assert rules_of("""
        import horovod_tpu as hvd
        ids = tokens.astype(np.int32)
        hvd.allreduce(ids, name="ids", compression="none")
    """) == []


def test_uniform_size_condition_not_flagged():
    # size() is identical on every rank — `if size > 1` is safe.
    assert rules_of("""
        import horovod_tpu as hvd
        if hvd.size() > 1:
            hvd.allreduce(x, "t")
    """) == []


def test_rank_variable_dataflow():
    # rank held in a variable (the common idiom) is still caught.
    assert "rank-conditional-collective" in rules_of("""
        import horovod_tpu as hvd
        rank, world = hvd.rank(), hvd.size()
        if rank == 0:
            hvd.broadcast(x, 0, "t")
    """)


def test_dict_iteration_is_warning_set_is_error():
    findings = lint_source(textwrap.dedent("""
        import horovod_tpu as hvd
        for k, v in params.items():
            hvd.allreduce(v, name=k)
    """))
    assert [f.severity for f in findings
            if f.rule == "unordered-name-iteration"] == ["warning"]
    findings = lint_source(textwrap.dedent("""
        import horovod_tpu as hvd
        for k in set(names):
            hvd.allreduce(x, name=k)
    """))
    assert [f.severity for f in findings
            if f.rule == "unordered-name-iteration"] == ["error"]


def test_elastic_commit_under_rank_conditional():
    assert "rank-conditional-collective" in rules_of("""
        import horovod_tpu as hvd
        from horovod_tpu import elastic
        state = elastic.ElasticState(params)
        if hvd.rank() == 0:
            state.commit()
    """)


def test_checkpoint_rank_guard_variants():
    # restore under a guard is the same deadlock as save.
    assert "checkpoint-in-rank-guard" in rules_of("""
        import horovod_tpu.jax as hvd
        from horovod_tpu.jax import checkpoint
        if hvd.rank() == 0:
            tree = checkpoint.restore("/ckpt", template, step=5)
    """)
    # Dotted access through the hvd alias counts too.
    assert "checkpoint-in-rank-guard" in rules_of("""
        import horovod_tpu.jax as hvd
        r = hvd.rank()
        if r == 0:
            hvd.checkpoint.save("/ckpt", tree)
    """)
    # The generic rank-conditional-collective rule must NOT double-fire
    # on the same site.
    findings = lint_source(textwrap.dedent("""
        import horovod_tpu.jax as hvd
        from horovod_tpu.jax import checkpoint
        if hvd.rank() == 0:
            checkpoint.save("/ckpt", tree)
    """))
    assert [f.rule for f in findings] == ["checkpoint-in-rank-guard"]


def test_checkpoint_rank_guard_ignores_unrelated_save():
    # model.save() / state.save() under a rank guard is ordinary
    # rank-0-only work (no collectives inside) — not our business.
    assert rules_of("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            model.save("/weights.h5")
            state.save()
    """) == []


def test_parse_error_is_a_finding():
    findings = lint_source("def broken(:\n")
    assert [f.rule for f in findings] == ["parse-error"]
    assert findings[0].severity == "error"


# --- suppressions -----------------------------------------------------------

def test_inline_suppression_same_line():
    assert rules_of("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.allreduce(x, "t")  # hvd-lint: disable=rank-conditional-collective
    """) == []


def test_inline_suppression_preceding_line():
    assert rules_of("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            # hvd-lint: disable=rank-conditional-collective
            hvd.allreduce(x, "t")
    """) == []


def test_bare_disable_suppresses_all():
    assert rules_of("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.allreduce(x, name="g.%d" % hvd.rank())  # hvd-lint: disable
    """) == []


def test_stacked_standalone_suppressions_accumulate():
    assert rules_of("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            # hvd-lint: disable=rank-conditional-collective
            # hvd-lint: disable=rank-dependent-name
            hvd.allreduce(x, name="g.%d" % hvd.rank())
    """) == []


def test_suppression_on_multiline_call_closing_line():
    assert rules_of("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.allreduce(
                x,
                "t")  # hvd-lint: disable=rank-conditional-collective
    """) == []


def test_suppression_is_rule_scoped():
    # Suppressing one rule must not hide another on the same line.
    found = rules_of("""
        import horovod_tpu as hvd
        if hvd.rank() == 0:
            hvd.allreduce(x, name="g.%d" % hvd.rank())  # hvd-lint: disable=rank-conditional-collective
    """)
    assert "rank-dependent-name" in found
    assert "rank-conditional-collective" not in found


# --- CLI exit codes and formats ---------------------------------------------

def _write(tmp_path, name, source):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return str(path)


def test_cli_exit_zero_on_clean(tmp_path, capsys):
    target = _write(tmp_path, "good.py", GOOD_CORPUS["rank-dependent-name"])
    assert lint_main([target]) == 0
    assert "clean" in capsys.readouterr().err


def test_cli_exit_one_on_findings(tmp_path, capsys):
    target = _write(tmp_path, "bad.py",
                    BAD_CORPUS["rank-conditional-collective"])
    assert lint_main([target]) == 1
    out = capsys.readouterr().out
    assert "rank-conditional-collective" in out


def test_cli_fail_on_error_ignores_warnings(tmp_path):
    target = _write(tmp_path, "warn.py",
                    BAD_CORPUS["missing-initial-broadcast"])
    assert lint_main([target]) == 1  # default: warnings fail
    assert lint_main([target, "--fail-on", "error"]) == 0


def test_cli_disable_rule(tmp_path):
    target = _write(tmp_path, "bad.py", BAD_CORPUS["loop-auto-name"])
    assert lint_main([target, "--disable", "loop-auto-name"]) == 0


def test_cli_usage_errors_exit_two(tmp_path):
    with pytest.raises(SystemExit) as exc:
        lint_main(["/nonexistent/path.py"])
    assert exc.value.code == 2
    target = _write(tmp_path, "x.py", "pass\n")
    with pytest.raises(SystemExit) as exc:
        lint_main([target, "--disable", "no-such-rule"])
    assert exc.value.code == 2


def test_cli_json_format(tmp_path, capsys):
    target = _write(tmp_path, "bad.py", BAD_CORPUS["rank-dependent-name"])
    assert lint_main([target, "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["files_checked"] == 1
    assert payload["findings"][0]["rule"] == "rank-dependent-name"
    assert payload["findings"][0]["path"] == target


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in RULES:
        assert rule_id in out


def test_directory_recursion(tmp_path):
    sub = tmp_path / "pkg"
    sub.mkdir()
    (sub / "bad.py").write_text(textwrap.dedent(
        BAD_CORPUS["duplicate-collective-name"]))
    findings, checked = lint_paths([str(tmp_path)])
    assert checked == 1
    assert [f.rule for f in findings] == ["duplicate-collective-name"]


# --- repo self-lint ---------------------------------------------------------

def test_repo_examples_and_models_are_clean():
    """The shipped examples and models must lint clean (intentional
    patterns carry inline suppressions). A new hazard in them fails
    tier-1 here before it ships."""
    findings, checked = lint_paths([
        os.path.join(REPO_ROOT, "examples"),
        os.path.join(REPO_ROOT, "horovod_tpu", "models"),
    ])
    assert checked >= 30
    assert findings == [], "\n".join(
        "%s:%d %s %s" % (f.path, f.line, f.rule, f.message)
        for f in findings)


def _worker_scripts():
    tests_dir = os.path.join(REPO_ROOT, "tests")
    return sorted(
        os.path.join(tests_dir, name) for name in os.listdir(tests_dir)
        if name.endswith("_worker.py"))


def test_repo_elastic_fleet_and_workers_lint_clean():
    """Self-lint beyond the example corpus: the elastic and fleet
    packages (library code that itself issues collectives) and every
    tests/*_worker.py launch script. Workers deliberately exercise
    hazards (divergence, mixed modes, non-member submissions) — those
    sites carry inline `# hvd-lint: disable=` suppressions, so a NEW
    unsuppressed hazard fails tier-1 here."""
    workers = _worker_scripts()
    assert len(workers) >= 30
    findings, checked = lint_paths([
        os.path.join(REPO_ROOT, "horovod_tpu", "elastic"),
        os.path.join(REPO_ROOT, "horovod_tpu", "fleet"),
        os.path.join(REPO_ROOT, "horovod_tpu", "serve"),
    ] + workers)
    assert checked >= 45
    assert findings == [], "\n".join(
        "%s:%d %s %s" % (f.path, f.line, f.rule, f.message)
        for f in findings)


def test_repo_schedules_verify_clean():
    """hvd-verify self-check: the example corpus, the model zoo, and
    every worker script run through the symbolic schedule verifier.
    Intentional-hazard fixtures (divergence_worker and friends) carry
    suppressions; tests/test_verify.py separately proves the findings
    reappear when the suppressions are stripped."""
    from horovod_tpu.lint import verify_paths

    findings, checked = verify_paths([
        os.path.join(REPO_ROOT, "examples"),
        os.path.join(REPO_ROOT, "horovod_tpu", "models"),
        os.path.join(REPO_ROOT, "horovod_tpu", "serve"),
    ] + _worker_scripts())
    assert checked >= 65
    assert findings == [], "\n".join(
        "%s:%d %s %s" % (f.path, f.line, f.rule, f.message)
        for f in findings)
