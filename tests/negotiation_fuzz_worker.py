"""Negotiation fuzz: every rank enqueues the same set of collectives in
a different (rank-seeded) order, interleaving allreduce/allgather/
broadcast, then synchronizes in yet another order. The coordinator's
whole job is to make this safe (reference CI covers it implicitly via
framework-threaded enqueue; here it is explicit)."""

import random
import sys

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


def main():
    import os
    import threading

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n >= 2

    # Metrics-plane race check (make check-tsan / check-asan run with
    # HVD_TPU_METRICS=1): a scraper thread hammers the C snapshot API
    # while the background thread and the fuzz's out-of-order enqueue
    # threads mutate the registry — any locking regression in
    # native/metrics.{h,cc} shows up as a sanitizer report here.
    stop_scraper = threading.Event()
    scraper = None
    if os.environ.get("HVD_TPU_METRICS") == "1":
        def scrape_loop():
            while not stop_scraper.is_set():
                snap = hvd.metrics()
                assert "counters" in snap
                hvd.job_metrics()
                # Autotune state API under concurrent knob mutation: the
                # sanitizer autotune variant (native/Makefile) runs the
                # tuner with per-cycle sampling, so this read races a
                # live ReadyTune unless the manager's mutex discipline
                # holds.
                assert "params" in hvd.autotune()
        scraper = threading.Thread(target=scrape_loop, daemon=True)
        scraper.start()

    # Trace-plane race check (the sanitizer tracing variant,
    # native/Makefile TRACE_FUZZ_ENV): with a deliberately tiny ring
    # (HVD_TPU_TRACE_RING=256) the recorder wraps constantly while the
    # background thread, the codec worker threads, and THIS hammer
    # thread all write spans — any seqlock regression in native/trace.h
    # is a sanitizer report. Mid-fuzz the hammer also dumps a bundle
    # through the C API (empty pending table: that API is callable from
    # any thread, unlike the coordinator-side dump paths), racing the
    # bundle's ring snapshot against live writers.
    stop_hammer = threading.Event()
    hammer = None
    if os.environ.get("HVD_TPU_FUZZ_TRACE") == "1":
        from horovod_tpu.common.basics import get_basics

        def hammer_loop():
            b = get_basics()
            i = 0
            while not stop_hammer.is_set():
                t0 = b.trace_now_ns()
                b.trace_record("hammer.%d" % (i % 7), 8, t0,
                               b.trace_now_ns(), nbytes=i)
                i += 1
                if i % 512 == 0:
                    b.trace_dump_bundle("fuzz")
                    counters = b.trace_counters()
                    assert counters["trace_spans_total"] >= 0
        hammer = threading.Thread(target=hammer_loop, daemon=True)
        hammer.start()

    # HVD_TPU_FUZZ_TENSORS trims the run; HVD_TPU_FUZZ_ROUNDS repeats
    # the enqueue+verify cycle with fresh names so negotiation traffic
    # flows across the WHOLE run instead of batching into the first few
    # coordinator cycles. The chaos matrix (test_chaos.py) relies on the
    # rounds to place an injected fault deterministically mid-run.
    num_tensors = int(os.environ.get("HVD_TPU_FUZZ_TENSORS", "40"))
    rounds = int(os.environ.get("HVD_TPU_FUZZ_ROUNDS", "1"))
    seed = int(os.environ.get("HVD_TPU_FUZZ_SEED", "1234"))

    # Durable-writer race check (the sanitizer durable variant,
    # native/Makefile): a background checkpoint writer commits every
    # round — pickling snapshots, calling the crc32c and ckpt-metrics C
    # APIs from ITS thread — concurrently with the fuzz's out-of-order
    # enqueues, the background coordination thread, and the scraper.
    # HVD_TPU_CKPT_FAULT_SPEC additionally drives the retry/degrade
    # paths under the same concurrency.
    state = None
    if os.environ.get("HVD_TPU_FUZZ_DURABLE") == "1":
        from horovod_tpu import elastic

        state = elastic.ElasticState(
            w=np.arange(4096, dtype=np.float64) * (r + 1), step=0)
        state.enable_durable()  # HVD_TPU_CKPT_DIR
    # HVD_TPU_FUZZ_SHARDED=1 (the sanitizer sharded-update variant,
    # native/Makefile) folds reduce-scatter into the kind cycle: the
    # standalone REDUCESCATTER op negotiates/executes concurrently with
    # the other kinds from out-of-order user threads, with the
    # compression codec (HVD_TPU_COMPRESSION) riding each hop. Constant
    # fills quantize exactly, so the value assertions stay bit-strict.
    kinds = ("allreduce", "allgather", "broadcast")
    if os.environ.get("HVD_TPU_FUZZ_SHARDED") == "1":
        kinds = ("allreduce", "allgather", "broadcast", "reduce_scatter")
    # HVD_TPU_FUZZ_GROUPS=1 (the sanitizer grouped-negotiation variant,
    # native/Makefile): two OVERLAPPING process groups — {0, 1} and {0}
    # — fold group-scoped collectives into the out-of-order kind cycle,
    # so group-keyed negotiation, the per-group response-cache bits
    # (vacuous hits on non-members), lazy group-ring construction, and
    # rank-remapped execution all run concurrently with the world-group
    # kinds under compression and injected frame jitter. Rank 0
    # additionally drives the singleton group each round (its tensors
    # negotiate with a ready count of ONE while world tensors are
    # pending — the overlap case).
    groups_mode = os.environ.get("HVD_TPU_FUZZ_GROUPS") == "1"
    g_pair = g_solo = None
    if groups_mode:
        g_pair = hvd.new_group([0, 1])
        g_solo = hvd.new_group([0])
        kinds = kinds + ("group_allreduce", "group_reduce_scatter")
    jobs = []
    for i in range(num_tensors):
        jobs.append((i, kinds[i % len(kinds)]))

    for rnd in range(rounds):
        # Same job set, rank-specific enqueue order (reshuffled per round).
        order = list(range(num_tensors))
        random.Random(seed + r + 101 * rnd).shuffle(order)

        handles = {}
        for i in order:
            idx, kind = jobs[i]
            name = "fuzz.%d.%d" % (rnd, idx)
            if kind == "allreduce":
                arr = np.full((idx + 1, 3), float(r + 1), np.float32)
                handles[idx] = ("allreduce",
                                ops.allreduce_async(arr, name))  # hvd-lint: disable=loop-auto-name
            elif kind == "reduce_scatter":
                arr = np.full((idx + 1, 3), float(r + 1), np.float32)
                handles[idx] = ("reduce_scatter",
                                ops.reduce_scatter_async(arr, name))  # hvd-lint: disable=loop-auto-name
            elif kind == "group_allreduce":
                if r in g_pair.ranks:
                    arr = np.full((idx + 1, 3), float(r + 1), np.float32)
                    # group membership is env-conditional here (the
                    # verifier cannot know fuzz_groups), and the fuzz
                    # DELIBERATELY enqueues rank-shuffled orders the
                    # coordinator must tolerate
                    handles[idx] = ("group_allreduce",
                                    ops.allreduce_async(arr, name,  # hvd-lint: disable=loop-auto-name,verify-divergent-schedule
                                                        group=g_pair))
            elif kind == "group_reduce_scatter":
                if r in g_pair.ranks:
                    arr = np.full((idx + 1, 3), float(r + 1), np.float32)
                    handles[idx] = ("group_reduce_scatter",
                                    ops.reduce_scatter_async(  # hvd-lint: disable=loop-auto-name
                                        arr, name, group=g_pair))
            elif kind == "allgather":
                # Rank-dependent fill so a permuted segment order is
                # caught.
                arr = np.full((r + 1, 2), float(idx * 1000 + r),
                              np.float32)
                handles[idx] = ("allgather",
                                ops.allgather_async(arr, name))  # hvd-lint: disable=loop-auto-name
            else:
                arr = np.full((2, idx + 1), float(r * 100 + idx),
                              np.float32)
                handles[idx] = ("broadcast",
                                ops.broadcast_async(arr, idx % n, name))  # hvd-lint: disable=loop-auto-name

        # The overlapping singleton group: rank 0 alone, mid-burst.
        if groups_mode and r in g_solo.ranks:
            solo = ops.allreduce(
                np.full(5, 7.0, np.float32), "fuzz_solo.%d" % rnd,
                group=g_solo)
            assert np.allclose(solo, 7.0), solo

        # Synchronize in a different rank-specific order.
        sync_order = list(range(num_tensors))
        random.Random(seed * 3 + 7 + r + 101 * rnd).shuffle(sync_order)
        for idx in sync_order:
            if idx not in handles:
                continue  # group kind on a non-member rank
            kind, handle = handles[idx]
            out = ops.synchronize(handle)
            if kind == "group_allreduce":
                expected = sum(m + 1 for m in g_pair.ranks)
                assert out.shape == (idx + 1, 3), (idx, out.shape)
                assert np.allclose(out, expected), (idx, out)
                continue
            if kind == "group_reduce_scatter":
                k = len(g_pair.ranks)
                expected = sum(m + 1 for m in g_pair.ranks)
                counts, _ = ops.shard_partition((idx + 1) * 3, k)
                gr = g_pair.ranks.index(r)
                assert out.shape == (counts[gr],), (idx, out.shape)
                assert np.allclose(out, expected), (idx, out)
                continue
            if kind == "allreduce":
                expected = sum(rr + 1 for rr in range(n))
                assert out.shape == (idx + 1, 3), (idx, out.shape)
                assert np.allclose(out, expected), (idx, out)
            elif kind == "reduce_scatter":
                expected = sum(rr + 1 for rr in range(n))
                counts, _ = ops.shard_partition((idx + 1) * 3, n)
                assert out.shape == (counts[r],), (idx, out.shape)
                assert np.allclose(out, expected), (idx, out)
            elif kind == "allgather":
                assert out.shape == (sum(rr + 1 for rr in range(n)), 2), \
                    (idx, out.shape)
                expected = np.concatenate(
                    [np.full((rr + 1, 2), float(idx * 1000 + rr),
                             np.float32)
                     for rr in range(n)])
                assert np.allclose(out, expected), (idx, out)
            else:
                root = idx % n
                assert out.shape == (2, idx + 1), (idx, out.shape)
                assert np.allclose(out, float(root * 100 + idx)), (idx,
                                                                   out)

        if state is not None:
            state.step = rnd + 1
            state.w = state.w + 1.0
            state.commit()  # hvd-lint: disable=rank-conditional-collective

    if state is not None:
        assert state._durable.flush(timeout=120), \
            "durable writer did not drain"

    if hammer is not None:
        stop_hammer.set()
        hammer.join(timeout=10)

    if scraper is not None:
        stop_scraper.set()
        scraper.join(timeout=10)
        snap = hvd.metrics()
        assert snap["counters"]["tensors_enqueued_total"] >= num_tensors, snap

    print("rank %d: negotiation fuzz passed" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
