"""Kill-tree hardening: a worker's descendant that re-sessioned with
setsid escapes process-group kills; the exec middleman must still reap
it (reference analogue: safe_shell_exec's middleman,
run/common/util/safe_shell_exec.py)."""

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, subprocess, sys, time
pidfile = sys.argv[1]
# Grandchild in its OWN session: killpg on the worker's group misses it.
subprocess.Popen(
    [sys.executable, "-c",
     "import os,sys,time; open(sys.argv[1],'w').write(str(os.getpid()));"
     "time.sleep(300)", pidfile],
    start_new_session=True)
time.sleep(300)
"""


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def _wait_for(path, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path) and os.path.getsize(path) > 0:
            with open(path) as f:
                return int(f.read())
        time.sleep(0.1)
    raise TimeoutError(path)


def test_middleman_reaps_setsid_grandchild(tmp_path):
    pidfile = str(tmp_path / "grandchild.pid")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    mm = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.run.exec_middleman", "--",
         sys.executable, "-c", WORKER, pidfile],
        env=env, start_new_session=True)
    try:
        grandchild = _wait_for(pidfile)
        assert _alive(grandchild)
        # The launcher's teardown path: signal the middleman's group.
        os.killpg(os.getpgid(mm.pid), signal.SIGTERM)
        mm.wait(timeout=30)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and _alive(grandchild):
            time.sleep(0.2)
        assert not _alive(grandchild), \
            "setsid grandchild %d survived the kill" % grandchild
    finally:
        if mm.poll() is None:
            mm.kill()
        if os.path.exists(pidfile):
            try:
                os.kill(int(open(pidfile).read()), signal.SIGKILL)
            except (OSError, ValueError):
                pass


def test_middleman_sweeps_stragglers_on_clean_exit(tmp_path):
    """Command exits 0 but left a re-sessioned helper behind: the
    middleman sweeps it instead of leaking it past the job."""
    pidfile = str(tmp_path / "straggler.pid")
    script = (
        "import os, subprocess, sys, time\n"
        "subprocess.Popen([sys.executable, '-c',\n"
        " \"import os,sys,time; open(sys.argv[1],'w')"
        ".write(str(os.getpid())); time.sleep(300)\", sys.argv[1]],\n"
        " start_new_session=True)\n"
        # Exit only once the straggler is up (interpreter boot takes
        # seconds on this host), so the sweep provably kills a LIVE,
        # observable straggler.\n"
        "for _ in range(600):\n"
        "    if os.path.exists(sys.argv[1]) and "
        "os.path.getsize(sys.argv[1]) > 0: break\n"
        "    time.sleep(0.1)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    mm = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run.exec_middleman", "--",
         sys.executable, "-c", script, pidfile],
        env=env, timeout=60)
    assert mm.returncode == 0
    straggler = _wait_for(pidfile, timeout=5)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and _alive(straggler):
        time.sleep(0.2)
    try:
        assert not _alive(straggler), \
            "straggler %d outlived the middleman" % straggler
    finally:
        try:
            os.kill(straggler, signal.SIGKILL)
        except OSError:
            pass
