"""Fusion-knob boundary worker: 8 batches of 4 concurrent 1 KB (256
float32) allreduces with a long (50 ms) cycle so all four tensors of a
batch are queued when the cycle fires; grouping is then decided purely
by HVD_TPU_FUSION_THRESHOLD. Verifies every value and prints rank 0's
response/tensor counters and the effective threshold."""

import sys

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    size = hvd.size()
    r = hvd.rank()
    base = np.arange(256, dtype=np.float32)  # 1 KB
    batches, per_batch = 8, 4
    for i in range(batches):
        handles = [hvd.allreduce_async(base + float(r), "fuse.%d" % j)
                   for j in range(per_batch)]
        for h in handles:
            out = hvd.synchronize(h)
            expected = base * size + sum(range(size))
            if not np.allclose(out, expected):
                print("MISMATCH batch %d" % i)
                return 1
    if r == 0:
        responses, tensors = hvd.get_basics().perf_counters()
        print("FUSION_COUNTERS responses=%d tensors=%d threshold=%d" %
              (responses, tensors,
               hvd.get_basics().effective_fusion_threshold()))
    print("rank %d done" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
