"""Fault-injection worker for the torch C-extension path: rank 1 dies
mid-job; a surviving rank's in-flight zero-copy allreduce must surface
HorovodInternalError through the cext wait (or be torn down by the
launcher) — never hang, never return silently-wrong data as success.
"""

import os
import sys
import time

import torch

import horovod_tpu.torch as hvd


def main():
    os.environ.setdefault("HVD_TPU_REQUIRE_CEXT", "1")
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    x = torch.ones(4)
    hvd.allreduce_(x, average=False, name="pre_crash")
    assert float(x[0]) == n, x
    from horovod_tpu.torch import _cext
    assert _cext.load() is not None
    if r == 1:
        print("rank 1 crashing now", flush=True)
        os._exit(17)
    try:
        y = torch.ones(4)
        hvd.allreduce_(y, average=False, name="post_crash")
    except hvd.HorovodInternalError as e:
        print("rank %d: cext collective failed after crash: %s" % (r, e),
              flush=True)
        return 1
    time.sleep(300)  # launcher teardown covers the no-error case
    return 0


if __name__ == "__main__":
    sys.exit(main())
