"""Fake ssh for launcher tests: skips ssh-style options, ignores the
hostname, and executes the remote command string locally under bash —
so the launcher's REAL remote branch (ssh argv construction, stdin
secret piping, env-export filtering, middleman wrapping) runs end to
end without an ssh daemon (reference analogue: the mock-the-shell test
strategy of test/test_run.py).

Used via HVD_TPU_SSH_CMD="<python> tests/fake_ssh.py".
"""

import subprocess
import sys


def main():
    args = sys.argv[1:]
    # Strip ssh-style options: "-o value", "-p value", bare flags.
    while args and args[0].startswith("-"):
        if args[0] in ("-o", "-p", "-i", "-l", "-F", "-E"):
            args = args[2:]
        else:
            args = args[1:]
    if len(args) < 2:
        sys.stderr.write("fake_ssh: expected <host> <command>\n")
        return 2
    command = " ".join(args[1:])  # args[0] is the ignored hostname
    return subprocess.call(["bash", "-c", command])


if __name__ == "__main__":
    sys.exit(main())
