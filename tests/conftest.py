"""Test config: provide 8 virtual CPU devices so multi-device sharding paths
compile and run without TPU hardware (the driver separately dry-runs the
multi-chip path). If a TPU plugin is present it may still register; tests
use `cpu_devices()` / the `cpu_mesh` fixture to target the CPU backend
explicitly."""

import os

# The suite is CPU-only by design. An accelerator PJRT plugin that
# dials a remote service during jax plugin REGISTRATION (the tunnel
# plugin in this environment does) hangs every `import jax` when that
# service is down — drop its pool pointer before anything imports jax
# so registration never engages. bench.py / __graft_entry__.py keep it.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

os.environ["JAX_PLATFORMS"] = os.environ.get("HVD_TPU_TEST_PLATFORM", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
# Persistent compile cache: in-process tests recompile the same jit
# programs every suite run otherwise (the launcher workers already get
# this via the run_launcher fixture env).
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/hvd_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import pathlib
import sys

import numpy as np
import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# Older jax (<= 0.4.x) lacks jax.shard_map / check_vma; install the
# forwarding shim once so every test file can use the current API.
from horovod_tpu.compat import ensure_jax_compat  # noqa: E402

ensure_jax_compat()


def cpu_devices():
    import jax
    return jax.devices("cpu")


def clean_worker_env(extra_env=None):
    """Worker-subprocess env: delegates to the framework's single
    source of truth (horovod_tpu.run.util.cpu_worker_env), adding the
    repo root to PYTHONPATH."""
    from horovod_tpu.run.util import cpu_worker_env
    return cpu_worker_env(extra_env=extra_env, repo_root=REPO_ROOT)


@pytest.fixture
def run_launcher():
    """Runs a worker script under the launcher (`-np N` on localhost) —
    the shared harness for the multi-process tests (SURVEY.md §4)."""
    import subprocess

    def _run(np_, script, extra_env=None, timeout=300):
        env = clean_worker_env(extra_env)
        script_path = os.path.join(REPO_ROOT, "tests", script)
        return subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run.run", "-np", str(np_),
             "--", sys.executable, script_path],
            env=env, timeout=timeout, capture_output=True, text=True)

    return _run


@pytest.fixture
def cpu_mesh_1d():
    """8-device mesh over axis 'hvd' on the CPU backend."""
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices("cpu")), ("hvd",))
