"""Test config: provide 8 virtual CPU devices so multi-device sharding paths
compile and run without TPU hardware (the driver separately dry-runs the
multi-chip path). If a TPU plugin is present it may still register; tests
use `cpu_devices()` / the `cpu_mesh` fixture to target the CPU backend
explicitly."""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("HVD_TPU_TEST_PLATFORM", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import pathlib
import sys

import numpy as np
import pytest

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def cpu_devices():
    import jax
    return jax.devices("cpu")


@pytest.fixture
def cpu_mesh_1d():
    """8-device mesh over axis 'hvd' on the CPU backend."""
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices("cpu")), ("hvd",))
