"""Autotune e2e worker: runs collectives with HVD_TPU_AUTOTUNE=1 so the
parameter manager cycles through warmup + Bayesian samples while the job
trains. Batched async enqueues keep tensors flowing every cycle without
paying a full (possibly autotuned-to-100ms) cycle wait per op. Asserts
every allreduce stays correct while knobs change underneath, then prints
the final synchronized parameters as one JSON line."""

import json
import sys

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    size = hvd.size()
    r = hvd.rank()
    base = np.arange(65536, dtype=np.float32)  # 256 KB
    for i in range(120):
        handles = []
        for j in range(8):
            x = base + float(r)
            handles.append(hvd.allreduce_async(
                x, "autotune.%d" % j))
        for j, h in enumerate(handles):
            out = hvd.synchronize(h)
            expected = base * size + sum(range(size))
            if not np.allclose(out, expected):
                print("MISMATCH iter %d tensor %d" % (i, j))
                return 1
    params = hvd.get_basics().autotune_params()
    print("AUTOTUNE_PARAMS %s" % json.dumps(params))
    print("rank %d done" % r)
    return 0


if __name__ == "__main__":
    sys.exit(main())
