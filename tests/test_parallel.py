"""Sequence-parallel attention and DP train-step tests on the 8-device
virtual CPU mesh (the TPU-less analogue of the reference's 2-process
localhost distributed tests, SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# Numerical-equivalence tests compare two computation orders; pin matmuls
# to exact f32 so only the math (not backend matmul quantization) differs.
jax.config.update("jax_default_matmul_precision", "highest")


def _mesh(n, name):
    return Mesh(np.array(jax.devices("cpu")[:n]), (name,))


def _dense_reference(q, k, v, causal=True):
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * D ** -0.5
    if causal:
        L = s.shape[-1]
        mask = np.tril(np.ones((L, L), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    from horovod_tpu.parallel import ring_attention
    n = 4
    B, L, H, D = 2, 32, 4, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    expected = _dense_reference(q, k, v, causal)

    mesh = _mesh(n, "sp")
    f = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_flash_path_values_and_grads(monkeypatch):
    """The TPU kernel ring path (forced via interpret mode on CPU):
    values AND gradients must match dense — pins the custom VJP that
    makes the Pallas path differentiable (a plain pallas_call is not)."""
    from horovod_tpu.parallel import ring_attention
    monkeypatch.setenv("HVD_TPU_PALLAS_INTERPRET", "1")
    n = 2
    B, L, H, D = 1, 256, 2, 16  # 128-per-shard, kernel path eligible
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    expected = _dense_reference(q, k, v, causal=True)

    mesh = _mesh(n, "sp")

    def loss(q, k, v):
        out = ring_attention(q, k, v, "sp", causal=True)
        return out, jnp.sum(out.astype(jnp.float32) ** 2)

    f = jax.jit(jax.shard_map(
        lambda q, k, v: (loss(q, k, v)[0],) + tuple(
            jax.grad(lambda q, k, v: loss(q, k, v)[1],
                     argnums=(0, 1, 2))(q, k, v)),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=(P(None, "sp"),) * 4, check_vma=False))
    out, gq, gk, gv = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)

    def dense_loss(q, k, v):
        return jnp.sum(_dense_reference(q, k, v, True) ** 2)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, exp in ((gq, dq), (gk, dk), (gv, dv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=2e-4, atol=2e-4)


def test_zigzag_ring_matches_dense(monkeypatch):
    """schedule='zigzag' (causal load-balanced layout): values AND all
    three gradients must equal dense attention on the natural-order
    sequence, round-tripped through zigzag_shard/zigzag_unshard.
    Lq=1024/rank -> two 512-token chunks; with bq=256/bk=512 the q
    chunks span TWO blocks each (the per-block offset arrays carry
    real discontiguities) while each kv chunk is one block."""
    from horovod_tpu.parallel import (ring_attention, zigzag_shard,
                                      zigzag_unshard)
    monkeypatch.setenv("HVD_TPU_PALLAS_INTERPRET", "1")
    n = 4
    B, L, H, D = 1, 4096, 2, 16  # 1024/rank = 2 x 512-token chunks
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    expected = _dense_reference(q, k, v, causal=True)

    qz, kz, vz, wz = (zigzag_shard(x, n) for x in (q, k, v, w))
    mesh = _mesh(n, "sp")

    def fwd_and_grads(q, k, v, w):
        def loss(q, k, v):
            out = ring_attention(q, k, v, "sp", causal=True,
                                 schedule="zigzag")
            return jnp.sum(out.astype(jnp.float32) * w), out
        (_, out), grads = jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
        return (out,) + grads

    f = jax.jit(jax.shard_map(
        fwd_and_grads, mesh=mesh, in_specs=(P(None, "sp"),) * 4,
        out_specs=(P(None, "sp"),) * 4, check_vma=False))
    out, gq, gk, gv = f(qz, kz, vz, wz)

    np.testing.assert_allclose(
        np.asarray(zigzag_unshard(out, n)), np.asarray(expected),
        rtol=2e-5, atol=2e-5)

    def dense_loss(q, k, v):
        return jnp.sum(_dense_reference(q, k, v, True) * w)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, exp, nm in ((gq, dq, "dq"), (gk, dk, "dk"), (gv, dv, "dv")):
        np.testing.assert_allclose(
            np.asarray(zigzag_unshard(got, n)), np.asarray(exp),
            rtol=2e-4, atol=2e-4, err_msg=nm)


def test_zigzag_shard_roundtrip_and_validation():
    """zigzag_shard/unshard invert each other; ring_attention rejects
    zigzag with non-causal or unaligned shards."""
    from horovod_tpu.parallel import (ring_attention, zigzag_shard,
                                      zigzag_unshard)
    x = jnp.arange(2 * 1024 * 3, dtype=jnp.float32).reshape(2, 1024, 3)
    for n in (2, 4):
        np.testing.assert_array_equal(
            np.asarray(zigzag_unshard(zigzag_shard(x, n), n)),
            np.asarray(x))
    q = jnp.zeros((1, 256, 2, 16), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, "sp", causal=False, schedule="zigzag")
    with pytest.raises(ValueError, match="256"):
        ring_attention(q[:, :128], q[:, :128], q[:, :128], "sp",
                       causal=True, schedule="zigzag")
    with pytest.raises(ValueError, match="unknown ring schedule"):
        ring_attention(q, q, q, "sp", schedule="stripey")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_backward_multiblock(monkeypatch, causal):
    """Multi-block shards (1024/shard -> num_qb=4, num_kb=2): the
    backward ring kernels' cross-block accumulate (kj>0 / qi>0
    load-accumulate-store) and the non-causal visible branch must
    produce dense-matching gradients, not just the single-block case."""
    from horovod_tpu.parallel import ring_attention
    monkeypatch.setenv("HVD_TPU_PALLAS_INTERPRET", "1")
    n = 2
    B, L, H, D = 1, 2048, 1, 16
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)

    mesh = _mesh(n, "sp")

    def loss(q, k, v):
        out = ring_attention(q, k, v, "sp", causal=causal)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    f = jax.jit(jax.shard_map(
        lambda q, k, v: jax.grad(loss, argnums=(0, 1, 2))(q, k, v),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=(P(None, "sp"),) * 3, check_vma=False))
    try:
        gq, gk, gv = f(q, k, v)
    except Exception as e:  # jaxlib.xla_extension.XlaRuntimeError
        if "PartitionId instruction is not supported" in str(e):
            # Old XLA: the SPMD partitioner rejects partition-id in this
            # lowering; the causal variant (and real TPU lowering) work.
            pytest.skip("old jaxlib cannot SPMD-partition this lowering")
        raise

    def dense_loss(q, k, v):
        return jnp.sum(_dense_reference(q, k, v, causal) ** 2)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, exp in ((gq, dq), (gk, dk), (gv, dv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_attention_matches_dense():
    from horovod_tpu.parallel import ulysses_attention
    n = 4
    B, L, H, D = 2, 32, 8, 16  # H divisible by n
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    expected = _dense_reference(q, k, v, causal=True)

    mesh = _mesh(n, "sp")
    f = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_make_train_step_decreases_loss():
    import optax
    from horovod_tpu.models import MnistCNN
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step
    from horovod_tpu.parallel.train import cross_entropy_loss

    model = MnistCNN(dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (16, 28, 28, 1))
    y = jax.random.randint(rng, (16,), 0, 10)
    variables = model.init(rng, x[:1], train=False)
    params = variables["params"]

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["x"], train=True)
        return cross_entropy_loss(logits, batch["y"])

    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    opt = optax.sgd(0.05)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    params_p, opt_state = step.place(params, opt.init(params))
    batch = {"x": x, "y": y}

    losses = []
    for _ in range(5):
        params_p, opt_state, loss = step(params_p, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_train_step_gradients_are_averaged():
    """Each shard sees different data; the resulting params must be
    identical to a single-device run on the full batch (the defining
    property of synchronous data parallelism)."""
    import optax
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step

    w0 = jnp.ones((4,))
    x = jnp.arange(32.0).reshape(8, 4) / 32.0
    y = jnp.ones((8,))

    def loss_fn(params, batch):
        pred = batch["x"] @ params
        return jnp.mean((pred - batch["y"]) ** 2)

    opt = optax.sgd(0.1)
    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    params_p, opt_state = step.place(w0, opt.init(w0))
    params_p, _, _ = step(params_p, opt_state, {"x": x, "y": y})

    g = jax.grad(loss_fn)(w0, {"x": x, "y": y})
    expected = w0 - 0.1 * g
    np.testing.assert_allclose(np.asarray(params_p), np.asarray(expected),
                               rtol=1e-6)


def test_hybrid_mesh_shapes():
    from horovod_tpu.parallel import hybrid_mesh, mesh_axis_size
    mesh = hybrid_mesh((-1, 4), ("dp", "sp"), devices=jax.devices("cpu"))
    assert mesh_axis_size(mesh, "dp") == 2
    assert mesh_axis_size(mesh, "sp") == 4


def test_train_step_gradient_accumulation():
    """accum_steps=k (the flagship analogue of torch's
    backward_passes_per_step): k scanned microbatches with one deferred
    allreduce+update must equal the single-pass step on the same global
    batch (exact for mean-reduction losses)."""
    import optax

    from horovod_tpu.parallel import data_parallel_mesh, make_train_step

    rng = np.random.RandomState(4)
    params = {"w": jnp.asarray(rng.randn(6, 3).astype(np.float32))}
    batch = {
        "x": jnp.asarray(rng.randn(32, 6).astype(np.float32)),
        "y": jnp.asarray(rng.randn(32, 3).astype(np.float32)),
    }

    def loss_fn(params, b):
        return jnp.mean((b["x"] @ params["w"] - b["y"]) ** 2)

    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    opt = optax.adam(1e-2)

    one = make_train_step(loss_fn, opt, mesh, donate=False)
    p1, s1, b1 = one.place(params, opt.init(params), batch)
    acc = make_train_step(loss_fn, opt, mesh, donate=False,
                          accum_steps=4)
    p2, s2, b2 = acc.place(params, opt.init(params), batch)

    for _ in range(2):
        p1, s1, loss1 = one(p1, s1, b1)
        p2, s2, loss2 = acc(p2, s2, b2)
    np.testing.assert_allclose(float(loss2), float(loss1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(p1["w"]),
                               rtol=1e-5, atol=1e-6)


def test_train_step_accum_composes_with_zero1():
    """accum_steps and zero1 together: still equal to the plain step."""
    import optax

    from horovod_tpu.parallel import data_parallel_mesh, make_train_step

    rng = np.random.RandomState(5)
    params = {"w": jnp.asarray(rng.randn(6, 3).astype(np.float32))}
    batch = {
        "x": jnp.asarray(rng.randn(32, 6).astype(np.float32)),
        "y": jnp.asarray(rng.randn(32, 3).astype(np.float32)),
    }

    def loss_fn(params, b):
        return jnp.mean((b["x"] @ params["w"] - b["y"]) ** 2)

    mesh = data_parallel_mesh(devices=jax.devices("cpu"))
    opt = optax.adam(1e-2)
    one = make_train_step(loss_fn, opt, mesh, donate=False)
    p1, s1, b1 = one.place(params, opt.init(params), batch)
    z = make_train_step(loss_fn, opt, mesh, donate=False, zero1=True,
                        accum_steps=2)
    p2, s2, b2 = z.place(params, None, batch)
    for _ in range(2):
        p1, s1, loss1 = one(p1, s1, b1)
        p2, s2, loss2 = z(p2, s2, b2)
    np.testing.assert_allclose(float(loss2), float(loss1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(p1["w"]),
                               rtol=1e-5, atol=1e-6)


def _dense_gqa_reference(q, k, v, causal=True, rotary_base=None):
    """Dense reference for q [B,L,H,D], k/v [B,L,G,D]: rotate outside
    (the production model path), repeat kv across head groups."""
    H, G = q.shape[2], k.shape[2]
    if rotary_base is not None:
        from horovod_tpu.models.transformer import _rotary
        B, L = q.shape[:2]
        pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                               (B, L))
        q = _rotary(q, pos, rotary_base)
        k = _rotary(k, pos, rotary_base)
    if H != G:
        k = jnp.repeat(k, H // G, axis=2)
        v = jnp.repeat(v, H // G, axis=2)
    return _dense_reference(q, k, v, causal)


def test_ring_gqa_rotary_jnp_path_matches_dense():
    """The jnp ring fallback with grouped kv heads and rotary: the
    small G-head shards travel the ring and are repeated per step."""
    from horovod_tpu.parallel import ring_attention
    n = 4
    B, L, H, G, D = 2, 32, 4, 2, 16
    rng = np.random.RandomState(21)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    expected = _dense_gqa_reference(q, k, v, True, 10000.0)

    mesh = _mesh(n, "sp")
    f = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True,
                                       rotary_base=10000.0),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_gqa_rotary_values_and_grads(monkeypatch):
    """Kernel ring path (interpret mode) with grouped kv heads + fused
    rotary: values AND gradients vs dense. Pins the grouped-rows ring
    layout, in-kernel rotation from SMEM offsets, and the post-loop
    counter-rotation of dq (shard q positions) and dk (home kv
    positions after the full ring trip)."""
    from horovod_tpu.parallel import ring_attention
    monkeypatch.setenv("HVD_TPU_PALLAS_INTERPRET", "1")
    n = 2
    B, L, H, G, D = 1, 256, 4, 2, 16
    rng = np.random.RandomState(23)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    expected = _dense_gqa_reference(q, k, v, True, 10000.0)

    mesh = _mesh(n, "sp")

    def fwd_and_grads(q, k, v, w):
        def loss(q, k, v):
            out = ring_attention(q, k, v, "sp", causal=True,
                                 rotary_base=10000.0)
            return jnp.sum(out.astype(jnp.float32) * w), out
        (_, out), grads = jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
        return (out,) + grads

    f = jax.jit(jax.shard_map(
        fwd_and_grads, mesh=mesh, in_specs=(P(None, "sp"),) * 4,
        out_specs=(P(None, "sp"),) * 4, check_vma=False))
    out, gq, gk, gv = f(q, k, v, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)

    def dense_loss(q, k, v):
        return jnp.sum(_dense_gqa_reference(q, k, v, True, 10000.0) * w)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, exp, nm in ((gq, dq, "dq"), (gk, dk, "dk"), (gv, dv, "dv")):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=2e-4, atol=2e-4, err_msg=nm)


def test_zigzag_mqa_rotary_matches_dense(monkeypatch):
    """zigzag schedule + MQA (G=1) + fused rotary: the in-kernel
    rotation must use the discontiguous per-chunk global positions and
    the post-loop counter-rotation the chunked shard_positions."""
    from horovod_tpu.parallel import (ring_attention, zigzag_shard,
                                      zigzag_unshard)
    monkeypatch.setenv("HVD_TPU_PALLAS_INTERPRET", "1")
    n = 4
    B, L, H, G, D = 1, 4096, 2, 1, 16
    rng = np.random.RandomState(29)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    expected = _dense_gqa_reference(q, k, v, True, 10000.0)

    qz, kz, vz, wz = (zigzag_shard(x, n) for x in (q, k, v, w))
    mesh = _mesh(n, "sp")

    def fwd_and_grads(q, k, v, w):
        def loss(q, k, v):
            out = ring_attention(q, k, v, "sp", causal=True,
                                 schedule="zigzag", rotary_base=10000.0)
            return jnp.sum(out.astype(jnp.float32) * w), out
        (_, out), grads = jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
        return (out,) + grads

    f = jax.jit(jax.shard_map(
        fwd_and_grads, mesh=mesh, in_specs=(P(None, "sp"),) * 4,
        out_specs=(P(None, "sp"),) * 4, check_vma=False))
    out, gq, gk, gv = f(qz, kz, vz, wz)
    np.testing.assert_allclose(
        np.asarray(zigzag_unshard(out, n)), np.asarray(expected),
        rtol=2e-5, atol=2e-5)

    def dense_loss(q, k, v):
        return jnp.sum(_dense_gqa_reference(q, k, v, True, 10000.0) * w)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, exp, nm in ((gq, dq, "dq"), (gk, dk, "dk"), (gv, dv, "dv")):
        np.testing.assert_allclose(
            np.asarray(zigzag_unshard(got, n)), np.asarray(exp),
            rtol=2e-4, atol=2e-4, err_msg=nm)


def test_ulysses_gqa_matches_dense():
    """Ulysses with grouped kv heads: q splits H over the axis, k/v
    split G; contiguous split keeps the query->kv head grouping."""
    from horovod_tpu.parallel import ulysses_attention
    n = 4
    B, L, H, G, D = 2, 32, 8, 4, 16
    rng = np.random.RandomState(31)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, L, G, D), jnp.float32)
    expected = _dense_gqa_reference(q, k, v, True)

    mesh = _mesh(n, "sp")
    f = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"),
        check_vma=False))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)
