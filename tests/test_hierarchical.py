"""Hierarchical (two-level) collective tests: 4 ranks on localhost with a
forced 2-host x 2-slot topology (HVD_TPU_LOCAL_SIZE=2, CROSS_SIZE=2), so the
local/cross rings and the composite ops run without real multi-host hardware.
Mirrors the reference's NCCL hierarchical composite
(`horovod/common/ops/nccl_operations.cc:150-346`) and shared-memory
hierarchical allgather (`ops/mpi_operations.cc:168-321`) test obligations."""

import pytest

import os
import socket
import subprocess
import sys

pytestmark = pytest.mark.e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def run_hierarchical_workers(script, extra_env=None, timeout=300):
    """Launches 4 copies of `script` with a crafted 2x2 topology: rank r is
    slot r%2 on "host" r//2."""
    ports = _free_ports(4)
    addrs = ",".join("127.0.0.1:%d" % p for p in ports)
    procs = []
    for r in range(4):
        from horovod_tpu.run.util import cpu_worker_env
        env = cpu_worker_env(repo_root=REPO)
        env.update({
            "HVD_TPU_RANK": str(r),
            "HVD_TPU_SIZE": "4",
            "HVD_TPU_LOCAL_RANK": str(r % 2),
            "HVD_TPU_LOCAL_SIZE": "2",
            "HVD_TPU_CROSS_RANK": str(r // 2),
            "HVD_TPU_CROSS_SIZE": "2",
            "HVD_TPU_ADDRS": addrs,
            "HVD_TPU_HIERARCHICAL_ALLREDUCE": "1",
            "HVD_TPU_HIERARCHICAL_ALLGATHER": "1",
            "HVD_TPU_SKIP_JIT_TEST": "1",
        })
        if extra_env:
            env.update(extra_env)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    return procs, outs


def test_hierarchical_ops_correct(tmp_path):
    timeline = str(tmp_path / "hier_timeline.json")
    procs, outs = run_hierarchical_workers(
        "distributed_ops_worker.py", {"HVD_TPU_TIMELINE": timeline})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
        assert "all distributed op tests passed" in out, out
    # Prove the hierarchical path actually executed (rank 0's timeline
    # records per-op activities).
    with open(timeline) as f:
        text = f.read()
    assert "ALLREDUCE_HIERARCHICAL" in text, text[:2000]
    assert "ALLGATHER_HIERARCHICAL" in text, text[:2000]


def test_hierarchical_disabled_uses_flat_ring(tmp_path):
    timeline = str(tmp_path / "flat_timeline.json")
    procs, outs = run_hierarchical_workers(
        "distributed_ops_worker.py",
        {"HVD_TPU_TIMELINE": timeline,
         "HVD_TPU_HIERARCHICAL_ALLREDUCE": "0",
         "HVD_TPU_HIERARCHICAL_ALLGATHER": "0"})
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (r, out)
    with open(timeline) as f:
        text = f.read()
    assert "ALLREDUCE_HIERARCHICAL" not in text
    assert "ALLREDUCE_RING" in text
