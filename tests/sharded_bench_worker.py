"""Sharded-update A/B bench worker (bench.py --sharded-update): runs
HVD_TPU_BENCH_ITERS Adam steps over an HVD_TPU_BENCH_MB-MB flat f32
parameter buffer in one of two execution modes and reports one
`SHARDED_BENCH {...}` JSON line per rank:

  HVD_TPU_BENCH_SHARDED=0  replicated: allreduce the full gradient,
                           apply Adam to 100% of the parameters with
                           full-size moments on every rank
  HVD_TPU_BENCH_SHARDED=1  sharded (docs/ZERO.md): reduce-scatter the
                           gradient, Adam on this rank's 1/N shard
                           (1/N-size moments), allgather updated params

Reported: wall us/step, socket-layer data-ring bytes (the wire-parity
claim: reduce-scatter + allgather moves the same bytes the allreduce
did), optimizer-state bytes (the native opt_state_bytes gauge in
sharded mode — the N-fold memory claim), and executed reduce-scatter
count. With SHARDED_BENCH_CONV=1 rank 0's row also carries a 2-mode
convergence A/B through the real jax DistributedOptimizer wrappers
(max relative loss divergence, acceptance <= 1e-4). Both modes walk
the same deterministic trajectory; each row carries a params checksum
the bench driver cross-checks between modes, so a collective
regression fails the bench rather than biasing it."""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import json  # noqa: E402
import sys  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.common import ops  # noqa: E402
from horovod_tpu.common.ops import shard_partition  # noqa: E402

B1, B2, EPS, LR = 0.9, 0.999, 1e-8, 1e-3


def _adam(p, g, mu, nu, t):
    """Elementwise numpy Adam — identical math whether p/g/mu/nu are
    the full buffer (replicated) or one shard (sharded)."""
    mu = B1 * mu + (1.0 - B1) * g
    nu = B2 * nu + (1.0 - B2) * g * g
    mu_hat = mu / (1.0 - B1 ** t)
    nu_hat = nu / (1.0 - B2 ** t)
    return p - LR * mu_hat / (np.sqrt(nu_hat) + EPS), mu, nu


def _convergence(steps=40):
    """Replicated vs sharded DistributedOptimizer on the same tiny MLP
    regression (host plane, real collectives): returns the loss-curve
    stats; run on every rank (collective), reported by rank 0."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import jax as hvd_jax

    r, n = hvd.rank(), hvd.size()
    rng = np.random.RandomState(0)
    d_in, d_h, per = 24, 48, 16
    x = rng.randn(per * n, d_in).astype(np.float32)
    w_true = rng.randn(d_in, 1).astype(np.float32)
    y = np.tanh(x @ w_true).astype(np.float32)
    bx = jnp.asarray(x[r * per:(r + 1) * per])
    by = jnp.asarray(y[r * per:(r + 1) * per])

    def loss_fn(p):
        h = jnp.tanh(bx @ p["w1"])
        return jnp.mean((h @ p["w2"] - by) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    def init_params():
        pr = np.random.RandomState(1)
        return {"w1": jnp.asarray(pr.randn(d_in, d_h).astype(np.float32)
                                  * 0.1),
                "w2": jnp.asarray(pr.randn(d_h, 1).astype(np.float32)
                                  * 0.1)}

    curves = {}
    for mode in ("replicated", "sharded"):
        opt = hvd_jax.DistributedOptimizer(  # hvd-lint: disable=missing-initial-broadcast
            optax.adam(5e-2), sharded_update=(mode == "sharded"),
            name_prefix="conv_%s" % mode)
        p = init_params()
        s = opt.init(p)
        losses = []
        for _ in range(steps):
            _, g = grad_fn(p)
            if mode == "sharded":
                u, s = opt.update(g, s, p)
            else:
                u, s = opt.update(g, s)  # hvd-lint: disable=verify-mixed-modes
            p = optax.apply_updates(p, u)
            # Global loss over the FULL batch (identical on every rank).
            h = np.tanh(x @ np.asarray(p["w1"]))
            losses.append(float(np.mean((h @ np.asarray(p["w2"]) - y)
                                        ** 2)))
        curves[mode] = losses

    ref = np.asarray(curves["replicated"])
    got = np.asarray(curves["sharded"])
    rel = np.abs(got - ref) / (np.abs(ref) + 1e-12)
    return {
        "steps": steps, "ranks": n,
        "replicated_final_loss": round(float(ref[-1]), 8),
        "sharded_final_loss": round(float(got[-1]), 8),
        "max_rel_loss_divergence": float(rel.max()),
        "tolerance": 1e-4,
        "loss_match": bool(rel.max() <= 1e-4),
    }


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    iters = int(os.environ.get("HVD_TPU_BENCH_ITERS", "10"))
    mb = float(os.environ.get("HVD_TPU_BENCH_MB", "4"))
    sharded = os.environ.get("HVD_TPU_BENCH_SHARDED", "0") == "1"
    elems = int(mb * 1024 * 1024 / 4)
    counts, offsets = shard_partition(elems, n)
    lo, hi = offsets[r], offsets[r] + counts[r]

    params = ((np.arange(elems, dtype=np.float32) % 1003) / 501.0) - 1.0
    if sharded:
        mu = np.zeros(counts[r], np.float32)
        nu = np.zeros(counts[r], np.float32)
        hvd.get_basics().opt_state_metrics(mu.nbytes + nu.nbytes)
    else:
        mu = np.zeros(elems, np.float32)
        nu = np.zeros(elems, np.float32)
        hvd.get_basics().opt_state_metrics(mu.nbytes + nu.nbytes)

    def step(i, t):
        nonlocal params, mu, nu
        # Deterministic rank-varying gradient whose mean every rank can
        # verify: base + mean(rank offsets).
        g_local = 0.01 * params + 0.001 * r
        if sharded:
            g = ops.reduce_scatter(g_local, "sb.grad", average=True)  # hvd-lint: disable=verify-kind-mismatch
            p_new, mu, nu = _adam(params[lo:hi], g, mu, nu, t)
            params = np.asarray(ops.allgather(
                np.ascontiguousarray(p_new), "sb.param_ag"))
        else:
            g = ops.allreduce(g_local, "sb.grad", average=True)  # hvd-lint: disable=name-attr-mismatch
            params, mu, nu = _adam(params, g, mu, nu, t)
        assert params.size == elems

    step(-1, 1)  # warmup: connections, negotiation, cache entries
    c0 = hvd.metrics()["counters"]
    t0 = time.perf_counter()
    for i in range(iters):
        step(i, i + 2)
    dt = time.perf_counter() - t0
    c1 = hvd.metrics()["counters"]
    snap = hvd.metrics()

    row = {
        "rank": r, "size": n, "sharded": sharded, "iters": iters,
        "payload_mb": mb,
        "us_per_step": round(dt / iters * 1e6, 1),
        "ring_bytes_sent": c1["net_ring_bytes_sent_total"] -
                           c0["net_ring_bytes_sent_total"],
        "ring_bytes_recv": c1["net_ring_bytes_recv_total"] -
                           c0["net_ring_bytes_recv_total"],
        "reduce_scatter_ops": c1["reduce_scatter_total"] -
                              c0["reduce_scatter_total"],
        "opt_state_bytes": int(snap["gauges"]["opt_state_bytes"]),
        "shard_elems": counts[r], "total_elems": elems,
        # Cross-mode trajectory check (the bench compares replicated vs
        # sharded): both modes must land on ~the same parameters.
        "params_sum": float(np.sum(params, dtype=np.float64)),
    }
    if r == 0 and os.environ.get("SHARDED_BENCH_CONV", "0") == "1":
        row["convergence"] = _convergence()
    elif os.environ.get("SHARDED_BENCH_CONV", "0") == "1":
        _convergence()  # collective: every rank must participate
    print("SHARDED_BENCH %s" % json.dumps(row), flush=True)
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
