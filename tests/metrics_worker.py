"""Metrics-plane e2e worker (tests/test_metrics.py): a 2-process job
with a deliberate straggler that proves the whole plane live —

* every worker serves Prometheus text at HVD_TPU_METRICS_PORT + rank;
* rank 0 serves the aggregated job view at /job (per-rank summaries
  ingested from the RequestList piggyback + the announce-lag table);
* the scraped values agree with hvd.metrics() (parity on counters that
  are frozen once the workload quiesces);
* the straggling rank is identifiable WHILE THE JOB RUNS from the
  job view's rank_lag_seconds (and from `hvd-top --once`).

Rank 0 scrapes rank 1's endpoint while rank 1 is blocked inside the
final barrier collective — serving from inside a blocked worker is the
point of the plane (ctypes releases the GIL around native waits).
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np

import horovod_tpu as hvd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scrape(port, path="/metrics", timeout=15):
    url = "http://127.0.0.1:%d%s" % (port, path)
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def prom_value(text, family):
    """First sample value of `family` in Prometheus text (any labels)."""
    for line in text.splitlines():
        if line.startswith(family) and line[len(family)] in (" ", "{"):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError("no %s sample in:\n%s" % (family, text[:2000]))


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2, n
    base = int(os.environ["HVD_TPU_METRICS_PORT"])
    straggle = float(os.environ.get("HVD_TPU_TEST_STRAGGLE", "2.0"))

    steps = 20
    for i in range(steps):
        if r == 1 and i == 10:
            time.sleep(straggle)  # the deliberate straggler
        hvd.allreduce(np.ones(1024, np.float32), "metrics.grad")

    # Let at least one summary-sync interval pass so rank 0's job view
    # holds a post-workload rank-1 summary.
    time.sleep(1.0)

    if r == 0:
        own_m = hvd.metrics()
        # -- parity: scraped /json == hvd.metrics() on quiesced counters
        own_scraped = json.loads(scrape(base, "/json"))
        for field in ("tensors_enqueued_total", "tensors_performed_total",
                      "bytes_performed_total"):
            assert own_scraped["counters"][field] == \
                own_m["counters"][field], (field, own_scraped, own_m)
        # Each rank enqueued exactly `steps` collectives so far.
        assert own_m["counters"]["tensors_enqueued_total"] == steps, own_m

        # -- Prometheus text on BOTH workers' endpoints. Rank 1 is
        # already blocked in the exit barrier below (its enqueue count
        # includes that 21st op) — which is the point: its endpoint
        # answers from inside a blocked worker.
        own_prom = scrape(base)
        peer_prom = scrape(base + 1)
        assert prom_value(own_prom, "hvdtpu_tensors_enqueued_total") == steps
        assert prom_value(peer_prom, "hvdtpu_tensors_enqueued_total") in \
            (steps, steps + 1)
        assert prom_value(own_prom, "hvdtpu_rank") == 0
        assert prom_value(peer_prom, "hvdtpu_rank") == 1
        assert 'le="+Inf"' in own_prom
        # rank 0's scrape target carries the whole job (worker series).
        assert 'hvdtpu_worker_cycles_total{rank="1"}' in own_prom

        # -- histogram sanity (native bucketing): counts sum to count
        for name, h in own_m["histograms"].items():
            assert len(h["counts"]) == len(h["bounds"]) + 1, name
            assert sum(h["counts"]) == h["count"], (name, h)
        assert own_m["histograms"]["cycle_seconds"]["count"] > 0
        assert own_m["histograms"]["negotiation_seconds"]["count"] >= steps

        # -- job view: both ranks present, aggregate, straggler named
        job = json.loads(scrape(base, "/job"))
        assert set(job["per_rank"]) == {"0", "1"}, job
        assert job["per_rank"]["1"]["tensors_enqueued_total"] in \
            (steps, steps + 1), job
        agg = job["aggregate"]["tensors_enqueued_total"]
        assert agg["min"] == steps and agg["max"] <= steps + 1, agg
        lag = job["rank_lag_seconds"]
        assert lag[1] > max(straggle * 0.5, lag[0] + straggle * 0.25), \
            ("straggler not identified", lag)

        # -- hvd-top --once against the coordinator endpoint
        top = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "hvd-top"),
             "127.0.0.1:%d" % base, "--once"],
            capture_output=True, text=True, timeout=60)
        assert top.returncode == 0, top.stdout + top.stderr
        assert "straggler: rank 1" in top.stdout, top.stdout
        assert "size 2" in top.stdout, top.stdout

        print("METRICS_E2E_OK lag=%s" % json.dumps(lag), flush=True)

    # Exit barrier: holds rank 1 (blocked HERE, serving scrapes) alive
    # until rank 0 finishes scraping it above.
    hvd.allreduce(np.ones(1, np.float32), "metrics.done")
    print("rank %d done" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
