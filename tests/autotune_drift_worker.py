"""Workload-drift re-arm worker: train until the tuner converges on a
small-tensor workload, then SHIFT the workload (8x payload) and keep
training — the converged tuner's drift watch must notice the per-cycle
bytes distribution moving past HVD_TPU_AUTOTUNE_DRIFT and re-arm,
bootstrapping every rank back into tuning through the ResponseList wire.

Rank 0 decides each phase transition and broadcasts the verdict so all
ranks change workload (and exit) at the same collective count."""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    r = hvd.rank()
    small = [np.full(4096, float(i % 3), np.float32) for i in range(4)]
    big = [np.full(32768, float(i % 3), np.float32) for i in range(8)]

    def step(grads, tag, i):
        hs = [hvd.allreduce_async(g, "drift.%s.%d" % (tag, j))
              for j, g in enumerate(grads)]
        for h in hs:
            hvd.synchronize(h)

    # Phase 1: converge on the small workload.
    deadline = time.time() + 240
    steps = 0
    while True:
        step(small, "s", steps)
        steps += 1
        verdict = 1.0
        if r == 0:
            if not hvd.autotune()["active"]:
                verdict = 0.0
            elif time.time() > deadline:
                verdict = -1.0
        verdict = float(hvd.broadcast(np.array([verdict]), 0,
                                      "drift.p1.%d" % steps)[0])
        if verdict == 0.0:
            break
        if verdict < 0.0:
            print("DRIFT_TIMEOUT phase1 after %d steps" % steps, flush=True)
            return 1
    pre = hvd.autotune()
    print("DRIFT_CONVERGED %s" % json.dumps(
        {"steps": steps, "epoch": pre["rearm_epoch"],
         "rearms": pre["rearms_total"]}), flush=True)

    # Settle: the FIRST post-convergence window only CAPTURES the drift
    # baseline under the adopted knobs (parameter_manager.cc) — keep the
    # small workload flowing long enough for that window to fill, so the
    # shift below lands in a window that is actually CHECKED.
    window = int(os.environ.get("HVD_TPU_AUTOTUNE_DRIFT_WINDOW", "40"))
    for i in range(3 * window):
        step(small, "settle", i)

    # Phase 2: shift the workload; the drift watch must re-arm.
    steps2 = 0
    while True:
        step(big, "b", steps2)
        steps2 += 1
        verdict = 1.0
        if r == 0:
            at = hvd.autotune()
            if at["rearms_total"] > pre["rearms_total"]:
                verdict = 0.0
            elif time.time() > deadline:
                verdict = -1.0
        verdict = float(hvd.broadcast(np.array([verdict]), 0,
                                      "drift.p2.%d" % steps2)[0])
        if verdict == 0.0:
            break
        if verdict < 0.0:
            print("DRIFT_TIMEOUT phase2 after %d steps" % steps2, flush=True)
            return 1
    post = hvd.autotune()
    print("DRIFT_REARMED %s" % json.dumps(
        {"steps": steps2, "epoch": post["rearm_epoch"],
         "rearms": post["rearms_total"], "active": post["active"],
         "reason": post["last_rearm_reason"]}), flush=True)
    print("rank %d drift done" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
