"""Fault-injection worker: rank 1 dies mid-job; surviving ranks must be
torn down by the launcher's failure fan-out (no hang) and the job exits
nonzero (reference behavior: horovod's launcher kills the remaining
ranks when any rank fails)."""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    # One successful collective proves the job was healthy first.
    out = hvd.allreduce(np.ones(4, np.float32), "pre_crash")
    assert np.allclose(out, n), out
    if r == 1:
        print("rank 1 crashing now", flush=True)
        os._exit(17)
    # Survivors enqueue another collective that can never complete and
    # wait for the launcher to kill them; exiting on our own would make
    # the test pass vacuously.
    try:
        hvd.allreduce(np.ones(4, np.float32), "post_crash")
    except Exception as e:  # stall shutdown also acceptable
        print("rank %d: collective failed after crash: %s" % (r, e),
              flush=True)
        return 1
    time.sleep(300)
    return 0


if __name__ == "__main__":
    sys.exit(main())
