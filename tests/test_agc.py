"""Adaptive gradient clipping (ops/agc.py) — the norm-free route's
trainability knob: unit-norm rules, the optax transformation, and the
DistributedOptimizer wiring on the jax and torch planes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.agc import adaptive_grad_clip, agc_clip, unitwise_norm


def _ref_clip(g, w, clipping=0.01, eps=1e-3):
    if g.ndim <= 1:
        gn = np.sqrt((g ** 2).sum())
        pn = np.sqrt((w ** 2).sum())
        mx = clipping * max(pn, eps)
        return g * (mx / max(gn, 1e-16)) if gn > mx else g
    axes = tuple(range(g.ndim - 1))
    gn = np.sqrt((g ** 2).sum(axis=axes, keepdims=True))
    pn = np.sqrt((w ** 2).sum(axis=axes, keepdims=True))
    mx = clipping * np.maximum(pn, eps)
    return np.where(gn > mx, g * (mx / np.maximum(gn, 1e-16)), g)


@pytest.mark.parametrize("shape", [(16,), (8, 16), (3, 3, 8, 16), ()])
def test_agc_clip_matches_reference(shape):
    rng = np.random.RandomState(0)
    w = np.asarray(rng.randn(*shape), np.float32) * 0.1
    g = np.asarray(rng.randn(*shape), np.float32) * 10.0
    out = np.asarray(agc_clip({"p": jnp.asarray(g)},
                              {"p": jnp.asarray(w)}, clipping=0.01)["p"])
    np.testing.assert_allclose(out, _ref_clip(g, w), rtol=1e-5, atol=1e-7)


def test_agc_clipped_unit_norms_bounded():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(3, 3, 4, 8).astype(np.float32) * 0.1)
    g = jnp.asarray(rng.randn(3, 3, 4, 8).astype(np.float32) * 100.0)
    c = agc_clip({"w": g}, {"w": w}, clipping=0.01)["w"]
    cn = np.asarray(unitwise_norm(c)).ravel()
    mx = 0.01 * np.maximum(np.asarray(unitwise_norm(w)).ravel(), 1e-3)
    assert (cn <= mx * (1 + 1e-5)).all()


def test_agc_leaves_small_gradients_untouched():
    w = jnp.ones((4, 8))
    g = jnp.full((4, 8), 1e-6)
    out = agc_clip({"w": g}, {"w": w}, clipping=0.01)["w"]
    np.testing.assert_array_equal(np.asarray(out), np.asarray(g))


def test_adaptive_grad_clip_optax_transformation():
    import optax

    tx = optax.chain(adaptive_grad_clip(0.01), optax.sgd(1.0))
    params = {"w": jnp.ones((4, 8)) * 0.5}
    state = tx.init(params)
    big = {"w": jnp.full((4, 8), 50.0)}
    updates, _ = tx.update(big, state, params)
    col_norms = np.sqrt((np.asarray(updates["w"]) ** 2).sum(0))
    expect = 0.01 * np.sqrt((np.asarray(params["w"]) ** 2).sum(0))
    np.testing.assert_allclose(col_norms, expect, rtol=1e-5)
    with pytest.raises(ValueError):
        tx.update(big, state)  # params required


def test_distributed_optimizer_agc_wiring():
    import optax

    import horovod_tpu as hvd
    import horovod_tpu.jax as hvd_jax

    hvd.init()
    tx = hvd_jax.DistributedOptimizer(optax.sgd(1.0), agc=0.01)
    rng = np.random.RandomState(2)
    params = {"w": jnp.asarray(rng.randn(4, 8).astype(np.float32) * 0.1)}
    grads = {"w": jnp.asarray(rng.randn(4, 8).astype(np.float32) * 10.0)}
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    ref = _ref_clip(np.asarray(grads["w"]), np.asarray(params["w"]))
    np.testing.assert_allclose(np.asarray(updates["w"]), -ref, rtol=1e-5)
    with pytest.raises(ValueError):
        tx.update(grads, state)  # params required with agc


def test_agc_rejected_under_sharding():
    import optax

    import horovod_tpu.jax as hvd_jax
    from horovod_tpu.parallel import data_parallel_mesh, make_train_step

    with pytest.raises(ValueError):
        hvd_jax.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                     agc=0.01)
    mesh = data_parallel_mesh(devices=jax.devices("cpu")[:1])
    with pytest.raises(ValueError):
        make_train_step(lambda p, b: 0.0, optax.sgd(0.1), mesh,
                        zero1=True, agc=0.01)


def test_torch_agc_clips_like_reference():
    torch = pytest.importorskip("torch")
    import horovod_tpu.torch as hvd_t

    m = torch.nn.Linear(8, 4, bias=False)
    with torch.no_grad():
        m.weight.mul_(0.01)
    opt = hvd_t.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=1.0),
        named_parameters=m.named_parameters(), agc=0.01)
    before = m.weight.detach().clone()
    x = torch.randn(4, 8)
    loss = (m(x) ** 2).sum() * 1e4  # huge gradients
    loss.backward()
    opt.step()
    delta = (before - m.weight.detach()).numpy()
    # torch layout (out, in): units are rows; each update row's norm is
    # bounded by clipping * max(row norm, eps) (lr=1).
    row_norms = np.sqrt((delta ** 2).sum(1))
    bound = 0.01 * np.maximum(
        np.sqrt((before.numpy() ** 2).sum(1)), 1e-3)
    assert (row_norms <= bound * (1 + 1e-4)).all(), (row_norms, bound)
    with pytest.raises(ValueError):
        hvd_t.DistributedOptimizer(torch.optim.SGD(m.parameters(), lr=1.0),
                                   sharded_update=True, agc=0.01)
