"""jax.distributed bootstrap e2e: horovod_tpu topology drives
jax.distributed.initialize so jit programs span hosts (the reference's
multi-host NCCL role, carried by XLA collectives over ICI/DCN —
SURVEY §2.6/§5.8). CPU backend stands in for multi-host here; the
cross-process sum rides jax's own distributed runtime."""

import pytest

pytestmark = pytest.mark.e2e


def test_jax_distributed_bootstrap(run_launcher):
    result = run_launcher(2, "jax_distributed_worker.py",
                          extra_env={"JAX_PLATFORMS": "cpu"})
    assert result.returncode == 0, result.stdout + result.stderr
    assert "PASS cross_process_sum" in result.stdout
    assert "PASS cross_process_train_step" in result.stdout
    assert "PASS cross_process_fsdp_step" in result.stdout
