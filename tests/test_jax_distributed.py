"""jax.distributed bootstrap e2e: horovod_tpu topology drives
jax.distributed.initialize so jit programs span hosts (the reference's
multi-host NCCL role, carried by XLA collectives over ICI/DCN —
SURVEY §2.6/§5.8). CPU backend stands in for multi-host here; the
cross-process collectives ride jax's own distributed runtime."""

import pytest

pytestmark = pytest.mark.e2e


def test_jax_distributed_bootstrap_4proc(run_launcher):
    """4-process global mesh, 2 virtual devices per process (8 global):
    device view, cross-process psum, the flagship DP train step, FSDP
    with params sharded across process boundaries, the hierarchical
    (dp_cross x dp_local) two-level train step, and pipeline stages
    spanning processes — loss agreement allgathered across all 4
    processes for every step flavor."""
    result = run_launcher(
        4, "jax_distributed_worker.py",
        extra_env={
            "JAX_PLATFORMS": "cpu",
            # 2 local devices per process: the 2-D (cross, local) mesh
            # needs a real local axis (and 4x8 inherited from the
            # pytest env would oversubscribe the host).
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
        timeout=900)
    assert result.returncode == 0, result.stdout + result.stderr
    if "SKIP multiprocess_cpu_unsupported" in result.stdout:
        pytest.skip("jaxlib CPU backend lacks cross-process collectives "
                    "(the bootstrap/device-view phase still passed)")
    for marker in ("PASS global_device_view (8 devices over 4 processes)",
                   "PASS cross_process_sum",
                   "PASS cross_process_train_step",
                   "PASS cross_process_fsdp_step",
                   "PASS cross_process_hierarchical_step",
                   "PASS cross_process_pp_step"):
        assert marker in result.stdout, (marker, result.stdout)
