"""Torch-binding tests: single-process API in-process, multi-process via
the launcher (reference analogue: test/test_torch.py)."""

import pytest

pytestmark = pytest.mark.e2e

torch = pytest.importorskip("torch")


def test_torch_distributed(run_launcher):
    proc = run_launcher(2, "torch_ops_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert ("rank %d: all torch tests passed" % r) in proc.stdout, \
            proc.stdout + proc.stderr


def test_compression_roundtrip():
    from horovod_tpu.torch.compression import Compression
    x = torch.randn(16)
    for codec in (Compression.none, Compression.fp16, Compression.bf16):
        c, ctx = codec.compress(x)
        out = codec.decompress(c, ctx)
        assert out.dtype == x.dtype
        assert torch.allclose(out, x, atol=1e-2)


def test_distributed_optimizer_single_process():
    """size==1: no hooks registered, step() must still work."""
    import horovod_tpu.torch as hvd
    hvd.init()
    if hvd.size() != 1:
        pytest.skip("single-process test")
    model = torch.nn.Linear(3, 1)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    before = [p.clone() for p in model.parameters()]
    loss = model(torch.ones(2, 3)).sum()
    loss.backward()
    opt.step()
    after = list(model.parameters())
    assert any(not torch.allclose(b, a) for b, a in zip(before, after))


def test_zero_copy_storage_identity():
    """allreduce_ / broadcast_ on contiguous CPU tensors keep the exact
    storage pointer — the core reduces into the tensor's own memory."""
    import horovod_tpu.torch as hvd
    hvd.init()
    x = torch.randn(1 << 10)
    ptr = x.data_ptr()
    ref = x.clone()
    hvd.allreduce_(x, average=False, name="zc_ptr_ar")
    assert x.data_ptr() == ptr
    if hvd.size() == 1:
        assert torch.allclose(x, ref)
    b = torch.randn(1 << 10)
    ptr = b.data_ptr()
    hvd.broadcast_(b, 0, name="zc_ptr_bc")
    assert b.data_ptr() == ptr


def test_zero_copy_speedup_100mb():
    """The zero-copy in-place path must beat the legacy two-copy path
    by >=2x on a 100 MB allreduce."""
    import time

    import numpy as np

    import horovod_tpu.torch as hvd
    from horovod_tpu.common import ops as _ops
    hvd.init()
    if hvd.size() != 1:
        pytest.skip("single-process micro-bench")
    n = 25 * (1 << 20)  # 100 MB of f32
    x = torch.ones(n)

    def legacy_allreduce_(t, name):
        # The pre-zero-copy data path: tensor -> numpy copy -> core ->
        # numpy copy -> tensor copy_.
        arr = t.detach().cpu().numpy().copy()
        out = _ops.synchronize(_ops.allreduce_async(arr, name))
        t.copy_(torch.from_numpy(out.copy()).reshape(t.shape))

    def median_time(fn, tag, iters=5):
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            fn("zc_bench_%s.%d" % (tag, i))
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    # Median-of-5 per path, one retry: the 1-core box shares the timer
    # with the background comm thread, so a single descheduled
    # iteration must not fail the suite.
    for attempt in range(2):
        legacy = median_time(lambda nm: legacy_allreduce_(x, nm),
                             "legacy%d" % attempt)
        fast = median_time(
            lambda nm: hvd.allreduce_(x, average=False, name=nm),
            "fast%d" % attempt)
        if fast * 2 <= legacy:
            break
    assert fast * 2 <= legacy, (fast, legacy)


def test_cext_glue_loaded_and_used():
    """The C-extension binding glue must build and carry the collectives
    (reference-architecture parity: torch/mpi_ops_v2.cc is compiled
    glue, not interpreter marshalling). HVD_TPU_REQUIRE_CEXT makes a
    silent fallback a failure here."""
    import horovod_tpu.torch as hvd
    from horovod_tpu.torch import _cext
    hvd.init()
    assert _cext.load() is not None, "C extension failed to build"
    x = torch.randn(256)
    ptr = x.data_ptr()
    h = hvd.allreduce_async_(x, average=False, name="cext_route")
    assert h in hvd._cext_handles  # actually routed through the glue
    hvd.synchronize(h)
    assert x.data_ptr() == ptr


def test_cext_error_surface():
    """Handle lifecycle through the C extension: synchronize consumes
    the handle (second call is the same ValueError as the ctypes
    path)."""
    import horovod_tpu.torch as hvd
    hvd.init()
    if hvd.size() != 1:
        pytest.skip("single-process check")
    x = torch.ones(4)
    h = hvd.allreduce_async_(x, average=False, name="cext_err")
    out = hvd.synchronize(h)
    assert out is x
    with pytest.raises(ValueError):
        hvd.synchronize(h)  # handle already consumed
