"""Torch-binding tests: single-process API in-process, multi-process via
the launcher (reference analogue: test/test_torch.py)."""

import pytest

pytestmark = pytest.mark.e2e

torch = pytest.importorskip("torch")


def test_torch_distributed(run_launcher):
    proc = run_launcher(2, "torch_ops_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert ("rank %d: all torch tests passed" % r) in proc.stdout, \
            proc.stdout + proc.stderr


def test_compression_roundtrip():
    from horovod_tpu.torch.compression import Compression
    x = torch.randn(16)
    for codec in (Compression.none, Compression.fp16, Compression.bf16):
        c, ctx = codec.compress(x)
        out = codec.decompress(c, ctx)
        assert out.dtype == x.dtype
        assert torch.allclose(out, x, atol=1e-2)


def test_distributed_optimizer_single_process():
    """size==1: no hooks registered, step() must still work."""
    import horovod_tpu.torch as hvd
    hvd.init()
    if hvd.size() != 1:
        pytest.skip("single-process test")
    model = torch.nn.Linear(3, 1)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    before = [p.clone() for p in model.parameters()]
    loss = model(torch.ones(2, 3)).sum()
    loss.backward()
    opt.step()
    after = list(model.parameters())
    assert any(not torch.allclose(b, a) for b, a in zip(before, after))
