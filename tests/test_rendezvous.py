"""Dynamic rendezvous tests: the KV server protocol, worker-side topology
resolution with worker-chosen ports, and the launcher e2e path (reference
analogue: horovod/run/rendezvous/http_server.py + gloo http_store)."""

import threading

import pytest

from horovod_tpu.run import rendezvous


@pytest.fixture
def server():
    s = rendezvous.RendezvousServer(host="127.0.0.1")
    s.start()
    yield s
    s.stop()


def test_kv_put_get_list(server):
    addr = "127.0.0.1:%d" % server.port
    assert rendezvous.get(addr, "s", "k") is None
    rendezvous.put(addr, "s", "k", b"value-1")
    assert rendezvous.get(addr, "s", "k") == b"value-1"
    rendezvous.put(addr, "s", "k2", "value-2")
    rendezvous.put(addr, "other", "k", "hidden")
    assert rendezvous.list_scope(addr, "s") == {"k": "value-1",
                                                "k2": "value-2"}


def test_wait_all_timeout(server):
    addr = "127.0.0.1:%d" % server.port
    rendezvous.put(addr, rendezvous.SCOPE_ADDRS, "0", "127.0.0.1:1")
    with pytest.raises(TimeoutError) as e:
        rendezvous.wait_all(addr, rendezvous.SCOPE_ADDRS, range(3),
                            timeout=0.5, poll_interval=0.05)
    assert "missing ranks" in str(e.value)


def test_resolve_topology_worker_chosen_ports(server):
    """Three 'workers' rendezvous concurrently with no pre-assigned ports;
    everyone must converge on one table with 3 distinct self-chosen ports
    and consistent local topology (same IP -> one host)."""
    addr = "127.0.0.1:%d" % server.port
    envs = [None] * 3
    errors = []

    def worker(rank):
        try:
            envs[rank] = rendezvous.resolve_topology(rank, 3, addr,
                                                     timeout=20)
        except Exception as e:  # pragma: no cover
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors
    tables = {e["HVD_TPU_ADDRS"] for e in envs}
    assert len(tables) == 1  # everyone sees the same table
    addrs = tables.pop().split(",")
    ports = [int(a.rsplit(":", 1)[1]) for a in addrs]
    assert len(set(ports)) == 3 and all(p > 0 for p in ports)
    # All on one IP -> single host: local == world, cross size 1.
    for rank, env in enumerate(envs):
        assert env["HVD_TPU_RANK"] == str(rank)
        assert env["HVD_TPU_SIZE"] == "3"
        assert env["HVD_TPU_LOCAL_RANK"] == str(rank)
        assert env["HVD_TPU_LOCAL_SIZE"] == "3"
        assert env["HVD_TPU_CROSS_SIZE"] == "1"


def test_publish_burst(server):
    """Every worker of a large job publishes at the same instant; the
    deep listen backlog + client retry must absorb the burst (the
    socketserver default backlog of 5 dropped connections at 32 ranks)."""
    addr = "127.0.0.1:%d" % server.port
    n = 64
    errors = []

    def publish(i):
        try:
            rendezvous.put(addr, "burst", str(i), b"w%d" % i, timeout=30)
        except Exception as e:  # pragma: no cover
            errors.append((i, e))

    threads = [threading.Thread(target=publish, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors[:3]
    table = rendezvous.list_scope(addr, "burst")
    assert len(table) == n


def test_hmac_auth(monkeypatch):
    """Signed-request parity with the reference's HMAC-authenticated
    launcher services (run/common/util/secret.py): unsigned or
    wrongly-signed requests are rejected, signed ones succeed."""
    import urllib.error

    key = rendezvous.make_secret()
    server = rendezvous.RendezvousServer(host="127.0.0.1", key=key)
    server.start()
    addr = "127.0.0.1:%d" % server.port
    try:
        monkeypatch.delenv(rendezvous.KEY_ENV, raising=False)
        with pytest.raises(urllib.error.HTTPError) as e:
            rendezvous.put(addr, "s", "k", b"unsigned")
        assert e.value.code == 403

        monkeypatch.setenv(rendezvous.KEY_ENV, "wrong-" + key)
        with pytest.raises(RuntimeError) as e2:
            rendezvous.wait_all(addr, "s", ["k"], timeout=2)
        assert "auth failed" in str(e2.value)

        monkeypatch.setenv(rendezvous.KEY_ENV, key)
        rendezvous.put(addr, "s", "k", b"signed")
        assert rendezvous.get(addr, "s", "k") == b"signed"
    finally:
        server.stop()


def test_resolve_topology_picks_reachable_interface(server, monkeypatch):
    """A multi-NIC worker whose kernel-routed first candidate is
    unreachable: the coordinator's probe must skip it and select the
    interface that actually accepts connections (previously the bad
    guess went straight into the table and native init hung)."""
    addr = "127.0.0.1:%d" % server.port
    # 10.255.255.1 plays the unreachable NIC. The CI sandbox proxies
    # every TCP connect (any ip:port "succeeds"), so the socket-level
    # probe is simulated; the selection logic runs for real.
    monkeypatch.setattr(rendezvous, "candidate_ips",
                        lambda *a, **k: ["10.255.255.1", "127.0.0.1"])
    monkeypatch.setattr(
        rendezvous, "probe_connect",
        lambda ip, port, timeout=None: ip == "127.0.0.1")
    envs = [None] * 2
    errors = []

    def worker(rank):
        try:
            envs[rank] = rendezvous.resolve_topology(rank, 2, addr,
                                                     timeout=30)
        except Exception as e:
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    rendezvous.release_held_ports()
    assert not errors, errors
    for env in envs:
        for entry in env["HVD_TPU_ADDRS"].split(","):
            assert entry.startswith("127.0.0.1:"), env["HVD_TPU_ADDRS"]


def test_resolve_topology_unreachable_advertise_fails_fast(server,
                                                           monkeypatch):
    """Every advertised interface unreachable: rank 0's probe must fail
    within seconds with an error naming the rank and its candidates —
    not hang until the native start timeout."""
    import time as _time

    addr = "127.0.0.1:%d" % server.port
    monkeypatch.setattr(rendezvous, "candidate_ips",
                        lambda *a, **k: ["10.255.255.1"])
    # Simulated cross-host unreachability (the CI sandbox proxies every
    # real TCP connect, so negative probes must be faked).
    monkeypatch.setattr(rendezvous, "probe_connect",
                        lambda ip, port, timeout=None: False)
    errors = []

    def worker(rank):
        try:
            rendezvous.resolve_topology(rank, 2, addr, timeout=15)
        except Exception as e:
            errors.append((rank, e))

    t0 = _time.monotonic()
    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    elapsed = _time.monotonic() - t0
    rendezvous.release_held_ports()
    # BOTH ranks fail, fast, with the actionable message (rank 0 from
    # its own probe; rank 1 via the published coordinator failure).
    assert len(errors) == 2, errors
    for _, e in errors:
        msg = str(e)
        assert "10.255.255.1" in msg and "firewall" in msg, msg
    assert elapsed < 20, elapsed


@pytest.mark.e2e
def test_launcher_dynamic_rendezvous(run_launcher):
    """Launcher end-to-end with NO pre-assigned ports: workers bind their
    own, publish, and run real collectives."""
    result = run_launcher(2, "distributed_ops_worker.py")
    assert result.returncode == 0, result.stderr
