"""Pluggable gradient compression (docs/COMPRESSION.md): codec unit
tests (round-trip error bounds per block size/dtype, wire-size math
pinned against the native layout), the jax ring allreduce with fused
per-hop quantization, negotiation/cache semantics (mode change = cache
miss; mixed-mode ranks rejected naming both modes), and the hvd-top
renderer's tolerance for workers that predate the cmp fields."""

import numpy as np
import pytest

from horovod_tpu import compression as comp


# --- codec units ------------------------------------------------------------


@pytest.mark.parametrize("block", [64, 128, 256, 512])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_int8_roundtrip_error_bound(block, dtype):
    """|x - dequant(quant(x))| <= scale/2 per element, for every block
    size and float dtype (f64 goes through the f32 wire view)."""
    rng = np.random.RandomState(block)
    for scale_mag in (1e-4, 1.0, 1e4):
        x = (rng.randn(block * 3 + 17) * scale_mag).astype(dtype)
        q, scales = comp.quantize_int8(x, block=block)
        y = comp.dequantize_int8(q, scales, block=block)
        bound = np.repeat(scales / 2.0, block)[:x.size]
        # + one f32 ulp of the input magnitude: the f64 input is first
        # narrowed to the f32 wire dtype.
        slack = np.abs(x).max() * 1e-6 + 1e-12
        assert np.all(np.abs(x.astype(np.float32) - y) <= bound + slack), \
            (block, scale_mag)


def test_int8_exact_on_constants_and_zeros():
    # A constant block quantizes exactly (q = +-127, scale = |c|/127);
    # all-zero blocks produce scale 0 and decode to exact zeros.
    for c in (1.0, -3.5, 0.0):
        x = np.full(1000, c, np.float32)
        q, s = comp.quantize_int8(x)
        y = comp.dequantize_int8(q, s)
        np.testing.assert_allclose(y, x, rtol=1e-6, atol=0)


def test_int8_nonfinite_blocks_stay_nonfinite():
    """An overflowed (inf/NaN) gradient must NOT decode to finite
    numbers — downstream isfinite / loss-scale skip-step guards have to
    keep firing after the allreduce (numpy and jax planes agree)."""
    import jax.numpy as jnp

    x = np.ones(600, np.float32)
    x[300] = np.nan
    x[10] = np.inf
    q, s = comp.quantize_int8(x)
    y = comp.dequantize_int8(q, s)
    # Both poisoned blocks decode nonfinite; clean blocks stay clean.
    assert not np.isfinite(y[:512]).any()
    assert np.isfinite(y[512:]).all()

    xj = jnp.zeros(512, jnp.float32).at[5].set(jnp.nan)
    qj, sj = comp.quantize_int8_jax(xj)
    yj = np.asarray(comp.dequantize_int8_jax(qj, sj))
    assert not np.isfinite(yj[:256]).any()
    assert np.isfinite(yj[256:]).all()


def test_int8_symmetric_range():
    """-128 is never produced (symmetric [-127, 127])."""
    x = np.linspace(-1000, 1000, 4096).astype(np.float32)
    q, _ = comp.quantize_int8(x)
    assert q.min() >= -127 and q.max() <= 127


def test_bf16_roundtrip_matches_ml_dtypes():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    rng = np.random.RandomState(7)
    x = (rng.randn(4096) * 100).astype(np.float32)
    got = comp.bf16_roundtrip(x)
    want = x.astype(ml_dtypes.bfloat16).astype(np.float32)
    assert np.array_equal(got, want)


def test_wire_bytes_matches_native_layout():
    from horovod_tpu.common.basics import get_basics
    b = get_basics()
    for count in (0, 1, 255, 256, 257, 1000, 1 << 20):
        for mode_name, mode_id in (("none", 0), ("bf16", 1), ("int8", 2)):
            assert comp.wire_bytes(count, mode_name) == \
                b.compressed_size(count, mode_id), (count, mode_name)
    # ~3.9x for block-aligned int8, exactly 2x for bf16.
    n = 1 << 20
    assert comp.wire_bytes(n, "none") / comp.wire_bytes(n, "int8") > 3.8
    assert comp.wire_bytes(n, "none") == 2 * comp.wire_bytes(n, "bf16")


def test_effective_mode_degrades_non_f32():
    from horovod_tpu.common.basics import get_basics, numpy_to_hvd_dtype
    b = get_basics()
    f32 = numpy_to_hvd_dtype(np.float32)
    for np_dtype in (np.int32, np.int64, np.float64, np.float16, np.uint8):
        hv = numpy_to_hvd_dtype(np_dtype)
        assert b.effective_compression(comp.INT8, hv) == comp.NONE
        assert b.effective_compression(comp.BF16, hv) == comp.NONE
    assert b.effective_compression(comp.INT8, f32) == comp.INT8


def test_resolve_and_env_default(monkeypatch):
    assert comp.resolve(None) == comp.Compression.none
    assert comp.resolve("bf16") is comp.Compression.bf16
    assert comp.resolve("INT8") is comp.Compression.int8
    assert comp.resolve(comp.Compression.int8).name == "int8"
    assert comp.resolve(2) is comp.Compression.int8
    monkeypatch.setenv(comp.ENV_VAR, "int8")
    assert comp.resolve(None) is comp.Compression.int8
    # Explicit none overrides the env.
    assert comp.resolve("none") is comp.Compression.none
    # A typo'd env must not silently quantize.
    monkeypatch.setenv(comp.ENV_VAR, "int4")
    assert comp.resolve(None) is comp.Compression.none
    with pytest.raises(ValueError):
        comp.resolve("fp8")
    # Legacy codec objects belong to the binding layer, not the wire.
    from horovod_tpu import jax as hvd_jax
    with pytest.raises(TypeError):
        comp.resolve(hvd_jax.Compression.fp16)


# --- jax ring allreduce -----------------------------------------------------


def _mesh8():
    import jax
    from jax.sharding import Mesh
    devs = jax.devices("cpu")
    return Mesh(np.array(devs), ("hvd",))


@pytest.mark.parametrize("mode,tol", [("none", 1e-5), ("bf16", 2e-2),
                                      ("int8", 4e-2)])
def test_ring_allreduce_matches_psum(mode, tol):
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.ring import ring_allreduce

    mesh = _mesh8()
    rng = np.random.RandomState(0)
    # Deliberately NOT a multiple of 8 * BLOCK: exercises pad/unpad.
    x = (rng.randn(8, 1003) * 5).astype(np.float32)

    f = jax.jit(jax.shard_map(
        lambda v: ring_allreduce(v, "hvd", compression=mode),
        mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"), check_vma=False))
    out = np.asarray(f(x))
    want = x.sum(axis=0, keepdims=True).repeat(8, 0)
    err = np.max(np.abs(out - want)) / np.max(np.abs(want))
    assert err < tol, (mode, err)
    # Every rank must hold the IDENTICAL reduced values (the allgather
    # phase forwards encoded chunks verbatim — no per-hop requant drift).
    for r in range(1, 8):
        assert np.array_equal(out[0], out[r]), (mode, r)


def test_ring_allreduce_non_f32_passthrough():
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.ring import ring_allreduce

    mesh = _mesh8()
    x = np.arange(8 * 64, dtype=np.int32).reshape(8, 64)
    f = jax.jit(jax.shard_map(
        lambda v: ring_allreduce(v, "hvd", compression="int8"),
        mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"), check_vma=False))
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out[0], x.sum(axis=0))


@pytest.mark.parametrize("mode,tol", [("bf16", 2e-2), ("int8", 4e-2)])
def test_jax_allreduce_compressed_in_jit(mode, tol):
    """hvd.jax.allreduce(compression=...) inside shard_map: compressed
    average matches the exact mean within the codec bound."""
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import jax as hvd_jax

    mesh = _mesh8()
    rng = np.random.RandomState(3)
    x = (rng.randn(8, 500) * 2).astype(np.float32)

    f = jax.jit(jax.shard_map(
        lambda v: hvd_jax.allreduce(v, average=True, compression=mode),
        mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"), check_vma=False))
    out = np.asarray(f(x))
    want = x.mean(axis=0, keepdims=True).repeat(8, 0)
    err = np.max(np.abs(out - want)) / np.max(np.abs(want))
    assert err < tol, (mode, err)


def test_jax_allreduce_legacy_codecs_still_work():
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import jax as hvd_jax

    mesh = _mesh8()
    x = np.full((8, 32), 2.0, np.float32)
    for codec in (hvd_jax.Compression.none, hvd_jax.Compression.fp16,
                  hvd_jax.Compression.bf16):
        f = jax.jit(jax.shard_map(
            lambda v: hvd_jax.allreduce(v, average=True, compression=codec),
            mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"),
            check_vma=False))
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, 2.0, rtol=1e-2)


# --- multi-process e2e (launcher) -------------------------------------------


@pytest.mark.e2e
@pytest.mark.parametrize("np_", [2, 4])
def test_compression_worker(run_launcher, np_):
    proc = run_launcher(np_, "compression_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(np_):
        assert ("rank %d: compression worker passed" % r) in proc.stdout, \
            proc.stdout + proc.stderr


@pytest.mark.e2e
def test_mixed_mode_rejected_at_negotiation(run_launcher):
    proc = run_launcher(2, "compression_mixed_worker.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for r in range(2):
        assert ("rank %d: mixed worker passed" % r) in proc.stdout, \
            proc.stdout + proc.stderr


@pytest.mark.e2e
def test_env_default_engages_compression(run_launcher):
    """HVD_TPU_COMPRESSION=int8 with no per-call argument: the fuzz
    worker's f32 allreduces ride the int8 wire (constant fills quantize
    exactly, so its value assertions hold bit-for-bit)."""
    proc = run_launcher(2, "negotiation_fuzz_worker.py",
                        extra_env={"HVD_TPU_COMPRESSION": "int8",
                                   "HVD_TPU_METRICS": "1",
                                   "HVD_TPU_FUZZ_TENSORS": "12"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.count("negotiation fuzz passed") == 2, \
        proc.stdout + proc.stderr


# --- hvd-top renderer tolerance ---------------------------------------------


def _job(per_rank):
    return {"size": len(per_rank), "generation": 1,
            "per_rank": per_rank,
            "age_seconds": {r: 0.0 for r in per_rank},
            "rank_lag_seconds": [0.0] * len(per_rank)}


def test_hvd_top_tolerates_workers_without_cmp_fields():
    """Mixed-version elastic job: rank 0 reports the new compression
    fields, rank 1 (older worker) does not. The renderer must keep the
    columns aligned and show '-' for the missing cmp value — not
    misalign or crash."""
    from horovod_tpu.run import top

    new_worker = {"cycles_total": 100.0, "cycle_seconds_sum": 1.0,
                  "compression_bytes_in_total": 4.0e6,
                  "compression_bytes_out_total": 1.0e6,
                  "cache_hit_total": 5, "cache_miss_total": 5}
    old_worker = {"cycles_total": 90.0, "cycle_seconds_sum": 1.0,
                  "cache_hit_total": 5, "cache_miss_total": 5}
    frame = top.render(_job({"0": new_worker, "1": old_worker}), None, 0.0,
                       "test:0")
    lines = frame.splitlines()
    rows = [ln for ln in lines if ln.strip().startswith(("0", "1"))]
    assert len(rows) == 2, frame
    header = next(ln for ln in lines if " cmp" in ln)
    cmp_col = header.index(" cmp")
    # New worker shows the live ratio; old worker shows '-' in the SAME
    # column span (no shift).
    assert "4.0x" in rows[0], frame
    assert rows[1][cmp_col:cmp_col + 5].strip() == "-", frame
    # Every row is exactly as wide as the header (nothing misaligned).
    assert all(len(r) == len(rows[0]) for r in rows), frame


def test_hvd_top_cmp_ratio_rendering():
    from horovod_tpu.run import top

    w = {"cycles_total": 10.0, "cycle_seconds_sum": 0.1,
         "compression_bytes_in_total": 39.0e6,
         "compression_bytes_out_total": 10.0e6}
    frame = top.render(_job({"0": w}), None, 0.0, "test:0")
    assert "3.9x" in frame, frame
    # Zero bytes out (compression never engaged) renders '-', not a
    # division error.
    w0 = dict(w, compression_bytes_in_total=0.0,
              compression_bytes_out_total=0.0)
    frame0 = top.render(_job({"0": w0}), None, 0.0, "test:0")
    assert frame0
