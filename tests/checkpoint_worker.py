"""2-rank checkpoint/restore worker: rank 0 saves and reads; rank 1
receives values purely over the broadcast plane (it passes a
nonexistent path, proving no shared filesystem is needed). The state
includes a non-alphabetical namedtuple (the optax-state shape) to pin
structure-faithful restore: same-dtype scalar fields must not permute."""

import collections
import os
import sys
import tempfile
import time

import numpy as np
import jax.numpy as jnp

import horovod_tpu.jax as hvd
from horovod_tpu.jax import checkpoint

# Field order deliberately non-alphabetical (zz before aa).
Counters = collections.namedtuple("Counters", ["zz_mini", "aa_grad"])


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    assert n == 2

    tree = {"w": jnp.full((2, 2), 10.0 + r),  # ranks differ pre-restore
            "step": jnp.int32(5 * (r + 1)),
            # Saved bf16, restored into an f32 template: the restore
            # must conform dtypes before the cross-rank broadcast.
            "mu": jnp.full((3,), 0.5, jnp.bfloat16),
            "counters": Counters(zz_mini=jnp.int32(111),
                                 aa_grad=jnp.int32(222))}
    tmpdir = tempfile.mkdtemp() if r == 0 else "/nonexistent/ckpt"
    checkpoint.save(tmpdir, tree, step=1)  # rank 1's path never touched

    template = {"w": jnp.zeros((2, 2)), "step": jnp.int32(0),
                "mu": jnp.zeros((3,), jnp.float32),
                "counters": Counters(zz_mini=jnp.int32(0),
                                     aa_grad=jnp.int32(0))}
    out = checkpoint.restore(tmpdir, template, step=1)
    # Everyone must hold rank 0's values, fields un-permuted, dtypes
    # conformed to the template.
    assert np.allclose(out["w"], 10.0), out["w"]
    assert int(out["step"]) == 5, out["step"]
    assert out["mu"].dtype == jnp.float32, out["mu"].dtype
    assert np.allclose(np.asarray(out["mu"]), 0.5), out["mu"]
    assert int(out["counters"].zz_mini) == 111, out["counters"]
    assert int(out["counters"].aa_grad) == 222, out["counters"]

    # Error paths must raise the NAMED error on EVERY rank, promptly —
    # historically a root-side failure left the other ranks blocked in
    # the completion barrier until the stall timeout (the satellite fix:
    # the root broadcasts a success flag before any barrier collective).
    t0 = time.monotonic()
    try:
        checkpoint.save("/proc/nonexistent/unwritable", tree)
    except checkpoint.CheckpointSaveError:
        pass
    else:
        raise AssertionError("save to an unwritable path did not raise")
    try:
        checkpoint.restore(tmpdir, template, step=99)  # never written
    except checkpoint.CheckpointRestoreError:
        pass
    else:
        raise AssertionError("restore of a missing step did not raise")
    elapsed = time.monotonic() - t0
    # Both failures must surface collectively in seconds, not via the
    # multi-minute stall timeout the old deadlock needed.
    assert elapsed < 30, "error propagation took %.1fs" % elapsed

    print("rank %d: checkpoint tests passed" % r, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
