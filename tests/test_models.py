"""Model-zoo shape/correctness tests (CPU, f32 to keep them cheap)."""

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_default_matmul_precision", "highest")


def test_resnet50_forward_shape():
    # Shape-only via eval_shape: un-jitted eager execution of the 53-conv
    # graph costs minutes of per-op CPU compiles and proves nothing more
    # (numeric execution is covered by the train-step and bench paths).
    from horovod_tpu.models import ResNet50
    model = ResNet50(num_classes=10, dtype=jnp.float32)
    x = jax.ShapeDtypeStruct((2, 64, 64, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda x: model.init(jax.random.PRNGKey(0), x, train=False), x)
    logits = jax.eval_shape(
        lambda v, x: model.apply(v, x, train=False), variables, x)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_resnet18_param_count():
    from horovod_tpu.models import ResNet18
    model = ResNet18(num_classes=1000, dtype=jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False))
    n = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    # torchvision resnet18 has 11.69M params; ours matches to within the
    # fc/in-shape differences.
    assert 11e6 < n < 12e6


def test_vgg16_param_count():
    from horovod_tpu.models import VGG16
    model = VGG16(num_classes=1000, dtype=jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 224, 224, 3)), train=False))
    n = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    assert abs(n - 138_357_544) < 1e5, n  # the canonical VGG-16 count


def test_inception_v3_shapes_and_params():
    from horovod_tpu.models import InceptionV3
    model = InceptionV3(num_classes=1000, dtype=jnp.float32)
    x = jax.ShapeDtypeStruct((2, 299, 299, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda x: model.init(jax.random.PRNGKey(0), x, train=False), x)
    n = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    # Keras InceptionV3 (no aux head): 23,851,784 params.
    assert 23e6 < n < 25e6, n
    logits = jax.eval_shape(
        lambda v, x: model.apply(v, x, train=False), variables, x)
    assert logits.shape == (2, 1000)


def test_mnist_cnn_forward():
    from horovod_tpu.models import MnistCNN
    model = MnistCNN(dtype=jnp.float32)
    x = jnp.zeros((4, 28, 28, 1))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = jax.jit(lambda v, x: model.apply(v, x, train=False))(
        variables, x)
    assert logits.shape == (4, 10)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_word2vec_loss_and_shapes():
    from horovod_tpu.models import SkipGram
    model = SkipGram(vocab_size=100, embedding_dim=16)
    center = jnp.array([1, 2, 3], jnp.int32)
    context = jnp.array([4, 5, 6], jnp.int32)
    neg = jnp.array([7, 8, 9, 10], jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), center)
    emb = model.apply(variables, center)
    assert emb.shape == (3, 16)
    loss = model.apply(variables, center, context, neg,
                       method=SkipGram.nce_loss)
    assert np.isfinite(float(loss)) and float(loss) > 0


def test_transformer_dense_forward():
    from horovod_tpu.models import Transformer, TransformerConfig
    cfg = TransformerConfig(vocab_size=128, num_layers=2, num_heads=4,
                            embed_dim=64, mlp_dim=128, dtype=jnp.float32)
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = jax.jit(model.apply)(variables, tokens)
    assert logits.shape == (2, 16, 128)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_transformer_ring_matches_dense():
    """Sequence-sharded ring transformer == single-device dense
    transformer on the same weights — end-to-end SP correctness."""
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.models import Transformer, TransformerConfig

    base = dict(vocab_size=64, num_layers=2, num_heads=4, embed_dim=32,
                mlp_dim=64, dtype=jnp.float32)
    dense_model = Transformer(TransformerConfig(**base))
    ring_model = Transformer(TransformerConfig(attention="ring",
                                               sp_axis="sp", **base))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    variables = dense_model.init(jax.random.PRNGKey(0), tokens)
    expected = dense_model.apply(variables, tokens)

    mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("sp",))
    positions = jnp.broadcast_to(jnp.arange(32, dtype=jnp.int32)[None],
                                 tokens.shape)

    def shard_fn(tokens, positions):
        return ring_model.apply(variables, tokens, positions)

    f = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))
    out = f(tokens, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_transformer_zigzag_ring_matches_dense(monkeypatch):
    """sp_schedule='zigzag' end-to-end: zigzag-shard tokens AND
    positions (rotary reads global positions, so any layout is exact),
    run the ring transformer, unshard, compare against the dense model
    on natural-order data. Kernel path via interpret mode; L=2048 over
    4 ranks -> 512/rank = two 256-token chunks."""
    from jax.sharding import Mesh, PartitionSpec as P
    from horovod_tpu.models import Transformer, TransformerConfig
    from horovod_tpu.parallel import zigzag_shard, zigzag_unshard

    monkeypatch.setenv("HVD_TPU_PALLAS_INTERPRET", "1")
    n, L = 4, 2048
    base = dict(vocab_size=64, num_layers=2, num_heads=2, embed_dim=32,
                mlp_dim=64, dtype=jnp.float32, max_seq_len=L)
    dense_model = Transformer(TransformerConfig(**base))
    zz_model = Transformer(TransformerConfig(
        attention="ring", sp_axis="sp", sp_schedule="zigzag", **base))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, L), 0, 64)
    variables = dense_model.init(jax.random.PRNGKey(0), tokens[:, :16])
    expected = dense_model.apply(variables, tokens)

    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("sp",))
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None],
                                 tokens.shape)
    tz = zigzag_shard(tokens, n)
    pz = zigzag_shard(positions, n)

    f = jax.jit(jax.shard_map(
        lambda t, p: zz_model.apply(variables, t, p),
        mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))
    out = zigzag_unshard(f(tz, pz), n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
