"""Compression A/B bench worker (bench.py --compression): allreduces a
gradient-bundle-sized f32 payload HVD_TPU_BENCH_ITERS times under
HVD_TPU_COMPRESSION, then reports wall time per op and the socket-layer
wire counters as one `COMPRESSION_BENCH {...}` JSON line per rank.

Values are verified every iteration (rank-offset ramp) so a codec
regression fails the bench rather than biasing it."""

import json
import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.common import ops


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()
    iters = int(os.environ.get("HVD_TPU_BENCH_ITERS", "20"))
    mb = float(os.environ.get("HVD_TPU_BENCH_MB", "4"))
    mode = os.environ.get("HVD_TPU_COMPRESSION", "none") or "none"
    elems = int(mb * 1024 * 1024 / 4)
    base = (np.arange(elems, dtype=np.float32) % 997) / 31.0
    want = base * n + sum(range(n))
    tol = {"none": 1e-5, "bf16": 2e-2, "int8": 4e-2}[mode]

    def counters():
        return hvd.metrics()["counters"]

    # Warmup (connection buffers, fusion path, cache entry).
    out = ops.allreduce(base + r, "cmpbench.warm")
    assert out.shape == base.shape

    c0 = counters()
    t0 = time.perf_counter()
    for i in range(iters):
        out = ops.allreduce(base + r, "cmpbench.%d" % i)
        err = np.max(np.abs(out - want)) / np.max(np.abs(want))
        assert err < tol, (mode, i, err)
    dt = time.perf_counter() - t0
    c1 = counters()

    row = {
        "rank": r, "size": n, "mode": mode, "iters": iters,
        "payload_mb": mb,
        "us_per_op": round(dt / iters * 1e6, 1),
        "ring_bytes_sent": c1["net_ring_bytes_sent_total"] -
                           c0["net_ring_bytes_sent_total"],
        "ring_bytes_recv": c1["net_ring_bytes_recv_total"] -
                           c0["net_ring_bytes_recv_total"],
        "codec_bytes_in": c1["compression_bytes_in_total"] -
                          c0["compression_bytes_in_total"],
        "codec_bytes_out": c1["compression_bytes_out_total"] -
                           c0["compression_bytes_out_total"],
    }
    print("COMPRESSION_BENCH %s" % json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
