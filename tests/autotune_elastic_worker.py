"""Autotune + elastic e2e worker: the closed-loop tuner must converge in
generation 0, RE-ARM when the membership shrinks (worker 1 self-kills),
converge again under the new world size, and survive the regrow — with
the re-tuned knob values broadcast to every rank.

Run under the elastic launcher (`-np 3 --min-np 1`) with fast sampling
env (HVD_TPU_AUTOTUNE_CYCLES_PER_SAMPLE etc) so each generation's tuning
pass completes in a handful of steps. Each step prints one `TUNE` line
carrying this rank's synchronized tuner view plus the step wall time;
the test asserts convergence/re-arm/param-change/throughput-recovery
from rank 0's stream.
"""

import os
import sys
import time

import numpy as np

import horovod_tpu as hvd
from horovod_tpu import elastic

TOTAL_STEPS = int(os.environ.get("AT_ELASTIC_TOTAL_STEPS", "60"))
CRASH_STEP = int(os.environ.get("AT_ELASTIC_CRASH_STEP", "30"))
COMMIT_EVERY = 5
WID = os.environ.get("HVD_TPU_WORKER_ID", "?")

K = 8          # gradients per step
ELEMS = 16384  # 64 KB each


@elastic.run
def train(state):
    grads = [np.full(ELEMS, float(i % 5), np.float32) for i in range(K)]
    while state.step < TOTAL_STEPS:
        gen = int(os.environ.get("HVD_TPU_GENERATION", "0") or 0)
        t0 = time.perf_counter()
        hs = [hvd.allreduce_async(g, "at.g%02d" % i)
              for i, g in enumerate(grads)]
        for h in hs:
            hvd.synchronize(h)
        dt = time.perf_counter() - t0
        state.step += 1
        at = hvd.autotune()
        print("TUNE worker %s gen %d step %d size %d active %d epoch %d "
              "rearms %d fusion %.6f cycle %.6f chunk %.3f ms %.3f"
              % (WID, gen, state.step, hvd.size(), int(at["active"]),
                 at["rearm_epoch"], at["rearms_total"],
                 at["params"]["fusion_mb"], at["params"]["cycle_time_ms"],
                 at["params"]["pipeline_chunk_kb"], dt * 1e3), flush=True)
        if WID == "1" and gen == 0 and state.step == CRASH_STEP:
            print("worker 1 crashing now", flush=True)
            os._exit(23)
        if state.step % COMMIT_EVERY == 0:
            state.commit()
    return state.step


def main():
    state = elastic.ElasticState(w=np.zeros(4, np.float64), step=0)
    done = train(state)
    if done is None:
        print("worker %s superseded (job already complete)" % WID,
              flush=True)
        return 0
    print("worker %s tune train done step %d" % (WID, state.step),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
