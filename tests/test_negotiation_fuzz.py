"""Out-of-order negotiation e2e: ranks enqueue and synchronize the same
collectives in different orders; the coordinator must still match and
complete everything (the property the response cache, fusion look-ahead
and cycle machinery all depend on)."""

import pytest

pytestmark = pytest.mark.e2e


@pytest.mark.parametrize("np_", [2, 4])
def test_negotiation_out_of_order(run_launcher, np_):
    result = run_launcher(np_, "negotiation_fuzz_worker.py")
    assert result.returncode == 0, result.stdout + result.stderr
    assert result.stdout.count("negotiation fuzz passed") == np_
